import numpy as np
import pytest

import distributed_tensorflow_tpu as dtx
from distributed_tensorflow_tpu.input.dataset import (
    AutoShardPolicy,
    Dataset,
    InputContext,
    auto_shard_dataset,
)


def test_from_tensor_slices_batch():
    ds = Dataset.from_tensor_slices(
        {"x": np.arange(10), "y": np.arange(10) * 2}).batch(4)
    batches = list(ds)
    assert [b["x"].shape[0] for b in batches] == [4, 4, 2]
    np.testing.assert_array_equal(batches[0]["y"], [0, 2, 4, 6])


def test_batch_drop_remainder():
    ds = Dataset.range(10).batch(4, drop_remainder=True)
    assert [np.shape(b)[0] for b in ds] == [4, 4]
    assert ds.cardinality() == 2


def test_map_filter_take_skip():
    ds = Dataset.range(10).map(lambda x: x * x).filter(lambda x: x % 2 == 0)
    assert list(ds) == [0, 4, 16, 36, 64]
    assert list(Dataset.range(10).skip(7)) == [7, 8, 9]
    assert list(Dataset.range(10).take(2)) == [0, 1]


def test_shuffle_deterministic_and_complete():
    ds = Dataset.range(20).shuffle(8, seed=42)
    out = list(ds)
    assert sorted(out) == list(range(20))
    assert out != list(range(20))
    assert list(Dataset.range(20).shuffle(8, seed=42)) == out


def test_repeat():
    assert list(Dataset.range(3).repeat(2)) == [0, 1, 2, 0, 1, 2]


def test_shard_data_policy():
    ds = Dataset.range(10).shard(4, 1)
    assert list(ds) == [1, 5, 9]


def test_shard_validates_arguments():
    """ISSUE 12 satellite: islice-backed shard would silently yield
    nothing (index >= num_shards) or raise deep inside itertools
    (negative index) — both must be loud ValueErrors instead."""
    ds = Dataset.range(10)
    for num_shards, index in ((0, 0), (-2, 0)):
        with pytest.raises(ValueError, match="num_shards"):
            ds.shard(num_shards, index)
    for index in (-1, 4, 99):
        with pytest.raises(ValueError, match="out of range"):
            ds.shard(4, index)
    # boundary indices stay valid
    assert list(ds.shard(4, 0)) == [0, 4, 8]
    assert list(ds.shard(4, 3)) == [3, 7]


def test_shard_files_validates_num_shards(tmp_path):
    f = tmp_path / "only.txt"
    f.write_text("")
    ds = Dataset.from_files([str(f)], reader=lambda p: iter([1]))
    for num_shards in (0, -1):
        with pytest.raises(ValueError, match="num_shards"):
            ds.shard_files(num_shards, 0)


def test_shard_files_policy():
    files = [f"f{i}" for i in range(4)]
    ds = Dataset.from_files(files, reader=lambda f: iter([f + "_a", f + "_b"]))
    sharded = ds.shard_files(2, 0)
    assert list(sharded) == ["f0_a", "f0_b", "f2_a", "f2_b"]


def test_auto_shard_policy_selection():
    files = [f"f{i}" for i in range(4)]
    file_ds = Dataset.from_files(files, reader=lambda f: iter([f]))
    assert list(auto_shard_dataset(file_ds, 2, 1)) == ["f1", "f3"]  # FILE
    plain = Dataset.range(6)
    assert list(auto_shard_dataset(plain, 2, 1)) == [1, 3, 5]  # DATA
    assert list(auto_shard_dataset(plain, 2, 1, AutoShardPolicy.OFF)) == \
        list(range(6))
    with pytest.raises(ValueError):
        auto_shard_dataset(plain, 2, 1, AutoShardPolicy.FILE)


def test_prefetch_matches():
    assert list(Dataset.range(50).prefetch(4)) == list(range(50))


def test_prefetch_propagates_errors():
    def gen():
        yield 1
        raise RuntimeError("boom")

    ds = Dataset.from_generator(gen).prefetch(2)
    it = iter(ds)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="boom"):
        list(it)


def test_input_context():
    ctx = InputContext(num_input_pipelines=2, input_pipeline_id=1,
                       num_replicas_in_sync=8)
    assert ctx.get_per_replica_batch_size(64) == 8
    with pytest.raises(ValueError):
        ctx.get_per_replica_batch_size(63)


def test_distributed_dataset_sharded_batches(devices):
    s = dtx.MirroredStrategy()
    ds = Dataset.from_tensor_slices(
        {"x": np.arange(64, dtype="float32").reshape(32, 2)}).batch(16)
    dist = s.experimental_distribute_dataset(ds)
    batches = list(dist)
    assert len(batches) == 2
    b = batches[0]["x"]
    assert b.shape == (16, 2)
    assert str(b.sharding.spec) == "PartitionSpec('dp',)"


def test_distributed_iterator_get_next(devices):
    s = dtx.MirroredStrategy()
    ds = Dataset.from_tensor_slices({"x": np.ones((8, 2), "float32")}).batch(8)
    it = iter(s.experimental_distribute_dataset(ds))
    assert it.get_next_as_optional() is not None
    assert it.get_next_as_optional() is None
    it2 = iter(s.experimental_distribute_dataset(ds))
    it2.get_next()
    with pytest.raises(StopIteration):
        it2.get_next()


def test_iter_per_replica(devices):
    s = dtx.MirroredStrategy()
    ds = Dataset.from_tensor_slices(
        {"x": np.arange(16, dtype="float32")}).batch(16)
    pr_batches = list(s.experimental_distribute_dataset(ds).iter_per_replica())
    pr = pr_batches[0]["x"]
    assert len(pr) == 8
    np.testing.assert_array_equal(pr.values[1], [2.0, 3.0])


def test_distribute_datasets_from_function(devices):
    s = dtx.MirroredStrategy()

    def dataset_fn(ctx):
        per_replica = ctx.get_per_replica_batch_size(32)
        return Dataset.from_tensor_slices(
            {"x": np.ones((64, 1), "float32")}).batch(
                per_replica * s.num_replicas_in_sync)

    dist = s.distribute_datasets_from_function(dataset_fn)
    b = next(iter(dist))
    assert b["x"].shape == (32, 1)


def test_interleave_round_robin():
    ds = Dataset.range(3).interleave(
        lambda i: Dataset.from_iterable([i * 10, i * 10 + 1, i * 10 + 2]),
        cycle_length=2, block_length=1)
    got = list(ds)
    # sources 0 and 1 open first, alternating; source 2 joins as one closes
    assert sorted(got) == [0, 1, 2, 10, 11, 12, 20, 21, 22]
    assert got[:4] == [0, 10, 1, 11]


def test_interleave_block_length_and_files_pattern():
    ds = Dataset.range(4).interleave(
        lambda i: Dataset.from_iterable([(i, j) for j in range(2)]),
        cycle_length=4, block_length=2)
    got = list(ds)
    assert got == [(0, 0), (0, 1), (1, 0), (1, 1),
                   (2, 0), (2, 1), (3, 0), (3, 1)]


def test_zip_stops_at_shortest():
    a = Dataset.range(5)
    b = Dataset.range(3).map(lambda x: x * 100)
    z = Dataset.zip(a, b)
    assert list(z) == [(0, 0), (1, 100), (2, 200)]
    assert z.cardinality() == 3


def test_cache_replays_without_upstream():
    calls = []

    def gen():
        for i in range(4):
            calls.append(i)
            yield i

    ds = Dataset.from_generator(gen).cache()
    assert list(ds) == [0, 1, 2, 3]
    assert list(ds) == [0, 1, 2, 3]
    assert len(calls) == 4          # second epoch served from the cache


def test_cache_partial_pass_does_not_poison():
    def gen():
        yield from range(10)

    ds = Dataset.from_generator(gen).cache()
    assert list(ds.take(3)) == [0, 1, 2]    # incomplete pass: not cached
    assert list(ds) == list(range(10))      # full pass still correct


def test_shard_files_replays_downstream_transforms(tmp_path):
    """FILE sharding rewrites the SOURCE and keeps map/batch — the
    pipeline shape tf.data's FILE auto-shard preserves by graph rewrite
    (a raw re-read of the sharded files would drop the parsing)."""
    files = []
    for i in range(4):
        f = tmp_path / f"f{i}.txt"
        f.write_text("")
        files.append(str(f))

    def reader(path):
        i = int(path[-5])
        yield from range(i * 10, i * 10 + 3)

    ds = (Dataset.from_files(files, reader)
          .map(lambda x: x * 2)
          .batch(3, drop_remainder=True))
    shard0 = list(ds.shard_files(2, 0))       # files 0, 2
    shard1 = list(ds.shard_files(2, 1))       # files 1, 3
    assert [b.tolist() for b in shard0] == [[0, 2, 4], [40, 42, 44]]
    assert [b.tolist() for b in shard1] == [[20, 22, 24], [60, 62, 64]]


def test_shard_files_rejects_unreplayable_chain():
    a = Dataset.range(3)
    b = Dataset.range(3)
    z = Dataset.zip(a, b)
    z._files = ["fake"]          # pretend a file root exists downstream
    with pytest.raises(ValueError, match="DATA"):
        z.shard_files(2, 0)


def test_interleave_rejects_bad_cycle_length():
    with pytest.raises(ValueError, match="cycle_length"):
        Dataset.range(3).interleave(lambda i: Dataset.range(1),
                                    cycle_length=0)


def test_padded_batch_ragged_to_max():
    ds = Dataset.from_iterable(
        [{"ids": np.arange(n, dtype=np.int64), "n": np.int64(n)}
         for n in (1, 3, 2, 4)]).padded_batch(2, padding_values=-1)
    b1, b2 = list(ds)
    assert b1["ids"].shape == (2, 3)
    assert b1["ids"][0].tolist() == [0, -1, -1]
    assert b2["ids"].shape == (2, 4)
    assert b1["n"].tolist() == [1, 3]


def test_padded_batch_explicit_shapes_and_overflow():
    ds = Dataset.from_iterable([np.arange(2), np.arange(3)])
    out = list(ds.padded_batch(2, padded_shapes=((5,),)))[0]
    assert out.shape == (2, 5)
    with pytest.raises(ValueError, match="exceeds"):
        list(Dataset.from_iterable([np.arange(9)])
             .padded_batch(1, padded_shapes=((5,),)))


def test_padded_batch_none_and_list_specs():
    """TF spellings: None / -1 dims mean pad-to-batch-max; lists work;
    rank mismatch raises."""
    ds = Dataset.from_iterable(
        [{"ids": np.arange(n, dtype=np.int64), "n": np.int64(n)}
         for n in (2, 3)])
    out = list(ds.padded_batch(
        2, padded_shapes={"ids": (None,), "n": ()}))[0]
    assert out["ids"].shape == (2, 3)
    out2 = list(Dataset.from_iterable([np.arange(2), np.arange(3)])
                .padded_batch(2, padded_shapes=[[-1]]))[0]
    assert out2.shape == (2, 3)
    with pytest.raises(ValueError, match="rank"):
        list(Dataset.from_iterable([np.arange(2)])
             .padded_batch(1, padded_shapes=((5, 2),)))

def test_shard_files_not_enough_files_raises_on_every_worker(tmp_path):
    """num_shards > len(files) must error loudly ON EVERY worker (≙
    tf.data FILE auto-shard 'not enough files'), not only on the
    empty-shard workers — otherwise the non-empty-shard workers enter
    collectives and deadlock waiting for crashed peers."""
    f = tmp_path / "only.txt"
    f.write_text("")

    def reader(path):
        yield from range(3)

    ds = Dataset.from_files([str(f)], reader).repeat()
    assert list(ds.shard_files(1, 0).take(3)) == [0, 1, 2]
    for index in range(2):       # both workers, incl. the non-empty one
        with pytest.raises(ValueError, match="num_shards"):
            ds.shard_files(2, index)


def test_shard_files_out_of_range_index_raises(tmp_path):
    """index >= num_shards (or negative) would silently alias another
    shard's files — duplicate samples — so it must raise."""
    files = []
    for i in range(4):
        f = tmp_path / f"f{i}.txt"
        f.write_text("")
        files.append(str(f))

    def reader(path):
        yield 0

    ds = Dataset.from_files(files, reader)
    for bad in (2, -1):
        with pytest.raises(ValueError, match="out of range"):
            ds.shard_files(2, bad)


def test_interleave_leaked_stopiteration_not_exhaustion():
    """A StopIteration raised INSIDE user map_fn must surface as an
    error (PEP 479 converts it to RuntimeError inside the generator),
    not silently truncate the dataset."""
    def bad_map_fn(i):
        if i == 1:
            raise StopIteration
        return Dataset.from_iterable([i])

    with pytest.raises(RuntimeError):
        list(Dataset.range(3).interleave(bad_map_fn, cycle_length=1))

def test_background_iterator_close_then_next_stops():
    """close() must leave a parked/subsequent next() with StopIteration,
    not a forever-blocking get, and must not self-join (finalizer can
    run on the worker thread under GC)."""
    from distributed_tensorflow_tpu.input.dataset import _BackgroundIterator

    bi = _BackgroundIterator(iter(range(1000)), 2)
    assert next(bi) == 0
    bi.close()
    with pytest.raises(StopIteration):
        while True:          # drain whatever was buffered, then sentinel
            next(bi)


def test_prefetch_abandoned_iterator_collected_and_thread_stopped():
    """Abandoning a prefetch iterator mid-consumption must let GC
    collect it (the worker closure must NOT capture self — the
    finalizer holds its args strongly) and stop+join the worker thread;
    guards both the leak and the interpreter-exit abort seen with a
    half-consumed distributed iterator."""
    import gc
    import itertools
    import threading
    import weakref
    from distributed_tensorflow_tpu.input.dataset import _BackgroundIterator

    bi = _BackgroundIterator(iter(itertools.count()), 2)
    assert next(bi) == 0
    thread = bi._thread
    ref = weakref.ref(bi)
    del bi
    gc.collect()
    assert ref() is None, "worker closure keeps the iterator alive"
    thread.join(timeout=5.0)
    assert not thread.is_alive(), "worker thread leaked after GC"

    # the generator-wrapped path (Dataset.prefetch) tears down too
    ds = Dataset.range(10_000).prefetch(2)
    it = iter(ds)
    assert next(it) == 0
    del it, ds
    gc.collect()

def test_background_iterator_exhaustion_is_sticky():
    """After normal exhaustion, subsequent next()/get_next_as_optional()
    must keep raising StopIteration / returning None, not block forever
    on the empty queue (the dead worker never puts again)."""
    from distributed_tensorflow_tpu.input.dataset import _BackgroundIterator

    bi = _BackgroundIterator(iter(range(3)), 2)
    assert list(bi) == [0, 1, 2]
    for _ in range(3):
        with pytest.raises(StopIteration):
            next(bi)


def test_distributed_iterator_abandoned_is_collected(devices):
    """The production path: a half-consumed DistributedIterator with
    fetch_to_device=True must be GC-collectable (the prefetch worker
    must hold no reference back through the iterator) and its worker
    thread must stop."""
    import gc
    import weakref
    import distributed_tensorflow_tpu as dtx

    strategy = dtx.MirroredStrategy()
    ds = Dataset.from_tensor_slices(
        np.arange(1024, dtype=np.float32)).batch(16).repeat()
    it = iter(strategy.experimental_distribute_dataset(ds))
    next(it)
    inner = it._it                     # the _BackgroundIterator
    thread = inner._thread
    ref = weakref.ref(inner)
    del it, inner
    gc.collect()
    assert ref() is None, "prefetch worker keeps DistributedIterator alive"
    thread.join(timeout=5.0)
    assert not thread.is_alive()

def test_flat_map_and_unbatch():
    ds = Dataset.range(3).flat_map(
        lambda i: Dataset.from_iterable([i, i * 10]))
    assert list(ds) == [0, 0, 1, 10, 2, 20]
    nb = Dataset.from_iterable(
        [np.arange(4).reshape(2, 2), np.arange(4, 8).reshape(2, 2)]
    ).unbatch()
    assert [r.tolist() for r in nb] == [[0, 1], [2, 3], [4, 5], [6, 7]]
    # batch-of-dicts unbatches per key
    d = Dataset.from_iterable(
        [{"a": np.array([1, 2]), "b": np.array([3, 4])}]).unbatch()
    assert [e["a"] for e in d] == [1, 2]


def test_window_matches_tf_semantics():
    """window(size, shift, stride) verified DIRECTLY against tf.data
    across parameter combinations (incl. shift > window span, the case
    a naive buffer implementation gets wrong)."""
    tf = pytest.importorskip("tensorflow")
    for n, size, shift, stride, drop in [
            (7, 3, 2, 1, False), (7, 3, 2, 1, True),
            (7, 2, 3, 1, False),               # shift > span
            (8, 2, 3, 2, True), (10, 4, 5, 2, False),
            (6, 3, 3, 1, False), (5, 1, 2, 1, False)]:
        ours = [list(w) for w in Dataset.range(n).window(
            size, shift=shift, stride=stride, drop_remainder=drop)]
        theirs = [[int(x) for x in w] for w in tf.data.Dataset.range(
            n).window(size, shift=shift, stride=stride,
                      drop_remainder=drop).map(
                          lambda w: w.batch(size).get_single_element()
                      ).as_numpy_iterator()]
        assert ours == theirs, (n, size, shift, stride, drop,
                                ours, theirs)
    # window + flat_map(batch) = the classic sliding-window batches
    flat = Dataset.range(6).window(3, shift=3).flat_map(
        lambda w: w.batch(3))
    assert [b.tolist() for b in flat] == [[0, 1, 2], [3, 4, 5]]


def test_bucket_by_sequence_length_bert_input(devices):
    """Bucketed batching of variable-length token sequences — the BERT
    input pattern (VERDICT r4 item 4c): per-bucket batch sizes, pad to
    batch max, and the batches feed the distributed dataset path."""
    rng = np.random.default_rng(0)
    lengths = rng.integers(3, 40, size=64)
    elements = [{"tokens": rng.integers(1, 100, L).astype(np.int64),
                 "length": np.int64(L)} for L in lengths]

    ds = Dataset.from_iterable(elements).bucket_by_sequence_length(
        lambda el: el["length"], bucket_boundaries=[10, 20, 30],
        bucket_batch_sizes=[8, 8, 8, 8], drop_remainder=True)
    batches = list(ds)
    assert batches, "no full buckets emitted"
    for b in batches:
        toks, lens = b["tokens"], b["length"]
        assert toks.shape[0] == 8
        # all rows in one batch fall in the same bucket
        bounds = [0, 10, 20, 30, 10**9]
        bucket = [i for i in range(4)
                  if bounds[i] <= lens.max() < bounds[i + 1]]
        assert all(bounds[bucket[0]] <= l < bounds[bucket[0] + 1]
                   for l in lens)
        # padded to the longest row in the batch, zeros after each length
        assert toks.shape[1] == lens.max()
        for row, L in zip(toks, lens):
            assert (row[L:] == 0).all() and (row[:L] > 0).all()


def test_bucket_by_sequence_length_boundary_padding():
    els = [np.arange(1, n) for n in (3, 4, 5)]   # lengths 2, 3, 4
    ds = Dataset.from_iterable(els).bucket_by_sequence_length(
        len, bucket_boundaries=[5], bucket_batch_sizes=[3, 3],
        pad_to_bucket_boundary=True)
    (batch,) = list(ds)
    assert batch.shape == (3, 4)     # boundary-1
    with pytest.raises(ValueError, match="entries"):
        Dataset.range(3).bucket_by_sequence_length(
            lambda x: 1, [5], [1])


def test_bucket_by_sequence_length_pads_trailing_dims():
    """tf.data pads every unknown dim, not just the leading axis
    (ADVICE r4): (T, feat) elements with varying feat must batch."""
    from distributed_tensorflow_tpu.input.dataset import Dataset
    els = [np.ones((2, 3), np.float32), np.ones((4, 5), np.float32),
           np.ones((3, 2), np.float32), np.ones((5, 4), np.float32)]
    ds = Dataset.from_iterable(els).bucket_by_sequence_length(
        lambda el: el.shape[0], bucket_boundaries=[4],
        bucket_batch_sizes=[2, 2])
    batches = list(ds)
    shapes = sorted(tuple(b.shape) for b in batches)
    # bucket <4: lens 2,3 feats 3,2 -> (2, 3, 3); bucket >=4: (2, 5, 5)
    assert shapes == [(2, 3, 3), (2, 5, 5)]
    total = sum(float(b.sum()) for b in batches)
    assert total == sum(float(e.sum()) for e in els)   # zero padding only


# ---------------------------------------------------------------------------
# Parallel host pipeline (ISSUE 3 tentpole): ordered fan-out determinism,
# clean shutdown, fault injection, telemetry
# ---------------------------------------------------------------------------

def _jittered_square(x):
    # latency varies per element so out-of-order completion is the NORM:
    # any reorder bug shows up immediately
    import time
    time.sleep(0.0015 * ((int(x) * 7) % 3))
    return int(x) * int(x)


@pytest.mark.parametrize("workers", [2, 5])
def test_parallel_map_order_bit_identical_vs_serial(workers):
    serial = list(Dataset.range(60).map(_jittered_square))
    par = Dataset.range(60).map(_jittered_square,
                                num_parallel_calls=workers)
    assert list(par) == serial
    # re-iteration of the same pipeline stays deterministic too
    assert list(par) == serial


def test_parallel_map_autotune_order_and_stats():
    from distributed_tensorflow_tpu.input.dataset import AUTOTUNE
    serial = list(Dataset.range(40).map(_jittered_square))
    ds = Dataset.range(40).map(_jittered_square,
                               num_parallel_calls=AUTOTUNE)
    assert list(ds) == serial
    (snap,) = ds.pipeline_stats()
    assert snap["name"].startswith("map")
    assert snap["workers"] >= 1
    assert snap["elements"] == 40
    assert snap["busy_s"] > 0


def test_parallel_map_invalid_worker_count():
    ds = Dataset.range(4).map(lambda x: x, num_parallel_calls=0)
    with pytest.raises(ValueError, match="num_parallel_calls"):
        list(ds)


def test_parallel_map_error_at_failing_ordinal():
    def bad(x):
        if x == 5:
            raise ValueError("boom at 5")
        return x

    it = iter(Dataset.range(10).map(bad, num_parallel_calls=3))
    got = []
    with pytest.raises(ValueError, match="boom at 5"):
        for v in it:
            got.append(v)
    assert got == [0, 1, 2, 3, 4]


@pytest.mark.parametrize("workers", [2, 4])
def test_parallel_interleave_order_bit_identical_vs_serial(workers):
    def mk(x):
        return Dataset.range(int(x) * 10, int(x) * 10 + 1 + int(x) % 3)

    kw = dict(cycle_length=3, block_length=2)
    serial = list(Dataset.range(9).interleave(mk, **kw))
    par = list(Dataset.range(9).interleave(
        mk, num_parallel_calls=workers, **kw))
    assert par == serial


def test_parallel_interleave_autotune_matches_serial():
    from distributed_tensorflow_tpu.input.dataset import AUTOTUNE

    def mk(x):
        return Dataset.range(int(x), int(x) + 4)

    serial = list(Dataset.range(7).interleave(mk, cycle_length=4))
    par = list(Dataset.range(7).interleave(
        mk, cycle_length=4, num_parallel_calls=AUTOTUNE))
    assert par == serial


def test_parallel_stages_shut_down_on_early_abandonment():
    import gc
    import threading
    import time as _time

    before = {t.name for t in threading.enumerate()}
    it = iter(Dataset.range(10_000)
              .map(lambda x: x + 1, num_parallel_calls=3)
              .prefetch(2))
    assert next(it) == 1
    assert next(it) == 2
    del it
    gc.collect()
    deadline = _time.monotonic() + 5.0
    while _time.monotonic() < deadline:
        alive = {t.name for t in threading.enumerate()} - before
        if not alive:
            break
        _time.sleep(0.05)
    assert not alive, f"pipeline threads leaked: {alive}"


def test_prefetch_fault_site_surfaces_instead_of_hanging():
    from distributed_tensorflow_tpu.resilience import faults

    sched = faults.FaultSchedule(rules=(
        faults.FaultRule(site="input.prefetch", hits=(3,)),))
    with faults.inject(sched) as registry:
        it = iter(Dataset.range(100).prefetch(2))
        got = []
        with pytest.raises(faults.FaultInjected):
            for v in it:
                got.append(v)
        # failed at element 3: everything before it was delivered, and
        # the pipeline is DEAD afterwards (no hang, no silent resume —
        # the generator closed when the fault propagated)
        assert got == [0, 1]
        with pytest.raises(StopIteration):
            next(it)
    assert [e[0] for e in registry.events()] == ["input.prefetch"]


def test_prefetch_and_pipeline_stats_expose_bottleneck():
    from distributed_tensorflow_tpu.utils import profiler

    ds = (Dataset.range(30)
          .map(_jittered_square, num_parallel_calls=2, name="sq")
          .prefetch(4, name="pf"))
    assert list(ds) == [x * x for x in range(30)]
    snaps = ds.pipeline_stats()
    assert [s["name"] for s in snaps] == ["map:sq", "prefetch:pf"]
    pf = snaps[1]
    assert pf["elements"] == 30
    assert pf["mean_queue_depth"] is not None
    # the same stages are visible process-wide for telemetry
    names = [s["name"] for s in profiler.pipeline_stats()]
    assert "map:sq" in names and "prefetch:pf" in names
    assert profiler.bottleneck_stage() is not None


def test_infeed_loop_records_wait_time():
    import time as _time

    from distributed_tensorflow_tpu.training.loops import InfeedLoop

    def slow_source():
        for i in range(5):
            _time.sleep(0.02)
            yield np.full((2,), i, np.float32)

    loop = InfeedLoop(slow_source(), buffer_size=2)
    out = [loop.next() for _ in range(5)]
    assert [int(b[0]) for b in out] == list(range(5))
    # a 20ms/element producer against an instant consumer: the loop
    # must have measured real wait
    assert loop.batches == 5
    assert loop.total_wait_s > 0.01
    assert loop.mean_wait_s > 0
    assert 0 < loop.wait_fraction(0.2) 
    with pytest.raises(StopIteration):
        loop.next()
