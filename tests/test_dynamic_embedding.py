"""Dynamic embedding tables (embedding/dynamic.py): frequency-capped
admission, LFU+TTL eviction, growth that preserves trained rows and
slots, row-sparse optimizer parity, and membership checkpointing."""

import numpy as np
import pytest

from distributed_tensorflow_tpu.embedding import dynamic as dyn
from distributed_tensorflow_tpu.embedding.embedding import (
    FTRL,
    Adagrad,
    Adam,
    SGD,
)


def _cfg(**kw):
    defaults = dict(dim=4, initial_capacity=8, max_capacity=16,
                    admission_threshold=2, ttl_steps=4,
                    optimizer=SGD(0.1))
    defaults.update(kw)
    return dyn.DynamicTableConfig(**defaults)


def test_config_validation_is_loud():
    with pytest.raises(ValueError, match="dim"):
        dyn.DynamicTableConfig(dim=0)
    with pytest.raises(ValueError, match="initial_capacity"):
        dyn.DynamicTableConfig(dim=4, initial_capacity=1)
    with pytest.raises(ValueError, match="max_capacity"):
        dyn.DynamicTableConfig(dim=4, initial_capacity=8,
                               max_capacity=4)
    with pytest.raises(ValueError, match="admission_threshold"):
        dyn.DynamicTableConfig(dim=4, admission_threshold=0)
    with pytest.raises(ValueError, match="growth_load_factor"):
        dyn.DynamicTableConfig(dim=4, growth_load_factor=1.5)


def test_admission_threshold_and_cold_row():
    t = dyn.DynamicTable(_cfg())
    # first sight: below threshold -> shared cold row
    rows = t.translate(np.array([42]))
    assert rows.tolist() == [dyn.COLD_ROW]
    assert t.mapped == 0
    # second sight crosses the threshold -> admitted to a real row
    rows = t.translate(np.array([42]))
    assert rows[0] != dyn.COLD_ROW
    assert t.mapped == 1 and t.admissions == 1
    # an id crossing the threshold WITHIN one batch admits immediately
    rows = t.translate(np.array([7, 7, 7]))
    assert rows[0] != dyn.COLD_ROW
    assert (rows == rows[0]).all()


def test_lfu_ttl_eviction_and_thrash_guard():
    cfg = _cfg(initial_capacity=4, max_capacity=4, ttl_steps=2)
    t = dyn.DynamicTable(cfg)       # 3 usable rows (cold reserved)
    for uid in (1, 2, 3):
        t.translate(np.array([uid, uid] * (uid + 1)))   # freqs differ
    assert t.mapped == 3 and not t._free
    # a cold candidate with LOWER frequency than every victim is
    # declined (no thrash), and rides the cold row
    rows = t.translate(np.array([9, 9]))
    assert rows.tolist() == [dyn.COLD_ROW] * 2
    assert t.declined >= 1
    # age the table past the TTL: now the expired LFU row is evicted
    for _ in range(4):
        t.end_step()
    hot = np.array([9] * 1)
    rows = t.translate(hot)
    assert rows[0] != dyn.COLD_ROW
    assert t.evictions == 1
    assert 1 not in t.id_to_row        # id 1 (least frequent) evicted


def test_growth_preserves_rows_and_slots():
    cfg = _cfg(initial_capacity=4, max_capacity=16,
               optimizer=Adam(0.1), growth_load_factor=0.5)
    t = dyn.DynamicTable(cfg)
    t.translate(np.array([5, 5]))
    idx = t.translate(np.array([5] * 4))
    t.apply_row_grads(idx, np.ones((4, 4), np.float32), pad_to=4)
    trained_row = int(t.id_to_row[5])
    before_row = np.asarray(t.rows[trained_row]).copy()
    before_m = np.asarray(t.slots["momenta"][trained_row]).copy()
    cap0 = t.capacity
    # admit ids until growth fires
    uid = 100
    while t.grows == 0:
        t.translate(np.array([uid, uid]))
        uid += 1
    assert t.capacity == cap0 * 2
    # trained row and its optimizer slots survived the doubling
    np.testing.assert_array_equal(np.asarray(t.rows[trained_row]),
                                  before_row)
    np.testing.assert_array_equal(
        np.asarray(t.slots["momenta"][trained_row]), before_m)
    # growth is capped at max_capacity
    while uid < 200:
        t.translate(np.array([uid, uid]))
        uid += 1
    assert t.capacity <= cfg.capacity_limit


@pytest.mark.parametrize("opt", [Adam(0.1), FTRL(0.1), Adagrad(0.1)])
def test_sparse_apply_parity_and_untouched_rows(opt):
    """Row-sparse apply == the optimizer's dense math restricted to the
    touched rows; untouched rows' weights AND slots are bit-identical
    (no spurious Adam moment decay / FTRL accumulator drift)."""
    cfg = _cfg(initial_capacity=8, optimizer=opt)
    t = dyn.DynamicTable(cfg)
    for uid in (1, 2, 3):
        t.translate(np.array([uid, uid]))
    idx = t.translate(np.array([1, 2, 1, 1]))
    rng = np.random.default_rng(0)
    grads = rng.normal(size=(4, 4)).astype(np.float32)
    table0 = np.asarray(t.rows).copy()
    slots0 = {k: np.asarray(v).copy() for k, v in t.slots.items()}
    t.apply_row_grads(idx, grads, pad_to=4)
    # reference: aggregate per unique row, apply the optimizer math
    uniq, inv = np.unique(idx, return_inverse=True)
    agg = np.zeros((len(uniq), 4), np.float32)
    np.add.at(agg, inv, grads)
    import jax.numpy as jnp
    ref_rows, ref_slots = opt.apply(
        jnp.asarray(table0[uniq]), jnp.asarray(agg),
        {k: jnp.asarray(v[uniq]) for k, v in slots0.items()},
        jnp.asarray(0))
    np.testing.assert_allclose(np.asarray(t.rows)[uniq],
                               np.asarray(ref_rows), rtol=1e-6)
    for k in slots0:
        np.testing.assert_allclose(np.asarray(t.slots[k])[uniq],
                                   np.asarray(ref_slots[k]), rtol=1e-6)
    # untouched rows: weights and slot state BIT-identical
    untouched = np.setdiff1d(np.arange(t.capacity), uniq)
    np.testing.assert_array_equal(np.asarray(t.rows)[untouched],
                                  table0[untouched])
    for k in slots0:
        np.testing.assert_array_equal(np.asarray(t.slots[k])[untouched],
                                      slots0[k][untouched])


def test_state_dict_roundtrip_restores_membership():
    cfg = _cfg(optimizer=FTRL(0.1))
    t = dyn.DynamicTable(cfg)
    for uid in (10, 20, 30):
        t.translate(np.array([uid, uid]))
    idx = t.translate(np.array([10, 20, 30, 10]))
    t.apply_row_grads(idx, np.ones((4, 4), np.float32), pad_to=4)
    sd = t.state_dict()
    t2 = dyn.DynamicTable(cfg)
    t2.load_state_dict(sd)
    assert t2.id_to_row == t.id_to_row
    assert t2.step == t.step and t2.admissions == t.admissions
    np.testing.assert_array_equal(np.asarray(t2.rows),
                                  np.asarray(t.rows))
    for k in t.slots:
        np.testing.assert_array_equal(np.asarray(t2.slots[k]),
                                      np.asarray(t.slots[k]))
    np.testing.assert_array_equal(t2.sketch.counts, t.sketch.counts)
    # restored membership translates identically — including the
    # admission decision for an id the sketch had seen once
    t.translate(np.array([77]))
    t2.translate(np.array([77]))
    np.testing.assert_array_equal(t.translate(np.array([77, 10])),
                                  t2.translate(np.array([77, 10])))


def test_sketch_bounded_and_conservative():
    s = dyn.CountMinSketch(width=64, depth=4, seed=1)
    ids = np.arange(1000)
    s.add(ids)
    s.add(ids[:10])
    est = s.estimate(ids[:10])
    assert (est >= 2).all()             # never undercounts
    assert s.counts.nbytes == 64 * 4 * 4


def test_static_hash_table_baseline():
    t = dyn.StaticHashTable(4, 32, optimizer=Adagrad(0.1), seed=3)
    ids = np.array([5, 123456789, 5])
    rows = t.translate(ids)
    assert rows[0] == rows[2] and 0 <= rows.min()
    assert rows.max() < 32
    before = np.asarray(t.rows).copy()
    t.apply_row_grads(rows, np.ones((3, 4), np.float32), pad_to=4)
    changed = np.unique(rows)
    untouched = np.setdiff1d(np.arange(32), changed)
    assert not np.array_equal(np.asarray(t.rows)[changed],
                              before[changed])
    np.testing.assert_array_equal(np.asarray(t.rows)[untouched],
                                  before[untouched])
    sd = t.state_dict()
    t2 = dyn.StaticHashTable(4, 32, optimizer=Adagrad(0.1), seed=3)
    t2.load_state_dict(sd)
    np.testing.assert_array_equal(np.asarray(t2.rows),
                                  np.asarray(t.rows))
