"""Cause-itemized production-day audit (ISSUE 19): phase timeline,
attribution windows, SLO budget itemization, CI gates, and the slow
end-to-end day scenario (domain spread passes; the blind ring fails
the warm-restore gate)."""

import pytest

from distributed_tensorflow_tpu.telemetry import audit
from distributed_tensorflow_tpu.telemetry import slo as tv_slo


def _ev(name, wall, **fields):
    return dict(fields, ev=name, wall=wall)


def _day_events():
    """A hand-built day: night -> spike -> peak_2, rack kill at 2.2,
    recovery at 2.35, and ten completion records with one bad record
    per attribution bucket (spike, recovery, replay, unattributed)."""
    driver = [
        _ev("day.phase", 0.0, phase="night", rate_rps=40.0),
        _ev("day.phase", 1.0, phase="spike", rate_rps=1400.0),
        _ev("day.phase", 2.0, phase="peak_2", rate_rps=250.0),
        _ev("day.rack_kill", 2.2, domain="rack2", victims=[4, 5]),
        _ev("day.load", 2.9, generated=10),
        _ev("day.end", 3.0),
    ]
    trainer = [
        _ev("recovery.worker_death", 2.21, task_id=4),
        _ev("recovery.generation_start", 2.35, generation=2),
        _ev("recovery.restore_tier", 2.4, tier="peer", step=8),
    ]
    records = [
        # night, bad, outside every window -> unattributed
        _ev("serve.request", 0.5, dur_s=0.3),
        # spike, bad -> spike_overload
        _ev("serve.request", 1.5, dur_s=0.3),
        # inside the recovery window (which also lies inside the
        # spike's drain) -> recovery wins on priority
        _ev("serve.request", 2.5, dur_s=0.3),
        # record-level evidence beats every window
        _ev("serve.request", 2.5, dur_s=0.3, replayed_tokens=5),
    ] + [_ev("serve.request", 0.1 + 0.05 * i, dur_s=0.01)
         for i in range(6)]
    return {"driver": driver, 4: trainer, 0: records}


def _slo():
    return tv_slo.SLO("lat", "latency", objective=0.9, threshold_s=0.1)


def test_phase_spans_close_on_next_marker_and_day_end():
    spans = audit.phase_spans(_day_events())
    assert [s["phase"] for s in spans] == ["night", "spike", "peak_2"]
    assert [(s["start"], s["end"]) for s in spans] == \
        [(0.0, 1.0), (1.0, 2.0), (2.0, 3.0)]
    assert spans[0]["rate_rps"] == 40.0


def test_cause_windows_from_control_plane_events():
    ws = audit.cause_windows(_day_events())
    # recovery: kill/death onset backdated, closed at the next
    # generation_start plus drain (both onsets merge into one window)
    assert len(ws["recovery"]) == 1
    lo, hi = ws["recovery"][0]
    assert lo == pytest.approx(2.2 - 0.25)
    assert hi == pytest.approx(2.35 + 1.0)
    # spike phase extended by the drain margin
    assert ws["spike_overload"] == [(1.0, pytest.approx(2.0 + 2.0))]
    assert ws["scale_transition"] == []


def test_attribute_priority_and_unattributed():
    ws = audit.cause_windows(_day_events())
    assert audit.attribute({"wall": 0.5, "latency_s": 0.3}, ws) is None
    assert audit.attribute({"wall": 1.5, "latency_s": 0.3}, ws) \
        == "spike_overload"
    # recovery outranks the spike drain that also covers 2.5
    assert audit.attribute({"wall": 2.5, "latency_s": 0.3}, ws) \
        == "recovery"
    # replayed_tokens beats every window
    assert audit.attribute(
        {"wall": 2.5, "latency_s": 0.3, "replayed_tokens": 5}, ws) \
        == "preempt_replay"


def test_itemize_slos_partitions_budget_exactly():
    events = _day_events()
    records = audit.day_records(events)
    windows = audit.cause_windows(events)
    slo = _slo()
    evaluated = tv_slo.evaluate_records(records, [slo])
    max_unattr = audit.itemize_slos(records, [slo], evaluated, windows)
    res = evaluated["lat"]
    assert res["requests"] == 10 and res["bad"] == 4
    bad_by_cause = {c: v["bad"] for c, v in res["by_cause"].items()
                    if v["bad"]}
    assert bad_by_cause == {"recovery": 1, "spike_overload": 1,
                            "preempt_replay": 1}
    assert res["unattributed"]["bad"] == 1
    assert max_unattr == pytest.approx(0.25)
    # the per-cause spends partition budget_consumed exactly
    spent = sum(v["budget_consumed"] for v in res["by_cause"].values())
    spent += res["unattributed"]["budget_consumed"]
    assert spent == pytest.approx(res["budget_consumed"], abs=1e-4)


def test_audit_day_scorecard_fields():
    out = audit.audit_day(_day_events(), slos=[_slo()])
    assert [p["phase"] for p in out["phases"]] == \
        ["night", "spike", "peak_2"]
    rack = out["rack_loss"]
    assert rack["domain"] == "rack2" and rack["victims"] == [4, 5]
    assert rack["mttr_s"] == pytest.approx(0.15)
    assert rack["restore_tiers"] == ["peer"] and rack["warm"]
    assert out["requests"] == {"generated": 10, "completed": 10,
                               "dropped": 0}
    assert out["max_unattributed_frac"] == pytest.approx(0.25)


def _audit_fixture(*, identity_frac=0.0, goodput=0.96, unattr=0.0,
                   rack="warm", dropped=0):
    racks = {
        "warm": {"restore_tiers": ["host", "peer"], "warm": True,
                 "mttr_s": 0.04},
        "cold": {"restore_tiers": ["durable"], "warm": False,
                 "mttr_s": 0.04},
        "slow": {"restore_tiers": ["peer"], "warm": True, "mttr_s": 9.0},
        None: None,
    }
    return {
        "ledger": {"identity_error_frac": identity_frac,
                   "identity_error_s": identity_frac * 10.0,
                   "wall_s": 10.0, "goodput_frac": goodput},
        "slos": {"lat": {"requests": 100, "bad": 10,
                         "unattributed": {"frac_of_bad": unattr,
                                          "bad": int(10 * unattr)}}},
        "rack_loss": racks[rack],
        "requests": {"generated": 100, "completed": 100 - dropped,
                     "dropped": dropped},
    }


def test_check_audit_passes_clean_day():
    assert audit.check_audit(_audit_fixture(), goodput_floor=0.5,
                             require_warm_restore=True,
                             max_rack_mttr_s=1.0) == []


@pytest.mark.parametrize("kwargs,gate,needle", [
    ({"identity_frac": 0.05}, {}, "identity broken"),
    ({"goodput": 0.3}, {"goodput_floor": 0.5}, "below"),
    ({"unattr": 0.5}, {}, "unattributed"),
    ({"rack": "cold"}, {"require_warm_restore": True}, "warm tiers"),
    ({"rack": None}, {"require_warm_restore": True}, "no rack loss"),
    ({"rack": "slow"}, {"max_rack_mttr_s": 1.0}, "MTTR"),
    ({"dropped": 3}, {}, "dropped"),
])
def test_check_audit_gates_fire(kwargs, gate, needle):
    fails = audit.check_audit(_audit_fixture(**kwargs), **gate)
    assert any(needle in f for f in fails), fails


# ---------------------------------------------------------------------------
# End-to-end: the compressed day over the real supervisor
# ---------------------------------------------------------------------------

def _run_day(tmp_path, *, domain_spread):
    from distributed_tensorflow_tpu.telemetry import events as tv_events
    from distributed_tensorflow_tpu.testing import day_sim

    logdir = str(tmp_path / ("spread" if domain_spread else "blind"))
    rep = day_sim.DaySim(seed=1, logdir=logdir,
                         domain_spread=domain_spread).run()
    assert rep["completed_run"], rep["error"]
    return audit.audit_day(tv_events.read_run(logdir))


@pytest.mark.slow
def test_day_domain_spread_passes_gates(tmp_path):
    out = _run_day(tmp_path, domain_spread=True)
    fails = audit.check_audit(out, require_warm_restore=True,
                              goodput_floor=0.5)
    assert fails == []
    assert out["rack_loss"]["warm"]
    assert out["requests"]["dropped"] == 0


@pytest.mark.slow
def test_day_two_tenant_stream_passes_gates(tmp_path):
    """The optional two-tenant day (ISSUE 20): a seeded batch share of
    the serving stream admits after interactive each tick. Batch only
    queues extra inside already-attributed overload/recovery windows,
    so every audit gate still holds — and the records carry the tenant
    stamps per-tenant SLO evaluation partitions on."""
    from distributed_tensorflow_tpu.telemetry import events as tv_events
    from distributed_tensorflow_tpu.testing import day_sim

    logdir = str(tmp_path / "tenants")
    rep = day_sim.DaySim(seed=1, logdir=logdir,
                         two_tenant=True).run()
    assert rep["completed_run"], rep["error"]
    tt = rep["two_tenant"]
    assert tt["batch_completed"] > 0
    assert tt["interactive_completed"] > tt["batch_completed"]
    evs = tv_events.read_run(logdir)
    out = audit.audit_day(evs)
    fails = audit.check_audit(out, require_warm_restore=True,
                              goodput_floor=0.5)
    assert fails == []
    assert out["requests"]["dropped"] == 0
    stamps = {(e.get("tenant"), e.get("kind"))
              for es in evs.values() for e in es
              if e.get("ev") == "serve.request"}
    assert stamps == {("acme", "interactive"), ("batchco", "batch")}


@pytest.mark.slow
def test_day_blind_ring_fails_warm_restore_gate(tmp_path):
    """The acceptance-criteria negative: same day, same rack kill, but
    the blind (pid-1)%N replica ring — the kill takes owners and their
    replicas together, the restore falls to the durable tier, and the
    warm-restore gate fails."""
    out = _run_day(tmp_path, domain_spread=False)
    rack = out["rack_loss"]
    assert rack is not None and not rack["warm"]
    assert rack["restore_tiers"] == ["durable"]
    fails = audit.check_audit(out, require_warm_restore=True)
    assert any("warm tiers" in f for f in fails), fails
