"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's fake-multi-device test vehicle
(test_util.set_logical_devices_to_at_least, SURVEY.md §4): strategies that
target an 8-chip slice run on CPU-only CI by splitting the host into 8
XLA devices. Must run before any jax backend initialization.
"""

import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "multiprocess: spawns real OS processes (multi_process_runner)")


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture()
def mesh8(devices):
    from distributed_tensorflow_tpu.cluster.topology import make_mesh
    return make_mesh({"dp": 8})


@pytest.fixture()
def mesh2d(devices):
    from distributed_tensorflow_tpu.cluster.topology import make_mesh
    return make_mesh({"dp": 4, "tp": 2})
