"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's fake-multi-device test vehicle
(test_util.set_logical_devices_to_at_least, SURVEY.md §4): strategies that
target an 8-chip slice run on CPU-only CI by splitting the host into 8
XLA devices. Must run before any jax backend initialization.
"""

import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

# Persistent compilation cache: the suite is XLA-CPU-compile dominated
# (hundreds of distinct SPMD programs on a 1-core box). Keys are
# HLO+config hashes, so code changes invalidate exactly the programs
# they touch; repeat CI runs skip recompiling everything else.
# Set via env BEFORE importing jax (config defaults read env at import)
# and not via jax.config, so multi_process_runner children inherit it.
# (≙ the reference's bazel-level test result caching — same role.)
# Location: DTX_TEST_CACHE_DIR if set, else a REPO-LOCAL .cache dir —
# the repo survives across driver rounds while ~/.cache may be wiped,
# so repeat runs stay warm wherever the checkout lives.
_repo_cache = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".cache", "dtx_jax_cache")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.environ.get("DTX_TEST_CACHE_DIR", _repo_cache))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

import jax

jax.config.update("jax_platforms", "cpu")
# sitecustomize imports jax before conftest, so the env defaults above
# only reach SPAWNED children; the parent needs runtime updates.
jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _jit_cache_pressure_guard():
    """Release JAX's in-process jit caches when the suite nears the
    kernel memory-map ceiling.

    Every Engine/strategy instance jits fresh closures, and jax's
    global pjit cache (capacity 4096 entries) keeps their executables —
    each one several mmap'd code+const regions — alive long after the
    owning test finished. Over the full suite that compounds to
    ~65k maps, and the first compile past ``vm.max_map_count`` (65530)
    dies with a hard SIGSEGV inside XLA's executable deserializer
    rather than a Python error (observed deterministically at ~96% of
    the tier-1 run). Dropping the caches at a module boundary once maps
    pass a threshold costs only re-trace + persistent-cache deserialize
    for whatever the next modules reuse, and keeps headroom bounded no
    matter how many engine-heavy modules the suite grows.
    """
    yield
    try:
        with open(f"/proc/{os.getpid()}/maps") as f:
            n_maps = sum(1 for _ in f)
    except OSError:
        return
    if n_maps > 25_000:
        import gc
        jax.clear_caches()
        gc.collect()


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "multiprocess: spawns real OS processes (multi_process_runner)")
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection scenario (resilience/faults.py; "
        "seed via DTX_CHAOS_SEED, sweep via tools/chaos_sweep.py)")
    config.addinivalue_line(
        "markers",
        "slow: heavy run excluded from tier-1 (-m 'not slow')")


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture()
def mesh8(devices):
    from distributed_tensorflow_tpu.cluster.topology import make_mesh
    return make_mesh({"dp": 8})


@pytest.fixture()
def mesh2d(devices):
    from distributed_tensorflow_tpu.cluster.topology import make_mesh
    return make_mesh({"dp": 4, "tp": 2})
