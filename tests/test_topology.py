import jax
import numpy as np
import pytest

from distributed_tensorflow_tpu.cluster.topology import (
    DeviceAssignment,
    Topology,
    make_mesh,
    mesh_axis_size,
)


def test_topology_detect(devices):
    topo = Topology.detect()
    assert topo.num_devices == 8
    assert topo.num_processes == 1
    assert topo.platform == "cpu"
    assert len(topo.local_devices()) == 8


def test_device_assignment(devices):
    da = DeviceAssignment.build(num_replicas=4, num_cores_per_replica=2)
    assert da.device(0, 0) is devices[0]
    assert da.device(1, 0) is devices[2]
    assert len(da.replica_devices(3)) == 2


def test_device_assignment_overflow(devices):
    with pytest.raises(ValueError):
        DeviceAssignment.build(num_replicas=8, num_cores_per_replica=2)


def test_make_mesh_default(devices):
    mesh = make_mesh()
    assert mesh.shape == {"dp": 8}


def test_make_mesh_wildcard(devices):
    mesh = make_mesh({"dp": -1, "tp": 2})
    assert mesh.shape == {"dp": 4, "tp": 2}


def test_make_mesh_mismatch(devices):
    with pytest.raises(ValueError):
        make_mesh({"dp": 3, "tp": 2})


def test_mesh_axis_size(mesh2d):
    assert mesh_axis_size(mesh2d, "dp") == 4
    assert mesh_axis_size(mesh2d, "dp", "tp") == 8
    assert mesh_axis_size(mesh2d, "missing") == 1
