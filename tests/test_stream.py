"""Append-only event-log stream source (input/stream.py): record
format and torn-tail semantics, resumable consumption, and the
exactly-once contract — a trainer killed between apply and commit
replays into bit-identical state (≙ the write-once/lease discipline of
the data service, applied to an unbounded log)."""

import os
import threading

import numpy as np
import pytest

from distributed_tensorflow_tpu.input import stream as st


def _write(path, n, seed=0, chunk=64):
    w = st.StreamWriter.open(path)
    while w.next_offset < n:
        k = min(chunk, n - w.next_offset)
        st.append_chunk(w, st.seeded_events(seed, w.next_offset, k,
                                            n_users=500, n_items=200))
    w.close()


def test_roundtrip_offsets_and_payloads(tmp_path):
    path = str(tmp_path / "s.log")
    _write(path, 100)
    assert st.count_records(path) == 100
    got = list(st.StreamDataset(path).events(end_offset=100,
                                             idle_timeout_s=1.0))
    assert [o for o, _ in got] == list(range(100))
    # payloads are the seeded chunk events, bit-for-bit
    ref = st.seeded_events(0, 0, 64, n_users=500, n_items=200)
    assert got[3][1]["user"] == int(ref["user"][3])
    np.testing.assert_array_equal(got[3][1]["dense"], ref["dense"][3])


def test_torn_tail_is_invisible_and_truncated_on_append(tmp_path):
    path = str(tmp_path / "s.log")
    _write(path, 20)
    with open(path, "ab") as f:
        f.write(b"\xda\x5e\xff\x00\x01")      # torn header/payload
    count, end = st.scan_log(path)
    assert count == 20
    # readers never see the torn record
    assert len(list(st.StreamDataset(path).events(
        end_offset=25, idle_timeout_s=0.2))) == 20
    # a restarted producer truncates the tail and appends contiguously
    w = st.StreamWriter.open(path)
    assert w.next_offset == 20
    w.append_event({"x": 1})
    w.close()
    assert st.count_records(path) == 21
    assert os.path.getsize(path) > end


def test_midfile_corruption_raises(tmp_path):
    path = str(tmp_path / "s.log")
    _write(path, 10)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size // 2)
        f.write(b"\x00\x00\x00\x00")
    with pytest.raises(st.StreamCorruptError):
        st.scan_log(path)


def test_resume_from_offset_and_seek_past_end(tmp_path):
    path = str(tmp_path / "s.log")
    _write(path, 50)
    ds = st.StreamDataset(path, start_offset=30)
    got = [o for o, _ in ds.events(end_offset=50, idle_timeout_s=1.0)]
    assert got == list(range(30, 50))
    r = st.StreamReader(path)
    with pytest.raises(ValueError):
        r.seek(51)


def test_tailing_consumer_sees_concurrent_producer(tmp_path):
    path = str(tmp_path / "s.log")

    def produce():
        w = st.StreamWriter.open(path)
        for i in range(0, 120, 24):
            st.append_chunk(w, st.seeded_events(0, i, 24,
                                                n_users=100,
                                                n_items=50))
        w.close()

    t = threading.Thread(target=produce)
    t.start()
    got = [o for o, _ in st.StreamDataset(path, poll_s=0.01).events(
        end_offset=120, idle_timeout_s=5.0)]
    t.join()
    assert got == list(range(120))


def test_seeded_chunks_are_deterministic():
    a = st.seeded_events(7, 128, 32)
    b = st.seeded_events(7, 128, 32)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k])
    c = st.seeded_events(8, 128, 32)
    assert not np.array_equal(a["user"], c["user"])


# ---------------------------------------------------------------------------
# The exactly-once regression: kill the trainer BETWEEN apply and
# commit; the reformed trainer must replay the uncommitted records and
# converge to state bit-identical to an uninterrupted run.
# ---------------------------------------------------------------------------

def _tiny_cfg():
    from distributed_tensorflow_tpu.models.online_dlrm import (
        OnlineConfig)
    return OnlineConfig.tiny(batch_size=8)


@pytest.mark.parametrize("crash_after", [2, 7])
def test_kill_between_apply_and_commit_replays_exactly_once(
        tmp_path, crash_after):
    from distributed_tensorflow_tpu.models import online_dlrm as od

    cfg = _tiny_cfg()
    path = str(tmp_path / "s.log")
    w = st.StreamWriter.open(path)
    st.append_chunk(w, st.seeded_events(
        0, 0, 120, n_users=cfg.n_users, n_items=cfg.n_items,
        n_dense=cfg.n_dense))
    w.close()

    ref = od.OnlineTrainer(cfg, path, str(tmp_path / "ck_ref"),
                           commit_every=3)
    ref.restore()
    ref_summary = ref.run(120, idle_timeout_s=2.0)
    assert ref_summary["offset"] == 120

    ck = str(tmp_path / "ck")
    t1 = od.OnlineTrainer(cfg, path, ck, commit_every=3)
    t1.restore()
    with pytest.raises(od._InjectedCrash):
        t1.run(120, idle_timeout_s=2.0, crash_after_batches=crash_after)
    # the dead incarnation applied batches past its last commit — a
    # reformed trainer resumes at the COMMITTED cursor and replays
    t2 = od.OnlineTrainer(cfg, path, ck, commit_every=3)
    resumed = t2.restore()
    assert resumed == (crash_after // 3) * 3 * cfg.batch_size
    summary = t2.run(120, idle_timeout_s=2.0)
    assert summary["offset"] == 120
    # bit-identical convergence: every record applied exactly once in
    # the surviving lineage, membership included
    np.testing.assert_array_equal(np.asarray(t2.user_table.rows),
                                  np.asarray(ref.user_table.rows))
    np.testing.assert_array_equal(np.asarray(t2.item_table.rows),
                                  np.asarray(ref.item_table.rows))
    for k in ref.dense_params:
        np.testing.assert_array_equal(np.asarray(t2.dense_params[k]),
                                      np.asarray(ref.dense_params[k]))
    assert t2.user_table.id_to_row == ref.user_table.id_to_row
    assert t2.item_table.id_to_row == ref.item_table.id_to_row


def test_cursor_rides_the_checkpoint_atomically(tmp_path):
    """The cursor is a LEAF of the committed checkpoint: restore
    returns cursor and model from the same atomic commit."""
    from distributed_tensorflow_tpu.checkpoint.checkpoint import (
        Checkpoint, latest_checkpoint)
    from distributed_tensorflow_tpu.models import online_dlrm as od

    cfg = _tiny_cfg()
    path = str(tmp_path / "s.log")
    w = st.StreamWriter.open(path)
    st.append_chunk(w, st.seeded_events(
        0, 0, 48, n_users=cfg.n_users, n_items=cfg.n_items,
        n_dense=cfg.n_dense))
    w.close()
    ck = str(tmp_path / "ck")
    t = od.OnlineTrainer(cfg, path, ck, commit_every=2)
    t.restore()
    t.run(48, idle_timeout_s=2.0)
    tmpl = Checkpoint(single_writer=True,
                      online=od.checkpoint_template(cfg))
    flat = tmpl.restore(latest_checkpoint(ck, "online"))
    state = od.unpack_restored(flat)
    assert int(np.asarray(state["offset"])) == 48
    assert float(np.asarray(state["commit_wall"])) > 0
    # membership came back with the same commit
    assert od._is_dynamic(state["user"])
