"""Serving-speed optimisations: prefix-cache CoW, speculative decoding,
quantized KV pool (ISSUE 14).

The load-bearing contract for all three: greedy outputs are IDENTICAL
with the feature on or off — prefix caching byte-identically (shared
blocks hold the exact K/V prefill wrote, divergence copies-on-write
first), speculation exactly (every committed token is the target's
argmax in its true greedy context), int8 exactly on short sequences
and within a measured logit-error bound on long ones. The features are
pure speed: correctness never depends on cache state, draft quality,
or storage dtype.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.models.transformer import (
    TransformerConfig, TransformerLM)
from distributed_tensorflow_tpu.serving import (
    BlockAllocator, CacheConfig, InferenceEngine, PrefixCache, Request,
    kv_quantization_probe, truncated_draft)

#: Documented int8 KV logit-error bound for the CI-sized config (the
#: probe measures ~0.004 on this box; README's KV-dtype table cites
#: this ceiling).
INT8_LOGIT_ERR_BOUND = 0.05


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig.tiny(max_seq_len=64)
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, params


def reference_greedy(cfg, params, prompt, n):
    model = TransformerLM(cfg)
    t = list(prompt)
    for _ in range(n):
        logits = model.apply({"params": params}, jnp.asarray([t]))
        t.append(int(jnp.argmax(logits[0, len(t) - 1])))
    return t[len(prompt):]


# a 16-token base prompt: two full blocks at block_size=8, so later
# requests can match one full block plus a partial tail (the CoW case)
X = [7, 3, 9, 1, 4, 4, 2, 8, 5, 5, 1, 9, 2, 6, 3, 7]


def _engine(cfg, params, **kw):
    kw.setdefault("num_blocks", 32)
    kw.setdefault("block_size", 8)
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_prompt_len", 16)
    return InferenceEngine(cfg, params, **kw)


def _assert_blocks_conserved(engine):
    """Every pool block is either free or held by the prefix cache
    once nothing is running — shared refs all unwound."""
    held = (len(engine.scheduler.prefix_cache)
            if engine.scheduler.prefix_cache is not None else 0)
    assert (engine.scheduler.allocator.num_free + held
            == engine.cache_cfg.usable_blocks)


# ---------------------------------------------------------------------------
# prefix cache: unit level
# ---------------------------------------------------------------------------

class TestPrefixCacheUnit:
    def _cache(self, num_blocks=16, bs=4):
        a = BlockAllocator(num_blocks)
        return a, PrefixCache(a, bs)

    def test_match_walks_registered_chain(self):
        a, pc = self._cache()
        toks = list(range(10))                   # 2 full blocks + 2
        blocks = a.alloc(3)
        pc.register(toks, blocks)                # indexes blocks 0..1
        n, got = pc.match(toks + [99])           # limit = 10
        assert n == 8 and got == blocks[:2]
        assert a.refcount(blocks[0]) == 3        # owner + cache + match
        a.free(got)                              # hand the match back
        # a diverging prompt matches only the agreeing prefix
        n, got = pc.match(list(range(4)) + [77] * 6)
        assert n == 4 and got == blocks[:1]
        a.free(got)

    def test_partial_tail_match(self):
        """A prompt ending mid-block can still match a cached block
        whose tokens extend it — the block the matching sequence will
        later copy-on-write."""
        a, pc = self._cache()
        blocks = a.alloc(2)
        pc.register(list(range(8)), blocks)
        n, got = pc.match(list(range(7)))         # limit 6: 1 full + 2
        assert n == 6 and got == blocks[:2]
        a.free(got)

    def test_match_never_covers_last_token(self):
        a, pc = self._cache()
        blocks = a.alloc(2)
        pc.register(list(range(8)), blocks)
        n, got = pc.match(list(range(8)))         # identical prompt
        assert n == 7                             # 8 would leave prefill
        a.free(got)                               # nothing to compute

    def test_eviction_lru_and_never_refcounted(self):
        """Eviction frees LRU unreferenced entries only: a block a
        sequence still shares (refcount > 1) survives any pressure."""
        a, pc = self._cache(num_blocks=8, bs=4)
        b1 = a.alloc(1)
        b2 = a.alloc(1)
        pc.register(list(range(4)), b1)
        pc.register(list(range(10, 14)), b2)
        a.free(b1)                                # cache is sole owner
        a.free(b2)
        n, shared = pc.match(list(range(5)))      # a "sequence" shares b1
        assert n == 4 and shared == b1
        freed = pc.evict(5)
        assert freed == 1                         # only b2 was evictable
        assert a.refcount(b1[0]) == 2             # untouched
        assert pc.match(list(range(10, 15)))[0] == 0   # b2's entry gone
        a.free(shared)                            # seq lets go
        assert pc.evict(5) == 1                   # NOW b1 is evictable
        assert a.num_free == 7

    def test_interior_of_chain_not_evicted_before_leaf(self):
        a, pc = self._cache()
        blocks = a.alloc(2)
        pc.register(list(range(8)), blocks)
        a.free(blocks)                            # cache sole owner
        assert pc.evict(1) == 1                   # evicts the LEAF
        n, got = pc.match(list(range(4)) + [9])   # parent still matches
        assert n == 4
        a.free(got)


# ---------------------------------------------------------------------------
# prefix cache: engine level (the byte-parity contract)
# ---------------------------------------------------------------------------

class TestPrefixCacheEngine:
    def test_hit_skips_prefill_and_outputs_match_cold(self, tiny):
        """Second request with the same prompt: prefill computes only
        the suffix, outputs byte-identical to a cold engine."""
        cfg, params = tiny
        e = _engine(cfg, params, prefix_caching=True)
        e.submit(Request(id="a", tokens=tuple(X), max_new_tokens=6))
        done_a = e.run_until_idle()
        e.submit(Request(id="b", tokens=tuple(X), max_new_tokens=6))
        done_b = e.run_until_idle()
        st = e.stats()["prefix_cache"]
        assert st["hit_tokens"] == 15            # all but the last token
        assert done_b["b"]["tokens"] == done_a["a"]["tokens"] \
            == reference_greedy(cfg, params, X, 6)
        _assert_blocks_conserved(e)

    def test_shared_then_diverge_byte_parity(self, tiny):
        """The CoW case: request B matches one full block of A's prompt
        plus a PARTIAL tail block, then writes its own divergent tokens
        into that block — which must be copied first. B's outputs (and
        A's on a re-serve) are byte-identical to a cold cache."""
        cfg, params = tiny
        B_prompt = X[:12] + [9, 9]               # diverges mid-block 2
        e = _engine(cfg, params, prefix_caching=True)
        outs = {}
        for rid, p in (("a", X), ("b", B_prompt), ("a2", X)):
            e.submit(Request(id=rid, tokens=tuple(p), max_new_tokens=6))
            outs[rid] = e.run_until_idle()[rid]["tokens"]
        st = e.stats()["prefix_cache"]
        assert st["hit_tokens"] > 0 and st["hit_requests"] >= 2
        assert outs["a"] == outs["a2"] \
            == reference_greedy(cfg, params, X, 6)
        assert outs["b"] == reference_greedy(cfg, params, B_prompt, 6)
        _assert_blocks_conserved(e)

    def test_caching_on_off_parity_under_preemption(self, tiny):
        """A pool too small for the concurrency — preemption + replay
        + cache eviction all fire — and a shared-prefix workload still
        decodes byte-identically with caching on and off."""
        cfg, params = tiny
        prompts = [X, X[:12] + [9, 9], X[:5], list(X)]
        outs = {}
        for on in (False, True):
            e = _engine(cfg, params, num_blocks=8, block_size=4,
                        prefix_caching=on)
            outs[on] = e.generate(prompts, max_new_tokens=8)
            assert e.scheduler.preemptions > 0
            _assert_blocks_conserved(e)
        assert outs[True] == outs[False]
        for p, o in zip(prompts, outs[True]):
            assert o == reference_greedy(cfg, params, p, 8)

    def test_cache_parity_dp_tp_mesh(self, tiny, mesh2d):
        """Suffix prefill through the replicated extend program on a
        dp=4 × tp=2 mesh: hits adopt tp-sharded pool blocks and the
        outputs stay byte-identical to recompute."""
        cfg, params = tiny
        e = InferenceEngine(cfg, params, mesh=mesh2d, num_blocks=32,
                            block_size=8, max_slots=8, max_prompt_len=16,
                            prefix_caching=True)
        outs = {}
        for rid, p in (("a", X), ("b", X), ("c", X[:12] + [9, 9])):
            e.submit(Request(id=rid, tokens=tuple(p), max_new_tokens=6))
            outs[rid] = e.run_until_idle()[rid]["tokens"]
        assert e.stats()["prefix_cache"]["hit_tokens"] > 0
        assert outs["a"] == outs["b"] \
            == reference_greedy(cfg, params, X, 6)
        assert outs["c"] == reference_greedy(cfg, params,
                                             X[:12] + [9, 9], 6)

    def test_preempted_request_readmits_onto_warm_blocks(self, tiny):
        """A preempted sequence's registered prompt blocks survive its
        release (the cache holds them), so replay re-admits with a
        cache hit — replayed-token accounting unchanged."""
        cfg, params = tiny
        e = _engine(cfg, params, num_blocks=10, block_size=4,
                    prefix_caching=True)
        prompts = [X, X[:9], X[:6]]
        outs = e.generate(prompts, max_new_tokens=8)
        assert e.scheduler.preemptions > 0
        for p, o in zip(prompts, outs):
            assert o == reference_greedy(cfg, params, p, 8)
        assert e.stats()["prefix_cache"]["hit_tokens"] > 0
        _assert_blocks_conserved(e)


# ---------------------------------------------------------------------------
# speculative decoding
# ---------------------------------------------------------------------------

PROMPTS = [X, X[:12] + [9, 9], X[:5], [3, 1, 4, 1, 5]]


class TestSpeculative:
    @pytest.mark.parametrize("k", [1, 3])
    def test_greedy_parity_1device(self, tiny, k):
        """Whatever the (default truncated-target) draft proposes,
        committed tokens are exactly the non-speculative greedy ones."""
        cfg, params = tiny
        e = _engine(cfg, params, speculative_k=k)
        outs = e.generate(PROMPTS, max_new_tokens=6)
        for p, o in zip(PROMPTS, outs):
            assert o == reference_greedy(cfg, params, p, 6)
        st = e.stats()["speculative"]
        assert st["proposed"] > 0 and 0.0 <= st["accepted_rate"] <= 1.0

    def test_greedy_parity_with_adversarial_draft(self, tiny):
        """A draft from completely different weights (worst case: near-
        zero acceptance) still yields exact outputs — speculation only
        ever changes HOW MANY target forwards run, never what commits."""
        cfg, params = tiny
        other = TransformerLM(cfg).init(
            jax.random.PRNGKey(42), jnp.zeros((1, 8), jnp.int32))["params"]
        e = _engine(cfg, params, speculative_k=3,
                    draft_params=other, draft_cfg=cfg)
        outs = e.generate(PROMPTS, max_new_tokens=6)
        for p, o in zip(PROMPTS, outs):
            assert o == reference_greedy(cfg, params, p, 6)

    def test_self_draft_accepts_everything(self, tiny):
        """draft == target: every proposal is the target's own argmax,
        so acceptance is 1.0 — the accounting's upper anchor."""
        cfg, params = tiny
        e = _engine(cfg, params, speculative_k=3,
                    draft_params=params, draft_cfg=cfg)
        e.generate(PROMPTS, max_new_tokens=6)
        st = e.stats()["speculative"]
        assert st["proposed"] > 0
        assert st["accepted"] == st["proposed"]

    def test_greedy_parity_dp_tp_mesh(self, tiny, mesh2d):
        """Same contract on a dp=4 × tp=2 mesh: the verify forward's
        slots shard over dp, heads/vocab over tp."""
        cfg, params = tiny
        e = InferenceEngine(cfg, params, mesh=mesh2d, num_blocks=32,
                            block_size=8, max_slots=8, max_prompt_len=16,
                            speculative_k=3)
        outs = e.generate(PROMPTS, max_new_tokens=6)
        for p, o in zip(PROMPTS, outs):
            assert o == reference_greedy(cfg, params, p, 6)

    def test_parity_under_preemption_replay(self, tiny):
        """Speculation + a starved pool: preempted sequences replay
        their generated tokens as prompt and re-enter the speculative
        loop — outputs still exact, blocks conserved."""
        cfg, params = tiny
        pp = [[7, 7, 7], [8, 8, 8, 8], [9, 9]]
        e = _engine(cfg, params, num_blocks=6, block_size=4,
                    speculative_k=2)
        outs = e.generate(pp, max_new_tokens=8)
        assert e.scheduler.preemptions > 0
        for p, o in zip(pp, outs):
            assert o == reference_greedy(cfg, params, p, 8)
        _assert_blocks_conserved(e)

    def test_eos_respected_mid_speculation(self, tiny):
        """An EOS inside an accepted draft span truncates the commit
        exactly where sequential decode would stop."""
        cfg, params = tiny
        ref = reference_greedy(cfg, params, [5, 6, 7], 6)
        eos = ref[2]
        e = _engine(cfg, params, speculative_k=3)
        e.submit(Request(id="e", tokens=(5, 6, 7), max_new_tokens=6,
                         eos_id=eos))
        done = e.run_until_idle()
        assert done["e"]["tokens"] == ref[:3]

    def test_truncated_draft_shapes(self, tiny):
        cfg, params = tiny
        dcfg, dparams = truncated_draft(cfg, params, 1)
        assert dcfg.n_layers == 1
        assert dparams["layers"]["attn"]["query"].shape[0] == 1
        with pytest.raises(ValueError):
            truncated_draft(cfg, params, cfg.n_layers + 1)


# ---------------------------------------------------------------------------
# quantized KV pool
# ---------------------------------------------------------------------------

class TestQuantizedKV:
    @pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
    def test_greedy_parity_short_sequences(self, tiny, kv_dtype):
        """Short prompts + short generations: quantisation error is
        far below the argmax margins of this model, so tokens are
        exactly the f32 ones (fixed seeds -> deterministic)."""
        cfg, params = tiny
        e = _engine(cfg, params, kv_dtype=kv_dtype)
        outs = e.generate(PROMPTS, max_new_tokens=6)
        for p, o in zip(PROMPTS, outs):
            assert o == reference_greedy(cfg, params, p, 6)

    def test_int8_logit_error_within_documented_bound(self, tiny):
        """The probe drives the SAME tokens through an f32 and an int8
        pool over a long rollout; the worst logit divergence must stay
        under the bound the README documents."""
        cfg, params = tiny
        probe = kv_quantization_probe(cfg, params, X, "int8",
                                      n_steps=24)
        assert probe["max_abs_logit_err"] < INT8_LOGIT_ERR_BOUND
        assert probe["positions_checked"] == 25

    def test_bf16_logit_error_smaller_than_int8(self, tiny):
        cfg, params = tiny
        p8 = kv_quantization_probe(cfg, params, X, "int8", n_steps=8)
        p16 = kv_quantization_probe(cfg, params, X, "bf16", n_steps=8)
        assert p16["max_abs_logit_err"] <= p8["max_abs_logit_err"]

    def test_int8_doubles_slots_at_equal_budget(self):
        """Acceptance gate: at an equal pool byte budget the int8
        config fits >= 2x the f32 block count (and so >= 2x the
        servable slots), for both the CI head_dim and a production
        one."""
        for head_dim in (16, 64, 128):
            kw = dict(n_layers=2, n_heads=4, head_dim=head_dim,
                      num_blocks=8, block_size=16)
            f32 = CacheConfig(**kw, kv_dtype="f32")
            i8 = CacheConfig(**kw, kv_dtype="int8")
            budget = 1 << 20
            assert i8.blocks_for_budget(budget) \
                >= 2 * f32.blocks_for_budget(budget)
            assert f32.bytes_per_token >= 2 * i8.bytes_per_token

    def test_kv_dtype_spelling_validated(self):
        with pytest.raises(ValueError):
            CacheConfig(n_layers=1, n_heads=1, head_dim=8, num_blocks=4,
                        kv_dtype="fp4")

    def test_int8_with_prefix_cache_and_speculation(self, tiny):
        """All three optimisations stacked: shared-prefix workload,
        speculation, int8 pool — outputs equal the f32 baseline
        (fixed seeds; the stacked path reuses quantized cached blocks
        and verifies drafts against dequantized gathers)."""
        cfg, params = tiny
        prompts = [X, list(X), X[:12] + [9, 9]]
        base = _engine(cfg, params).generate(prompts, max_new_tokens=6)
        e = _engine(cfg, params, prefix_caching=True, speculative_k=2,
                    kv_dtype="int8")
        outs = e.generate(prompts, max_new_tokens=6)
        assert outs == base
        assert e.stats()["prefix_cache"]["hit_tokens"] > 0
        _assert_blocks_conserved(e)


# ---------------------------------------------------------------------------
# scheduler regression: zombie-table growth
# ---------------------------------------------------------------------------

def test_preempted_batch_member_not_grown(tiny):
    """Regression (found wiring speculation): grow_for_decode iterates
    a snapshot of the batch, so a sequence preempted by an EARLIER
    grower in the same step must be skipped — growing its released
    table would allocate blocks into a zombie table and leak them.
    The conservation assert catches any recurrence."""
    cfg, params = tiny
    pp = [[7, 7, 7], [8, 8, 8, 8], [9, 9]]
    e = _engine(cfg, params, num_blocks=6, block_size=4,
                prefix_caching=True, speculative_k=2)
    e.generate(pp, max_new_tokens=8)
    assert e.scheduler.preemptions > 0
    _assert_blocks_conserved(e)
