"""Fused AdamW kernel numerics (ops/fused_adamw.py): the one-pass
aliased update must match optax.adamw exactly — values of params, mu,
nu, count — standalone, under shard_map on the 8-device mesh, and wired
into the full train step via cfg.fused_optimizer. ≙ the reference's
fused resource_apply_adam (TF/python/training/training_ops.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from distributed_tensorflow_tpu.ops.fused_adamw import (
    adamw_reference, fused_adamw_update)
from distributed_tensorflow_tpu.models import transformer


def _tree_close(a, b, atol):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(x, np.float32),
                                   np.asarray(y, np.float32), atol=atol)


@pytest.mark.parametrize("mu_dtype", [None, jnp.bfloat16])
def test_fused_adamw_matches_optax_multi_step(mu_dtype):
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(130, 70), jnp.float32),
              "nested": {"b": jnp.asarray(rng.randn(77), jnp.float32)}}
    lr, wd = 3e-4, 0.01
    tx = optax.adamw(lr, weight_decay=wd, mu_dtype=mu_dtype)
    opt_state = tx.init(params)
    adam = opt_state[0]
    p_opt, s_opt = params, opt_state
    p_f, mu, nu, count = params, adam.mu, adam.nu, adam.count

    step = jax.jit(lambda p, g, m, v, c: fused_adamw_update(
        p, g, m, v, c, lr=lr, weight_decay=wd,
        implementation="interpret"))
    for i in range(4):
        grads = jax.tree_util.tree_map(
            lambda p, i=i: jnp.asarray(
                np.random.RandomState(i).standard_normal(p.shape),
                jnp.float32), params)
        upd, s_opt = tx.update(grads, s_opt, p_opt)
        p_opt = optax.apply_updates(p_opt, upd)
        p_f, mu, nu, count = step(p_f, grads, mu, nu, count)

    tol = 1e-6 if mu_dtype is None else 5e-2
    _tree_close(p_opt, p_f, 1e-6 if mu_dtype is None else 1e-4)
    _tree_close(s_opt[0].mu, mu, tol)
    _tree_close(s_opt[0].nu, nu, 1e-6)
    assert int(count) == int(s_opt[0].count) == 4
    for leaf, ref in zip(jax.tree_util.tree_leaves(mu),
                         jax.tree_util.tree_leaves(s_opt[0].mu)):
        assert leaf.dtype == ref.dtype


def test_fused_adamw_sharded_matches_reference():
    """shard_map path on the 8-device mesh: fsdp/tp-sharded leaves
    update per-shard; result equals the reference math."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2),
                ("fsdp", "tp"))
    rng = np.random.RandomState(1)
    mk = lambda *s: jnp.asarray(rng.randn(*s), jnp.float32)
    params = {"emb": mk(64, 32), "w": mk(32, 16), "b": mk(16)}
    specs = {"emb": P("fsdp", None), "w": P(None, "tp"), "b": P()}
    grads = jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.randn(*p.shape), jnp.float32), params)
    mu = jax.tree_util.tree_map(jnp.zeros_like, params)
    nu = jax.tree_util.tree_map(jnp.zeros_like, params)
    count = jnp.zeros((), jnp.int32)

    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))
    params_s = jax.tree_util.tree_map(jax.device_put, params, shardings)

    with mesh:
        p_s, mu_s, nu_s, c_s = jax.jit(
            lambda p, g, m, v, c: fused_adamw_update(
                p, g, m, v, c, lr=1e-3, weight_decay=0.1,
                implementation="interpret", mesh=mesh,
                param_specs=specs))(params_s, grads, mu, nu, count)

    p_r, mu_r, nu_r, c_r = fused_adamw_update(
        params, grads, mu, nu, count, lr=1e-3, weight_decay=0.1,
        implementation="reference")
    _tree_close(p_s, p_r, 1e-6)
    _tree_close(mu_s, mu_r, 1e-6)
    _tree_close(nu_s, nu_r, 1e-6)


def test_train_step_fused_optimizer_matches_optax():
    """Full tiny sharded train step with cfg.fused_optimizer=True: loss
    trajectory over 3 steps matches the optax path."""
    from distributed_tensorflow_tpu.cluster.topology import make_mesh

    mesh = make_mesh({"dp": 2, "fsdp": 2, "tp": 2},
                     devices=jax.devices()[:8])
    losses = {}
    for fused in (False, True):
        cfg = transformer.TransformerConfig.tiny(
            fused_optimizer=fused, optimizer_impl="interpret")
        state, step = transformer.make_sharded_train_step(
            cfg, mesh, global_batch=4, seed=0)
        traj = []
        for i in range(3):
            tokens = transformer.synthetic_tokens(
                4, cfg.max_seq_len, cfg.vocab_size, seed=i)
            state, metrics = step(state, {"tokens": tokens})
            traj.append(float(metrics["loss"]))
        losses[fused] = traj
    np.testing.assert_allclose(losses[True], losses[False], rtol=2e-5)


def test_adamw_reference_bias_correction_first_step():
    """First-step update equals -lr * sign-ish g/(|g|+eps) shape: with
    mu=nu=0 and bias correction, mu_hat = g, nu_hat = g² exactly."""
    g = jnp.asarray([[0.5, -2.0, 1e-3] * 43 + [0.0]], jnp.float32)
    p = jnp.zeros_like(g)
    z = jnp.zeros_like(g)
    p2, mu2, nu2 = adamw_reference(p, g, z, z, 1.0 / (1 - 0.9),
                                   1.0 / (1 - 0.999), lr=1e-2, b1=0.9,
                                   b2=0.999, eps=1e-8, wd=0.0)
    expect = -1e-2 * g / (jnp.abs(g) + 1e-8)
    np.testing.assert_allclose(np.asarray(p2), np.asarray(expect),
                               atol=1e-6)
