"""Flash attention kernel vs unfused reference (CPU, interpret mode).

On the CPU test mesh both paths are exact fp32, so tolerances are tight —
the TPU bf16-MXU run is covered by bench.py on hardware.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.ops.attention import (
    flash_attention, mha_reference)


@pytest.fixture(scope="module")
def qkv():
    rng = jax.random.PRNGKey(0)
    return jax.random.normal(rng, (3, 2, 3, 64, 32), dtype=jnp.float32)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference(qkv, causal):
    q, k, v = qkv
    ref = mha_reference(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal,
                          implementation="interpret",
                          block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_reference(qkv, causal):
    q, k, v = qkv

    def loss_ref(q, k, v):
        return (mha_reference(q, k, v, causal=causal) ** 2).sum()

    def loss_pal(q, k, v):
        return (flash_attention(q, k, v, causal=causal,
                                implementation="interpret",
                                block_q=16, block_k=16) ** 2).sum()

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gp = jax.grad(loss_pal, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gr, gp):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.parametrize("causal", [False, True])
def test_uneven_blocks(qkv, causal):
    """Sequence length not a multiple of the block size (40 = 2.5 blocks)."""
    q, k, v = qkv
    q, k, v = q[:, :, :40], k[:, :, :40], v[:, :, :40]
    ref = mha_reference(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal,
                          implementation="interpret",
                          block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    gr = jax.grad(lambda *a: (mha_reference(*a, causal=causal) ** 2).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    gp = jax.grad(lambda *a: (flash_attention(
        *a, causal=causal, implementation="interpret",
        block_q=16, block_k=16) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gr, gp):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.parametrize("causal", [False, True])
def test_cross_attention_shapes(qkv, causal):
    """kv length != q length (decode / encoder-decoder attention).

    Causal alignment is bottom-right (tril k=ks-qs), matching
    mha_reference: the last query row sees all keys.
    """
    q, k, v = qkv
    q_short = q[:, :, :32]
    ref = mha_reference(q_short, k, v, causal=causal)
    out = flash_attention(q_short, k, v, causal=causal,
                          implementation="interpret",
                          block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    gr = jax.grad(lambda *a: (mha_reference(*a, causal=causal) ** 2).sum(),
                  argnums=(0, 1, 2))(q_short, k, v)
    gp = jax.grad(lambda *a: (flash_attention(
        *a, causal=causal, implementation="interpret",
        block_q=16, block_k=16) ** 2).sum(), argnums=(0, 1, 2))(q_short, k, v)
    for name, a, b in zip("qkv", gr, gp):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")
