"""Flash attention kernel vs unfused reference (CPU, interpret mode).

On the CPU test mesh both paths are exact fp32, so tolerances are tight —
the TPU bf16-MXU run is covered by bench.py on hardware.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.ops.attention import (
    flash_attention, mha_reference)


@pytest.fixture(scope="module")
def qkv():
    rng = jax.random.PRNGKey(0)
    return jax.random.normal(rng, (3, 2, 3, 64, 32), dtype=jnp.float32)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference(qkv, causal):
    q, k, v = qkv
    ref = mha_reference(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal,
                          implementation="interpret",
                          block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_reference(qkv, causal):
    q, k, v = qkv

    def loss_ref(q, k, v):
        return (mha_reference(q, k, v, causal=causal) ** 2).sum()

    def loss_pal(q, k, v):
        return (flash_attention(q, k, v, causal=causal,
                                implementation="interpret",
                                block_q=16, block_k=16) ** 2).sum()

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gp = jax.grad(loss_pal, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gr, gp):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.parametrize("causal", [False, True])
def test_uneven_blocks(qkv, causal):
    """Sequence length not a multiple of the block size (40 = 2.5 blocks)."""
    q, k, v = qkv
    q, k, v = q[:, :, :40], k[:, :, :40], v[:, :, :40]
    ref = mha_reference(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal,
                          implementation="interpret",
                          block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    gr = jax.grad(lambda *a: (mha_reference(*a, causal=causal) ** 2).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    gp = jax.grad(lambda *a: (flash_attention(
        *a, causal=causal, implementation="interpret",
        block_q=16, block_k=16) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gr, gp):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")


def test_fully_masked_rows_zero_grads(qkv):
    """Causal with q_len > k_len: bottom-right alignment leaves the first
    q_len - k_len query rows with no visible keys. Their outputs and their
    contribution to dq/dk/dv must be exactly zero (ADVICE r1: the saved
    lse must not make backward recompute p = 1 on those rows)."""
    q, k, v = qkv
    k_short, v_short = k[:, :, :32], v[:, :, :32]
    ref = mha_reference(q, k_short, v_short, causal=True)
    out = flash_attention(q, k_short, v_short, causal=True,
                          implementation="interpret",
                          block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    assert np.abs(np.asarray(out)[:, :, :32]).max() == 0.0

    gr = jax.grad(lambda *a: (mha_reference(*a, causal=True) ** 2).sum(),
                  argnums=(0, 1, 2))(q, k_short, v_short)
    gp = jax.grad(lambda *a: (flash_attention(
        *a, causal=True, implementation="interpret",
        block_q=16, block_k=16) ** 2).sum(), argnums=(0, 1, 2))(
            q, k_short, v_short)
    for name, a, b in zip("qkv", gr, gp):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")
    # The empty query rows themselves get zero gradient.
    assert np.abs(np.asarray(gp[0])[:, :, :32]).max() == 0.0


def test_sharded_flash_no_allgather(devices):
    """sharded_flash_attention partitions the Pallas custom call over
    batch/head axes via shard_map: the compiled module must contain no
    all-gather (replicated-kernel symptom, ADVICE r1 medium)."""
    from distributed_tensorflow_tpu.cluster.topology import make_mesh
    from distributed_tensorflow_tpu.ops.attention import \
        sharded_flash_attention
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh({"dp": 4, "tp": 2})
    rng = jax.random.PRNGKey(0)
    q, k, v = jax.random.normal(rng, (3, 8, 4, 64, 16), dtype=jnp.float32)
    shard = NamedSharding(mesh, P("dp", "tp", None, None))
    q, k, v = (jax.device_put(t, shard) for t in (q, k, v))

    fn = jax.jit(lambda q, k, v: sharded_flash_attention(
        q, k, v, mesh, causal=True, implementation="interpret",
        block_q=16, block_k=16))
    compiled = fn.lower(q, k, v).compile()
    hlo = compiled.as_text()
    assert "all-gather" not in hlo and "all-to-all" not in hlo, \
        "attention operands were gathered — kernel not partitioned"
    out = fn(q, k, v)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_cross_attention_shapes(qkv, causal):
    """kv length != q length (decode / encoder-decoder attention).

    Causal alignment is bottom-right (tril k=ks-qs), matching
    mha_reference: the last query row sees all keys.
    """
    q, k, v = qkv
    q_short = q[:, :, :32]
    ref = mha_reference(q_short, k, v, causal=causal)
    out = flash_attention(q_short, k, v, causal=causal,
                          implementation="interpret",
                          block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    gr = jax.grad(lambda *a: (mha_reference(*a, causal=causal) ** 2).sum(),
                  argnums=(0, 1, 2))(q_short, k, v)
    gp = jax.grad(lambda *a: (flash_attention(
        *a, causal=causal, implementation="interpret",
        block_q=16, block_k=16) ** 2).sum(), argnums=(0, 1, 2))(q_short, k, v)
    for name, a, b in zip("qkv", gr, gp):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")
