"""Real multi-process distributed tests.

≙ the reference's multi_process_runner-based test suites (SURVEY.md §4:
multi_process_runner.py:107, multi_worker_test_base.py:123,
coordinator/fault_tolerance_test.py): every test here spawns actual OS
processes, each with its own JAX runtime, connected through the TSL
coordination service — the paths single-process virtual-device tests
cannot exercise (bootstrap.initialize, cross-process collectives,
multi-host checkpoint commit, preemption agreement, process death).
"""

import os
import time

import numpy as np
import pytest

from distributed_tensorflow_tpu.testing import multi_process_runner as mpr

pytestmark = pytest.mark.multiprocess


# ---------------------------------------------------------------------------
# worker fns (module-level: spawn pickles them by reference)
# ---------------------------------------------------------------------------

def _psum_worker():
    from distributed_tensorflow_tpu.cluster import bootstrap
    runtime = bootstrap.initialize()          # reads TF_CONFIG
    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    assert jax.process_count() == runtime.num_processes
    # global cross-process reduction over the CPU "DCN": each process
    # contributes (process_id + 1); sum must be N(N+1)/2.
    x = jnp.ones((4,)) * (runtime.process_id + 1)
    gathered = multihost_utils.process_allgather(x)
    total = float(gathered.sum() / 4)
    bootstrap.shutdown()
    return runtime.process_id, runtime.num_processes, total


def _kv_barrier_worker():
    from distributed_tensorflow_tpu.cluster import bootstrap
    from distributed_tensorflow_tpu.cluster.coordination import (
        coordination_service)
    runtime = bootstrap.initialize()
    agent = coordination_service()
    agent.key_value_set(f"greeting/{runtime.process_id}",
                        f"hello-{runtime.process_id}")
    agent.barrier("all-wrote", timeout_s=60)
    peer = (runtime.process_id + 1) % runtime.num_processes
    got = agent.key_value_get(f"greeting/{peer}", timeout_s=30).decode()
    n = agent.key_value_increment("counter", 1)
    agent.barrier("all-read", timeout_s=60)
    final = int(agent.key_value_get("counter", timeout_s=30))
    bootstrap.shutdown()
    return got, n, final


def _ckpt_worker(tmpdir):
    """Sharded multi-host checkpoint: each process owns half of a global
    array; save must barrier so the index lands only after ALL shards."""
    from distributed_tensorflow_tpu.cluster import bootstrap
    runtime = bootstrap.initialize()
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from distributed_tensorflow_tpu.parallel.values import DistributedVariable
    from distributed_tensorflow_tpu.checkpoint.checkpoint import Checkpoint

    mesh = Mesh(np.array(jax.devices()), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    nproc = runtime.num_processes
    rows = 4 * nproc
    global_data = np.arange(rows * 3, dtype=np.float32).reshape(rows, 3)
    arr = jax.make_array_from_callback(
        (rows, 3), sharding, lambda idx: global_data[idx])
    var = DistributedVariable(arr, name="table")

    ckpt = Checkpoint(table=var, step=jnp.asarray(7, jnp.int32))
    path = os.path.join(tmpdir, "ckpt-1")
    ckpt.write(path)
    # after write returns (exit barrier), the index must exist everywhere
    assert os.path.exists(os.path.join(path, "checkpoint.index.json"))

    # wipe local state, restore, verify global content
    var.assign(jnp.zeros((rows, 3), jnp.float32))
    restored = Checkpoint(table=var, step=jnp.asarray(0, jnp.int32)) \
        .restore(path)
    local = np.concatenate(
        [np.asarray(s.data) for s in
         sorted(var.read_value().addressable_shards,
                key=lambda s: s.index[0].start or 0)], axis=0)
    expect = global_data[runtime.process_id * 4:(runtime.process_id + 1) * 4]
    ok = np.array_equal(local, expect) and int(restored["step"]) == 7
    bootstrap.shutdown()
    return bool(ok)


def _barrier_timeout_worker():
    """Worker 1 never reaches the barrier; worker 0 must fail fast with
    BarrierTimeoutError instead of hanging (≙ the reference's
    check_health timeout, collective_all_reduce_strategy.py:990)."""
    from distributed_tensorflow_tpu.cluster import bootstrap
    from distributed_tensorflow_tpu.cluster.coordination import (
        coordination_service, BarrierTimeoutError)
    runtime = bootstrap.initialize()
    agent = coordination_service()
    outcome = "unknown"
    if runtime.process_id == 0:
        try:
            agent.barrier("never-met", timeout_s=3)
            outcome = "passed"
        except BarrierTimeoutError:
            outcome = "timeout"
    else:
        time.sleep(6)       # deliberately skip the barrier
        outcome = "skipped"
    bootstrap.shutdown()
    return outcome


def _preemption_worker(tmpdir):
    """Cross-process preemption agreement: only process 0 receives the
    signal; BOTH processes must checkpoint at the agreed step."""
    from distributed_tensorflow_tpu.cluster import bootstrap
    from distributed_tensorflow_tpu.cluster.coordination import (
        coordination_service)
    runtime = bootstrap.initialize()
    agent = coordination_service()
    import jax.numpy as jnp
    from distributed_tensorflow_tpu.checkpoint.checkpoint import (
        Checkpoint, CheckpointManager)
    from distributed_tensorflow_tpu.checkpoint.failure_handling import (
        PreemptionCheckpointHandler, TerminationConfig)

    state = {"w": jnp.zeros(())}

    def train_step():
        state["w"] = state["w"] + 1.0

    ckpt = Checkpoint(w=state["w"])
    mgr = CheckpointManager(ckpt, tmpdir, checkpoint_name="pre")
    handler = PreemptionCheckpointHandler(
        mgr, TerminationConfig(exit_fn=lambda: None))
    saved_at = None
    for i in range(100):
        # per-step barrier stands in for the SPMD step's collectives:
        # real training is in lockstep because every step psums
        agent.barrier(f"step/{i}", timeout_s=60)
        ckpt._objects["w"] = state["w"]
        handler.run(train_step)
        if runtime.process_id == 0 and i == 4:
            handler.watch_preemption()      # signal arrives on proc 0 only
        if handler._exited:
            saved_at = handler.total_run_calls
            break
        time.sleep(0.05)   # realistic step time >> the signal poll period
    bootstrap.shutdown()
    return runtime.process_id, saved_at


def _killed_worker_detection(tmpdir):
    """Workers 0/1 proceed; worker 2 hangs and is SIGKILLed by the
    parent. Survivors must observe the death as a barrier timeout —
    the organic failure signal (≙ coordination-service task states,
    SURVEY.md §5.3)."""
    from distributed_tensorflow_tpu.cluster import bootstrap
    from distributed_tensorflow_tpu.cluster.coordination import (
        coordination_service, CoordinationError)
    runtime = bootstrap.initialize()
    agent = coordination_service()
    if runtime.process_id == 2:
        # tell the parent it is safe to kill us (initialize() done — the
        # rendezvous completed, peers are not blocked on our connect)
        with open(os.path.join(tmpdir, "w2_ready"), "w") as f:
            f.write("1")
        time.sleep(120)                     # killed long before this ends
        return "should-not-survive"
    agent.key_value_set(f"alive/{runtime.process_id}", "1")
    # wait until the parent confirms the kill happened
    while not os.path.exists(os.path.join(tmpdir, "w2_killed")):
        time.sleep(0.2)
    try:
        agent.barrier("post-kill", timeout_s=8)
        outcome = "passed"
    except CoordinationError:
        outcome = "peer-death-detected"
    # Exit ordering: process 0 hosts the coordination service, so it must
    # exit LAST — service teardown hard-aborts any peer with a live
    # client (its PollForError thread calls LOG(FATAL)). Non-hosts report
    # and leave immediately; the host waits for their reports + grace.
    try:
        agent.key_value_set(f"detected/{runtime.process_id}", outcome)
        if runtime.process_id == 0:
            deadline = time.monotonic() + 20
            while (agent.key_value_try_get("detected/1") is None
                   and time.monotonic() < deadline):
                time.sleep(0.1)
            time.sleep(1.0)       # let the peer finish reporting and exit
    except Exception:
        pass
    # NOTE: no clean shutdown — the coordination service may already
    # consider the job unhealthy; survivors just exit.
    return runtime.process_id, outcome


def _remote_square(x):
    return x * x


def _remote_slow_identity(x):
    time.sleep(0.4)
    return x


def _remote_dispatch_worker(tmpdir, slow):
    """proc 0 = coordinator; procs 1..N-1 = remote worker services."""
    from distributed_tensorflow_tpu.cluster import bootstrap
    from distributed_tensorflow_tpu.coordinator import remote_dispatch
    from distributed_tensorflow_tpu.coordinator.cluster_coordinator import (
        ClusterCoordinator)
    runtime = bootstrap.initialize()
    if runtime.process_id != 0:
        if slow and runtime.process_id == 2:
            # mark readiness so the parent knows when to kill us
            with open(os.path.join(tmpdir, "victim_ready"), "w") as f:
                f.write("1")
        remote_dispatch.run_worker_loop()
        return ("worker-done", runtime.process_id)

    coord = ClusterCoordinator(
        remote_worker_ids=list(range(1, runtime.num_processes)))
    fn = _remote_slow_identity if slow else _remote_square
    if slow:
        # give the victim worker time to pick up a closure, then have the
        # parent kill it mid-flight
        while not os.path.exists(os.path.join(tmpdir, "victim_ready")):
            time.sleep(0.1)
    results = [coord.schedule(fn, args=(i,)) for i in range(10)]
    if slow:
        with open(os.path.join(tmpdir, "kill_now"), "w") as f:
            f.write("1")
    coord.join(timeout=120)
    values = sorted(coord.fetch(results))
    coord.shutdown()
    expect = sorted(i * i for i in range(10)) if not slow \
        else list(range(10))
    return ("coordinator", values == expect, values)


def _remote_failover_worker(tmpdir):
    return _remote_dispatch_worker(tmpdir, slow=True)


def _range_dataset():
    return iter(range(100, 1000, 100))


def _consume_next(it):
    return next(it)


def _per_worker_dataset_worker():
    """Worker-side datasets: the iterator LIVES on the worker process;
    closures consume it through an opaque handle (≙ per-worker datasets,
    cluster_coordinator.py:1604)."""
    from distributed_tensorflow_tpu.cluster import bootstrap
    from distributed_tensorflow_tpu.coordinator import remote_dispatch
    from distributed_tensorflow_tpu.coordinator.cluster_coordinator import (
        ClusterCoordinator, PerWorkerValues)
    runtime = bootstrap.initialize()
    if runtime.process_id != 0:
        remote_dispatch.run_worker_loop()
        return ("worker-done", runtime.process_id)

    coord = ClusterCoordinator(
        remote_worker_ids=list(range(1, runtime.num_processes)))
    per_worker_it = coord.create_per_worker_dataset(_range_dataset)
    assert isinstance(per_worker_it, PerWorkerValues)
    # schedule 4 closures: each consumes the NEXT element of whichever
    # worker's iterator it lands on — worker-side state advances
    rvs = [coord.schedule(_consume_next, args=(per_worker_it,))
           for _ in range(4)]
    coord.join(timeout=120)
    values = sorted(coord.fetch(rvs))
    coord.shutdown()
    # 2 workers × first two elements each (whatever the dispatch split,
    # values come from {100, 200, 300, 400} with per-worker monotonicity)
    ok = all(v in (100, 200, 300, 400) for v in values) and \
        values[0] == 100
    return ("coordinator", ok, values)


def _remote_basic_worker(tmpdir):
    return _remote_dispatch_worker(tmpdir, slow=False)


def _resume_training_worker(tmpdir, preempt_at, total_steps):
    """One generation of a preemptible training job: restore if a
    checkpoint exists, train, optionally get preempted mid-run (signal
    lands on process 0 only), checkpoint-and-stop."""
    from distributed_tensorflow_tpu.cluster import bootstrap
    from distributed_tensorflow_tpu.cluster.coordination import (
        coordination_service)
    runtime = bootstrap.initialize()
    agent = coordination_service()
    import jax.numpy as jnp
    from distributed_tensorflow_tpu.checkpoint.checkpoint import (
        Checkpoint, CheckpointManager)
    from distributed_tensorflow_tpu.checkpoint.failure_handling import (
        PreemptionCheckpointHandler, TerminationConfig)

    # "model": w_{t+1} = w_t * 1.5 + t  (order-sensitive: any lost or
    # repeated step changes the final value)
    state = {"w": jnp.asarray(1.0), "t": 0}

    def train_step():
        state["w"] = state["w"] * 1.5 + state["t"]
        state["t"] += 1

    ckpt = Checkpoint(w=state["w"], t=jnp.asarray(0))
    mgr = CheckpointManager(ckpt, tmpdir, checkpoint_name="resume")
    handler = PreemptionCheckpointHandler(
        mgr, TerminationConfig(exit_fn=lambda: None))
    # restore training position from the checkpoint contents
    if mgr.latest_checkpoint:
        restored = Checkpoint(w=state["w"], t=jnp.asarray(0)).restore(
            mgr.latest_checkpoint)
        state["w"] = jnp.asarray(restored["w"])
        state["t"] = int(restored["t"])

    for i in range(1000):
        if state["t"] >= total_steps:
            break
        agent.barrier(f"gen-step/{state['t']}", timeout_s=60)
        ckpt._objects["w"] = state["w"]
        ckpt._objects["t"] = jnp.asarray(state["t"])
        handler.run(train_step)
        if (preempt_at is not None and runtime.process_id == 0
                and state["t"] == preempt_at):
            handler.watch_preemption()
        if handler._exited:
            break
        time.sleep(0.03)
    bootstrap.shutdown()
    return runtime.process_id, state["t"], float(state["w"])


# ---------------------------------------------------------------------------
# tests
# ---------------------------------------------------------------------------
# Tests that never kill a task run on module-scoped POOLS (persistent
# processes, fresh cluster ports per run — ≙ the reference's
# MultiProcessPoolRunner, multi_process_runner.py:902) to amortize the
# spawn + jax-import cost that dominates this suite's wall-clock.
# Fault-injection tests keep the spawn-per-task MultiProcessRunner.

@pytest.fixture(scope="module")
def pool2():
    pool = mpr.MultiProcessPoolRunner(num_workers=2)
    yield pool
    pool.shutdown()


@pytest.fixture(scope="module")
def pool3():
    pool = mpr.MultiProcessPoolRunner(num_workers=3)
    yield pool
    pool.shutdown()


def test_cross_process_collective(pool2):
    result = pool2.run(_psum_worker, timeout=180)
    vals = sorted(result.return_values)
    assert vals == [(0, 2, 3.0), (1, 2, 3.0)]


def test_kv_store_barrier_increment(pool2):
    result = pool2.run(_kv_barrier_worker, timeout=180)
    assert len(result.return_values) == 2
    gots = sorted(v[0] for v in result.return_values)
    assert gots == ["hello-0", "hello-1"]
    # increments are atomic: post-increment values are a permutation of
    # {1, 2} and everyone converges on 2
    assert sorted(v[1] for v in result.return_values) == [1, 2]
    assert all(v[2] == 2 for v in result.return_values)


def test_multi_host_sharded_checkpoint(tmp_path, pool2):
    result = pool2.run(_ckpt_worker, args=(str(tmp_path),), timeout=240)
    assert result.return_values == [True, True]


def test_barrier_timeout_fails_fast(pool2):
    result = pool2.run(_barrier_timeout_worker, timeout=180)
    outcomes = sorted(result.return_values)
    assert outcomes == ["skipped", "timeout"]


def _finalize_laggard_worker(tmpdir):
    """Unequal-length loops + late preemption signal (ADVICE r2 medium):
    proc 0's data ends at step 5, proc 1's at step 8, and the signal
    lands on proc 1 near its end — the agreed run-to step is beyond
    BOTH loops. finalize() must still commit ONE checkpoint containing
    both hosts' shards (the laggard may not silently drop out)."""
    from distributed_tensorflow_tpu.cluster import bootstrap
    runtime = bootstrap.initialize()
    import jax.numpy as jnp
    from distributed_tensorflow_tpu.checkpoint.checkpoint import (
        Checkpoint, CheckpointManager)
    from distributed_tensorflow_tpu.checkpoint.failure_handling import (
        PreemptionCheckpointHandler, TerminationConfig)

    state = {"w": jnp.zeros(())}

    def train_step():
        state["w"] = state["w"] + 1.0

    ckpt = Checkpoint(w=state["w"])
    mgr = CheckpointManager(ckpt, tmpdir, checkpoint_name="fin")
    handler = PreemptionCheckpointHandler(
        mgr, TerminationConfig(exit_fn=lambda: None))
    n_steps = 5 if runtime.process_id == 0 else 8
    for i in range(n_steps):
        ckpt._objects["w"] = state["w"]
        handler.run(train_step)
        if runtime.process_id == 1 and i == n_steps - 2:
            handler.watch_preemption()   # signal near proc 1's end only
        if handler._exited:
            break
        time.sleep(0.05)
    ckpt._objects["w"] = state["w"]
    if runtime.process_id == 0:
        # deterministically let the peer's (late) signal land before
        # finalizing — in production the 600s agreement timeouts cover
        # this race; the test shouldn't wait that long
        from distributed_tensorflow_tpu.cluster.coordination import (
            coordination_service)
        agent = coordination_service()
        deadline = time.monotonic() + 60
        while (agent.key_value_try_get(handler._SIGNAL_KEY) is None
               and time.monotonic() < deadline):
            time.sleep(0.05)
    handler.finalize()                   # must not hang or skip a host
    saved = mgr.latest_checkpoint
    bootstrap.shutdown()
    return runtime.process_id, saved is not None


def test_preemption_agreement_across_processes(tmp_path, pool2):
    result = pool2.run(_preemption_worker, args=(str(tmp_path),),
                       timeout=240)
    assert len(result.return_values) == 2
    by_proc = dict(result.return_values)
    # both processes checkpointed (at the agreed step); save steps match
    assert by_proc[0] is not None and by_proc[1] is not None
    assert by_proc[0] == by_proc[1]
    # exactly one complete checkpoint exists with both hosts' shards
    cks = [d for d in os.listdir(tmp_path) if d.startswith("pre-")
           and os.path.isdir(tmp_path / d)]
    assert len(cks) == 1
    files = os.listdir(tmp_path / cks[0])
    assert "checkpoint.index.json" in files
    assert "shard_0.npz" in files and "shard_1.npz" in files


def test_remote_coordinator_dispatch(tmp_path, pool3):
    """Closures scheduled on the coordinator run in remote worker
    PROCESSES (≙ cluster_coordinator.py:1027 grpc dispatch)."""
    result = pool3.run(_remote_basic_worker, args=(str(tmp_path),),
                       timeout=240)
    coord = [v for v in result.return_values if v[0] == "coordinator"][0]
    assert coord[1], f"wrong results: {coord[2]}"
    workers = [v for v in result.return_values if v[0] == "worker-done"]
    assert len(workers) == 2     # both worker loops exited via shutdown


# jaxlib <= 0.4.36 (missing-AxisType vintage gate): passes standalone,
# but under full-suite pooled-process state this vintage's runtime
# intermittently rejects re-executed programs with "Buffer passed to
# Execute() ... is on device TFRT_CPU_0, but replica is assigned to
# TFRT_CPU_0" (NOTES_r6.md: the deserialized-executable family). Skip
# on the broken vintage rather than carry known in-suite noise.
_legacy_pooled_runtime_bug = pytest.mark.skipif(
    not hasattr(__import__("jax").sharding, "AxisType"),
    reason="jaxlib<=0.4.36 pooled-process Execute() buffer-device bug "
           "under full-suite state (pre-existing, NOTES_r6.md)")


@_legacy_pooled_runtime_bug
def test_per_worker_datasets_on_remote_workers(pool3):
    """create_per_worker_dataset places iterators ON worker processes;
    scheduled closures consume them via resource handles."""
    result = pool3.run(_per_worker_dataset_worker, timeout=240)
    coord = [v for v in result.return_values if v[0] == "coordinator"][0]
    assert coord[1], f"unexpected values: {coord[2]}"


def test_remote_dispatch_failover_on_worker_kill(tmp_path):
    """A killed worker's in-flight closure is transparently re-run on a
    surviving worker (≙ WorkerPreemptionHandler.wait_on_failure :879 —
    the organic producer of WorkerPreemptionError)."""
    spec = mpr.create_cluster_spec(num_workers=3)
    runner = mpr.MultiProcessRunner(
        _remote_failover_worker, spec, args=(str(tmp_path),), timeout=240)
    runner.start()
    deadline = time.monotonic() + 120
    while not (tmp_path / "kill_now").exists():
        assert time.monotonic() < deadline, "coordinator never signalled"
        time.sleep(0.1)
    time.sleep(0.2)               # let worker 2 take a closure in flight
    runner.terminate("worker", 2)
    result = runner.join(timeout=180, raise_on_error=False)
    coord = [t for t in result.tasks.values()
             if t.error is None and t.exitcode == 0
             and t.value and t.value[0] == "coordinator"]
    assert coord, {k: (t.exitcode, t.error and t.error[-500:])
                   for k, t in result.tasks.items()}
    assert coord[0].value[1], f"wrong results: {coord[0].value[2]}"
    assert result.tasks[("worker", 2)].exitcode != 0   # really killed


def test_preemption_restart_resume_training(tmp_path, pool2):
    """The full fault-tolerance story across PROCESS GENERATIONS:
    generation 1 trains, gets preempted (signal on one process),
    checkpoints at the agreed step and stops; generation 2 (fresh
    processes, fresh coordination service) restores and finishes. The
    final state must equal uninterrupted training — the order-sensitive
    recurrence catches any lost, repeated, or torn step."""
    total = 12
    r1 = pool2.run(_resume_training_worker,
                   args=(str(tmp_path), 4, total), timeout=300)
    assert len(r1.return_values) == 2
    for _pid, t, _w in r1.return_values:
        assert t < total, "generation 1 should have been preempted"
    # a complete checkpoint exists
    cks = [d for d in os.listdir(tmp_path) if d.startswith("resume-")]
    assert cks, os.listdir(tmp_path)

    r2 = pool2.run(_resume_training_worker,
                   args=(str(tmp_path), None, total), timeout=300)
    expect = 1.0
    for t in range(total):
        expect = expect * 1.5 + t
    for _pid, t, w in r2.return_values:
        assert t == total
        assert abs(w - expect) < 1e-3 * abs(expect), (w, expect)


def test_killed_process_detected(tmp_path):
    spec = mpr.create_cluster_spec(num_workers=3)
    runner = mpr.MultiProcessRunner(
        _killed_worker_detection, spec, args=(str(tmp_path),), timeout=120)
    runner.start()
    deadline = time.monotonic() + 90
    while not (tmp_path / "w2_ready").exists():
        assert time.monotonic() < deadline, "worker 2 never became ready"
        time.sleep(0.2)
    runner.terminate("worker", 2)
    (tmp_path / "w2_killed").write_text("1")
    result = runner.join(timeout=90, raise_on_error=False)
    survivors = {t.task_id: t for t in result.tasks.values()
                 if t.exitcode == 0 and t.error is None}
    assert set(survivors) == {0, 1}
    for t in survivors.values():
        assert t.value[1] == "peer-death-detected", t.value
    # the killed task died by SIGKILL
    assert result.tasks[("worker", 2)].exitcode != 0


@pytest.mark.multiprocess
def test_finalize_commits_full_checkpoint_on_unequal_stops(tmp_path, pool2):
    result = pool2.run(_finalize_laggard_worker, args=(str(tmp_path),),
                       timeout=240)
    by_proc = dict(result.return_values)
    assert by_proc[0] and by_proc[1]
    cks = [d for d in os.listdir(tmp_path) if d.startswith("fin-")
           and os.path.isdir(tmp_path / d)]
    assert len(cks) >= 1
    # the newest checkpoint has BOTH hosts' shards + a committed index
    newest = sorted(cks)[-1]
    files = os.listdir(tmp_path / newest)
    assert "checkpoint.index.json" in files
    assert "shard_0.npz" in files and "shard_1.npz" in files





def _dlrm_ps_worker(tmpdir):
    """Config #4 composed end-to-end: DLRM through the embedding API,
    trained async via remote dispatch across worker PROCESSES, surviving
    one worker kill mid-run (≙ parameter_server_strategy_v2.py:77 +
    tpu_embedding_v2.py:76 used together — BASELINE.md config #4)."""
    from distributed_tensorflow_tpu.cluster import bootstrap
    from distributed_tensorflow_tpu.coordinator import remote_dispatch
    from distributed_tensorflow_tpu.coordinator.cluster_coordinator import (
        ClusterCoordinator)
    from distributed_tensorflow_tpu.models import wide_deep as wd
    runtime = bootstrap.initialize()
    if runtime.process_id != 0:
        if runtime.process_id == 2:
            with open(os.path.join(tmpdir, "victim_ready"), "w") as f:
                f.write("1")
        remote_dispatch.run_worker_loop()
        return ("worker-done", runtime.process_id)

    cfg = wd.WideDeepConfig.tiny(learning_rate=0.05)
    coord = ClusterCoordinator(
        remote_worker_ids=list(range(1, runtime.num_processes)))
    while not os.path.exists(os.path.join(tmpdir, "victim_ready")):
        time.sleep(0.1)

    def on_step(n):
        if n == 10:      # mid-run: datasets live, closures in flight
            with open(os.path.join(tmpdir, "kill_now"), "w") as f:
                f.write("1")           # parent kills worker 2 now
            # block until the kill really happened so the remaining 50
            # steps all run WITHOUT worker 2
            deadline = time.monotonic() + 60
            while not os.path.exists(os.path.join(tmpdir, "killed")):
                assert time.monotonic() < deadline, "kill never confirmed"
                time.sleep(0.05)

    state, losses = wd.train_dlrm_async_ps(cfg, coord, steps=60,
                                           batch_size=32,
                                           max_in_flight=4,
                                           on_step=on_step)
    coord.shutdown()
    first = sum(losses[:10]) / 10
    last = sum(losses[-10:]) / 10
    return ("coordinator", len(losses), first, last)


@pytest.mark.multiprocess
def test_dlrm_async_ps_end_to_end(tmp_path):
    spec = mpr.create_cluster_spec(num_workers=3)
    runner = mpr.MultiProcessRunner(
        _dlrm_ps_worker, spec, args=(str(tmp_path),), timeout=300)
    runner.start()
    deadline = time.monotonic() + 180
    while not (tmp_path / "kill_now").exists():
        assert time.monotonic() < deadline, "coordinator never started"
        time.sleep(0.1)
    runner.terminate("worker", 2)
    (tmp_path / "killed").write_text("1")
    result = runner.join(timeout=300, raise_on_error=False)
    coord = [t for t in result.tasks.values()
             if t.error is None and t.exitcode == 0
             and t.value and t.value[0] == "coordinator"]
    assert coord, {k: (t.exitcode, t.error and t.error[-500:])
                   for k, t in result.tasks.items()}
    _, n_losses, first, last = coord[0].value
    assert n_losses == 60          # every scheduled step completed
    assert last < first, (first, last)     # loss still converging
    assert result.tasks[("worker", 2)].exitcode != 0   # really killed


def _train_and_evaluate_task(tmpdir):
    """Role-dispatched train_and_evaluate: chief+worker train and write
    rotating checkpoints; the evaluator task (OUTSIDE the SPMD world)
    evaluates each one and writes TB summaries
    (≙ distribute_coordinator.py:627 evaluator orchestration)."""
    import jax.numpy as jnp
    from distributed_tensorflow_tpu.checkpoint.checkpoint import (
        Checkpoint, CheckpointManager)
    from distributed_tensorflow_tpu.cluster import bootstrap
    from distributed_tensorflow_tpu.coordinator.evaluator import (
        SidecarEvaluator, train_and_evaluate)

    FINAL = 3                               # checkpoints 1..3

    def train_fn(ctx):
        # both trainers run lockstep SPMD-style steps; the chief saves
        runtime = bootstrap.runtime()
        from distributed_tensorflow_tpu.cluster.coordination import (
            coordination_service)
        agent = coordination_service()
        w = jnp.zeros(())
        ckpt = Checkpoint(w=w)
        mgr = CheckpointManager(ckpt, tmpdir, checkpoint_name="tne",
                                max_to_keep=2)
        for step in range(1, FINAL + 1):
            w = w + 1.0
            ckpt._objects["w"] = w
            agent.barrier(f"tne_step/{step}", timeout_s=120)
            mgr.save(checkpoint_number=step)
            time.sleep(0.3)       # give the evaluator a rotation window
        bootstrap.shutdown()
        return ("trainer", runtime.process_id)

    def eval_fn(ctx):
        assert ctx.task_type == "evaluator"
        ckpt = Checkpoint(w=jnp.zeros(()))
        ev = SidecarEvaluator(
            ckpt, tmpdir,
            lambda c, step: {"w": float(np.asarray(c._objects["w"]))},
            checkpoint_name="tne",
            summary_dir=os.path.join(tmpdir, "eval_logs"),
            poll_interval_s=0.1, final_step=FINAL, idle_timeout_s=90)
        evaluated = ev.run()
        return ("evaluator", evaluated)

    return train_and_evaluate(train_fn, eval_fn, strategy=None)


@pytest.mark.multiprocess
def test_train_and_evaluate_with_evaluator_task(tmp_path):
    result = mpr.run(_train_and_evaluate_task, num_workers=2,
                     has_evaluator=True, args=(str(tmp_path),),
                     timeout=300)
    values = result.return_values
    trainers = [v for v in values if v[0] == "trainer"]
    evals = [v for v in values if v[0] == "evaluator"]
    assert len(trainers) == 2 and len(evals) == 1, values
    evaluated = evals[0][1]
    steps = [s for s, _ in evaluated]
    # the evaluator saw checkpoints as they rotated and STOPPED at the
    # final one; metrics came from the restored state (w == step)
    assert steps[-1] == 3, evaluated
    for s, m in evaluated:
        assert m["w"] == float(s), evaluated
    # TB event file with eval scalars exists
    logs = os.listdir(tmp_path / "eval_logs")
    assert any("events.out.tfevents" in f for f in logs), logs


# ---------------------------------------------------------------------------
# pool-runner semantics
# ---------------------------------------------------------------------------

def _own_pid():
    import os as _os
    return _os.getpid()


def _raise_worker():
    raise ValueError("intentional")


@_legacy_pooled_runtime_bug
def test_pool_reuses_processes_across_runs(pool2):
    """The whole point of the pool: consecutive runs land on the SAME
    OS processes (no spawn / jax re-import), and a fresh distributed
    cluster still comes up correctly on every run."""
    pids1 = sorted(pool2.run(_own_pid, timeout=60).return_values)
    pids2 = sorted(pool2.run(_own_pid, timeout=60).return_values)
    assert pids1 == pids2 and len(pids1) == 2
    # distributed runs work on the same pooled processes before/after
    r = pool2.run(_psum_worker, timeout=180)
    assert sorted(r.return_values) == [(0, 2, 3.0), (1, 2, 3.0)]
    pids3 = sorted(pool2.run(_own_pid, timeout=60).return_values)
    assert pids3 == pids1


def test_pool_task_error_does_not_break_pool(pool2):
    """A raising closure reports SubprocessError; the pool stays usable
    (≙ MultiProcessPoolRunner surviving test failures)."""
    pids_before = sorted(pool2.run(_own_pid, timeout=60).return_values)
    with pytest.raises(mpr.SubprocessError, match="intentional"):
        pool2.run(_raise_worker, timeout=60)
    pids_after = sorted(pool2.run(_own_pid, timeout=60).return_values)
    assert pids_after == pids_before


def test_pool_restarts_after_idle_child_death(pool2):
    """A pool child that dies while idle must not strand the fixture:
    the next run detects the dead task and restarts the pool."""
    pids = sorted(pool2.run(_own_pid, timeout=60).return_values)
    pool2._procs[("worker", 0)].kill()
    pool2._procs[("worker", 0)].join(10)
    pids2 = sorted(pool2.run(_own_pid, timeout=120).return_values)
    assert len(pids2) == 2 and pids2 != pids
    # and distributed runs still work on the restarted pool
    r = pool2.run(_psum_worker, timeout=180)
    assert sorted(r.return_values) == [(0, 2, 3.0), (1, 2, 3.0)]
