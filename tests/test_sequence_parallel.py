"""Ring attention / Ulysses SP vs single-device full attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.cluster.topology import make_mesh
from distributed_tensorflow_tpu.ops.attention import mha_reference
from distributed_tensorflow_tpu.parallel.sequence_parallel import (
    make_ring_attention)


@pytest.fixture(scope="module")
def qkv():
    rng = jax.random.PRNGKey(7)
    # seq 64 sharded 8 ways -> 8-token chunks; 8 heads so ulysses divides
    return jax.random.normal(rng, (3, 2, 8, 64, 16), dtype=jnp.float32)


@pytest.fixture(scope="module")
def qkv4():
    """Smaller operand for the GRADIENT tests on an sp=4 mesh: autodiff
    through the unrolled ring multiplies jaxpr size by ring length, and
    on the 1-core CI box the sp=8 grad programs alone cost minutes of
    XLA-CPU compile. Ring semantics (multi-step rotation, causal skip,
    rotating dk/dv accumulators) are length-independent; forward parity
    vs full attention stays at sp=8 below."""
    rng = jax.random.PRNGKey(11)
    return jax.random.normal(rng, (3, 2, 4, 32, 16), dtype=jnp.float32)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
# causal=False duplicates the easier half of the machinery the
# causal=True variant already exercises (no block skipping/mask
# edge) — tiered out of tier-1 (ISSUE 3 cold-suite item)
@pytest.mark.parametrize(
    "causal", [pytest.param(False, marks=pytest.mark.slow), True])
def test_sp_matches_full_attention(qkv, impl, causal, devices):
    q, k, v = qkv
    mesh = make_mesh({"sp": 8})
    fn = make_ring_attention(mesh, causal=causal, impl=impl)
    out = fn(q, k, v)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


# causal=False duplicates the easier half of the machinery the
# causal=True variant already exercises (no block skipping/mask
# edge) — tiered out of tier-1 (ISSUE 3 cold-suite item)
@pytest.mark.parametrize(
    "causal", [pytest.param(False, marks=pytest.mark.slow), True])
def test_ring_attention_grads(qkv4, causal, devices):
    """ppermute has a well-defined transpose, so autodiff through the ring
    must match full-attention gradients."""
    q, k, v = qkv4
    mesh = make_mesh({"sp": 4}, devices=jax.devices()[:4])
    fn = make_ring_attention(mesh, causal=causal, impl="ring")
    gr = jax.grad(lambda *a: (mha_reference(*a, causal=causal) ** 2).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    gp = jax.grad(lambda *a: (fn(*a) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gr, gp):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=1e-4, rtol=1e-4,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
# causal=False duplicates the easier half of the machinery the
# causal=True variant already exercises (no block skipping/mask
# edge) — tiered out of tier-1 (ISSUE 3 cold-suite item)
@pytest.mark.parametrize(
    "causal", [pytest.param(False, marks=pytest.mark.slow), True])
def test_sp_flash_matches_full_attention(qkv4, impl, causal, devices):
    """The Pallas-kernel SP paths (interpret mode on CPU): forward parity
    with full attention — the fast path the chip runs. (sp=4 for CI
    compile time; the real Mosaic kernels also run under shard_map on
    the chip every bench run — bench.py sp_kernel_smoke.)"""
    q, k, v = qkv4
    mesh = make_mesh({"sp": 4}, devices=jax.devices()[:4])
    fn = make_ring_attention(mesh, causal=causal, impl=impl,
                             attn_impl="interpret", block_q=8, block_k=8)
    out = fn(q, k, v)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


# causal=False duplicates the easier half of the machinery the
# causal=True variant already exercises (no block skipping/mask
# edge) — tiered out of tier-1 (ISSUE 3 cold-suite item)
@pytest.mark.parametrize(
    "causal", [pytest.param(False, marks=pytest.mark.slow), True])
def test_ring_flash_grads(qkv4, causal, devices):
    """Flash-ring custom VJP (per-block backward against the global lse,
    rotating dk/dv accumulators) == full-attention gradients."""
    q, k, v = qkv4
    mesh = make_mesh({"sp": 4}, devices=jax.devices()[:4])
    fn = make_ring_attention(mesh, causal=causal, impl="ring",
                             attn_impl="interpret", block_q=8, block_k=8)
    gr = jax.grad(lambda *a: (mha_reference(*a, causal=causal) ** 2).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    gp = jax.grad(lambda *a: (fn(*a) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gr, gp):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=1e-4, rtol=1e-4,
                                   err_msg=f"d{name} mismatch")


def test_striped_attention_matches_full(qkv4, devices):
    """Striped (load-balanced) causal ring == full attention, forward."""
    q, k, v = qkv4
    mesh = make_mesh({"sp": 4}, devices=jax.devices()[:4])
    fn = make_ring_attention(mesh, causal=True, impl="striped",
                             attn_impl="interpret", block_q=8, block_k=8)
    out = fn(q, k, v)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


# jaxlib <= 0.4.36 (feature-probed via the missing AxisType, the
# vintage gate PR 3 applied to the fsdp params of
# test_sharded_training_matches_single_device): this grad program is in
# the same XLA-CPU family whose mid-suite heap state intermittently
# escalates to a process-killing SIGSEGV/SIGABRT — both tier-1 runs of
# 2026-08-04's session died HERE (faulthandler dump at line 131) while
# the test passes 3/3 standalone. Skip on the broken vintage rather
# than let it take down the whole tier-1 run.
@pytest.mark.skipif(
    not hasattr(jax.sharding, "AxisType"),
    reason="jaxlib<=0.4.36 XLA-CPU runtime instability on sharded grad "
           "executables (intermittent whole-process SIGSEGV mid-suite)")
def test_striped_attention_grads(qkv4, devices):
    """Striped custom VJP == full-attention gradients."""
    q, k, v = qkv4
    mesh = make_mesh({"sp": 4}, devices=jax.devices()[:4])
    fn = make_ring_attention(mesh, causal=True, impl="striped",
                             attn_impl="interpret", block_q=8, block_k=8)
    gr = jax.grad(lambda *a: (mha_reference(*a, causal=True) ** 2).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    gp = jax.grad(lambda *a: (fn(*a) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gr, gp):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=1e-4, rtol=1e-4,
                                   err_msg=f"d{name} mismatch")


def test_stripe_layout_roundtrip(devices):
    from distributed_tensorflow_tpu.parallel.sequence_parallel import (
        stripe_layout, unstripe_layout)
    x = jnp.arange(2 * 3 * 16 * 4).reshape(2, 3, 16, 4).astype(jnp.float32)
    s = stripe_layout(x, 8)
    np.testing.assert_allclose(np.asarray(unstripe_layout(s, 8)),
                               np.asarray(x))
    # device 0's shard (rows 0..1 of 16/8) holds global positions 0 and 8
    np.testing.assert_allclose(np.asarray(s[:, :, 0]),
                               np.asarray(x[:, :, 0]))
    np.testing.assert_allclose(np.asarray(s[:, :, 1]),
                               np.asarray(x[:, :, 8]))


def test_ring_attention_in_jit(qkv, devices):
    q, k, v = qkv
    mesh = make_mesh({"sp": 8})
    fn = jax.jit(make_ring_attention(mesh, causal=True))
    out = fn(q, k, v)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_striped_one_token_per_device_no_nan(devices):
    """seq == sp size: every strict step is an EMPTY block (the kernel's
    +inf-lse sentinel) — the recombination must treat it as zero
    contribution, not poison the output with NaN."""
    rng = jax.random.PRNGKey(3)
    q, k, v = jax.random.normal(rng, (3, 2, 4, 8, 16), jnp.float32)
    mesh = make_mesh({"sp": 8})
    fn = make_ring_attention(mesh, causal=True, impl="striped",
                             attn_impl="interpret", block_q=8, block_k=8)
    out = fn(q, k, v)
    assert np.isfinite(np.asarray(out)).all()
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_make_ring_attention_rejects_unknown_impl(devices):
    mesh = make_mesh({"sp": 8})
    with pytest.raises(ValueError, match="impl="):
        make_ring_attention(mesh, impl="zigzag")
    with pytest.raises(ValueError, match="flash kernel"):
        make_ring_attention(mesh, causal=True, impl="striped",
                            attn_impl="unfused")


# causal=False duplicates the easier half of the machinery the
# causal=True variant already exercises (no block skipping/mask
# edge) — tiered out of tier-1 (ISSUE 3 cold-suite item)
@pytest.mark.parametrize(
    "causal", [pytest.param(False, marks=pytest.mark.slow), True])
def test_ulysses_grads(qkv4, causal, devices):
    """all_to_all has a well-defined transpose: Ulysses gradients must
    match full attention (the one SP schedule previously without
    gradient coverage)."""
    q, k, v = qkv4
    mesh = make_mesh({"sp": 4}, devices=jax.devices()[:4])
    fn = make_ring_attention(mesh, causal=causal, impl="ulysses")
    gr = jax.grad(lambda *a: (mha_reference(*a, causal=causal) ** 2).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    gp = jax.grad(lambda *a: (fn(*a) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gr, gp):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=1e-4, rtol=1e-4,
                                   err_msg=f"d{name} mismatch")
