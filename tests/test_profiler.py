"""Profiler/tracing subsystem: trace collection produces XPlane output."""

import glob
import os

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.utils import profiler


def test_trace_produces_xplane(tmp_path):
    logdir = str(tmp_path / "profile")
    with profiler.profile(logdir):
        with profiler.Trace("annotated_matmul", step=1):
            x = jnp.ones((64, 64))
            jax.block_until_ready(x @ x)
    produced = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                         recursive=True)
    assert produced, f"no xplane output under {logdir}"


def test_step_marker_and_decorator(tmp_path):
    logdir = str(tmp_path / "profile2")

    @profiler.annotate_function
    def work():
        return jax.block_until_ready(jnp.ones((32, 32)) * 2)

    with profiler.profile(logdir):
        for i in range(2):
            with profiler.step_marker(i):
                work()
    assert glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                     recursive=True)


def test_options_accepted():
    opts = profiler.ProfilerOptions(host_tracer_level=3)
    assert opts.host_tracer_level == 3
