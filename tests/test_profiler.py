"""Profiler/tracing subsystem: trace collection produces XPlane output."""

import glob
import os

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.utils import profiler


def test_trace_produces_xplane(tmp_path):
    logdir = str(tmp_path / "profile")
    with profiler.profile(logdir):
        with profiler.Trace("annotated_matmul", step=1):
            x = jnp.ones((64, 64))
            jax.block_until_ready(x @ x)
    produced = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                         recursive=True)
    assert produced, f"no xplane output under {logdir}"


def test_step_marker_and_decorator(tmp_path):
    logdir = str(tmp_path / "profile2")

    @profiler.annotate_function
    def work():
        return jax.block_until_ready(jnp.ones((32, 32)) * 2)

    with profiler.profile(logdir):
        for i in range(2):
            with profiler.step_marker(i):
                work()
    assert glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                     recursive=True)


def test_options_accepted():
    opts = profiler.ProfilerOptions(host_tracer_level=3)
    assert opts.host_tracer_level == 3


def test_local_trace_collection(tmp_path, devices):
    """trace(target='local') runs an on-host session and writes a trace
    (the remote form dispatches the same closure over remote_dispatch)."""
    import os
    from distributed_tensorflow_tpu.utils import profiler
    profiler.trace("local", str(tmp_path), duration_ms=50)
    found = []
    for root, _dirs, files in os.walk(tmp_path):
        found.extend(files)
    assert found, "no trace files written"


def test_trace_rejects_address_targets():
    import pytest
    from distributed_tensorflow_tpu.utils import profiler
    with pytest.raises(TypeError, match="grpc ProfilerService"):
        profiler.trace("host:6009", "/tmp/x")
