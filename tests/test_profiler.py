"""Profiler/tracing subsystem: trace collection produces XPlane output."""

import glob
import os

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.utils import profiler


def test_trace_produces_xplane(tmp_path):
    logdir = str(tmp_path / "profile")
    with profiler.profile(logdir):
        with profiler.Trace("annotated_matmul", step=1):
            x = jnp.ones((64, 64))
            jax.block_until_ready(x @ x)
    produced = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                         recursive=True)
    assert produced, f"no xplane output under {logdir}"


def test_step_marker_and_decorator(tmp_path):
    logdir = str(tmp_path / "profile2")

    @profiler.annotate_function
    def work():
        return jax.block_until_ready(jnp.ones((32, 32)) * 2)

    with profiler.profile(logdir):
        for i in range(2):
            with profiler.step_marker(i):
                work()
    assert glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                     recursive=True)


def test_options_accepted():
    opts = profiler.ProfilerOptions(host_tracer_level=3)
    assert opts.host_tracer_level == 3


def test_local_trace_collection(tmp_path, devices):
    """trace(target='local') runs an on-host session and writes a trace
    (the remote form dispatches the same closure over remote_dispatch)."""
    import os
    from distributed_tensorflow_tpu.utils import profiler
    profiler.trace("local", str(tmp_path), duration_ms=50)
    found = []
    for root, _dirs, files in os.walk(tmp_path):
        found.extend(files)
    assert found, "no trace files written"


def test_trace_rejects_address_targets():
    import pytest
    from distributed_tensorflow_tpu.utils import profiler
    with pytest.raises(TypeError, match="grpc ProfilerService"):
        profiler.trace("host:6009", "/tmp/x")


def test_op_profile_reads_back_device_ops(tmp_path):
    """op_profile aggregates the collected trace into a per-op table
    (device plane; on the CPU suite the host TFRT plane carries the
    XLA Ops line)."""
    import pytest
    logdir = str(tmp_path / "profile3")
    with profiler.profile(logdir):
        x = jnp.ones((256, 256))
        for _ in range(3):
            x = jax.block_until_ready(x @ x + 1.0)
    try:
        rows = profiler.op_profile(logdir, top=10, device_substr="CPU")
    except ImportError as e:
        pytest.skip(str(e))
    assert rows and rows[0].total_ms >= 0
    allrows = profiler.op_profile(logdir, top=10000, device_substr="CPU")
    assert abs(sum(r.fraction for r in allrows) - 1) < 1e-6
    assert any(("fusion" in r.name or "dot" in r.name
                or "custom" in r.name or "jit" in r.name)
               for r in rows), [r.name for r in rows]
