"""Host/peer snapshot tiers: store retention, memdir survival, pack
round-trip, ring assignment, and the cluster-consistent restore
negotiation (all in-process — the multi-process drills live in
tests/test_elastic.py)."""

import os

import numpy as np
import pytest

from distributed_tensorflow_tpu.checkpoint import peer_snapshot as ps
from distributed_tensorflow_tpu.checkpoint.checkpoint import (
    Checkpoint,
    CheckpointManager,
)


def _snap(owner=0, step=1, world=2, arrays=None):
    return ps.HostSnapshot(
        owner=owner, step=step, world=world,
        index={"leaves": {"w": {"kind": "array", "shape": [3],
                                "dtype": "float64"}}, "format": 1},
        arrays=arrays if arrays is not None
        else {"w": np.arange(3.0) + step})


def test_pack_unpack_roundtrip():
    snap = _snap(owner=1, step=7, world=4)
    out = ps.unpack(ps.pack(snap))
    assert (out.owner, out.step, out.world) == (1, 7, 4)
    assert out.index == snap.index
    np.testing.assert_array_equal(out.arrays["w"], snap.arrays["w"])


def test_pack_unpack_empty_arrays():
    """A non-chief's capture of fully replicated state has no arrays —
    still a valid (and required) snapshot."""
    snap = _snap(arrays={})
    out = ps.unpack(ps.pack(snap))
    assert out.arrays == {}
    assert out.step == 1


def test_store_prunes_per_owner_keep(tmp_path):
    store = ps.SnapshotStore(str(tmp_path), keep=2)
    for step in (1, 2, 3):
        store.put(_snap(owner=0, step=step))
    store.put(_snap(owner=1, step=1))
    inv = store.inventory()
    assert sorted(inv[0]) == [2, 3]          # oldest evicted
    assert sorted(inv[1]) == [1]             # other owners untouched
    # memdir mirror pruned too
    assert sorted(os.listdir(tmp_path / "o0")) == ["s2", "s3"]


def test_store_memdir_survives_restart(tmp_path):
    store = ps.SnapshotStore(str(tmp_path), keep=2)
    store.put(_snap(owner=0, step=5))
    store.put(_snap(owner=1, step=5))
    # "process restart": a fresh store over the same memdir
    store2 = ps.SnapshotStore(str(tmp_path), keep=2)
    assert store2.load_surviving() == 2
    got = store2.get(1, 5)
    np.testing.assert_array_equal(got.arrays["w"], np.arange(3.0) + 5)
    # torn mirror (no meta.json) is skipped, not fatal
    os.unlink(tmp_path / "o0" / "s5" / "meta.json")
    store3 = ps.SnapshotStore(str(tmp_path), keep=2)
    assert store3.load_surviving() == 1


def test_ring_assignment():
    assert ps.ring_source(0, 4) == 1
    assert ps.ring_source(3, 4) == 0
    for pid in range(4):
        assert ps.ring_replicator(ps.ring_source(pid, 4), 4) == pid


def test_decide_prefers_fresh_complete_memory_over_disk():
    inv = {0: {0: {8: 2}, 1: {8: 2}}}        # pid 0 holds both owners @8
    d = ps._decide(inv, disk_best=(5, "/ckpt-5", "local"))
    assert d["source"] == "memory" and d["step"] == 8
    assert d["holders"] == {"0": 0, "1": 0}


def test_decide_incomplete_memory_falls_to_disk():
    inv = {0: {0: {8: 2}}}                   # owner 1's snapshot lost
    d = ps._decide(inv, disk_best=(5, "/ckpt-5", "durable"))
    assert d["source"] == "disk" and d["step"] == 5
    assert d["tier"] == "durable" and d["mem_step"] is None


def test_decide_memory_wins_step_ties():
    inv = {0: {0: {5: 1}}}
    d = ps._decide(inv, disk_best=(5, "/ckpt-5", "local"))
    assert d["source"] == "memory"           # warmer tier at same step


def test_decide_nothing_anywhere():
    assert ps._decide({0: {}}, None) == {"source": "none"}


def test_decide_holders_prefer_owner_then_lowest_pid():
    inv = {0: {1: {4: 2}},                   # pid 0 replicates owner 1
           1: {1: {4: 2}, 0: {4: 2}}}       # pid 1 has own + owner 0
    d = ps._decide(inv, None)
    assert d["holders"] == {"0": 1, "1": 1}  # owner serves itself


def test_manager_restore_latest_host_tier_single_process(tmp_path):
    state = {"w": np.arange(4.0)}
    store = ps.SnapshotStore(str(tmp_path / "mem"), keep=2)
    mgr = CheckpointManager(Checkpoint(state=state),
                            str(tmp_path / "durable"),
                            local_dir=str(tmp_path / "local"),
                            snapshot_store=store)
    mgr.save(checkpoint_number=4)
    mgr.checkpoint.sync()
    state["w"] = np.arange(4.0) * 2          # drift, then snapshot only
    mgr.snapshot(6)
    tier, step, restored = mgr.restore_latest()
    assert (tier, step) == ("host", 6)       # memory fresher than disk
    np.testing.assert_array_equal(restored["state/w"], np.arange(4.0) * 2)

    # memdir wiped (machine death) -> local disk tier at the save step
    import shutil
    shutil.rmtree(tmp_path / "mem")
    ck2 = Checkpoint(state={"w": np.zeros(4)})
    mgr2 = CheckpointManager(ck2, str(tmp_path / "durable"),
                             local_dir=str(tmp_path / "local"),
                             snapshot_store=ps.SnapshotStore(
                                 str(tmp_path / "mem"), keep=2))
    tier2, step2, restored2 = mgr2.restore_latest()
    assert (tier2, step2) == ("local", 4)
    np.testing.assert_array_equal(restored2["state/w"], np.arange(4.0))


def test_restore_latest_emits_restore_tier_event(tmp_path, monkeypatch):
    from distributed_tensorflow_tpu.telemetry import events as tv
    monkeypatch.setattr(tv, "_LOG", None)
    tv.configure(str(tmp_path / "tel"), process_id=0)
    try:
        state = {"w": np.arange(2.0)}
        mgr = CheckpointManager(Checkpoint(state=state),
                                str(tmp_path / "durable"))
        mgr.save(checkpoint_number=3)
        res = mgr.restore_latest()
        assert res[0] == "durable" and res[1] == 3
    finally:
        tv.shutdown()
    events = tv.read_events(str(tmp_path / "tel" / "events-0.jsonl"))
    evs = [e for e in events if e["ev"] == "recovery.restore_tier"]
    assert evs, events
    ev = evs[-1]
    assert ev["tier"] == "durable" and ev["step"] == 3
    assert ev["best_available"] == "durable"
    assert ev["available"]["durable"] == 3
    assert ev["available"]["memory"] is None


def test_exchange_noop_single_process():
    """Outside a distributed job the exchange is a no-op (no KV)."""
    from distributed_tensorflow_tpu.cluster.coordination import (
        coordination_service)
    agent = coordination_service()
    if agent.is_distributed:
        pytest.skip("test assumes single-process run")
    store = ps.SnapshotStore(None, keep=1)
    assert ps.exchange(store, _snap(), agent) is False


def test_store_keep_validation():
    with pytest.raises(ValueError, match="keep"):
        ps.SnapshotStore(None, keep=0)

# ---------------------------------------------------------------------------
# Failure-domain replica placement (ISSUE 19)
# ---------------------------------------------------------------------------

_RACKS = {0: "r0", 1: "r0", 2: "r1", 3: "r1"}


def test_assign_replicators_blind_matches_historical_ring():
    for world in (2, 3, 4, 7):
        assert ps.assign_replicators(world) == \
            {o: (o - 1) % world for o in range(world)}
        for pid in range(world):
            assert ps.replica_sources(pid, world) == \
                (ps.ring_source(pid, world),)
    assert ps.assign_replicators(1) == {}


def test_assign_replicators_spread_crosses_domains():
    for world, wpd in ((4, 2), (6, 2), (8, 4), (7, 3)):
        domains = {p: f"r{p // wpd}" for p in range(world)}
        assign = ps.assign_replicators(world, domains)
        for owner, rep in assign.items():
            assert rep != owner
            assert domains[rep] != domains[owner], (world, wpd, owner)
        # deterministic: every participant computes the same map
        assert assign == ps.assign_replicators(world, domains)
        # the inverse covers exactly the owners
        held = [o for p in range(world)
                for o in ps.replica_sources(p, world, domains)]
        assert sorted(held) == list(range(world))


def test_assign_replicators_single_domain_falls_back_to_any_peer():
    domains = {p: "r0" for p in range(3)}
    assign = ps.assign_replicators(3, domains)
    for owner, rep in assign.items():
        assert rep != owner                  # still never self


def _exchanged_stores(domains):
    """The store contents ring replication leaves behind: each pid
    holds its own snapshot plus every replica the placement assigns
    it (byte-equivalent to the collective exchange, no threads)."""
    world = 4
    stores = {p: ps.SnapshotStore(None, keep=2) for p in range(world)}
    for pid in range(world):
        stores[pid].put(_snap(owner=pid, step=8, world=world))
        for src in ps.replica_sources(pid, world, domains):
            stores[pid].put(_snap(owner=src, step=8, world=world))
    return stores


def test_rack_kill_blind_ring_falls_to_durable():
    """The regression the placement policy exists for: with racks of
    adjacent pids, the blind (pid-1)%N ring puts owner 3's only
    replica on pid 2 — the SAME rack — so killing rack r1 loses both
    and the restore decision falls through to the durable tier."""
    stores = _exchanged_stores(domains=None)
    surviving = {p: stores[p].inventory() for p in (0, 1)}  # r1 dead
    d = ps._decide(surviving, disk_best=(0, "cold://seed", "durable"))
    assert d["source"] == "disk" and d["tier"] == "durable"


def test_rack_kill_domain_spread_restores_from_memory():
    """Same kill, domain-spread placement: every replica lives outside
    its owner's rack, so the survivors still cover all four owners and
    the restore stays at the memory tier (no durable round-trip)."""
    stores = _exchanged_stores(domains=_RACKS)
    surviving = {p: stores[p].inventory() for p in (0, 1)}
    d = ps._decide(surviving, disk_best=(0, "cold://seed", "durable"))
    assert d["source"] == "memory" and d["step"] == 8
    held = set()
    for p in (0, 1):
        held.update(stores[p].inventory())
    assert held == {0, 1, 2, 3}


def test_exchange_collective_spreads_replicas_across_domains():
    """The real collective over the in-process coordination service:
    four workers exchange one snapshot step with the domain map and
    each store ends up holding exactly the assignment's replicas."""
    import threading

    from distributed_tensorflow_tpu.cluster import coordination
    from distributed_tensorflow_tpu.testing import day_sim

    service = coordination._LocalService()
    agents = [day_sim._PeerAgent(service, p, 4) for p in range(4)]
    stores = {p: ps.SnapshotStore(None, keep=2) for p in range(4)}
    oks = {}

    def worker(pid):
        oks[pid] = ps.exchange(stores[pid], _snap(owner=pid, step=3,
                                                  world=4),
                               agents[pid], timeout_s=10.0,
                               domains=_RACKS)

    threads = [threading.Thread(target=worker, args=(p,), daemon=True)
               for p in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert all(oks.get(p) for p in range(4)), oks
    # exchange stores the REPLICAS this pid was assigned (the caller
    # puts its own capture in the store separately)
    assign = ps.assign_replicators(4, _RACKS)
    for pid in range(4):
        want = {o for o, r in assign.items() if r == pid}
        assert set(stores[pid].inventory()) == want
        for owner in want:
            assert _RACKS[owner] != _RACKS[pid]
