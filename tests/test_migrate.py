"""KV-block migration: the primitive behind disaggregated serving.

The load-bearing contract (ISSUE 16): greedy outputs are byte-identical
disaggregated vs monolithic — for every ``kv_dtype``, through the real
pack/unpack wire format, under preemption + rescue, and on meshes —
because a migration ships raw pool block rows (quantisation scales
included) and the generated tokens travel as LIVE state, so the adopter
replays nothing. Plus the satellites that ride on the same primitive:
torn publishes are never adoptable (chunk COUNT commits last),
host-tier cache spill round-trips bit-exactly behind the pool-epoch
fence, admission deferrals split by cause, and the ``kv_migrate``
badput bucket prices handoffs without breaking the ledger identity.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.models.transformer import (
    TransformerConfig, TransformerLM)
from distributed_tensorflow_tpu.serving import (
    BlockAllocator, DisaggregatedEngine, FileKV, HostTier,
    InferenceEngine, OutOfBlocksError, Request, fetch_payload,
    pack_payload, publish_payload, unpack_payload)
from distributed_tensorflow_tpu.serving.kv_cache import PrefixCache
from distributed_tensorflow_tpu.serving.migrate import payload_committed
from distributed_tensorflow_tpu.telemetry import goodput


# ---------------------------------------------------------------------------
# shared tiny model
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig.tiny(max_seq_len=64)
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, params


def reference_greedy(cfg, params, prompt, n):
    """Argmax rollout via FULL-sequence recompute each step."""
    model = TransformerLM(cfg)
    t = list(prompt)
    for _ in range(n):
        logits = model.apply({"params": params}, jnp.asarray([t]))
        t.append(int(jnp.argmax(logits[0, len(t) - 1])))
    return t[len(prompt):]


PROMPTS = [[1, 2, 3], [4, 5, 6, 7], [9, 8], [3, 1, 4, 1, 5]]

# one shape family shared with test_serving — the persistent compile
# cache then amortizes every engine in this module
ENGINE_KW = dict(num_blocks=32, block_size=8, max_slots=4,
                 max_prompt_len=16)

KV_DTYPES = ("f32", "bf16", "int8")


def _prefill_one(engine, tokens, rid="x", max_new=8, steps=1):
    engine.submit(Request(id=rid, tokens=tuple(tokens),
                          max_new_tokens=max_new))
    for _ in range(steps):
        engine.step()
    seq = next(s for s in engine.scheduler.running.values()
               if s.request.id == rid)
    assert seq.prefilled and not seq.done
    return seq


def _assert_clean(engine):
    acct = engine.block_accounting()
    assert acct["leaked_refs"] == 0 and acct["conserved"]


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------

class TestWireFormat:
    @pytest.mark.parametrize("dt", KV_DTYPES)
    def test_pack_unpack_bit_exact(self, tiny, dt):
        """Every pool dtype round-trips the blob bit-exactly —
        bfloat16 included, because array bytes are never reinterpreted
        through a lossy dtype; int8 payloads carry their scales."""
        cfg, params = tiny
        eng = InferenceEngine(cfg, params, kv_dtype=dt, **ENGINE_KW)
        seq = _prefill_one(eng, [3, 1, 4, 1, 5, 9, 2, 6], steps=3)
        payload = eng.export_sequence(seq)
        if dt == "int8":
            assert "k_scale" in payload.arrays
        back = unpack_payload(pack_payload(payload))
        assert back.request_id == payload.request_id
        assert back.tokens == payload.tokens
        assert back.generated == payload.generated
        assert back.length == payload.length
        assert back.fingerprint == payload.fingerprint
        assert back.pool_epoch == payload.pool_epoch
        assert set(back.arrays) == set(payload.arrays)
        for n, a in payload.arrays.items():
            b = back.arrays[n]
            assert b.dtype == a.dtype and b.shape == a.shape
            assert b.tobytes() == a.tobytes()

    def test_trailing_bytes_rejected(self, tiny):
        cfg, params = tiny
        eng = InferenceEngine(cfg, params, **ENGINE_KW)
        seq = _prefill_one(eng, [1, 2, 3, 4])
        blob = pack_payload(eng.export_sequence(seq))
        with pytest.raises(ValueError, match="trailing"):
            unpack_payload(blob + b"\x00")

    def test_torn_publish_never_adoptable(self, tiny, tmp_path):
        """A publisher SIGKILLed mid-migration leaves chunks but no
        count key: the blob is not committed, a fetch times out, and
        once the full publish lands it round-trips."""
        cfg, params = tiny
        agent = FileKV(str(tmp_path))
        # torn publish: a chunk landed, the count key did not
        agent.key_value_set("mig/r1/c0", b"half a payload")
        assert not payload_committed(agent, "mig/r1")
        with pytest.raises(TimeoutError):
            fetch_payload(agent, "mig/r1", timeout_s=0.05)
        eng = InferenceEngine(cfg, params, **ENGINE_KW)
        seq = _prefill_one(eng, [5, 3, 1, 2])
        payload = eng.export_sequence(seq)
        publish_payload(agent, "mig/r1", payload)
        assert payload_committed(agent, "mig/r1")
        fetched = fetch_payload(agent, "mig/r1", timeout_s=1.0)
        assert fetched.arrays["k"].tobytes() == \
            payload.arrays["k"].tobytes()


# ---------------------------------------------------------------------------
# disaggregated vs monolithic parity
# ---------------------------------------------------------------------------

class TestDisaggregatedParity:
    @pytest.mark.parametrize("dt", KV_DTYPES)
    def test_matches_monolithic_greedy(self, tiny, dt):
        """Placement never changes argmax: the disaggregated engine's
        greedy outputs equal the monolithic engine's per kv_dtype,
        with every hop through the real wire format."""
        cfg, params = tiny
        mono = InferenceEngine(cfg, params, kv_dtype=dt, **ENGINE_KW)
        want = mono.generate(PROMPTS, max_new_tokens=6)
        dis = DisaggregatedEngine(cfg, params, num_decode=2, wire=True,
                                  kv_dtype=dt, **ENGINE_KW)
        got = dis.generate(PROMPTS, max_new_tokens=6)
        assert got == want
        if dt == "f32":
            for p, o in zip(PROMPTS, got):
                assert o == reference_greedy(cfg, params, p, 6)
        st = dis.stats()
        assert st["migrations"] == len(dis.migrations) > 0
        assert st["migrated_bytes"] > 0
        assert 0 < st["migrate_p50_ms"] <= st["migrate_p99_ms"]
        acct = dis.block_accounting()
        assert acct["leaked_refs"] == 0 and acct["conserved"]
        for eng in [dis.prefill] + dis.decoders:
            assert (eng.scheduler.allocator.num_free
                    == eng.cache_cfg.usable_blocks)

    def test_parity_under_preemption_and_rescue(self, tiny):
        """Pools too small for the concurrency force preemption on the
        decode replicas; the rescue hook migrates victims to siblings
        when one has room, the replay path runs otherwise — outputs
        stay exactly the no-pressure greedy either way."""
        cfg, params = tiny
        prompts = [[7, 7, 7], [8, 8, 8, 8], [9, 9], [1, 2, 3]]
        dis = DisaggregatedEngine(cfg, params, num_decode=2, wire=True,
                                  rescue=True, num_blocks=6,
                                  block_size=4, max_slots=4,
                                  max_prompt_len=16)
        outs = dis.generate(prompts, max_new_tokens=8)
        for p, o in zip(prompts, outs):
            assert o == reference_greedy(cfg, params, p, 8)
        assert dis.stats()["migrations_rescue"] == sum(
            e.scheduler.migrated_out for e in dis.decoders)
        acct = dis.block_accounting()
        assert acct["leaked_refs"] == 0 and acct["conserved"]

    def test_matches_recompute_dp_tp_mesh(self, tiny, mesh2d):
        """Same parity on a dp=4 × tp=2 mesh — migration gathers and
        scatters through sharded pools."""
        cfg, params = tiny
        dis = DisaggregatedEngine(cfg, params, mesh=mesh2d,
                                  num_decode=1, wire=True,
                                  num_blocks=32, block_size=8,
                                  max_slots=8, max_prompt_len=16)
        outs = dis.generate(PROMPTS, max_new_tokens=4)
        for p, o in zip(PROMPTS, outs):
            assert o == reference_greedy(cfg, params, p, 4)
        assert dis.stats()["migrations"] > 0


# ---------------------------------------------------------------------------
# drain handoff: export / adopt between independent engines
# ---------------------------------------------------------------------------

class TestExportAdopt:
    def test_export_releases_source_adopt_continues(self, tiny,
                                                    tmp_path):
        """Drain-by-migration: the source exports a live sequence
        (slot + blocks released at export), the blob travels through
        FileKV's chunked write-once transport, and the adopter finishes
        the request with ZERO replayed tokens — the completion equals
        the monolithic run byte for byte."""
        cfg, params = tiny
        prompt = [2, 7, 1, 8, 2, 8]
        a = InferenceEngine(cfg, params, **ENGINE_KW)
        b = InferenceEngine(cfg, params, **ENGINE_KW)
        seq = _prefill_one(a, prompt, rid="d0", max_new=8, steps=3)
        already = len(seq.generated)
        assert 0 < already < 8
        payload = a.export_sequence(seq, reason="drain")
        # source-side release happened at export
        assert not a.scheduler.running
        assert (a.scheduler.allocator.num_free
                == a.cache_cfg.usable_blocks)
        _assert_clean(a)
        assert a.migrations_out == 1
        agent = FileKV(str(tmp_path))
        publish_payload(agent, "drain/d0", payload)
        fetched = fetch_payload(agent, "drain/d0", timeout_s=1.0)
        assert b.can_adopt(fetched)
        b.adopt_sequence(fetched)
        assert b.migrations_in == 1
        done = b.run_until_idle()
        rec = done["d0"]
        assert rec["tokens"] == reference_greedy(cfg, params, prompt, 8)
        assert rec["replayed_tokens"] == 0
        _assert_clean(b)

    def test_adopt_rejects_pool_fingerprint_mismatch(self, tiny):
        """An incompatible pool (different storage dtype) must never
        serve migrated rows — adoption raises and leaks nothing."""
        cfg, params = tiny
        a = InferenceEngine(cfg, params, kv_dtype="f32", **ENGINE_KW)
        b = InferenceEngine(cfg, params, kv_dtype="int8", **ENGINE_KW)
        seq = _prefill_one(a, [1, 2, 3, 4])
        payload = a.export_sequence(seq)
        free_before = b.scheduler.allocator.num_free
        slots_before = len(b.scheduler._free_slots)
        with pytest.raises(ValueError, match="fingerprint"):
            b.adopt_sequence(payload)
        assert b.scheduler.allocator.num_free == free_before
        assert len(b.scheduler._free_slots) == slots_before
        _assert_clean(b)

    def test_can_adopt_probes_capacity_and_full_adopt_raises(self,
                                                             tiny):
        """can_adopt is the source's pre-ship check; a forced adopt
        into a slot-exhausted engine raises OutOfBlocksError and frees
        the blocks it allocated — nothing leaks, the busy engine keeps
        serving."""
        cfg, params = tiny
        a = InferenceEngine(cfg, params, **ENGINE_KW)
        b = InferenceEngine(cfg, params, **ENGINE_KW)
        seq = _prefill_one(a, [6, 1, 6, 1])
        payload = a.export_sequence(seq)
        for i, p in enumerate(PROMPTS):   # fill all 4 of b's slots
            b.submit(Request(id=f"f{i}", tokens=tuple(p),
                             max_new_tokens=6))
        b.step()
        assert not b.scheduler._free_slots
        assert not b.can_adopt(payload)
        free_before = b.scheduler.allocator.num_free
        with pytest.raises(OutOfBlocksError):
            b.adopt_sequence(payload)
        assert b.scheduler.allocator.num_free == free_before
        done = b.run_until_idle()
        for i, p in enumerate(PROMPTS):
            assert done[f"f{i}"]["tokens"] == \
                reference_greedy(cfg, params, p, 6)
        assert b.can_adopt(payload)
        _assert_clean(b)


# ---------------------------------------------------------------------------
# host-tier cache spill
# ---------------------------------------------------------------------------

class TestHostTierSpill:
    @pytest.mark.parametrize("dt", KV_DTYPES)
    def test_spill_readopt_bit_exact(self, tiny, dt):
        """An evicted prefix-cache block spills to host RAM and comes
        back into a FRESH pool block bit-exactly on the next chain
        walk — for every pool dtype, scales included."""
        cfg, params = tiny
        tier = HostTier(capacity_blocks=8)
        eng = InferenceEngine(cfg, params, kv_dtype=dt,
                              prefix_caching=True, spill_tier=tier,
                              num_blocks=16, block_size=4,
                              max_slots=4, max_prompt_len=16)
        bs = eng.cache_cfg.block_size
        # 13 tokens = 3 full blocks; the chain walk re-adopts full
        # blocks only, so every entry must sit at n + bs <= len - 1
        prompt = [5, 3, 1, 2, 6, 4, 2, 7, 9, 9, 1, 3, 5]
        first = eng.generate([prompt], max_new_tokens=4)
        pc = eng.scheduler.prefix_cache
        assert len(pc) == len(prompt) // bs > 0

        def block_bytes(block):
            rows = jnp.arange(block * bs, (block + 1) * bs,
                              dtype=jnp.int32)
            g = eng._gather(eng.pool, rows)
            return {n: np.asarray(jax.device_get(a)).tobytes()
                    for n, a in g.items()}

        before = {e.key: block_bytes(e.block)
                  for e in pc._entries.values()}
        assert pc.evict(len(pc)) == len(before)
        assert len(pc) == 0 and len(tier) == len(before)
        assert tier.spilled == len(before)
        # same prompt again: the chain walk re-adopts every block
        second = eng.generate([prompt], max_new_tokens=4)
        assert second == first
        assert pc.spill_hits == len(before)
        assert tier.readopted == len(before) and len(tier) == 0
        for key, want in before.items():
            got = block_bytes(pc._entries[key].block)
            assert got == want
        if dt == "f32":
            assert first[0] == reference_greedy(cfg, params, prompt, 4)
        _assert_clean(eng)

    def test_lru_never_spills_shared_block(self):
        """Eviction (and therefore spill) only touches cache-private
        blocks: a block any sequence still references — or an interior
        block a longer cached chain hangs off — stays on device."""
        alloc = BlockAllocator(8)
        pc = PrefixCache(alloc, block_size=2)
        tier = HostTier(capacity_blocks=4)
        inserted = []
        pc.attach_spill(tier,
                        extract=lambda b: {"k": np.zeros(1)},
                        insert=lambda b, a: inserted.append(b),
                        epoch="E0")
        blocks = alloc.alloc(2)
        pc.register((1, 2, 3, 4), blocks)
        alloc.free(blocks)                 # the sequence released its refs
        leaf = next(e.block for e in pc._entries.values()
                    if not pc._children.get(e.key))
        alloc.incref(leaf)                 # a running sequence shares it
        assert pc.evict(10) == 0           # leaf shared, parent interior
        assert len(pc) == 2 and len(tier) == 0 and tier.spilled == 0
        alloc.free([leaf])                 # the sequence finished
        assert pc.evict(10) == 2           # now both spill, leaf first
        assert len(tier) == 2 and tier.spilled == 2
        assert not inserted                # spill never wrote the pool

    def test_stale_epoch_readopt_rejected(self):
        """A spill from a previous engine incarnation (pool-epoch
        mismatch) is dropped at re-adoption, never served — the cache
        falls back to prefill recompute."""
        alloc = BlockAllocator(8)
        pc = PrefixCache(alloc, block_size=2)
        tier = HostTier(capacity_blocks=4)
        inserted = []
        pc.attach_spill(tier,
                        extract=lambda b: {"k": np.zeros(1)},
                        insert=lambda b, a: inserted.append(b),
                        epoch="gen1")
        tier.put((None, (1, 2)), None, (1, 2), {"k": np.zeros(1)},
                 epoch="gen0")             # spilled before the restart
        n, blocks = pc.match((1, 2, 9))
        assert n == 0 and blocks == []
        assert pc.spill_rejects == 1 and tier.rejected == 1
        assert len(tier) == 0              # stale entry dropped, not kept
        assert not inserted


# ---------------------------------------------------------------------------
# admission deferral split by cause
# ---------------------------------------------------------------------------

class TestDeferralSplit:
    def test_prefill_budget_deferral(self, tiny):
        """Two prompts whose combined prefill exceeds the step token
        budget: the second defers as deferred_prefill (the
        interference disaggregation removes), not deferred_blocks."""
        cfg, params = tiny
        eng = InferenceEngine(cfg, params, token_budget=16, **ENGINE_KW)
        prompts = [[1] * 10, [2] * 10]
        for i, p in enumerate(prompts):
            eng.submit(Request(id=f"r{i}", tokens=tuple(p),
                               max_new_tokens=4))
        eng.step()
        sched = eng.scheduler
        assert sched.deferred_prefill == 1
        assert sched.deferred_blocks == 0
        done = eng.run_until_idle()
        for i, p in enumerate(prompts):
            assert done[f"r{i}"]["tokens"] == \
                reference_greedy(cfg, params, p, 4)
        st = eng.stats()
        assert st["deferred_prefill"] == 1
        assert st["deferred_blocks"] == 0

    def test_pool_exhaustion_deferral(self, tiny):
        """Two prompts whose blocks exceed the free pool: the second
        defers as deferred_blocks (capacity — disaggregation does NOT
        fix this), not deferred_prefill."""
        cfg, params = tiny
        eng = InferenceEngine(cfg, params, num_blocks=6, block_size=4,
                              max_slots=4, max_prompt_len=16)
        prompts = [[1] * 8, [2] * 8]       # 3 blocks each, 5 usable
        for i, p in enumerate(prompts):
            eng.submit(Request(id=f"r{i}", tokens=tuple(p),
                               max_new_tokens=3))
        eng.step()
        sched = eng.scheduler
        assert sched.deferred_blocks >= 1
        assert sched.deferred_prefill == 0
        done = eng.run_until_idle()
        for i, p in enumerate(prompts):
            assert done[f"r{i}"]["tokens"] == \
                reference_greedy(cfg, params, p, 3)


# ---------------------------------------------------------------------------
# kv_migrate badput pricing
# ---------------------------------------------------------------------------

def _ev(name, wall, **kw):
    return {"ev": name, "wall": wall, "pid": 0, **kw}


class TestMigrateGoodput:
    def test_event_ledger_prices_kv_migrate(self):
        """kv.migrate spans land in the kv_migrate bucket, advance the
        cursor (never double-counted against serve time), and the
        identity wall == goodput + Σ badput stays exact."""
        events = {0: [
            _ev("serve.step", 100.0, dur_s=0.5),
            _ev("kv.migrate", 100.8, dur_s=0.2),     # idle 0.6 before
            _ev("serve.step", 101.3, dur_s=0.5),
        ]}
        led = goodput.ledger_from_events(events)
        b = led["badput_s"]
        assert abs(led["wall_s"] - 1.8) < 1e-9       # opens 100.0 - 0.5
        assert abs(b["kv_migrate"] - 0.2) < 1e-9
        assert abs(b["idle"] - 0.6) < 1e-9
        assert abs(led["goodput_s"] - 1.0) < 1e-9
        assert abs(led["identity_error_s"]) < 1e-9

    def test_event_ledger_clips_migration_overlapping_step(self):
        """A migration claiming time already attributed to the step it
        nests inside is clipped to the uncovered interval — lying
        durations cannot break the identity."""
        events = {0: [
            _ev("serve.step", 100.0, dur_s=0.5),
            _ev("kv.migrate", 100.1, dur_s=5.0),     # claims > gap
        ]}
        led = goodput.ledger_from_events(events)
        assert abs(led["badput_s"]["kv_migrate"] - 0.1) < 1e-9
        assert abs(led["identity_error_s"]) < 1e-9

    def test_live_ledger_records_migration(self, tiny):
        """export/adopt feed the ACTIVE GoodputLedger: a disaggregated
        run prices its handoffs in kv_migrate and the snapshot identity
        holds."""
        cfg, params = tiny
        led = goodput.GoodputLedger(register=False)
        prev = goodput.activate(led)
        try:
            dis = DisaggregatedEngine(cfg, params, num_decode=1,
                                      **ENGINE_KW)
            dis.generate(PROMPTS[:2], max_new_tokens=4)
        finally:
            goodput.activate(prev)
        snap = led.snapshot()
        assert snap["badput_s"]["kv_migrate"] > 0.0
        total = snap["goodput_s"] + sum(snap["badput_s"].values())
        assert abs(snap["wall_s"] - total) <= 0.01 * snap["wall_s"]
