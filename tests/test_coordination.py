"""Coordination-service failure paths (single-process).

tests/test_multi_process.py covers the cross-process happy paths plus a
real barrier timeout; these exercise the error surfaces — timeout, peer
error, exception hierarchy, directory-delete semantics — against the
in-process fallback, with fault injection standing in for the failures
only a distributed run could produce organically (ISSUE 2 satellite:
barrier-timeout and peer-error propagation coverage)."""

import threading
import time

import pytest

from distributed_tensorflow_tpu.cluster import coordination
from distributed_tensorflow_tpu.cluster.coordination import (
    BarrierTimeoutError,
    CoordinationError,
    CoordinationServiceAgent,
)
from distributed_tensorflow_tpu.resilience import (
    FaultRule,
    FaultSchedule,
    RetryPolicy,
    faults,
)


@pytest.fixture()
def agent():
    """Isolated local KV service per test."""
    old = coordination._LOCAL
    coordination._LOCAL = coordination._LocalService()
    a = CoordinationServiceAgent()
    a._local = coordination._LOCAL
    yield a
    coordination._LOCAL = old


def test_kv_get_timeout_raises_coordination_error(agent):
    t0 = time.monotonic()
    with pytest.raises(CoordinationError, match="timed out"):
        agent.key_value_get("never-set", timeout_s=0.2)
    assert time.monotonic() - t0 < 5.0


def test_kv_get_wakes_on_concurrent_set(agent):
    def setter():
        time.sleep(0.1)
        agent.key_value_set("late", "v")

    t = threading.Thread(target=setter)
    t.start()
    assert agent.key_value_get("late", timeout_s=10) == b"v"
    t.join()


def test_kv_set_no_overwrite_conflict(agent):
    agent.key_value_set("k", "a", allow_overwrite=False)
    with pytest.raises(CoordinationError, match="already exists"):
        agent.key_value_set("k", "b", allow_overwrite=False)


def test_kv_delete_is_directory_style(agent):
    agent.key_value_set("d", "root")
    agent.key_value_set("d/x", "1")
    agent.key_value_set("d/y/z", "2")
    agent.key_value_set("dz", "survives")     # prefix-sibling, not child
    agent.key_value_delete("d")
    assert agent.key_value_try_get("d") is None
    assert agent.key_value_try_get("d/x") is None
    assert agent.key_value_try_get("d/y/z") is None
    assert agent.key_value_try_get("dz") == b"survives"


def test_kv_increment_and_dir_get_sorted(agent):
    assert agent.key_value_increment("n") == 1
    assert agent.key_value_increment("n", 4) == 5
    agent.key_value_set("p/b", "2")
    agent.key_value_set("p/a", "1")
    assert agent.key_value_dir_get("p/") == [("p/a", b"1"), ("p/b", b"2")]


def test_barrier_timeout_is_coordination_error():
    """The propagation contract: code catching CoordinationError (peer
    death handling, e.g. the killed-worker survivors path in
    test_multi_process.py) must also see barrier timeouts."""
    assert issubclass(BarrierTimeoutError, CoordinationError)


def test_injected_barrier_timeout_propagates(agent):
    sched = FaultSchedule(rules=[
        FaultRule(site="coord.barrier", tag="meet", hits=(1,))])
    with faults.inject(sched):
        with pytest.raises(BarrierTimeoutError, match="injected"):
            agent.barrier("meet", timeout_s=5)
        agent.barrier("other", timeout_s=5)   # untargeted barrier passes
        agent.barrier("meet", timeout_s=5)    # second hit passes


def test_injected_peer_error_on_kv_get(agent):
    """A service-side failure (dead peer, teardown) surfaces as
    CoordinationError from key_value_get — the class every caller
    (RemoteLane.wait, preemption sync) keys its handling on."""
    agent.key_value_set("k", "v")
    sched = FaultSchedule(rules=[
        FaultRule(site="coord.kv_get", tag="k", hits=(1,))])
    with faults.inject(sched):
        with pytest.raises(CoordinationError, match="injected"):
            agent.key_value_get("k", timeout_s=5)
        # try_get is NOT instrumented: liveness polling stays fault-free
        assert agent.key_value_try_get("k") == b"v"
    assert agent.key_value_get("k", timeout_s=5) == b"v"


def test_barrier_retry_under_policy(agent):
    """A transient barrier timeout retried by the shared RetryPolicy —
    the composition the chaos suite leans on."""
    sched = FaultSchedule(rules=[
        FaultRule(site="coord.barrier", tag="flaky", hits=(1,))])
    attempts = []
    policy = RetryPolicy(max_attempts=3, retryable=(BarrierTimeoutError,))
    with faults.inject(sched) as reg:
        policy.call(lambda: (attempts.append(1),
                             agent.barrier("flaky", timeout_s=5)))
        assert len(attempts) == 2
        assert [e[3] for e in reg.events()] == ["raise"]


# -- elastic generation namespacing (ISSUE 5 tentpole) -----------------------

def test_generation_namespaces_kv_and_barriers(agent):
    """Every KV key/barrier is namespaced by the elastic cluster
    generation: a reformed cluster (gen N) cannot see a dead
    incarnation's keys, and generation 0 is byte-identical to the
    historical unprefixed layout."""
    from distributed_tensorflow_tpu.cluster import elastic

    try:
        assert elastic.namespace("job/x") == "job/x"      # gen 0: raw
        agent.key_value_set("job/x", "old-gen")
        elastic.set_generation(3)
        assert elastic.namespace("job/x") == "gen3/job/x"
        # the old generation's value is invisible from gen 3...
        assert agent.key_value_try_get("job/x") is None
        agent.key_value_set("job/x", "new-gen")
        assert agent.key_value_get("job/x", timeout_s=5) == b"new-gen"
        assert agent.key_value_increment("job/ctr") == 1
        agent.barrier("meet", timeout_s=5)
        # ...and the raw store really holds both namespaces side by side
        assert agent._local.try_get("job/x") == b"old-gen"
        assert agent._local.try_get("gen3/job/x") == b"new-gen"
        # deletes stay inside the generation
        agent.key_value_delete("job/x")
        assert agent._local.try_get("job/x") == b"old-gen"
    finally:
        elastic.set_generation(None)
    assert agent.key_value_try_get("job/x") == b"old-gen"


def test_generation_from_environment(monkeypatch):
    from distributed_tensorflow_tpu.cluster import elastic

    monkeypatch.delenv(elastic.ENV_GENERATION, raising=False)
    assert elastic.generation() == 0
    monkeypatch.setenv(elastic.ENV_GENERATION, "7")
    assert elastic.generation() == 7
    assert elastic.namespace("a/b") == "gen7/a/b"
    monkeypatch.setenv(elastic.ENV_GENERATION, "bogus")
    assert elastic.generation() == 0                      # defensive
    elastic.set_generation(2)                             # explicit wins
    try:
        assert elastic.generation() == 2
    finally:
        elastic.set_generation(None)


def test_generation_override_is_thread_local():
    from distributed_tensorflow_tpu.cluster import elastic

    seen = {}

    def worker(gen):
        with elastic.generation_override(gen):
            time.sleep(0.02)               # overlap the two overrides
            seen[gen] = elastic.namespace("k")

    ts = [threading.Thread(target=worker, args=(g,)) for g in (0, 3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert seen == {0: "k", 3: "gen3/k"}
    assert elastic.namespace("k") == "k"   # override fully unwound


class _FakeLegacyClient:
    """A jaxlib<0.5 DistributedRuntimeClient double: string get +
    write-once set only, no try_get/increment, counts every RPC."""

    def __init__(self):
        self.kv: dict[str, bytes] = {}
        self.rpcs = 0

    def blocking_key_value_get(self, key, wait_ms):
        self.rpcs += 1
        if key not in self.kv:
            raise RuntimeError("DEADLINE_EXCEEDED")
        return self.kv[key].decode()

    def blocking_key_value_get_bytes(self, key, wait_ms):
        self.rpcs += 1
        if key not in self.kv:
            raise RuntimeError("DEADLINE_EXCEEDED")
        return self.kv[key]

    def key_value_set_bytes(self, key, data, allow_overwrite=True):
        self.rpcs += 1
        if not allow_overwrite and key in self.kv:
            raise RuntimeError(f"ALREADY_EXISTS: {key}")
        self.kv[key] = data


def test_legacy_increment_cold_start_seeds_probe_hint(monkeypatch):
    """ISSUE 11: the slot-ladder increment fallback must not probe the
    whole ladder on cold start — the p-th process seeding its hint from
    the published value key pays O(1) RPCs, not O(p) (O(N^2) total
    across a fleet)."""
    fake = _FakeLegacyClient()
    # 200 increments already claimed by earlier processes
    for i in range(1, 201):
        fake.kv[f"ctr/__c__/{i}"] = b"1"
    fake.kv["ctr"] = b"200"

    agent = CoordinationServiceAgent()
    monkeypatch.setattr(type(agent), "_client", property(lambda s: fake))
    assert agent._is_legacy(fake)
    fake.rpcs = 0
    assert agent.key_value_increment("ctr") == 201
    # 1 hint read + 1 successful claim + 1 value publish — NOT ~200 probes
    assert fake.rpcs <= 4, fake.rpcs
    # warm path: the hint advances, still O(1)
    fake.rpcs = 0
    assert agent.key_value_increment("ctr") == 202
    assert fake.rpcs <= 3, fake.rpcs


def test_legacy_increment_republishes_over_stale_value(monkeypatch):
    """Lost-update hardening: the best-effort value-key publish can be
    overwritten by a SLOWER peer's smaller value landing late (the
    2-process barrier/increment flake). The verify-read after our
    publish must detect the stale smaller value and re-assert ours."""
    fake = _FakeLegacyClient()
    # one increment already claimed by a slow peer that has not
    # finished publishing
    fake.kv["ctr/__c__/1"] = b"1"
    fake.kv["ctr"] = b"1"
    reads = {"ctr": 0}
    orig_get = fake.blocking_key_value_get

    def get(key, wait_ms):
        if key == "ctr":
            reads["ctr"] += 1
            if reads["ctr"] == 2:
                # the verify read races the slow peer's stale publish:
                # its value-1 write lands right before we look
                fake.kv["ctr"] = b"1"
        return orig_get(key, wait_ms)

    fake.blocking_key_value_get = get
    agent = CoordinationServiceAgent()
    monkeypatch.setattr(type(agent), "_client", property(lambda s: fake))
    assert agent._is_legacy(fake)
    assert agent.key_value_increment("ctr") == 2
    # the stale 1 was overwritten by the re-publish
    assert fake.kv["ctr"] == b"2"
