"""Parity tests for input/image_ops.py against the installed tf.image
(≙ TF/python/ops/image_ops_impl.py) — the contract behind the real-JPEG
ResNet path: geometry ops bit-exact, resize at float32 round-off, JPEG
decode toleranced (PIL and TF may use different IDCT implementations),
and stateless augmentation deterministic at any parallelism."""

import os

import numpy as np
import pytest

from distributed_tensorflow_tpu.input import image_ops

tf = pytest.importorskip("tensorflow")


@pytest.fixture(scope="module")
def img():
    rng = np.random.default_rng(7)
    # structured + noise: JPEG-realistic content, odd sizes on purpose
    yy, xx = np.mgrid[0:61, 0:47].astype(np.float32)
    base = np.sin(xx / 9.0) + np.cos(yy / 7.0)
    arr = np.stack([base * (c + 1) for c in range(3)], -1)
    arr = arr + rng.normal(0, 0.2, arr.shape)
    return ((arr - arr.min()) / (np.ptp(arr) + 1e-6) * 255).astype(
        np.uint8)


def test_jpeg_roundtrip_and_decode_parity_vs_tf(img):
    data = image_ops.encode_jpeg(img, quality=92)
    ours = image_ops.decode_jpeg(data)
    assert ours.shape == img.shape and ours.dtype == np.uint8
    # lossy codec, structured content: close to the original...
    assert np.mean(np.abs(ours.astype(int) - img.astype(int))) < 6.0
    # ...and within a few counts of TF's decoder on the same bytes
    theirs = tf.io.decode_jpeg(data, channels=3).numpy()
    diff = np.abs(ours.astype(int) - theirs.astype(int))
    assert diff.mean() < 2.0 and np.percentile(diff, 99) <= 8


def test_flip_crop_central_crop_bit_exact_vs_tf(img):
    np.testing.assert_array_equal(
        image_ops.flip_left_right(img),
        tf.image.flip_left_right(img).numpy())
    np.testing.assert_array_equal(
        image_ops.crop_to_bounding_box(img, 3, 5, 40, 30),
        tf.image.crop_to_bounding_box(img, 3, 5, 40, 30).numpy())
    for frac in (0.5, 0.7, 0.875, 1.0):
        a = image_ops.central_crop(img, frac)
        b = tf.image.central_crop(img, frac).numpy()
        assert a.shape == b.shape
        np.testing.assert_array_equal(a, b)
    with pytest.raises(ValueError):
        image_ops.crop_to_bounding_box(img, 0, 0, 100, 100)


def test_resize_bilinear_parity_vs_tf(img):
    for hw in ((17, 29), (80, 100), (61, 47), (21, 90)):
        ours = image_ops.resize_bilinear(img, *hw)
        theirs = tf.image.resize(img, hw, method="bilinear").numpy()
        assert ours.dtype == np.float32
        np.testing.assert_allclose(ours, theirs, atol=1e-3)


def test_rescaling_matches_tf_keras(img):
    tf_keras = pytest.importorskip("tf_keras")
    layer = image_ops.Rescaling(1.0 / 127.5, offset=-1.0)
    ref = tf_keras.layers.Rescaling(1.0 / 127.5, offset=-1.0)
    np.testing.assert_allclose(
        layer(img), ref(img[None].astype("float32")).numpy()[0],
        rtol=1e-6, atol=1e-6)


def test_stateless_random_ops_deterministic_and_valid(img):
    flip = image_ops.RandomFlip(seed=3)
    crop = image_ops.RandomCrop(32, 24, seed=3)
    for es in (0, 1, 7, 12345):
        np.testing.assert_array_equal(flip(img, seed=es),
                                      flip(img, seed=es))
        np.testing.assert_array_equal(crop(img, seed=es),
                                      crop(img, seed=es))
        out = flip(img, seed=es)
        assert (np.array_equal(out, img)
                or np.array_equal(out, image_ops.flip_left_right(img)))
        c = crop(img, seed=es)
        assert c.shape == (32, 24, 3)
    # both branches of the coin occur over many element seeds
    flips = [not np.array_equal(flip(img, seed=s), img)
             for s in range(40)]
    assert any(flips) and not all(flips)
    # undersized input: upsized then cropped, never an error
    small = img[:16, :16]
    assert crop(small, seed=0).shape == (32, 24, 3)


def test_generate_decode_pipeline_elements(tmp_path):
    files = image_ops.generate_jpeg_directory(
        str(tmp_path), 6, image_size=40, num_classes=4, seed=1)
    assert len(files) == 6 and all(os.path.exists(f) for f in files)
    labels = [image_ops.label_from_path(f) for f in files]
    assert all(0 <= l < 4 for l in labels)
    fn = image_ops.make_decode_fn(32, seed=0)
    el = fn(files[0])
    assert el["image"].shape == (32, 32, 3)
    assert el["image"].dtype == np.float32
    assert 0.0 <= el["image"].min() and el["image"].max() <= 1.0
    assert el["label"] == labels[0]
    # stateless: the same path decodes identically every time (the
    # parallel-map determinism contract for augmented elements)
    np.testing.assert_array_equal(el["image"], fn(files[0])["image"])


def test_jpeg_pipeline_order_matches_serial(tmp_path):
    from distributed_tensorflow_tpu.input.dataset import AUTOTUNE

    files = image_ops.generate_jpeg_directory(
        str(tmp_path), 8, image_size=40, num_classes=4, seed=2)
    kw = dict(batch_size=4, image_size=32, repeat=False, seed=5)
    serial = list(image_ops.jpeg_pipeline(files, num_parallel_calls=None,
                                          prefetch_depth=0, **kw))
    parallel = list(image_ops.jpeg_pipeline(
        files, num_parallel_calls=AUTOTUNE, prefetch_depth=2, **kw))
    assert len(serial) == len(parallel) == 2
    for s, p in zip(serial, parallel):
        np.testing.assert_array_equal(s["image"], p["image"])
        np.testing.assert_array_equal(s["label"], p["label"])


def test_jpeg_tfrecord_pipeline_native_loader_route(tmp_path):
    """JPEGs packed as tf.train.Examples in TFRecord framing, read by
    the native C++ loader, decoded in the parallel map — the full
    native-route data plane, with parallel == serial determinism."""
    from distributed_tensorflow_tpu.input.dataset import AUTOTUNE

    files = image_ops.generate_jpeg_directory(
        str(tmp_path / "jpegs"), 8, image_size=40, num_classes=4, seed=3)
    shard = str(tmp_path / "train.tfrecord")
    n = image_ops.write_jpeg_tfrecords(shard, files)
    assert n == 8

    kw = dict(batch_size=4, image_size=32, repeat=False, seed=9)
    serial = list(image_ops.jpeg_tfrecord_pipeline(
        shard, num_parallel_calls=None, prefetch_depth=0, **kw))
    parallel = list(image_ops.jpeg_tfrecord_pipeline(
        shard, num_parallel_calls=AUTOTUNE, prefetch_depth=2, **kw))
    assert len(serial) == len(parallel) == 2
    for s, p in zip(serial, parallel):
        assert s["image"].shape == (4, 32, 32, 3)
        assert s["label"].dtype == np.int32
        np.testing.assert_array_equal(s["image"], p["image"])
        np.testing.assert_array_equal(s["label"], p["label"])
    # record order is file order when shuffle=False: labels roundtrip
    got = np.concatenate([b["label"] for b in serial])
    np.testing.assert_array_equal(
        got, [image_ops.label_from_path(f) for f in files])
