"""Multi-tenant serving frontend (serving/router.py + tenancy.py).

Layers under test, bottom up:

- the weighted max-min allocator (``fair_shares``) against hand-worked
  examples, and ``plan_tick``'s batch-subordinate split with the aged
  (anti-starvation) promotion;
- token-bucket quotas: an over-quota offer is rejected with
  ``cause="quota"`` charged to the RIGHT tenant, and refills admit
  again later;
- the routing cascade (affinity > least-loaded > seeded random) on a
  synthetic block map, including dead-replica exclusion and the
  full-block-only chain-key rule;
- dispatch flow control: stale/saturated replicas hold the queue AT
  THE ROUTER (no credit accrual, no sheds), release is priority-
  ordered, batch sheds first under budget pressure, DRR credit makes
  progress on requests costlier than one tick's budget;
- re-route damping: never to another stale replica, never past
  ``MAX_REROUTES``, never when no survivor exists;
- the decision journal: replay after a torn tail is idempotent — a
  resumed router re-offers nothing, double-routes nothing, and keeps
  routed-but-unacked work with its replica;
- per-tenant SLO partitioning (one tenant's overrun cannot fire
  another's verdict).

Everything runs on a fake clock — determinism is the point.
"""

from __future__ import annotations

import json
import os

import pytest

from distributed_tensorflow_tpu.serving.router import (
    AffinityMap,
    ROUTER_JOURNAL,
    Router,
    RouterJournal,
    RoutingPolicy,
    prefix_chain_keys,
    seeded_tenant_workload,
)
from distributed_tensorflow_tpu.serving.scheduler import Request
from distributed_tensorflow_tpu.serving.tenancy import (
    TenancyController,
    TenantConfig,
    TokenBucket,
    evaluate_tenants,
    fair_shares,
    partition_records,
)


def _req(rid, *, n_tokens=8, new=4, tenant="inter",
         pclass="interactive"):
    return Request(id=rid, tokens=tuple(range(1, n_tokens + 1)),
                   max_new_tokens=new, tenant=tenant, pclass=pclass)


def _tenants(**overrides):
    base = dict(
        inter=TenantConfig(name="inter", pclass="interactive",
                           weight=2.0, slo_latency_s=2.0),
        batch=TenantConfig(name="batch", pclass="batch", weight=1.0,
                           slo_latency_s=10.0, starvation_frac=0.5),
    )
    base.update(overrides)
    return tuple(base.values())


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _router(tmp_path=None, *, tenants=None, policy="least_loaded",
            replicas=("r0", "r1"), budget=1000, clock=None,
            **kw):
    clock = clock or FakeClock()
    calls = []
    r = Router(replicas=replicas, tenants=tenants or _tenants(),
               submit_fn=lambda rep, req, meta: calls.append(
                   (rep, req.id, meta)),
               policy=policy, block_size=4,
               tick_token_budget=budget,
               run_dir=str(tmp_path) if tmp_path else None,
               clock=clock, **kw)
    return r, calls, clock


# -- fair shares + plan_tick (hand-computed) --------------------------------

class TestFairShares:
    def test_hand_worked_example(self):
        # round 1: weights {2,1,1} split 100 as a=50 b=25 c=25;
        # a (demand 50) and c (demand 10) fit -> granted exactly,
        # surplus 40 returns; round 2: b alone, budget-bound at 40
        out = fair_shares({"a": 50, "b": 100, "c": 10},
                          {"a": 2, "b": 1, "c": 1}, 100)
        assert out == {"a": 50.0, "b": 40.0, "c": 10.0}

    def test_budget_covers_all(self):
        out = fair_shares({"a": 5, "b": 7}, {"a": 1, "b": 1}, 100)
        assert out == {"a": 5.0, "b": 7.0}

    def test_zero_budget(self):
        out = fair_shares({"a": 5}, {"a": 1}, 0)
        assert out == {"a": 0.0}

    def test_order_independent(self):
        d1 = {"a": 30, "b": 80, "c": 20}
        d2 = dict(reversed(list(d1.items())))
        w = {"a": 1, "b": 2, "c": 1}
        assert fair_shares(d1, w, 60) == fair_shares(d2, w, 60)


class TestPlanTick:
    def test_batch_subordinate(self):
        tc = TenancyController(_tenants())
        # interactive (weight 2) takes its full demand first; batch
        # divides the remainder
        alloc = tc.plan_tick({"inter": 80, "batch": 50}, budget=100)
        assert alloc["inter"] == 80.0
        assert alloc["batch"] == 20.0

    def test_aged_batch_promoted(self):
        tc = TenancyController(_tenants())
        # aged batch joins the first-pool weighted-fair split
        # (weights 2:1 over 100 -> inter 66.7, batch 33.3; batch's
        # demand 30 fits, surplus to inter)
        alloc = tc.plan_tick({"inter": 80, "batch": 30}, budget=100,
                             aged={"batch"})
        assert alloc["batch"] == 30.0
        assert alloc["inter"] == 70.0

    def test_starvation_deadline_derived(self):
        cfg = TenantConfig(name="b", pclass="batch",
                           slo_latency_s=10.0, starvation_frac=0.5)
        assert cfg.starvation_deadline_s == 5.0


# -- quotas ------------------------------------------------------------------

class TestQuota:
    def test_bucket_refills(self):
        b = TokenBucket(rate=10.0, burst=20.0, now=0.0)
        assert b.take(20, now=0.0)
        assert not b.take(1, now=0.0)
        assert b.take(10, now=1.0)          # 10 tokens refilled

    def test_offer_rejects_right_tenant_with_quota_cause(self):
        tenants = _tenants(
            burst=TenantConfig(name="burst", pclass="interactive",
                               quota_tokens_per_s=1.0, quota_burst=10.0,
                               slo_latency_s=2.0))
        r, calls, clock = _router(tenants=tenants)
        big = _req("burst-0000", n_tokens=10, new=4, tenant="burst")
        assert r.offer(big) == "rejected:quota"       # cost 14 > 10
        assert r.offer(_req("inter-0000")) == "admitted"
        c = r.tenancy.counters
        assert c["burst"]["rejected"] == {"quota": 1}
        assert c["inter"]["rejected"] == {}
        # the rejection is a DECISION: re-offering is a duplicate
        assert r.offer(big) == "duplicate"
        # refill admits the same-shaped request later
        clock.t = 10.0
        ok = _req("burst-0001", n_tokens=5, new=4, tenant="burst")
        assert r.offer(ok) == "admitted"

    def test_unknown_tenant_raises(self):
        r, _, _ = _router()
        with pytest.raises(KeyError):
            r.offer(_req("x-0000", tenant="nobody"))


# -- routing cascade ---------------------------------------------------------

class TestRoutingPolicy:
    def test_chain_keys_full_blocks_only(self):
        # 9 tokens, block 4: only tokens[:-1]=8 chain -> 2 keys; the
        # final prompt position never counts as cacheable
        toks = tuple(range(9))
        keys = prefix_chain_keys(toks, 4)
        assert len(keys) == 2
        assert prefix_chain_keys(toks[:5], 4) == keys[:1]
        # content-addressed: same tokens, same keys
        assert prefix_chain_keys(tuple(range(9)), 4) == keys

    def test_affinity_beats_load(self):
        p = RoutingPolicy(["r0", "r1"], block_size=4,
                          policy="affinity", seed=0)
        session = tuple(range(10, 19))          # 2 full blocks
        p.observe_route(session, "r0")
        p.observe_depth("r0", 99)               # r0 heavily loaded
        p.observe_depth("r1", 0)
        # affinity still wins: the KV is THERE
        assert p.route(session) == ("r0", "affinity")
        # a novel prompt falls through to least-loaded
        rep, reason = p.route(tuple(range(100, 109)))
        assert (rep, reason) == ("r1", "least_loaded")

    def test_dead_replica_excluded_and_forgotten(self):
        p = RoutingPolicy(["r0", "r1"], block_size=4,
                          policy="affinity", seed=0)
        session = tuple(range(10, 19))
        p.observe_route(session, "r0")
        rep, reason = p.route(session, exclude=("r0",))
        assert rep == "r1" and reason != "affinity"
        p.forget("r0")
        rep, reason = p.route(session)
        assert reason != "affinity"             # its cache died with it

    def test_random_ignores_depth(self):
        p = RoutingPolicy(["r0", "r1"], block_size=4, policy="random",
                          seed=3)
        p.observe_depth("r0", 99)
        reasons = {p.route((1, 2, 3, 4, 5))[1] for _ in range(8)}
        assert reasons == {"random"}

    def test_no_live_replica_raises(self):
        p = RoutingPolicy(["r0"], block_size=4)
        with pytest.raises(RuntimeError):
            p.route((1, 2, 3), exclude=("r0",))

    def test_affinity_map_lru_bound(self):
        m = AffinityMap(4, capacity=2)
        m.observe(tuple(range(5)), "r0")        # 1 key
        m.observe(tuple(range(10, 15)), "r1")   # 1 key
        m.observe(tuple(range(20, 25)), "r1")   # evicts the oldest
        assert m.lookup(tuple(range(5)), {"r0", "r1"}) is None
        assert m.lookup(tuple(range(10, 15)), {"r0", "r1"}) is not None


# -- dispatch: flow control, priority order, sheds, DRR ----------------------

class TestDispatch:
    def test_all_stale_holds_queue_without_sheds_or_credit(self):
        r, calls, clock = _router(budget=8)
        r.offer(_req("inter-0000"))
        r.offer(_req("batch-0000", tenant="batch", pclass="batch"))
        for _ in range(5):
            assert r.dispatch(stale={"r0", "r1"}) == []
        assert r.queued == 2 and not calls
        assert r.tenancy.counters["batch"]["sheds"] == 0
        # no credit hoarded across the held ticks: one open tick at a
        # budget below one request's cost still dispatches nothing...
        assert r.dispatch(budget=8) == []
        # ...but DRR carry across OPEN ticks eventually covers it
        assert len(r.dispatch(budget=8)) >= 1

    def test_release_is_priority_ordered(self):
        r, calls, _ = _router(budget=1000)
        r.offer(_req("batch-0000", tenant="batch", pclass="batch"))
        r.offer(_req("batch-0001", tenant="batch", pclass="batch"))
        r.offer(_req("inter-0000"))
        r.offer(_req("inter-0001"))
        out = r.dispatch()
        assert [q.pclass for q in out[:2]] == ["interactive"] * 2
        assert len(out) == 4                    # budget covers all

    def test_inflight_cap_closes_replica(self):
        r, calls, _ = _router(max_inflight_per_replica=1)
        for i in range(5):
            r.offer(_req(f"inter-{i:04d}"))
        assert len(r.dispatch()) == 2           # one per replica
        assert r.queued == 3
        assert r.dispatch() == []               # fleet saturated
        routed = [rid for _, rid, _ in calls]
        r.note_completed(routed)                # acks free the slots
        assert len(r.dispatch()) == 2

    def test_batch_sheds_first_under_pressure(self):
        r, calls, _ = _router(budget=12)
        r.offer(_req("inter-0000", n_tokens=8, new=4))       # cost 12
        r.offer(_req("batch-0000", tenant="batch",
                     pclass="batch", n_tokens=8, new=4))
        out = r.dispatch()
        assert [q.tenant for q in out] == ["inter"]
        assert r.tenancy.counters["batch"]["sheds"] == 1
        assert r.queued == 1

    def test_aged_batch_not_shed(self):
        r, calls, clock = _router(budget=12)
        r.offer(_req("batch-0000", tenant="batch",
                     pclass="batch", n_tokens=8, new=4))
        clock.t = 6.0            # past 10s*0.5 starvation deadline
        out = r.dispatch()
        assert [q.tenant for q in out] == ["batch"]
        assert r.tenancy.counters["batch"]["sheds"] == 0


# -- re-route damping --------------------------------------------------------

class TestReroute:
    def _loaded(self, tmp_path=None, **kw):
        r, calls, clock = _router(tmp_path, **kw)
        r.offer(_req("inter-0000"))
        r.offer(_req("inter-0001"))
        r.dispatch()
        return r, calls, clock

    def test_reroute_moves_to_survivor(self):
        r, calls, _ = self._loaded()
        dead = calls[0][0]
        survivor = "r1" if dead == "r0" else "r0"
        n = r.replica_died(dead)
        assert n >= 1
        assert all(st["replica"] == survivor
                   for st in r.inflight.values())

    def test_never_to_another_stale_replica(self):
        r, calls, _ = self._loaded(replicas=("r0", "r1", "r2"))
        owners = {rep for rep, _, _ in calls}
        stale = owners | {"r1"}
        if len(stale) == 3:                     # keep one survivor
            stale.discard("r2")
        n = r.replica_died(next(iter(owners)), exclude=stale)
        for st in r.inflight.values():
            assert st["replica"] not in stale or n == 0

    def test_no_survivor_means_no_reroute(self):
        r, calls, _ = self._loaded()
        assert r.replica_died("r0", exclude={"r1"}) == 0
        assert r.tick_reroutes(stale={"r0", "r1"}) == 0
        assert r.reroutes == 0

    def test_max_reroutes_cap(self):
        r, calls, _ = self._loaded(replicas=("r0", "r1", "r2"))
        moved = 0
        for _ in range(6):                      # ping-pong attempts
            owners = {st["replica"] for st in r.inflight.values()}
            n = 0
            for o in sorted(owners):
                n += r.replica_died(o)
            moved += n
            if n == 0:
                break
        assert all(st["reroutes"] <= Router.MAX_REROUTES
                   for st in r.inflight.values())
        assert moved <= 2 * Router.MAX_REROUTES

    def test_ack_timeout_sweep_needs_age(self):
        r, calls, clock = self._loaded(reroute_timeout_s=3.0)
        assert r.tick_reroutes(stale={calls[0][0]}) == 0   # too fresh
        clock.t = 5.0
        assert r.tick_reroutes(stale={calls[0][0]}) >= 1


# -- journal: torn tail, idempotent resume -----------------------------------

class TestJournal:
    def test_torn_tail_skipped(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        j = RouterJournal(path)
        j.record("route", id="a", replica="r0")
        j.record("ack", id="a")
        j.close()
        with open(path, "a") as f:
            f.write('{"seq": 3, "kind": "route", "id": "b"')  # torn
        recs = RouterJournal.replay(path)
        assert [r["kind"] for r in recs] == ["route", "ack"]

    def test_resume_is_idempotent(self, tmp_path):
        tenants = _tenants(
            burst=TenantConfig(name="burst", pclass="interactive",
                               quota_tokens_per_s=1.0,
                               quota_burst=10.0, slo_latency_s=2.0))
        r1, calls1, _ = _router(tmp_path, tenants=tenants)
        a, b = _req("inter-0000"), _req("inter-0001")
        r1.offer(a)
        r1.offer(b)
        r1.dispatch()
        owner = {rid: rep for rep, rid, _ in calls1}
        r1.note_completed(["inter-0000"])
        assert r1.offer(_req("burst-0000", n_tokens=10, new=4,
                             tenant="burst")) == "rejected:quota"
        # SIGKILL stand-in: journal abandoned unflushed-close, plus a
        # torn trailing line
        with open(os.path.join(str(tmp_path), ROUTER_JOURNAL),
                  "a") as f:
            f.write('{"kind": "route", "id": "torn-')

        r2, calls2, _ = _router(tmp_path, tenants=tenants)
        assert not calls2                       # resume NEVER re-submits
        assert r2.resumed == 1
        assert "inter-0000" in r2.acked
        # routed-but-unacked stays with its replica
        assert r2.inflight["inter-0001"]["replica"] == \
            owner["inter-0001"]
        # every prior decision is final
        assert r2.offer(a) == "duplicate"
        assert r2.offer(b) == "duplicate"
        assert r2.offer(_req("burst-0000", n_tokens=10, new=4,
                             tenant="burst")) == "duplicate"
        # resumed entries carry no Request body: a replica death does
        # NOT replay them from the router (the respawned replica's
        # inbox re-read is their recovery path)
        assert r2.replica_died(owner["inter-0001"]) == 0
        # new traffic routes normally
        assert r2.offer(_req("inter-0002")) == "admitted"
        assert len(r2.dispatch()) == 1
        assert len(calls2) == 1

    def test_double_resume_stable(self, tmp_path):
        r1, _, _ = _router(tmp_path)
        r1.offer(_req("inter-0000"))
        r1.dispatch()
        r2, c2, _ = _router(tmp_path)
        r3, c3, _ = _router(tmp_path)
        assert r2.resumed == r3.resumed == 1
        assert not c2 and not c3


# -- per-tenant SLOs ---------------------------------------------------------

class TestTenantSLOs:
    def test_partition_by_stamp(self):
        recs = [{"tenant": "a", "wall": 0.0},
                {"tenant": "b", "wall": 1.0}, {"wall": 2.0}]
        parts = partition_records(recs)
        assert set(parts) == {"a", "b", "-"}

    def test_one_tenants_overrun_cannot_fire_anothers(self):
        fast = TenantConfig(name="fast", pclass="interactive",
                            slo_latency_s=0.1)
        slow = TenantConfig(name="slow", pclass="batch",
                            slo_latency_s=10.0)
        recs = []
        for i in range(50):
            recs.append({"tenant": "fast", "wall": float(i),
                         "latency_s": 0.01, "ok": True})
            recs.append({"tenant": "slow", "wall": float(i) + 0.5,
                         "latency_s": 8.0, "ok": True})
        out = evaluate_tenants(recs, (fast, slow), now=50.0)
        assert not out["fast"]["fast/p99_latency"]["firing"]
        assert not out["slow"]["slow/p99_latency"]["firing"]
        # now the slow tenant blows ITS OWN budget; fast is untouched
        recs2 = [dict(r, latency_s=20.0) if r["tenant"] == "slow"
                 else r for r in recs]
        out2 = evaluate_tenants(recs2, (fast, slow), now=50.0)
        assert out2["slow"]["slow/p99_latency"]["firing"]
        assert not out2["fast"]["fast/p99_latency"]["firing"]


# -- seeded workload ---------------------------------------------------------

class TestWorkload:
    def test_deterministic_and_sessionful(self):
        w1 = seeded_tenant_workload(7, duration_s=5.0)
        w2 = seeded_tenant_workload(7, duration_s=5.0)
        assert [(r.id, r.tokens) for r in w1] == \
            [(r.id, r.tokens) for r in w2]
        assert w1 != seeded_tenant_workload(8, duration_s=5.0)
        # arrivals sorted; every request stamped
        assert all(a.arrival_s <= b.arrival_s
                   for a, b in zip(w1, w1[1:]))
        assert all(r.tenant and r.pclass for r in w1)

    def test_spike_only_boosts_interactive(self):
        base = seeded_tenant_workload(3, duration_s=8.0)
        spiked = seeded_tenant_workload(3, duration_s=8.0,
                                        spike=(2.0, 5.0, 4.0))
        def count(w, pclass):
            return sum(1 for r in w if r.pclass == pclass)
        assert count(spiked, "interactive") > count(base,
                                                    "interactive")
