"""Remote-dispatch lifecycle: bounded KV usage, generations, payload guard.

These run against the in-process coordination-service fallback (identical
semantics to the TSL service — cluster/coordination.py), with the worker
service loop on a thread; the cross-process behavior is covered by
tests/test_multi_process.py.
"""

import threading
import time

import pytest

from distributed_tensorflow_tpu.cluster import coordination
from distributed_tensorflow_tpu.coordinator import remote_dispatch as rd


@pytest.fixture()
def fresh_service():
    """Isolated local KV service + fresh generation per test."""
    old = coordination._LOCAL
    coordination._LOCAL = coordination._LocalService()
    rd._reset_generation_for_tests()
    agent = coordination.CoordinationServiceAgent()
    yield agent
    rd._reset_generation_for_tests()
    coordination._LOCAL = old


def _start_worker(agent, worker_id=1):
    svc = rd.RemoteWorkerService(worker_id=worker_id, agent=agent)
    t = threading.Thread(target=svc.run, kwargs={"poll_s": 0.05},
                         daemon=True)
    t.start()
    return svc, t


def _kv_size(agent):
    return len(agent.key_value_dir_get(rd._ROOT))


def test_soak_10k_closures_bounded_kv(fresh_service):
    """10k closures through one lane: every consumed task/result key is
    deleted, so the KV footprint stays O(1) — a week-long async-PS job
    cannot grow the coordination service without bound (VERDICT r2 weak
    #3; ≙ the reference's per-closure grpc leaving no server state)."""
    agent = fresh_service
    _start_worker(agent, worker_id=1)
    lane = rd.RemoteLane(1, agent=agent, staleness_s=5.0)
    t0 = time.monotonic()
    for i in range(10_000):
        seq = lane.submit(_double, (i,), {})
        assert lane.wait(seq, timeout_s=30) == 2 * i
    elapsed = time.monotonic() - t0
    # generation counter + current_gen + incarnation + hb + done watermark
    size = _kv_size(agent)
    assert size <= 8, agent.key_value_dir_get(rd._ROOT)
    # sanity: latency stayed sane (in-process: thousands/s)
    assert elapsed < 120


def _double(x):
    return 2 * x


def test_coordinator_restart_cannot_read_stale_results(fresh_service):
    """ADVICE r2 medium: a crash-restarted coordinator's seq 0 must NOT
    see the previous incarnation's result 0 — generations namespace the
    keys, and the worker follows current_gen."""
    agent = fresh_service
    _start_worker(agent, worker_id=1)
    lane = rd.RemoteLane(1, agent=agent, staleness_s=5.0)
    seq = lane.submit(_double, (21,), {})
    assert lane.wait(seq, timeout_s=30) == 42

    # leave an UNCONSUMED result behind (submit, let worker finish,
    # don't wait): the dangerous stale state
    lane.submit(_double, (100,), {})
    deadline = time.monotonic() + 10
    gen1 = lane.generation
    while (agent.key_value_try_get(rd._result_key(gen1, 1, 1)) is None
           and time.monotonic() < deadline):
        time.sleep(0.01)

    # coordinator "restarts": new incarnation, new generation
    rd._reset_generation_for_tests()
    lane2 = rd.RemoteLane(1, agent=agent, staleness_s=5.0)
    assert lane2.generation != gen1
    seq = lane2.submit(_double, (5,), {})        # seq 0 again
    assert lane2.wait(seq, timeout_s=30) == 10   # NOT the stale 200


def test_worker_restart_fast_forwards_via_watermark(fresh_service):
    """A restarted worker resumes at the done-watermark, not at 0 — it
    must not re-run completed closures even though their result keys
    were already consumed and deleted."""
    agent = fresh_service
    svc, _ = _start_worker(agent, worker_id=1)
    lane = rd.RemoteLane(1, agent=agent, staleness_s=5.0)
    for i in range(3):
        assert lane.wait(lane.submit(_double, (i,), {}), 30) == 2 * i
    # stop the first incarnation, start a second
    gen = lane.generation
    svc._stop.set()
    agent.key_value_set(rd._shutdown_key(gen), "1")
    time.sleep(0.2)
    agent.key_value_delete(rd._shutdown_key(gen))
    svc2, _ = _start_worker(agent, worker_id=1)
    assert svc2._initial_seq(gen) == 3
    assert lane.wait(lane.submit(_double, (7,), {}), 30) == 14


def test_payload_size_guard(fresh_service):
    agent = fresh_service
    lane = rd.RemoteLane(1, agent=agent)
    with pytest.raises(ValueError, match="payload"):
        lane.submit(_double, (b"x" * (rd.MAX_PAYLOAD_BYTES + 1),), {})


def test_resource_handles_are_incarnation_scoped(fresh_service):
    """ADVICE r2 low: a stale handle from incarnation 1 must miss the
    registry of incarnation 2 (and self-heal via its builder) rather
    than alias a different resource with the same counter value."""
    agent = fresh_service
    svc1 = rd.RemoteWorkerService(worker_id=1, agent=agent)
    h1 = svc1.create_resource(list, builder=list)
    svc2 = rd.RemoteWorkerService(worker_id=1, agent=agent)
    h2 = svc2.create_resource(dict, builder=dict)
    assert h1.handle != h2.handle
    # resolving the stale handle on the new incarnation rebuilds, never
    # returns svc2's dict
    resolved = rd.resolve_resources((h1,), svc2.resources)[0]
    assert isinstance(resolved, list)


def test_live_nodes_task_id_parsing():
    """'/job:jax_worker_2/task:13'-style names parse to 13, not 213."""
    p = coordination._parse_task_id
    assert p(7) == 7
    assert p("3") == 3
    assert p("/job:jax_worker/task:3") == 3
    assert p("/job:jax_worker_2/task:13") == 13
    assert p("/job:worker2/task:0") == 0
    assert p("not-a-task") is None
