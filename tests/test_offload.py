"""Host-offloaded 1F1B activation stash (parallel/offload.py).

Claims pinned here: spilling the stash to host vs keeping it on device
is bit-identical end to end (the spill path moves bytes, never changes
them); the host-driven realization matches the fused single-jit 1F1B
step loss-for-loss from identical params (params drift only at the
cross-program fusion artifact, ~1e-9 — see parallel/zero.py for the
same phenomenon); a failed spill retries once and a double failure
surfaces as a clean ``OffloadSpillError`` on the consumer — never a
hang, never silently wrong activations.
"""

import jax
import numpy as np
import pytest

from distributed_tensorflow_tpu.cluster.topology import make_mesh
from distributed_tensorflow_tpu.models.transformer import (
    TransformerConfig, make_pipelined_train_step, synthetic_tokens)
from distributed_tensorflow_tpu.parallel.offload import (
    ActivationSpillStore, OffloadSpillError)
from distributed_tensorflow_tpu.resilience import faults

CFG = TransformerConfig.tiny(n_layers=4)
GB, M = 8, 4


@pytest.fixture(scope="module")
def tokens():
    return synthetic_tokens(GB, CFG.max_seq_len, CFG.vocab_size, seed=3)


@pytest.fixture(scope="module")
def spill_runner(devices):
    """One offloading step builder reused across tests (fault injection
    acts at runtime, so the same compiled programs serve every case)."""
    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    state, step = make_pipelined_train_step(
        CFG, mesh, GB, M, schedule="1f1b", offload_activations=True)
    return mesh, state, step


def _run(state, step, tokens, n=2):
    losses = []
    for _ in range(n):
        state, m = step(state, {"tokens": tokens})
        losses.append(float(m["loss"]))
    return state, losses


def _leaves_equal(pa, pb):
    for a, b in zip(jax.tree_util.tree_leaves(pa),
                    jax.tree_util.tree_leaves(pb)):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            return False
    return True


def test_offload_on_off_bit_identical(spill_runner, tokens, devices):
    """spill=True (host stash) vs 'device' (device stash, same host-
    driven loop) after 2 steps: every param leaf bit-identical."""
    mesh, state0, step = spill_runner
    s_spill, l_spill = _run(state0, step, tokens)
    state_d, step_d = make_pipelined_train_step(
        CFG, mesh, GB, M, schedule="1f1b", offload_activations="device")
    s_dev, l_dev = _run(state_d, step_d, tokens)
    assert l_spill == l_dev
    assert _leaves_equal(s_spill["params"], s_dev["params"])


def test_offload_matches_fused_1f1b(spill_runner, tokens, devices):
    """vs the fused single-jit 1F1B step: first-step loss bit-identical
    (identical params in, same schedule arithmetic), params allclose."""
    mesh, state0, step = spill_runner
    s_off, l_off = _run(state0, step, tokens)
    state_f, step_f = make_pipelined_train_step(
        CFG, mesh, GB, M, schedule="1f1b")
    s_fused, l_fused = _run(state_f, step_f, tokens)
    assert l_off[0] == l_fused[0]
    np.testing.assert_allclose(l_off, l_fused, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(s_off["params"]),
                    jax.tree_util.tree_leaves(s_fused["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_offload_spill_fault_retries_bit_identical(spill_runner, tokens,
                                                   devices):
    """A single injected spill failure is absorbed by the retry: the
    run's params are bit-identical to the fault-free run."""
    mesh, state0, step = spill_runner
    base, _ = _run(state0, step, tokens)
    sched = faults.FaultSchedule(seed=7, rules=(
        faults.FaultRule(site="offload.spill", tag="c3", hits=(1,),
                         max_fires=1),))
    with faults.inject(sched) as reg:
        faulted, _ = _run(state0, step, tokens)
    assert any(e[0] == "offload.spill" for e in reg.events())
    assert _leaves_equal(base["params"], faulted["params"])


def test_offload_double_spill_failure_raises_cleanly(spill_runner,
                                                     tokens, devices):
    """Both attempts failing surfaces OffloadSpillError at the cycle
    that needed the lost entry — a clean consumer-side error."""
    mesh, state0, step = spill_runner
    sched = faults.FaultSchedule(seed=7, rules=(
        faults.FaultRule(site="offload.spill", tag="c3", hits=(1, 2),
                         max_fires=2),))
    with faults.inject(sched):
        with pytest.raises(OffloadSpillError, match="cycle 3"):
            _run(state0, step, tokens, n=1)


def test_spill_store_unit():
    class FakeArr:
        def __init__(self, v):
            self.v = v

        def copy_to_host_async(self):
            pass

        def __array__(self, dtype=None):
            return np.asarray(self.v, dtype=dtype)

    store = ActivationSpillStore(spill=True)
    store.put(0, FakeArr([1.0, 2.0]))
    assert np.array_equal(store.get(0), [1.0, 2.0])
    store.drop_through(0)
    with pytest.raises(OffloadSpillError, match="missing"):
        store.get(0)


def test_offload_invalid_combinations(devices):
    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    for kw in ({"schedule": "gpipe", "offload_activations": True},
               {"schedule": "interleaved", "offload_activations": True},
               {"schedule": "1f1b", "offload_activations": "bogus"}):
        with pytest.raises(ValueError):
            make_pipelined_train_step(CFG, mesh, GB, M, **kw)
