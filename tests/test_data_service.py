"""Disaggregated data service (ISSUE 12): FILE split provider,
heartbeat-backed leases, exactly-once delivery under input-worker
churn and trainer reform."""

import threading
import time

import pytest

from distributed_tensorflow_tpu.cluster import coordination, elastic
from distributed_tensorflow_tpu.input import data_service as dsvc
from distributed_tensorflow_tpu.input.dataset import Dataset
from distributed_tensorflow_tpu.input.split_provider import SplitProvider
from distributed_tensorflow_tpu.resilience import faults
from distributed_tensorflow_tpu.testing import fleet_sim


def _file_provider(tmp_path, n_files=4, per_file=3, seed=3):
    files = []
    for i in range(n_files):
        p = tmp_path / f"f{i}.txt"
        p.write_text("\n".join(str(i * 10 + j) for j in range(per_file)))
        files.append(str(p))

    def reader(path):
        with open(path) as f:
            for line in f:
                yield int(line)

    ds = Dataset.from_files(files, reader).map(lambda x: x + 100)
    return SplitProvider.from_dataset(ds, seed=seed), files


# ---------------------------------------------------------------------------
# Split provider
# ---------------------------------------------------------------------------

def test_split_provider_replays_recorded_chain(tmp_path):
    provider, _files = _file_provider(tmp_path)
    assert provider.num_splits == 4
    # per-split rebuild == the chain over exactly that file
    for i in range(4):
        assert provider.elements(i) == [i * 10 + j + 100
                                        for j in range(3)]


def test_split_provider_epoch_order_deterministic(tmp_path):
    p1, files = _file_provider(tmp_path, n_files=8)
    p2 = SplitProvider.from_factory(
        files, lambda fs: Dataset.from_iterable(list(fs)), seed=3)
    for epoch in (0, 1, 7):
        order = p1.epoch_order(epoch)
        assert sorted(order) == list(range(8))     # a permutation
        assert order == p2.epoch_order(epoch)      # seed-pure
    assert p1.epoch_order(0) != p1.epoch_order(1)  # epoch-keyed


def test_split_provider_rejects_non_file_pipelines(tmp_path):
    with pytest.raises(ValueError, match=">= 1 file"):
        SplitProvider([], lambda fs: None)
    gen_rooted = Dataset.from_generator(lambda: iter(range(3)))
    with pytest.raises(ValueError, match="file source"):
        SplitProvider.from_dataset(gen_rooted)
    provider, _ = _file_provider(tmp_path)
    with pytest.raises(ValueError, match="out of range"):
        provider.build(99)


# ---------------------------------------------------------------------------
# Protocol units (real classes over one in-memory KV)
# ---------------------------------------------------------------------------

def _run_service(provider, *, num_workers, epochs=1, cfg=None):
    """Dispatcher + worker threads + client over one _LocalService;
    returns (sorted elements per epoch, dispatcher, workers)."""
    cfg = cfg or dsvc.DataServiceConfig(job="t", lease_timeout_s=0.4,
                                        poll_interval_s=0.01,
                                        fetch_timeout_s=20.0)
    service = coordination._LocalService()
    agents = [fleet_sim.SimAgent(service, p, num_workers + 2)
              for p in range(num_workers + 2)]
    disp = dsvc.DataServiceDispatcher(agents[-1], provider, cfg,
                                      num_workers=num_workers,
                                      epochs=epochs)
    stop = threading.Event()
    workers, threads = [], []
    for w in range(num_workers):
        iw = dsvc.DataInputWorker(agents[w], provider, cfg,
                                  worker_id=w, num_workers=num_workers,
                                  epochs=epochs)
        workers.append(iw)

        def run(iw=iw):
            try:
                iw.run(stop)
            except faults.FaultInjected:
                pass                     # simulated worker death

        t = threading.Thread(target=run, daemon=True)
        t.start()
        threads.append(t)
    disp.start()
    client = dsvc.DataServiceClient(agents[-2], cfg)
    try:
        per_epoch = [sorted(client.epoch(e)) for e in range(epochs)]
    finally:
        dsvc.signal_shutdown(agents[-2], cfg)
        stop.set()
        disp.stop()
        for t in threads:
            t.join(timeout=5.0)
    return per_epoch, disp, workers, client


def test_done_record_is_write_once(tmp_path):
    """Two workers completing the SAME split (a re-issued lease both
    sides finished) produce ONE done record — the first claim wins and
    the loser's attempt is silently discarded."""
    provider, _ = _file_provider(tmp_path)
    cfg = dsvc.DataServiceConfig(job="race")
    service = coordination._LocalService()
    a0, a1, a2 = (fleet_sim.SimAgent(service, p, 3) for p in range(3))
    dsvc.register_job(a0, cfg, provider, epochs=1, num_workers=2)
    w0 = dsvc.DataInputWorker(a0, provider, cfg, worker_id=0,
                              num_workers=2, epochs=1)
    w1 = dsvc.DataInputWorker(a1, provider, cfg, worker_id=1,
                              num_workers=2, epochs=1)
    w0._process(0, 2)
    w1._process(0, 2)                     # loses the claim race
    assert w0.splits_processed == 1
    assert w1.splits_processed == 0       # loser does not count it
    import json
    rec = json.loads(a2.key_value_try_get(
        dsvc._done_key(cfg, 0, 2)).decode())
    assert rec["worker"] == 0


def test_service_delivers_full_epoch(tmp_path):
    """Steady state over the real protocol classes: one epoch, every
    element delivered exactly once (the dead-worker cases are the
    chaos scenarios below)."""
    provider, _ = _file_provider(tmp_path)
    per_epoch, _disp, _workers, _c = _run_service(provider,
                                                  num_workers=2)
    assert per_epoch[0] == sorted(provider.elements(i)[j]
                                  for i in range(4) for j in range(3))


def test_client_retries_injected_fetch_faults(tmp_path):
    """A transient data.fetch failure is retried under the client's
    decorrelated RetryPolicy — delivery still exactly-once."""
    provider, _ = _file_provider(tmp_path)
    schedule = faults.FaultSchedule(rules=(
        faults.FaultRule(site="data.fetch", hits=(1, 3)),), seed=0)
    with faults.inject(schedule) as reg:
        per_epoch, _d, _w, _c = _run_service(provider, num_workers=2)
    expected = sorted(x for i in range(4)
                      for x in provider.elements(i))
    assert per_epoch[0] == expected
    assert any(site == "data.fetch" for site, *_ in reg.events())


# ---------------------------------------------------------------------------
# Exactly-once property: the consumed multiset per epoch is IDENTICAL
# across {no faults, worker killed mid-epoch, worker killed holding an
# unstarted lease, trainer reform mid-epoch}
# ---------------------------------------------------------------------------

_N_WORKERS, _N_SPLITS, _EPOCHS = 4, 10, 2


def _sim(fault_schedule=None, generation=0, seed=11):
    return fleet_sim.DataServiceSim(
        _N_WORKERS, _N_SPLITS, epochs=_EPOCHS, elements_per_split=3,
        lease_timeout_s=0.3, fault_schedule=fault_schedule,
        generation=generation, seed=seed)


def test_exactly_once_no_faults():
    sim = _sim()
    rep = sim.run()
    assert rep.completed, rep.error
    assert rep.duplicate_elements == 0 and rep.missing_elements == 0
    assert rep.epoch_multisets == [sim.expected_multiset()] * _EPOCHS
    # balanced-ish split distribution across the live fleet
    assert set(rep.splits_per_worker) == set(range(_N_WORKERS))


def test_exactly_once_worker_killed_mid_epoch():
    # victim dies on its SECOND split-processing attempt: it completed
    # work this epoch, then died holding a started lease
    schedule = faults.FaultSchedule(rules=(
        faults.FaultRule(site="data.worker_step", action="raise",
                         tag="1", hits=(2,)),), seed=1)
    sim = _sim(schedule)
    rep = sim.run()
    assert rep.completed, rep.error
    assert rep.workers_died == [1]
    assert rep.splits_reassigned >= 1
    assert rep.duplicate_elements == 0 and rep.missing_elements == 0
    assert rep.epoch_multisets == [sim.expected_multiset()] * _EPOCHS


def test_exactly_once_worker_killed_holding_unstarted_lease():
    # victim dies on its FIRST attempt: leases issued, nothing done
    schedule = faults.FaultSchedule(rules=(
        faults.FaultRule(site="data.worker_step", action="raise",
                         tag="2", hits=(1,)),), seed=2)
    sim = _sim(schedule)
    rep = sim.run()
    assert rep.completed, rep.error
    assert rep.workers_died == [2]
    assert rep.splits_reassigned >= 1
    assert rep.duplicate_elements == 0 and rep.missing_elements == 0
    assert rep.epoch_multisets == [sim.expected_multiset()] * _EPOCHS


def test_exactly_once_worker_stalled_past_lease_budget():
    # a STALL (not a death) past the lease budget also forfeits the
    # lease; the stalled worker's late completion loses the done race
    schedule = faults.FaultSchedule(rules=(
        faults.FaultRule(site="data.worker_step", action="delay",
                         delay_s=1.2, tag="0", hits=(2,)),), seed=4)
    sim = _sim(schedule)
    rep = sim.run()
    assert rep.completed, rep.error
    assert rep.workers_died == []          # stalled, not dead
    assert rep.splits_reassigned >= 1
    assert rep.duplicate_elements == 0 and rep.missing_elements == 0
    assert rep.epoch_multisets == [sim.expected_multiset()] * _EPOCHS


def test_exactly_once_trainer_reform_mid_epoch():
    """Generation fencing: a trainer reform mid-epoch abandons gen-1's
    half-delivered epoch; the gen-2 redelivery is complete and exact —
    no contamination from the dead generation's keys, and a gen-1
    straggler worker's late writes stay invisible to gen 2."""
    service = coordination._LocalService()

    def run_gen(gen, *, abandon_after=None, straggler_holdover=None):
        sim = _sim(generation=gen)
        sim.kv = service                  # SHARED service across gens
        if abandon_after is None:
            rep = sim.run()
            return sim, rep
        # gen-1 pass: consume only part of epoch 0, then walk away
        # (the reform kills the consumer mid-epoch)
        with elastic.generation_override(gen):
            stop = threading.Event()
            workers = []
            for w in range(_N_WORKERS):
                iw = dsvc.DataInputWorker(
                    sim._agent(w), sim.provider, sim.cfg, worker_id=w,
                    num_workers=_N_WORKERS, epochs=_EPOCHS)
                t = threading.Thread(target=iw.run, args=(stop,),
                                     daemon=True)
                t.start()
                workers.append(t)
            disp = dsvc.DataServiceDispatcher(
                sim._agent(_N_WORKERS), sim.provider, sim.cfg,
                num_workers=_N_WORKERS, epochs=_EPOCHS)
            disp.start()
            client = dsvc.DataServiceClient(
                sim._agent(_N_WORKERS + 1), sim.cfg)
            got = []
            for el in client.epoch(0):
                got.append(el)
                if len(got) >= abandon_after:
                    break                  # reform: consumer dies here
            disp.stop()
            stop.set()
            for t in workers:
                t.join(timeout=5.0)
            assert 0 < len(got) < _N_SPLITS * 3
        return sim, got

    _sim1, partial = run_gen(1, abandon_after=4)
    sim2, rep2 = run_gen(2)
    assert rep2.completed, rep2.error
    assert rep2.duplicate_elements == 0 and rep2.missing_elements == 0
    assert rep2.epoch_multisets == [sim2.expected_multiset()] * _EPOCHS
    # the dead generation's namespace still holds its keys, disjoint
    # from gen 2's (the lifecycle GC's job to sweep, not ours)
    with elastic.generation_override(1):
        agent = fleet_sim.SimAgent(service, 99, _N_WORKERS)
        assert agent.key_value_try_get(
            dsvc._spec_key(sim2.cfg)) is not None


@pytest.mark.slow
def test_exactly_once_hundred_workers_seeded_kills():
    """The tentpole's O(100) mode: 100 simulated input workers, seeded
    kill schedule, exactly-once delivery and tree-rollup visibility."""
    # at 150 splits / 100 workers each worker only sees ~1-2 leases:
    # pin every victim's death to its FIRST attempt so all three kills
    # actually fire
    schedule = fleet_sim.seeded_data_kill_schedule(
        7, 100, kills=3, attempt_range=(1, 2))
    sim = fleet_sim.DataServiceSim(
        100, 150, epochs=1, elements_per_split=2,
        lease_timeout_s=0.5, fault_schedule=schedule, seed=7,
        timeout_s=120.0)
    rep = sim.run()
    assert rep.completed, rep.error
    assert rep.duplicate_elements == 0 and rep.missing_elements == 0
    assert len(rep.workers_died) == 3
    assert rep.splits_reassigned >= 3
    assert rep.rollup_workers_seen >= 90   # dead workers stop publishing
    assert rep.rollup_splits_processed == 150


# ---------------------------------------------------------------------------
# Fetch-wait lands in the goodput ledger (ISSUE 12 satellite)
# ---------------------------------------------------------------------------

def test_fetch_wait_priced_as_infeed_badput(tmp_path):
    """Live path: the trainer feeds its fetch-wait into the ledger;
    event-walk path: a data-service run's train.step events carry
    infeed_wait_s and the wall == goodput + Σ badput identity holds."""
    from distributed_tensorflow_tpu.telemetry import events as tv_events
    from distributed_tensorflow_tpu.telemetry import goodput

    provider, _ = _file_provider(tmp_path, n_files=4, per_file=3)
    run_dir = tmp_path / "tel"
    tv_events.configure(str(run_dir), process_id=0)
    ledger = goodput.GoodputLedger(register=False)
    try:
        per_epoch, _d, _w, client = _run_service(provider,
                                                 num_workers=2)
        # a mini trainer step-loop over the delivered elements
        batch, step = [], 0
        last_wait = 0.0
        for el in per_epoch[0]:
            batch.append(el)
            if len(batch) < 6:
                continue
            wait = client.total_wait_s - last_wait
            last_wait = client.total_wait_s
            dur = 0.002 + wait
            time.sleep(0.002)
            tv_events.event("train.step", step=step,
                            dur_s=round(dur, 6),
                            infeed_wait_s=round(wait, 6))
            ledger.step_completed(dur, infeed_s=wait)
            batch, step = [], step + 1
    finally:
        tv_events.shutdown()
    assert client.total_wait_s > 0          # the service made us wait
    # live ledger: identity + infeed priced
    snap = ledger.snapshot()
    attributed = snap["goodput_s"] + sum(snap["badput_s"].values())
    assert attributed == pytest.approx(snap["wall_s"], rel=0.02)
    assert snap["badput_s"]["infeed_wait"] > 0
    # event-walk ledger over the run dir: same identity, same bucket
    walked = goodput.ledger_from_run(str(run_dir))
    assert abs(walked["identity_error_s"]) <= 0.01 * walked["wall_s"]
    assert walked["badput_s"]["infeed_wait"] > 0


# ---------------------------------------------------------------------------
# Domain-aware lease placement (ISSUE 19)
# ---------------------------------------------------------------------------

_DOMS = {0: "r0", 1: "r0", 2: "r1", 3: "r1"}


def _dispatcher(tmp_path, domains):
    provider, _ = _file_provider(tmp_path)
    cfg = dsvc.DataServiceConfig(job=f"dom{bool(domains)}")
    agent = fleet_sim.SimAgent(coordination._LocalService(), 0, 1)
    return dsvc.DataServiceDispatcher(agent, provider, cfg,
                                      num_workers=4, domains=domains)


def test_dispatcher_spreads_leases_across_domains(tmp_path):
    disp = _dispatcher(tmp_path, _DOMS)
    live = [0, 1, 2, 3]
    picks = []
    for split in range(4):
        w = disp._least_loaded(live)
        picks.append(w)
        disp._leases[split] = w
    # least-loaded DOMAIN first, then least-loaded worker within it:
    # the racks alternate instead of filling r0 first
    assert picks == [0, 2, 1, 3]
    by_dom = {}
    for w in picks:
        by_dom[_DOMS[w]] = by_dom.get(_DOMS[w], 0) + 1
    assert by_dom == {"r0": 2, "r1": 2}


def test_dispatcher_blind_placement_packs_by_worker(tmp_path):
    disp = _dispatcher(tmp_path, None)
    live = [0, 1, 2, 3]
    picks = []
    for split in range(4):
        w = disp._least_loaded(live)
        picks.append(w)
        disp._leases[split] = w
    assert picks == [0, 1, 2, 3]             # historical tie-break


def test_dispatcher_reissues_outside_dead_workers_domain(tmp_path):
    disp = _dispatcher(tmp_path, _DOMS)
    disp._leases = {0: 0, 1: 1}              # both leases on rack r0
    # worker 0 died; its rackmate 1 is (for now) still heartbeating —
    # the re-issue must jump the rack, not pile onto the survivor that
    # is probably about to be declared dead too
    disp._reissue_stale(live=[1, 2, 3])
    assert disp._leases[1] == 1              # live lease untouched
    assert disp._leases[0] in (2, 3)
    assert _DOMS[disp._leases[0]] == "r1"
    assert disp.splits_reassigned == 1


def test_dispatcher_reissue_falls_back_inside_domain_when_alone(tmp_path):
    disp = _dispatcher(tmp_path, _DOMS)
    disp._leases = {0: 0}
    disp._reissue_stale(live=[1])            # only the rackmate left
    assert disp._leases[0] == 1              # degrade, don't stall


def test_exactly_once_with_domain_topology_and_rack_mate_kill():
    """The full service under a domain topology: a worker death inside
    a rack still delivers every element exactly once, with the lease
    table spread by the placement policy."""
    schedule = faults.FaultSchedule(rules=(
        faults.FaultRule(site="data.worker_step", action="raise",
                         tag="1", hits=(1,)),), seed=5)
    sim = fleet_sim.DataServiceSim(
        _N_WORKERS, _N_SPLITS, epochs=_EPOCHS, elements_per_split=3,
        lease_timeout_s=0.3, fault_schedule=schedule, seed=5,
        topology=fleet_sim.DomainTopology(_N_WORKERS,
                                          workers_per_domain=2))
    rep = sim.run()
    assert rep.completed, rep.error
    assert rep.workers_died == [1]
    assert rep.splits_reassigned >= 1
    assert rep.duplicate_elements == 0 and rep.missing_elements == 0
    assert rep.epoch_multisets == [sim.expected_multiset()] * _EPOCHS
