"""Config #1 (MNIST CNN) as a VERBATIM reference-style Keras script.

This file is written exactly the way the reference's MNIST training
script is (SURVEY.md §3.1: Sequential under strategy.scope, compile,
fit) — the ONLY line that differs from the tf_keras original is the
import below. Everything after it is untouched reference style: same
layer constructors, same compile arguments, same fit/evaluate calls.

    reference:  import tensorflow as tf; keras = tf.keras
    here:       from distributed_tensorflow_tpu import keras
"""

import numpy as np

import distributed_tensorflow_tpu as tf_distribute
from distributed_tensorflow_tpu import keras


def load_data(n=4096, seed=0):
    """Synthetic MNIST-shaped data (zero-egress environment); labels
    derived from image statistics so the model can actually fit."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 28, 28, 1)).astype("float32")
    y = (np.abs(x.mean(axis=(1, 2, 3))) * 40).astype("int32") % 10
    return (x[: n - 512], y[: n - 512]), (x[n - 512:], y[n - 512:])


def main():
    (x_train, y_train), (x_test, y_test) = load_data()

    strategy = tf_distribute.MirroredStrategy()
    with strategy.scope():
        model = keras.Sequential([
            keras.Input((28, 28, 1)),
            keras.layers.Conv2D(32, 3, padding="same", activation="relu"),
            keras.layers.Conv2D(64, 3, padding="same", activation="relu"),
            keras.layers.MaxPooling2D(2),
            keras.layers.Flatten(),
            keras.layers.Dropout(0.25),
            keras.layers.Dense(128, activation="relu"),
            keras.layers.Dense(10),
        ])
        model.compile(
            optimizer=keras.optimizers.Adam(1e-3),
            loss=keras.losses.SparseCategoricalCrossentropy(
                from_logits=True),
            metrics=["accuracy"],
        )

    model.fit(x_train, y_train, batch_size=256, epochs=3,
              validation_data=(x_test, y_test))
    loss, acc = model.evaluate(x_test, y_test, batch_size=256)
    print(f"eval loss {loss:.4f}  accuracy {acc:.4f}")
    preds = model.predict(x_test[:8], batch_size=8)
    print("predicted classes:", preds.argmax(-1).tolist())


if __name__ == "__main__":
    main()
