#!/usr/bin/env python
"""Live model rollout: zero-downtime hot-swap + SLO-gated canary.

Two snapshots of the serving model sit in one checkpoint directory —
the BASE version (step 1) and a TARGET version (step 2). Supervised
serving replicas (shaped like serving/replica.serving_replica) serve a
seeded open-loop workload while a
``resilience.rollout.RolloutController``, ticked from the supervisor
watch loop exactly like the PR-13 autoscaler, ramps the fleet: the
first replica hot-swaps to the target immediately (the canary —
``InferenceEngine.begin_load_version`` restores in the background and
the flip lands at a step boundary, in-flight requests re-queued, zero
dropped), every further replica moves only after the canary's
per-version SLO burn stays clear, and a burning canary rolls the whole
fleet back to the pinned base (``load_version(base)`` →
``restore_latest(at_step=)``).

Modes the sweeps drive:

- ``--null-swap`` — step 2 has byte-identical weights: every completion
  must match the no-swap reference byte-for-byte (the zero-downtime
  gate);
- ``--bad-canary`` — the target version is degraded (a per-step delay
  while serving it): the canary burns, the controller must roll back;
- ``--restart-mode`` — the pre-hot-swap baseline: a reassigned replica
  ABORTS and lets the supervisor respawn it; the next incarnation
  pin-restores the target (``from_checkpoint(at_step=)``, a
  ``mode="restart"`` swap event). Same traffic, same events — the
  swap-vs-restart freshness comparison in ``bench.py --rollout`` is
  this flag and nothing else;
- ``--kills N`` — seeded SIGKILLs through the supervisor mid-rollout
  (``chaos_sweep.py --rollout``): completions must still cover the
  workload, and every completion's tokens must equal the PURE output
  of the version it is stamped with (no mixed-version token streams).

Run it::

    python examples/live_rollout.py --telemetry-dir /tmp/rollout --seed 0

then read the run::

    cat /tmp/rollout/rollout-summary.json
    python tools/health_report.py /tmp/rollout
"""

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

BASE_STEP = 1
TARGET_STEP = 2
_VOCAB = 256

ENGINE_KWARGS = dict(num_blocks=48, block_size=8, max_slots=4,
                     max_prompt_len=16, queue_capacity=4096,
                     prefix_caching=True)


def _cfg():
    from distributed_tensorflow_tpu.models.transformer import (
        TransformerConfig)
    return TransformerConfig.tiny(max_seq_len=64)


def write_snapshots(ckpt_dir: str, *, null_swap: bool = False) -> float:
    """Write the base (step 1) and target (step 2) snapshots; with
    ``null_swap`` the target carries byte-identical weights. Returns
    the target's publish wall (save-commit time)."""
    import time

    import jax
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.checkpoint import (
        Checkpoint, CheckpointManager)
    from distributed_tensorflow_tpu.models.transformer import (
        TransformerLM)

    cfg = _cfg()
    model = TransformerLM(cfg)

    def _params(seed: int) -> dict:
        p = model.init(jax.random.PRNGKey(seed),
                       jnp.zeros((1, 8), jnp.int32))["params"]
        return p.unfreeze() if hasattr(p, "unfreeze") else dict(p)

    for step, seed in ((BASE_STEP, 0),
                       (TARGET_STEP, 0 if null_swap else 7)):
        mgr = CheckpointManager(Checkpoint(params=_params(seed)),
                                ckpt_dir, max_to_keep=8)
        mgr.save(step)
    return time.time()


def rollout_workload(seed: int, *, duration_s: float = 24.0,
                     qps: float = 5.0) -> list:
    """Constant-rate seeded open-loop arrivals (the spike schedule with
    the spike flattened away) — same id space (``s.....``), same epoch
    anchoring, same replica sharding as the autoscale workload."""
    from distributed_tensorflow_tpu.serving.replica import (
        seeded_spike_schedule)
    return seeded_spike_schedule(
        seed, duration_s=duration_s, base_qps=qps, spike_qps=qps,
        spike_start_s=0.0, spike_end_s=0.0, vocab_size=_VOCAB,
        new_tokens_range=(2, 6))


def reference_outputs(ckpt_dir: str, requests: list, step: int) -> dict:
    """``{request_id: tokens}`` a PURE engine pinned at ``step``
    produces for ``requests`` — greedy decode over fixed weights is
    deterministic, so any completion stamped with this version must
    match byte-for-byte (the no-mixed-version oracle)."""
    from distributed_tensorflow_tpu.serving.engine import InferenceEngine
    eng = InferenceEngine.from_checkpoint(
        _cfg(), ckpt_dir, at_step=step, **ENGINE_KWARGS)
    out = {}
    for r in requests:
        eng.submit(r)
        while not eng.scheduler.idle:
            for rec in eng.step():
                out[rec["id"]] = list(rec["tokens"])
    return out


def rollout_replica(run_dir: str, ckpt_dir: str, assignment_path: str,
                    seed: int, *, duration_s: float = 24.0,
                    qps: float = 5.0, step_delay_s: float = 0.0,
                    bad_step: "int | None" = None,
                    bad_delay_s: float = 0.4,
                    restart_mode: bool = False,
                    engine_kwargs: "dict | None" = None,
                    max_retries: int = 50):
    """One generation of one rollout-managed serving replica.

    Identical contract to serving/replica.serving_replica (module-level,
    heartbeats per step, completion-log union for zero dropped
    requests) plus the rollout loop: every step it polls the
    controller's assignment file; when its assigned snapshot step
    differs from the engine's it hot-swaps via
    ``begin_load_version`` (background restore, flip at a step
    boundary) — or, under ``restart_mode``, aborts so the supervisor
    respawns it and the next incarnation adopts the assignment at
    startup (``from_checkpoint(at_step=)``). ``bad_step`` degrades
    serving while THAT version is live (per-step delay) — the seeded
    bad canary the rollback gate needs."""
    from distributed_tensorflow_tpu.cluster import bootstrap, elastic

    runtime = bootstrap.initialize()
    import contextlib
    import time as _time

    import jax
    if runtime.num_processes <= 1:
        with contextlib.suppress(Exception):
            jax.config.update("jax_cpu_collectives_implementation",
                              "none")

    from distributed_tensorflow_tpu.resilience.faults import FaultInjected
    from distributed_tensorflow_tpu.resilience.rollout import (
        read_assignment)
    from distributed_tensorflow_tpu.serving.engine import InferenceEngine
    from distributed_tensorflow_tpu.serving.replica import (
        completed_ids_all, run_epoch)
    from distributed_tensorflow_tpu.serving.scheduler import (
        Request as _Req)
    from distributed_tensorflow_tpu.telemetry import events as tv_events
    from distributed_tensorflow_tpu.telemetry import goodput

    task = runtime.process_id
    n_replicas = max(1, runtime.num_processes)
    tdir = os.environ.get(tv_events.ENV_TELEMETRY_DIR)
    if tdir:
        tv_events.configure(tdir, process_id=task)
    goodput.activate(goodput.GoodputLedger())

    def _assigned() -> "tuple[int, float | None]":
        a = read_assignment(assignment_path)
        if not a:
            return BASE_STEP, None
        return (int(a["assignment"].get(str(task), a["base_step"])),
                a.get("published_wall"))

    kwargs = dict(ENGINE_KWARGS)
    kwargs.update(engine_kwargs or {})
    # a (re)started replica adopts the CURRENT assignment at startup —
    # the restart-adoption path: its pin-restore emits the
    # mode="restart" serve.swap the freshness SLO closes on
    start_step, pub_wall = _assigned()
    engine = InferenceEngine.from_checkpoint(
        _cfg(), ckpt_dir, at_step=start_step, **kwargs)

    workload = rollout_workload(seed, duration_s=duration_s, qps=qps)
    done = completed_ids_all(run_dir)
    mine = [r for i, r in enumerate(workload)
            if i % n_replicas == task]
    todo = [r for r in mine if r.id not in done]
    gen = elastic.generation()
    print(f"[gen {gen} rollout-{task}] v{engine.weights_step}, "
          f"{len(mine) - len(todo)} already served, {len(todo)} of "
          f"{len(mine)} to go", flush=True)

    # warm the compiled programs BEFORE anchoring the epoch (compile
    # time is startup, not client-visible queueing)
    engine.submit(_Req(id=f"warmup-{task}-g{gen}", tokens=(1, 2, 3),
                       max_new_tokens=2))
    engine.run_until_idle(retry_faults=True)
    epoch = run_epoch(run_dir)

    import collections as _collections
    pending = _collections.deque(todo)
    served = 0
    step = 0
    retries = 0
    log_path = os.path.join(run_dir, f"served-{task}.jsonl")
    with open(log_path, "a", buffering=1) as log:
        while (pending or not engine.scheduler.idle
               or _time.time() - epoch < duration_s):
            elastic.heartbeat(step)
            target, pub_wall = _assigned()
            if (target != engine.weights_step
                    and engine._pending_swap is None
                    and (engine._swap_thread is None
                         or not engine._swap_thread.is_alive())):
                if restart_mode:
                    # the pre-hot-swap world: a new version means a
                    # rolling restart — abort, respawn, re-pin
                    print(f"[gen {gen} rollout-{task}] restart for "
                          f"v{target}", flush=True)
                    log.flush()
                    tv_events.shutdown()
                    os._exit(1)
                engine.begin_load_version(target,
                                          published_wall=pub_wall)
            now_rel = _time.time() - epoch
            while pending and pending[0].arrival_s <= now_rel:
                r = pending.popleft()
                engine.submit(r, arrival_wall=epoch + r.arrival_s)
            if engine.scheduler.idle and engine._pending_swap is None:
                _time.sleep(min(0.05, max(
                    0.001, (pending[0].arrival_s - now_rel)
                    if pending else 0.05)))
                continue
            if step_delay_s:
                _time.sleep(step_delay_s)
            if bad_step is not None and engine.weights_step == bad_step:
                # the degraded candidate: every step under it drags —
                # its completions (and ONLY its: records are stamped
                # with model_version) blow the latency SLO
                _time.sleep(bad_delay_s)
            try:
                finished = engine.step()
            except FaultInjected:
                retries += 1
                if retries > max_retries:
                    raise
                finished = []
            for rec in finished:
                log.write(json.dumps({
                    "id": rec["id"], "tokens": rec["tokens"],
                    "prompt_tokens": rec["prompt_tokens"],
                    "latency_s": round(rec["latency_s"], 6),
                    "model_version": rec["model_version"],
                    "gen": gen}) + "\n")
                served += 1
            step += 1
    elastic.heartbeat(step)
    print(f"[gen {gen} rollout-{task}] served {served}, final "
          f"v{engine.weights_step}, swaps={engine.swaps}, "
          f"{retries} injected-fault retries", flush=True)
    goodput.activate(None)
    if tdir:
        tv_events.shutdown()
    bootstrap.shutdown()
    return task, served, engine.weights_step


def build_policy(args):
    from distributed_tensorflow_tpu.resilience.rollout import (
        RolloutPolicy)
    from distributed_tensorflow_tpu.telemetry import slo as tv_slo
    slo = tv_slo.SLO("rollout_p99_latency", "latency", objective=0.9,
                     threshold_s=args.latency_slo_ms / 1e3,
                     windows=((args.burn_window_long,
                               args.burn_window_short,
                               args.burn_threshold),))
    return RolloutPolicy(
        fire_consecutive=args.fire_consecutive,
        clear_hold_s=args.clear_hold,
        clear_burn=args.clear_burn,
        cooldown_s=args.cooldown,
        interval_s=0.25,
        min_evidence=args.min_evidence,
        slo=slo)


def run_rollout(args) -> dict:
    """One supervised rollout run; returns the analysis summary (also
    written to ``<telemetry-dir>/rollout-summary.json``)."""
    import tempfile

    from distributed_tensorflow_tpu.resilience.rollout import (
        RolloutController)
    from distributed_tensorflow_tpu.resilience.supervisor import (
        RecoverySupervisor, seeded_kill_plan)
    from distributed_tensorflow_tpu.resilience.autoscaler import (
        serving_records_fn)

    tdir = args.telemetry_dir or tempfile.mkdtemp(prefix="dtx_rollout_")
    os.makedirs(tdir, exist_ok=True)
    ckpt_dir = args.ckpt_dir or os.path.join(tdir, "ckpt")
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(_REPO, ".cache", "dtx_jax_cache"))
    os.environ.setdefault(
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    os.environ.setdefault(
        "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    published_wall = write_snapshots(ckpt_dir,
                                     null_swap=args.null_swap)
    assignment_path = os.path.join(tdir, "rollout-target.json")
    policy = build_policy(args)
    ctrl = RolloutController(
        [str(i) for i in range(args.replicas)],
        base_step=BASE_STEP, target_step=TARGET_STEP,
        policy=policy, assignment_path=assignment_path,
        published_wall=published_wall,
        records_fn=serving_records_fn(tdir))
    kill_plan = (seeded_kill_plan(args.seed, args.replicas,
                                  kills=args.kills,
                                  step_range=tuple(args.kill_steps))
                 if args.kills else ())
    sup = RecoverySupervisor(
        rollout_replica,
        num_workers=args.replicas,
        args=(tdir, ckpt_dir, assignment_path, args.seed),
        kwargs=dict(duration_s=args.duration, qps=args.qps,
                    step_delay_s=args.step_delay,
                    bad_step=(TARGET_STEP if args.bad_canary else None),
                    bad_delay_s=args.bad_delay,
                    restart_mode=args.restart_mode),
        telemetry_dir=tdir,
        autoscaler=ctrl,
        kill_plan=kill_plan,
        max_restarts=max(6, 2 * args.replicas + 2 * args.kills),
        generation_timeout_s=args.generation_timeout)
    print(f"live rollout: {args.replicas} replica(s), v{BASE_STEP} -> "
          f"v{TARGET_STEP}"
          f"{' (null swap)' if args.null_swap else ''}"
          f"{' (bad canary)' if args.bad_canary else ''}"
          f"{' (restart mode)' if args.restart_mode else ''}"
          f"{f' ({args.kills} seeded kill(s))' if args.kills else ''}, "
          f"{args.duration}s @ {args.qps} qps", flush=True)
    sup.run()
    summary = analyze(tdir, ckpt_dir, args=args, controller=ctrl)
    with open(os.path.join(tdir, "rollout-summary.json"), "w") as f:
        json.dump(summary, f, indent=2)
    return summary


def analyze(tdir: str, ckpt_dir: str, *, args,
            controller=None) -> dict:
    """The rollout table, recomputed from telemetry + completion logs
    (nothing self-reported): coverage, per-version byte-identity
    against pure-engine references, swap/restart freshness, decisions,
    the priced ``rollout`` badput bucket and the ledger identity."""
    from distributed_tensorflow_tpu.resilience.rollout import (
        read_assignment, version_step)
    from distributed_tensorflow_tpu.serving.replica import (
        completed_ids_all)
    from distributed_tensorflow_tpu.telemetry import events as tv_events
    from distributed_tensorflow_tpu.telemetry import goodput as tv_goodput
    from distributed_tensorflow_tpu.telemetry import slo as tv_slo

    workload = rollout_workload(args.seed, duration_s=args.duration,
                                qps=args.qps)
    by_id = {r.id: r for r in workload}
    events = tv_events.read_run(tdir)
    flat = [e for evs in events.values() for e in evs]

    # --- coverage: the zero-dropped gate
    served = completed_ids_all(tdir)
    served = {k: v for k, v in served.items() if not
              k.startswith("warmup")}
    missing = sorted(set(by_id) - set(served))
    summary: dict = {
        "seed": args.seed,
        "mode": {"null_swap": args.null_swap,
                 "bad_canary": args.bad_canary,
                 "restart_mode": args.restart_mode,
                 "kills": args.kills},
        "requests": {"scheduled": len(workload), "served": len(served),
                     "dropped": len(missing),
                     "missing_ids": missing[:8]},
    }

    # --- versions: every completion's tokens must equal the PURE
    # output of the version it is stamped with (no mixed streams)
    versions: dict = {}
    for pid, evs in events.items():
        for e in evs:
            if e.get("ev") == "serve.request" and "id" in e:
                versions[e["id"]] = e.get("model_version")
    refs = {step: reference_outputs(
                ckpt_dir, [by_id[i] for i in sorted(set(served)
                                                    & set(by_id))],
                step)
            for step in (BASE_STEP, TARGET_STEP)}
    mixed = []
    unversioned = 0
    for rid, tokens in served.items():
        step = version_step(versions.get(rid))
        if step is None:
            unversioned += 1
            continue
        if list(tokens) != refs[step].get(rid):
            mixed.append(rid)
    summary["versions"] = {
        "mixed_or_wrong": len(mixed), "examples": mixed[:8],
        "unversioned": unversioned,
        "by_version": {str(s): sum(
            1 for rid in served
            if version_step(versions.get(rid)) == s)
            for s in (BASE_STEP, TARGET_STEP)}}

    # --- swaps + freshness (publish -> per-replica serve.swap)
    swaps = [e for e in flat if e.get("ev") == "serve.swap"]
    summary["swaps"] = {
        "hot": sum(1 for e in swaps if e.get("mode") == "swap"),
        "restart": sum(1 for e in swaps if e.get("mode") == "restart"),
        "requeued": sum(int(e.get("requeued") or 0) for e in swaps),
        "errors": sum(1 for e in flat
                      if e.get("ev") == "serve.swap_error")}
    fresh = tv_slo.freshness_records_from_events(events)
    target_fresh = [r["freshness_s"] for r in fresh
                    if r.get("step") == TARGET_STEP
                    and isinstance(r.get("freshness_s"), (int, float))]
    if target_fresh:
        lst = sorted(target_fresh)

        def _pct(q: float) -> float:
            return lst[min(len(lst) - 1, round(q * (len(lst) - 1)))]

        summary["freshness"] = {
            "n": len(lst),
            "p50_s": round(_pct(0.5), 3),
            "p99_s": round(_pct(0.99), 3),
            "max_s": round(lst[-1], 3)}

    # --- decisions + final state
    decisions = [e for e in flat if e.get("ev") == "rollout.decision"]
    assignment = read_assignment(
        os.path.join(tdir, "rollout-target.json")) or {}
    summary["rollout"] = {
        "decisions": [{k: d.get(k) for k in
                       ("action", "replica", "step", "reason")}
                      for d in decisions],
        "state": assignment.get("state"),
        "assignment": assignment.get("assignment"),
        "rolled_back": assignment.get("state") == "rolled_back",
        "promoted": assignment.get("state") == "promoted"}
    if controller is not None:
        summary["rollout"]["controller_state"] = controller.state

    # --- the ledger: transitions priced, identity intact
    led = tv_goodput.ledger_from_run(tdir)
    wall = led["wall_s"]
    summary["ledger"] = {
        "wall_s": round(wall, 3),
        "goodput_frac": (round(led["goodput_frac"], 4)
                         if led["goodput_frac"] is not None else None),
        "rollout_badput_s": round(led["badput_s"].get("rollout", 0.0),
                                  3),
        "identity_error_frac": (round(abs(led["identity_error_s"])
                                      / wall, 6) if wall > 0 else None),
    }
    return summary


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--telemetry-dir", default=None)
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--duration", type=float, default=24.0)
    ap.add_argument("--qps", type=float, default=5.0)
    ap.add_argument("--step-delay", type=float, default=0.02)
    # scenario switches (module docstring)
    ap.add_argument("--null-swap", action="store_true")
    ap.add_argument("--bad-canary", action="store_true")
    ap.add_argument("--bad-delay", type=float, default=0.4)
    ap.add_argument("--restart-mode", action="store_true")
    ap.add_argument("--kills", type=int, default=0)
    ap.add_argument("--kill-steps", type=int, nargs=2,
                    default=(20, 120),
                    help="heartbeat-step window seeded kills land in "
                         "(mid-swap territory at the default pacing)")
    # canary policy knobs (the README "Live rollout" table)
    ap.add_argument("--latency-slo-ms", type=float, default=500.0)
    ap.add_argument("--burn-threshold", type=float, default=2.0)
    ap.add_argument("--burn-window-long", type=float, default=6.0)
    ap.add_argument("--burn-window-short", type=float, default=2.0)
    ap.add_argument("--fire-consecutive", type=int, default=2)
    ap.add_argument("--clear-burn", type=float, default=1.0)
    ap.add_argument("--clear-hold", type=float, default=2.0)
    ap.add_argument("--cooldown", type=float, default=2.0)
    ap.add_argument("--min-evidence", type=int, default=3)
    ap.add_argument("--generation-timeout", type=float, default=600.0)
    args = ap.parse_args()

    summary = run_rollout(args)
    r = summary["requests"]
    v = summary["versions"]
    print(f"rollout table: state={summary['rollout']['state']} "
          f"dropped={r['dropped']} mixed={v['mixed_or_wrong']} "
          f"swaps={summary['swaps']['hot']}h/"
          f"{summary['swaps']['restart']}r "
          f"freshness_p99={summary.get('freshness', {}).get('p99_s', '-')}s "
          f"rollout_badput={summary['ledger']['rollout_badput_s']}s "
          f"identity_err={summary['ledger']['identity_error_frac']}")
    print(f"summary: {os.path.join(args.telemetry_dir or '', 'rollout-summary.json')}")


if __name__ == "__main__":
    main()


