#!/usr/bin/env python
"""Train Wide&Deep from TFRecord files of tf.Example protos.

The migration path a reference user actually takes: their click logs are
TFRecord shards of ``tf.train.Example`` (written by the reference's
tf.data pipelines). This script

1. writes synthetic click data as sharded tf.Example TFRecords
   (stand-in for an existing dataset — delete this step for real data),
2. builds the host pipeline with the framework's own parser:
   ``Dataset.from_files(shards, example_reader(spec)).map.shuffle.batch``,
   FILE auto-sharded across processes with ``auto_shard_dataset``
   (≙ input_ops.py:28 FILE policy — the transform chain replays on each
   process's shard of the file list), each process assembling its local
   slice into the global batch,
3. trains the Wide&Deep model with one jit SPMD step over a dp mesh.

    python examples/train_from_tfrecords.py --steps 60
"""

import argparse
import os
import tempfile

import jax
import numpy as np

from distributed_tensorflow_tpu.cluster import bootstrap
from distributed_tensorflow_tpu.cluster.topology import make_mesh
from distributed_tensorflow_tpu.input import (
    Dataset, FixedLenFeature, encode_example, example_reader)
from distributed_tensorflow_tpu.input.native_loader import write_tfrecords
from distributed_tensorflow_tpu.models import wide_deep as wd


def write_click_shards(cfg, out_dir: str, n_shards: int = 4,
                       per_shard: int = 512) -> list:
    """Synthetic click logs as tf.Example TFRecord shards."""
    data = wd.synthetic_clicks(cfg, n_shards * per_shard)
    paths = []
    for s in range(n_shards):
        lo = s * per_shard
        payloads = [
            encode_example({
                "dense": np.asarray(data["dense"][i]),
                "categorical": np.asarray(data["categorical"][i],
                                          np.int64),
                "label": np.asarray([int(data["label"][i])], np.int64),
            })
            for i in range(lo, lo + per_shard)
        ]
        path = os.path.join(out_dir, f"clicks-{s:05d}-of-{n_shards:05d}")
        write_tfrecords(path, payloads)
        paths.append(path)
    return paths


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--global-batch", type=int, default=128)
    ap.add_argument("--data-dir", default=None,
                    help="existing TFRecord dir (default: write synthetic)")
    args = ap.parse_args()

    bootstrap.initialize()
    cfg = wd.WideDeepConfig.tiny()

    if args.data_dir:
        files = sorted(os.path.join(args.data_dir, f)
                       for f in os.listdir(args.data_dir))
    else:
        tmp = tempfile.mkdtemp(prefix="clicks_")
        files = write_click_shards(cfg, tmp)
        print(f"wrote {len(files)} synthetic TFRecord shards to {tmp}")

    spec = {
        "dense": FixedLenFeature((cfg.num_dense_features,), np.float32),
        "categorical": FixedLenFeature((len(cfg.vocab_sizes),), np.int64),
        "label": FixedLenFeature((1,), np.int64),
    }

    def to_batch(ex):
        return {"dense": ex["dense"],
                "categorical": ex["categorical"].astype(np.int32),
                "label": ex["label"][0].astype(np.int32)}

    runtime = bootstrap.runtime()
    per_process = args.global_batch // runtime.num_processes
    # repeat BEFORE shuffle: a fresh shuffle pass per epoch (the
    # reshuffle_each_iteration=True behavior reference pipelines expect).
    ds = (Dataset.from_files(files, example_reader(spec))
          .map(to_batch)
          .repeat()
          .shuffle(1024, seed=runtime.process_id)
          .batch(per_process, drop_remainder=True)
          .prefetch(2))
    from distributed_tensorflow_tpu.input.dataset import (
        AutoShardPolicy, auto_shard_dataset)
    # FILE policy: each process re-reads ONLY its slice of the shard
    # list; the map/shuffle/batch chain replays on top.
    ds = auto_shard_dataset(ds, runtime.num_processes,
                            runtime.process_id, AutoShardPolicy.AUTO)

    mesh = make_mesh({"dp": -1})
    state, step_fn = wd.make_sharded_train_step(
        cfg, mesh, args.global_batch)

    from jax.sharding import NamedSharding, PartitionSpec as P
    sharding = NamedSharding(mesh, P("dp"))
    it = iter(ds)
    losses = []
    for i in range(args.steps):
        host = next(it)          # this process's per_process-sized slice
        batch = {k: jax.make_array_from_process_local_data(sharding, v)
                 for k, v in host.items()}
        state, metrics = step_fn(state, batch)
        losses.append(float(metrics["loss"]))
        if i % 20 == 0 or i == args.steps - 1:
            print(f"step {i}: loss={losses[-1]:.4f}", flush=True)
    first = sum(losses[:10]) / min(10, len(losses))
    last = sum(losses[-10:]) / min(10, len(losses))
    print(f"loss first-10 {first:.4f} -> last-10 {last:.4f} "
          f"({'improved' if last < first else 'NO IMPROVEMENT'})")
    bootstrap.shutdown()


if __name__ == "__main__":
    main()
