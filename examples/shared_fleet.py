#!/usr/bin/env python
"""Shared training+serving fleet with SLO-driven autoscaling (ISSUE 13).

A FIXED worker budget (default 3 processes) split between an elastic
MNIST training job (examples/train_mnist.py elastic_worker) and
transformer serving replicas (serving/replica.serving_replica), both
under real recovery supervisors composed by
``resilience.autoscaler.SharedFleetSupervisor``. A seeded open-loop
traffic spike saturates the serving replica; the p99-latency burn
windows fire; the arbiter makes training DONATE a worker (topology-
elastic shrink — the trainer resumes N-1-sharded from its warm
snapshot tiers, no cold restart) and grows serving; once the burn
clears and holds, serving drains the extra replica (zero dropped
requests) and training RECLAIMS the capacity. Every reform gap is
priced into the ``scale_transition`` badput bucket, so
``wall == goodput + Σ badput`` holds through the whole maneuver.

Run it::

    python examples/shared_fleet.py --telemetry-dir /tmp/fleet --seed 0

then read the run::

    python tools/health_report.py /tmp/fleet/serve     # SLO + ledger
    python tools/health_report.py /tmp/fleet/train     # donation cost
    cat /tmp/fleet/spike-summary.json                  # the spike table

``tools/chaos_sweep.py --spike`` sweeps seeds through this script and
gates scale-up firing, SLO recovery, the ledger identity and capacity
return; ``bench.py --autoscale`` captures AUTOSCALE_r*.json from the
same summary.
"""

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def build_policy(args):
    from distributed_tensorflow_tpu.resilience.autoscaler import (
        AutoscalePolicy,
    )
    from distributed_tensorflow_tpu.telemetry import slo as tv_slo
    slo = tv_slo.SLO("p99_latency", "latency", objective=0.99,
                     threshold_s=args.latency_slo_ms / 1e3,
                     windows=((args.burn_window_long,
                               args.burn_window_short,
                               args.burn_threshold),))
    return AutoscalePolicy(
        min_replicas=args.min_replicas,
        max_replicas=args.max_replicas,
        train_floor=args.train_floor,
        fire_consecutive=args.fire_consecutive,
        clear_burn=args.clear_burn,
        clear_hold_s=args.clear_hold,
        cooldown_s=args.cooldown,
        min_evidence=args.min_evidence,
        interval_s=0.5,
        slo=slo)


def spike_kwargs(args) -> dict:
    return dict(duration_s=args.duration, base_qps=args.base_qps,
                spike_qps=args.spike_qps,
                spike_start_s=args.spike_start,
                spike_end_s=args.spike_end,
                linger_s=args.linger)


def run_fleet(args) -> dict:
    """Run the shared fleet once; returns the analysis summary (also
    written to ``<telemetry-dir>/spike-summary.json``)."""
    import tempfile

    from distributed_tensorflow_tpu.resilience.autoscaler import (
        SharedFleetSupervisor,
    )
    from distributed_tensorflow_tpu.serving.replica import serving_replica
    from examples.train_mnist import elastic_worker

    tdir = args.telemetry_dir or tempfile.mkdtemp(prefix="shared_fleet_")
    os.makedirs(tdir, exist_ok=True)
    ckpt_dir = args.ckpt_dir or os.path.join(tdir, "ckpt")
    # persistent XLA compile cache for every spawned worker (the
    # tests/conftest.py discipline): a scale reform respawns processes,
    # and without the cache each incarnation pays a multi-second
    # recompile that both slows the reform and poisons the latency SLO
    # stream with compile-tail completions
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(_REPO, ".cache", "dtx_jax_cache"))
    os.environ.setdefault(
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    os.environ.setdefault(
        "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    policy = build_policy(args)
    spike = spike_kwargs(args)
    fleet = SharedFleetSupervisor(
        budget=args.budget,
        train_fn=elastic_worker,
        train_args=(ckpt_dir, args.train_steps, args.save_every,
                    64, 1e-3),
        train_kwargs={"local_dir": ckpt_dir.rstrip("/") + ".local",
                      "snapshot_every": args.snapshot_every,
                      "step_delay_s": args.train_step_delay},
        serve_fn=serving_replica,
        serve_args=(tdir, 0, args.seed),
        serve_kwargs={"spike": spike,
                      "step_delay_s": args.serve_step_delay,
                      "engine_kwargs": {"max_slots": args.max_slots,
                                        "num_blocks": 96}},
        train_workers=args.train_workers,
        serve_replicas=args.replicas,
        policy=policy,
        telemetry_dir=tdir,
        train_sup_kwargs=dict(
            generation_timeout_s=args.generation_timeout),
        serve_sup_kwargs=dict(
            generation_timeout_s=args.generation_timeout,
            drain_timeout_s=15.0))
    print(f"shared fleet: budget {args.budget} = "
          f"{args.train_workers} trainer(s) + {args.replicas} "
          f"replica(s); spike {args.spike_qps} qps in "
          f"[{args.spike_start}, {args.spike_end}]s of "
          f"{args.duration}s @ base {args.base_qps} qps", flush=True)
    result = fleet.run()
    print(f"fleet run done: serve scales={result.serve_scales} "
          f"train scales={result.train_scales} final split="
          f"{result.final_train_workers}+{result.final_serve_replicas}"
          f"{' (training stopped)' if result.train_stopped else ''}",
          flush=True)
    summary = analyze(tdir, seed=args.seed, spike=spike, policy=policy,
                      train_workers=args.train_workers)
    summary["result"] = {
        "serve_scales": result.serve_scales,
        "train_scales": result.train_scales,
        "final_train_workers": result.final_train_workers,
        "final_serve_replicas": result.final_serve_replicas,
        "train_stopped": result.train_stopped,
    }
    with open(os.path.join(tdir, "spike-summary.json"), "w") as f:
        json.dump(summary, f, indent=2)
    return summary


def _phase_ledger(events_by_pid: dict, lo: float, hi: float) -> dict:
    """Goodput over an event-wall slice (phase tables: before/during/
    after the spike). The walker is self-contained, so the identity
    holds within the slice too."""
    from distributed_tensorflow_tpu.telemetry import goodput
    sliced = {pid: [e for e in events
                    if isinstance(e.get("wall"), (int, float))
                    and lo <= e["wall"] < hi]
              for pid, events in events_by_pid.items()}
    return goodput.ledger_from_events(
        {p: ev for p, ev in sliced.items() if ev})


def analyze(tdir: str, *, seed: int, spike: dict, policy,
            train_workers: int) -> dict:
    """The spike table: scale-up latency, SLO recovery time, goodput
    before/during/after, transition pricing, capacity return — all
    recomputed from the run's telemetry (nothing self-reported)."""
    from distributed_tensorflow_tpu.serving.replica import (
        completed_ids_all, seeded_spike_schedule,
    )
    from distributed_tensorflow_tpu.telemetry import events as tv_events
    from distributed_tensorflow_tpu.telemetry import goodput as tv_goodput
    from distributed_tensorflow_tpu.telemetry import slo as tv_slo

    serve_dir = os.path.join(tdir, "serve")
    train_dir = os.path.join(tdir, "train")
    with open(os.path.join(tdir, "run-epoch.json")) as f:
        epoch = float(json.load(f)["epoch"])
    spike_start_wall = epoch + spike["spike_start_s"]
    serve_events = tv_events.read_run(serve_dir)
    train_events = tv_events.read_run(train_dir)
    flat_serve = [e for evs in serve_events.values() for e in evs]
    flat_train = [e for evs in train_events.values() for e in evs]

    def _applied(flat, direction=None, reason=None):
        out = [e for e in flat if e.get("ev") == "scale.applied"]
        if direction:
            out = [e for e in out if e.get("direction") == direction]
        if reason:
            out = [e for e in out if e.get("reason") == reason]
        return out

    decisions = [e for e in flat_serve
                 if e.get("ev") == "scale.decision"]
    up_dec = [d for d in decisions if d.get("direction") == "up"
              and d.get("outcome") in ("requested", "donate")]
    ups = _applied(flat_serve, "up")
    downs = _applied(flat_serve, "down")
    donations = _applied(flat_train, "down", "donate_to_serving")
    reclaims = _applied(flat_train, "up", "reclaim")

    records = tv_slo.records_from_events(serve_events)
    slo = policy.slo
    lw, sw, _burn = slo.windows[0]

    def burn_at(t: float) -> "tuple[float | None, float | None]":
        w = tv_slo.burn_windows(records, slo, now=t)[0]
        return w["burn_long"], w["burn_short"]

    summary: dict = {"seed": seed, "spike": dict(spike),
                     "slo": {"threshold_s": slo.threshold_s,
                             "windows": list(slo.windows)},
                     "epoch": epoch}
    # --- scale-up latency: spike start -> decision -> applied
    su: dict = {"decisions": len(decisions),
                "applied_up": len(ups), "applied_down": len(downs),
                "donations": len(donations), "reclaims": len(reclaims)}
    if up_dec:
        su["detect_s"] = round(up_dec[0]["wall"] - spike_start_wall, 3)
    if ups:
        su["scale_up_latency_s"] = round(
            ups[0]["wall"] - spike_start_wall, 3)
        if up_dec:
            su["actuation_s"] = round(
                ups[0]["wall"] - up_dec[0]["wall"], 3)
    summary["scale_up"] = su
    # --- burn trail + SLO recovery: earliest post-scale-up instant
    # where BOTH windows are back under 1.0x and stay there
    peak = max((b for b in (burn_at(t / 2.0 + spike_start_wall)[1]
                            for t in range(0, int(2 * (
                                spike["duration_s"]
                                - spike["spike_start_s"] + 10))))
                if b is not None), default=None)
    summary["burn_peak_short"] = (round(peak, 2)
                                  if peak is not None else None)
    # recovery is evidence-based, not silence-based: the reform gap has
    # no completions at all (burn reads None), which must not count as
    # "recovered". The SLO has recovered once bad completions STOP and
    # good traffic follows — measured over the span between the
    # scale-up and the scale-down reform (the scale-down's own respawn
    # gap delays whatever arrives during it; that is transition cost,
    # reported separately as post_reclaim_bad, not a failure of the
    # recovery the scale-up bought).
    recovery_wall = None
    post_reclaim_bad = 0
    if ups and records:
        last_wall = max(r["wall"] for r in records)
        span_end = downs[0]["wall"] if downs else last_wall
        in_span = [r for r in records if r["wall"] <= span_end]
        post_reclaim_bad = sum(
            1 for r in records
            if r["wall"] > span_end and slo.is_bad(r))
        bad_walls = [r["wall"] for r in in_span if slo.is_bad(r)]
        if not bad_walls:
            recovery_wall = ups[0]["wall"]
        else:
            candidate = max(max(bad_walls) + sw, ups[0]["wall"])
            good_after = [r for r in in_span
                          if r["wall"] > max(bad_walls)
                          and not slo.is_bad(r)]
            if candidate < span_end and good_after:
                recovery_wall = candidate
        if recovery_wall is not None:
            bl, bs = burn_at(span_end)
            # the burn must actually read clean at the span's end
            if (bl is not None and bl > 1.0) or \
                    (bs is not None and bs > 1.0):
                recovery_wall = None
        if recovery_wall is not None:
            summary["slo_recovery_s"] = round(
                recovery_wall - ups[0]["wall"], 3)
    summary["slo_recovered"] = recovery_wall is not None
    summary["post_reclaim_bad"] = post_reclaim_bad
    # --- capacity return
    summary["capacity_returned"] = bool(
        reclaims and reclaims[-1].get("to_workers") == train_workers)
    # --- zero dropped requests
    sched = seeded_spike_schedule(
        seed, **{k: v for k, v in spike.items() if k != "linger_s"})
    seen = completed_ids_all(tdir)
    missing = sorted({r.id for r in sched} - set(seen))
    summary["requests"] = {"scheduled": len(sched),
                           "served": len(seen),
                           "dropped": len(missing),
                           "missing_ids": missing[:8]}
    # --- goodput: whole-run per job + serve phases before/during/after
    ledgers = {}
    for role, d in (("serve", serve_dir), ("train", train_dir)):
        led = tv_goodput.ledger_from_run(d)
        wall = led["wall_s"]
        ledgers[role] = {
            "wall_s": round(wall, 3),
            "goodput_frac": (round(led["goodput_frac"], 4)
                             if led["goodput_frac"] is not None
                             else None),
            "identity_error_frac": (
                round(abs(led["identity_error_s"]) / wall, 6)
                if wall > 0 else None),
            "badput_s": {k: round(v, 3)
                         for k, v in led["badput_s"].items()},
        }
    summary["ledger"] = ledgers
    phases = {}
    bounds = {
        "before": (epoch, spike_start_wall),
        "during": (spike_start_wall,
                   recovery_wall if recovery_wall is not None
                   else epoch + spike["spike_end_s"]),
        "after": (recovery_wall if recovery_wall is not None
                  else epoch + spike["spike_end_s"],
                  epoch + spike["duration_s"]
                  + spike.get("linger_s", 0.0)),
    }
    for name, (lo, hi) in bounds.items():
        led = _phase_ledger(serve_events, lo, hi)
        phases[name] = {
            "wall_s": round(led["wall_s"], 3),
            "goodput_frac": (round(led["goodput_frac"], 4)
                             if led["goodput_frac"] is not None
                             else None)}
        in_phase = [r for r in records if lo <= r["wall"] < hi
                    and isinstance(r.get("latency_s"), (int, float))]
        if in_phase:
            lats = sorted(r["latency_s"] for r in in_phase)
            phases[name]["p99_latency_ms"] = round(
                lats[min(len(lats) - 1,
                         int(0.99 * (len(lats) - 1)))] * 1e3, 1)
            phases[name]["completions"] = len(in_phase)
    summary["phases"] = phases
    # --- warm resume evidence: restore tiers in the train job's scale
    # generations (the donation must NOT be a cold restart)
    scale_gens = {e.get("generation") for e in flat_train
                  if e.get("ev") == "scale.applied"}
    tiers = [{"generation": e.get("generation"), "tier": e.get("tier"),
              "step": e.get("step"),
              "best_available": e.get("best_available")}
             for e in flat_train
             if e.get("ev") == "recovery.restore_tier"
             and e.get("generation") in scale_gens]
    summary["train_restore_tiers"] = tiers
    summary["train_warm_resume"] = bool(
        tiers and all(t["tier"] not in (None, "none") for t in tiers)
        and any(t["tier"] in ("host", "peer", "memory")
                for t in tiers))
    return summary


def main():
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    ap.add_argument("--budget", type=int, default=3,
                    help="fixed worker budget shared by both jobs")
    ap.add_argument("--train-workers", type=int, default=2)
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--seed", type=int, default=0,
                    help="workload seed (arrivals + prompts)")
    ap.add_argument("--telemetry-dir", default=None)
    ap.add_argument("--ckpt-dir", default=None)
    # workload shape
    ap.add_argument("--duration", type=float, default=50.0)
    ap.add_argument("--base-qps", type=float, default=1.5)
    ap.add_argument("--spike-qps", type=float, default=6.0)
    ap.add_argument("--spike-start", type=float, default=10.0)
    ap.add_argument("--spike-end", type=float, default=26.0)
    ap.add_argument("--linger", type=float, default=25.0,
                    help="replicas keep serving (idle) this long past "
                         "the schedule so the clear window and the "
                         "reclaim happen in-run")
    # capacity/pacing
    ap.add_argument("--max-slots", type=int, default=2,
                    help="decode slots per replica (capacity knob)")
    ap.add_argument("--serve-step-delay", type=float, default=0.15,
                    help="per-engine-step pacing: sets one replica's "
                         "capacity (~3 req/s) just above base-qps and "
                         "well under spike-qps, so the spike — and "
                         "only the spike — saturates")
    ap.add_argument("--train-step-delay", type=float, default=0.05)
    ap.add_argument("--train-steps", type=int, default=100000,
                    help="effectively 'train forever'; the fleet stops "
                         "the trainer once serving completes")
    ap.add_argument("--save-every", type=int, default=40)
    ap.add_argument("--snapshot-every", type=int, default=10)
    # policy knobs (the README Autoscaling table)
    ap.add_argument("--latency-slo-ms", type=float, default=2000.0)
    ap.add_argument("--min-evidence", type=int, default=4,
                    help="completions required inside the short burn "
                         "window before a firing reading counts — at "
                         "base qps the window can't hold this many, "
                         "so only the spike can fire (no-evidence "
                         "startup blips can't)")
    ap.add_argument("--burn-threshold", type=float, default=2.0)
    ap.add_argument("--burn-window-long", type=float, default=6.0)
    ap.add_argument("--burn-window-short", type=float, default=2.0)
    ap.add_argument("--fire-consecutive", type=int, default=2)
    ap.add_argument("--clear-burn", type=float, default=1.0)
    ap.add_argument("--clear-hold", type=float, default=5.0)
    ap.add_argument("--cooldown", type=float, default=15.0,
                    help="min gap between applied scale actions; keep "
                         "it past long-window + reform time so the "
                         "transition's own slow completions can't "
                         "re-trigger a flap")
    ap.add_argument("--min-replicas", type=int, default=1)
    ap.add_argument("--max-replicas", type=int, default=2)
    ap.add_argument("--train-floor", type=int, default=1)
    ap.add_argument("--generation-timeout", type=float, default=600.0)
    args = ap.parse_args()

    summary = run_fleet(args)
    su = summary["scale_up"]
    print(f"spike table: scale_up_latency="
          f"{su.get('scale_up_latency_s', '-')}s "
          f"(detect {su.get('detect_s', '-')}s + actuate "
          f"{su.get('actuation_s', '-')}s), "
          f"burn peak {summary.get('burn_peak_short')}x, "
          f"slo_recovery={summary.get('slo_recovery_s', '-')}s, "
          f"capacity_returned={summary['capacity_returned']}, "
          f"dropped={summary['requests']['dropped']}")
    for role, led in summary["ledger"].items():
        print(f"  {role}: goodput {led['goodput_frac']}, "
              f"scale_transition {led['badput_s']['scale_transition']}s"
              f", identity err {led['identity_error_frac']}")
    print(f"summary: {os.path.join(args.telemetry_dir or '', 'spike-summary.json')}")


if __name__ == "__main__":
    main()
