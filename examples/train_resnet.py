#!/usr/bin/env python
"""ResNet-50 sync data-parallel training, single- or multi-worker.

≙ the reference's config #2 (BASELINE.md): ResNet-50 ImageNet under
`MultiWorkerMirroredStrategy` with NCCL allreduce (reference:
tensorflow/python/distribute/collective_all_reduce_strategy.py:57).
TPU-native shape: every process holds a slice of one global `jax.Array`
batch; ONE compiled SPMD step runs on the global mesh and GSPMD inserts
the gradient allreduce over ICI/DCN — no per-tensor RPC, no collective
executor.

Input: either synthetic device-resident batches (the perf-isolated
default) or REAL on-disk JPEGs through the parallel host pipeline
(input/image_ops.py + Dataset.map(num_parallel_calls=AUTOTUNE) +
prefetch + InfeedLoop double-buffered device_put), with per-step
infeed-wait reported so host-boundedness is a number, not a guess:

    # single process, all local devices, synthetic batches
    python examples/train_resnet.py --steps 30

    # REAL JPEG path: generate 512 JPEGs on disk, then train from them
    python examples/train_resnet.py --steps 30 --gen-jpegs 512

    # ... or from an existing directory (img_*_cls<label>.jpg layout)
    python examples/train_resnet.py --steps 30 --data-dir /data/jpegs

    # real multi-process sync DP on one box (3 workers, CPU backend),
    # TF_CONFIG injected per process exactly like a cluster launch;
    # JPEG files are FILE-auto-sharded across the workers:
    python examples/train_resnet.py --spawn 3 --steps 10 --gen-jpegs 512
"""

import argparse
import time


def _jpeg_infeed(data_dir: str, runtime, mesh, per_process_batch: int,
                 image_size: int, num_classes: int):
    """files -> FILE-sharded parallel decode pipeline -> InfeedLoop
    staging global jax.Arrays (the host data plane of this example)."""
    import glob
    import os

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu.input.dataset import AUTOTUNE
    from distributed_tensorflow_tpu.input.image_ops import jpeg_pipeline
    from distributed_tensorflow_tpu.training.loops import InfeedLoop

    files = sorted(glob.glob(os.path.join(data_dir, "*.jpg")))
    if len(files) < runtime.num_processes:
        raise SystemExit(
            f"{data_dir} has {len(files)} JPEGs; FILE sharding needs at "
            f"least one per process ({runtime.num_processes})")
    ds = jpeg_pipeline(
        files, batch_size=per_process_batch, image_size=image_size,
        num_parallel_calls=AUTOTUNE, prefetch_depth=4,
        num_shards=runtime.num_processes,
        shard_index=runtime.process_id)

    sharding = NamedSharding(mesh, P("dp"))

    def place(batch):
        if int(batch["label"].max()) >= num_classes:
            raise ValueError(
                f"label {int(batch['label'].max())} >= num_classes "
                f"{num_classes}; generate the data with matching classes")
        return {
            "image": jax.make_array_from_process_local_data(
                sharding, batch["image"]),
            "label": jax.make_array_from_process_local_data(
                sharding, batch["label"]),
        }

    return InfeedLoop(iter(ds), place_fn=place, buffer_size=3), ds


def worker_main(steps: int, global_batch: int, image_size: int,
                data_dir: str | None = None):
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu.cluster import bootstrap
    from distributed_tensorflow_tpu.cluster.topology import make_mesh
    from distributed_tensorflow_tpu.models import resnet

    runtime = bootstrap.initialize()           # reads TF_CONFIG if present
    mesh = make_mesh({"dp": -1})               # all global devices
    if global_batch < runtime.num_processes:
        raise SystemExit(
            f"--global-batch {global_batch} is smaller than the process "
            f"count {runtime.num_processes}; every process needs >= 1 "
            f"sample")
    if global_batch % runtime.num_processes:
        adjusted = (global_batch // runtime.num_processes
                    * runtime.num_processes)
        print(f"global batch {global_batch} not divisible by "
              f"{runtime.num_processes} processes; using {adjusted}",
              flush=True)
        global_batch = adjusted
    cfg = resnet.ResNetConfig.resnet50() if image_size >= 128 \
        else resnet.ResNetConfig.tiny()
    state, step_fn = resnet.make_sharded_train_step(
        cfg, mesh, global_batch, image_size=image_size)

    # Per-host input feeding (≙ dataset auto-sharding, input_lib.py:729):
    # each process materializes ONLY its slice of the global batch and
    # assembles the global jax.Array from process-local shards.
    sharding = NamedSharding(mesh, P("dp"))
    per_process = global_batch // runtime.num_processes

    infeed = None
    if data_dir is not None:
        infeed, _ds = _jpeg_infeed(data_dir, runtime, mesh, per_process,
                                   image_size, cfg.num_classes)
        next_batch = infeed.next
    else:
        local = resnet.synthetic_images(
            per_process, image_size, cfg.num_classes,
            seed=runtime.process_id)
        static = {
            "image": jax.make_array_from_process_local_data(
                sharding, local["image"]),
            "label": jax.make_array_from_process_local_data(
                sharding, local["label"]),
        }
        next_batch = lambda: static

    t0, imgs = None, 0
    for i in range(steps):
        batch = next_batch()
        state, metrics = step_fn(state, batch)
        if i == 0:                      # skip compile in the rate
            jax.block_until_ready(metrics["loss"])
            if infeed is not None:      # spin-up wait is not steady state
                infeed.total_wait_s = 0.0
                infeed.batches = 0
            t0 = time.time()
        else:
            imgs += global_batch
        if i % 10 == 0 or i == steps - 1:
            print(f"[p{runtime.process_id}] step {i}: "
                  f"loss={float(metrics['loss']):.4f} "
                  f"acc={float(metrics['accuracy']):.3f}", flush=True)
    jax.block_until_ready(state["step"])
    dt = time.time() - t0
    if runtime.is_chief and imgs:
        print(f"throughput: {imgs / dt:,.1f} images/sec "
              f"({runtime.num_processes} processes, "
              f"{len(jax.devices())} devices)", flush=True)
        if infeed is not None:
            frac = infeed.wait_fraction(dt)
            print(f"infeed wait: {infeed.total_wait_s * 1e3:.1f} ms over "
                  f"{infeed.batches} steps = {frac:.1%} of wall time "
                  f"({'host-bound' if frac >= 0.05 else 'device-bound'})",
                  flush=True)
    final_loss = float(metrics["loss"])
    if infeed is not None:
        infeed.stop()
    bootstrap.shutdown()
    return final_loss


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--global-batch", type=int, default=32)
    ap.add_argument("--image-size", type=int, default=32,
                    help="32 = tiny config for CPU demo; 224 = ResNet-50")
    ap.add_argument("--data-dir", default=None,
                    help="directory of img_*_cls<label>.jpg files; train "
                         "on REAL decoded JPEGs through the parallel "
                         "host pipeline")
    ap.add_argument("--gen-jpegs", type=int, default=0,
                    help="generate N JPEGs on disk first and train from "
                         "them (implies the real-data path)")
    ap.add_argument("--spawn", type=int, default=0,
                    help="spawn N local worker processes with TF_CONFIG "
                         "(multi-worker demo on one box)")
    args = ap.parse_args()

    data_dir = args.data_dir
    if args.gen_jpegs:
        import tempfile

        from distributed_tensorflow_tpu.input.image_ops import (
            generate_jpeg_directory)
        num_classes = 1000 if args.image_size >= 128 else 10
        data_dir = tempfile.mkdtemp(prefix="dtx_jpegs_")
        # sources ~25% larger than the train crop (RandomCrop headroom)
        generate_jpeg_directory(data_dir, args.gen_jpegs,
                                image_size=args.image_size * 5 // 4,
                                num_classes=num_classes)
        print(f"generated {args.gen_jpegs} JPEGs in {data_dir}",
              flush=True)

    if args.spawn > 1:
        from distributed_tensorflow_tpu.testing import multi_process_runner
        result = multi_process_runner.run(
            worker_main, num_workers=args.spawn,
            args=(args.steps, args.global_batch, args.image_size,
                  data_dir),
            timeout=900)
        losses = result.return_values
        print(f"all {len(losses)} workers done; final losses {losses}")
        assert len(set(round(x, 5) for x in losses)) == 1, \
            "sync DP must keep workers bit-identical"
    else:
        worker_main(args.steps, args.global_batch, args.image_size,
                    data_dir)


if __name__ == "__main__":
    main()
