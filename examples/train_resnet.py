#!/usr/bin/env python
"""ResNet-50 sync data-parallel training, single- or multi-worker.

≙ the reference's config #2 (BASELINE.md): ResNet-50 ImageNet under
`MultiWorkerMirroredStrategy` with NCCL allreduce (reference:
tensorflow/python/distribute/collective_all_reduce_strategy.py:57).
TPU-native shape: every process holds a slice of one global `jax.Array`
batch; ONE compiled SPMD step runs on the global mesh and GSPMD inserts
the gradient allreduce over ICI/DCN — no per-tensor RPC, no collective
executor.

    # single process, all local devices
    python examples/train_resnet.py --steps 30

    # real multi-process sync DP on one box (3 workers, CPU backend),
    # TF_CONFIG injected per process exactly like a cluster launch:
    python examples/train_resnet.py --spawn 3 --steps 10

    # on a real cluster: launch one process per host with TF_CONFIG set
    # (TFConfigClusterResolver semantics) and no --spawn flag.
"""

import argparse
import time


def worker_main(steps: int, global_batch: int, image_size: int):
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu.cluster import bootstrap
    from distributed_tensorflow_tpu.cluster.topology import make_mesh
    from distributed_tensorflow_tpu.models import resnet

    runtime = bootstrap.initialize()           # reads TF_CONFIG if present
    mesh = make_mesh({"dp": -1})               # all global devices
    if global_batch < runtime.num_processes:
        raise SystemExit(
            f"--global-batch {global_batch} is smaller than the process "
            f"count {runtime.num_processes}; every process needs >= 1 "
            f"sample")
    if global_batch % runtime.num_processes:
        adjusted = (global_batch // runtime.num_processes
                    * runtime.num_processes)
        print(f"global batch {global_batch} not divisible by "
              f"{runtime.num_processes} processes; using {adjusted}",
              flush=True)
        global_batch = adjusted
    cfg = resnet.ResNetConfig.resnet50() if image_size >= 128 \
        else resnet.ResNetConfig.tiny()
    state, step_fn = resnet.make_sharded_train_step(
        cfg, mesh, global_batch, image_size=image_size)

    # Per-host input feeding (≙ dataset auto-sharding, input_lib.py:729):
    # each process materializes ONLY its slice of the global batch and
    # assembles the global jax.Array from process-local shards.
    sharding = NamedSharding(mesh, P("dp"))
    local = resnet.synthetic_images(
        global_batch // runtime.num_processes, image_size,
        cfg.num_classes, seed=runtime.process_id)

    def global_batch_arrays():
        return {
            "image": jax.make_array_from_process_local_data(
                sharding, local["image"]),
            "label": jax.make_array_from_process_local_data(
                sharding, local["label"]),
        }

    batch = global_batch_arrays()
    t0, imgs = None, 0
    for i in range(steps):
        state, metrics = step_fn(state, batch)
        if i == 0:                      # skip compile in the rate
            jax.block_until_ready(metrics["loss"])
            t0 = time.time()
        else:
            imgs += global_batch
        if i % 10 == 0 or i == steps - 1:
            print(f"[p{runtime.process_id}] step {i}: "
                  f"loss={float(metrics['loss']):.4f} "
                  f"acc={float(metrics['accuracy']):.3f}", flush=True)
    jax.block_until_ready(state["step"])
    dt = time.time() - t0
    if runtime.is_chief and imgs:
        print(f"throughput: {imgs / dt:,.1f} images/sec "
              f"({runtime.num_processes} processes, "
              f"{len(jax.devices())} devices)", flush=True)
    final_loss = float(metrics["loss"])
    bootstrap.shutdown()
    return final_loss


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--global-batch", type=int, default=32)
    ap.add_argument("--image-size", type=int, default=32,
                    help="32 = tiny config for CPU demo; 224 = ResNet-50")
    ap.add_argument("--spawn", type=int, default=0,
                    help="spawn N local worker processes with TF_CONFIG "
                         "(multi-worker demo on one box)")
    args = ap.parse_args()

    if args.spawn > 1:
        from distributed_tensorflow_tpu.testing import multi_process_runner
        result = multi_process_runner.run(
            worker_main, num_workers=args.spawn,
            args=(args.steps, args.global_batch, args.image_size),
            timeout=900)
        losses = result.return_values
        print(f"all {len(losses)} workers done; final losses {losses}")
        assert len(set(round(x, 5) for x in losses)) == 1, \
            "sync DP must keep workers bit-identical"
    else:
        worker_main(args.steps, args.global_batch, args.image_size)


if __name__ == "__main__":
    main()
