"""Config #4 end-to-end: DLRM/Wide&Deep + embedding API + async PS.

≙ the reference's ParameterServerStrategyV2 + TPUEmbedding training flow
(parameter_server_strategy_v2.py:77 coordinator-owned variables +
tpu_embedding_v2.py:76 feature-config tables, BASELINE.md config #4):
the ClusterCoordinator schedules gradient closures onto workers holding
per-worker datasets, and the coordinator folds results into the server
copy asynchronously as they arrive.

Run locally (thread-lane workers, any backend)::

    python examples/train_dlrm_ps.py --steps 200 --workers 4

The REAL multi-process form (remote worker processes + kill-failover) is
exercised by tests/test_multi_process.py::test_dlrm_async_ps_end_to_end;
a production job runs the same `train_dlrm_async_ps` loop on process 0
with `remote_worker_ids=[1..N]` after `bootstrap.initialize()`, workers
running `remote_dispatch.run_worker_loop()`.
"""

from __future__ import annotations

import argparse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    from distributed_tensorflow_tpu.coordinator.cluster_coordinator import (
        ClusterCoordinator)
    from distributed_tensorflow_tpu.models import wide_deep as wd

    cfg = wd.WideDeepConfig.tiny()
    coord = ClusterCoordinator(num_workers=args.workers)
    try:
        state, losses = wd.train_dlrm_async_ps(
            cfg, coord, steps=args.steps, batch_size=args.batch_size,
            log_every=20)
    finally:
        coord.shutdown()
    first = sum(losses[:20]) / min(20, len(losses))
    last = sum(losses[-20:]) / min(20, len(losses))
    print(f"loss: first-20 avg {first:.4f} -> last-20 avg {last:.4f} "
          f"({'improved' if last < first else 'NO IMPROVEMENT'})")


if __name__ == "__main__":
    main()
