#!/usr/bin/env python
"""Serve the flagship transformer: continuous batching + KV-cache decode.

Default mode runs ONE in-process engine against a seeded request load
and prints the latency/throughput summary (the bench.py --serving loop,
human-sized). ``--elastic`` instead runs N *serving replicas* under the
recovery supervisor (resilience/supervisor.py) — each replica statically
owns a shard of the workload, heartbeats per engine step, and appends
completed requests to ``served-<task>.jsonl``. Kill one mid-load (try
``--kill-seed``) and the supervisor reforms the cluster; the restarted
replica re-queues its unfinished requests from the completion log and
serves them to the SAME tokens (greedy decode over fixed weights is
deterministic). Render the run with ``tools/obs_report.py
<telemetry-dir>`` — serving request latency and the recovery timeline
share one report.

``--elastic --disagg`` splits the replica fleet into one prefill
replica (task 0: owns admission, migrates each prefilled sequence's KV
blocks to a decode task over the write-once chunked blob transport) and
N-1 decode replicas. Greedy outputs stay byte-identical to the
monolithic fleet; chaos kills exercise prefill death mid-migration and
decode death while holding adopted blocks.

With ``--ckpt-dir`` the replicas restore weights down the checkpoint
recovery ladder (CheckpointManager.restore_latest — host snapshot >
peer replica > local disk > durable disk); ``--write-ckpt`` first
writes a seed-deterministic checkpoint there so the restore path is
exercised end-to-end.
"""

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def run_local(args):
    import time

    import jax
    import jax.numpy as jnp

    from distributed_tensorflow_tpu import telemetry
    from distributed_tensorflow_tpu.models.transformer import (
        TransformerConfig, TransformerLM)
    from distributed_tensorflow_tpu.serving import InferenceEngine
    from distributed_tensorflow_tpu.serving.replica import seeded_requests

    if args.telemetry_dir:
        telemetry.configure(args.telemetry_dir)
    cfg = TransformerConfig.tiny(max_seq_len=64)
    speed_kw = dict(prefix_caching=args.prefix_cache,
                    speculative_k=args.speculative,
                    kv_dtype=args.kv_dtype)
    if args.ckpt_dir:
        engine = InferenceEngine.from_checkpoint(
            cfg, args.ckpt_dir, num_blocks=64, block_size=8,
            max_slots=4, max_prompt_len=16,
            queue_capacity=args.requests + 1, **speed_kw)
    else:
        params = TransformerLM(cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
        engine = InferenceEngine(cfg, params, num_blocks=64, block_size=8,
                                 max_slots=4, max_prompt_len=16,
                                 queue_capacity=args.requests + 1,
                                 **speed_kw)
    reqs = seeded_requests(args.seed, args.requests, cfg.vocab_size)
    for r in reqs:
        engine.submit(r)
    t0 = time.perf_counter()
    done = engine.run_until_idle()
    span = time.perf_counter() - t0
    lats = sorted(r["latency_s"] for r in done.values())
    toks = sum(len(r["tokens"]) for r in done.values())
    p = lambda q: lats[min(len(lats) - 1, int(q * (len(lats) - 1)))]  # noqa: E731
    print(f"served {len(done)}/{args.requests} requests in {span:.2f}s "
          f"— {toks / span:.1f} tokens/s, latency p50 "
          f"{p(0.5) * 1e3:.1f}ms p99 {p(0.99) * 1e3:.1f}ms")
    print(f"engine stats: {engine.stats()}")
    if args.telemetry_dir:
        telemetry.shutdown()
        print(f"report: python tools/obs_report.py {args.telemetry_dir}")


def write_checkpoint(ckpt_dir: str):
    """Seed-deterministic serving checkpoint (what a trainer would have
    produced) so --ckpt-dir restores real weights."""
    import jax
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.checkpoint.checkpoint import (
        Checkpoint, CheckpointManager)
    from distributed_tensorflow_tpu.models.transformer import (
        TransformerConfig, TransformerLM)

    cfg = TransformerConfig.tiny(max_seq_len=64)
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    params = (params.unfreeze() if hasattr(params, "unfreeze")
              else dict(params))
    mgr = CheckpointManager(Checkpoint(params=params), ckpt_dir)
    mgr.save(checkpoint_number=1)
    print(f"wrote serving checkpoint to {ckpt_dir}")


def disagg_kill_plan(seed: int, num_workers: int, kills: int,
                     step_range):
    """Disaggregation-aware chaos schedule: alternate kills between the
    prefill replica (task 0 — dies mid-migration, since it exports KV
    blobs every step) and a seed-chosen decode replica (dies holding
    adopted blocks). Same seeding discipline as seeded_kill_plan."""
    import random

    from distributed_tensorflow_tpu.resilience import KillSpec

    rng = random.Random(f"dtx-kill-disagg:{seed}")
    plan = []
    for i in range(kills):
        worker = 0 if i % 2 == 0 else rng.randrange(1, num_workers)
        plan.append(KillSpec(worker=worker,
                             after_step=rng.randrange(*step_range)))
    return plan


def run_elastic(args):
    from distributed_tensorflow_tpu.resilience import (
        RecoverySupervisor, seeded_kill_plan)
    from distributed_tensorflow_tpu.serving.replica import serving_replica

    if args.disagg and args.workers < 2:
        raise SystemExit("--disagg needs --workers >= 2 "
                         "(one prefill + at least one decode replica)")
    run_dir = args.run_dir or args.telemetry_dir
    if not run_dir:
        import tempfile
        run_dir = tempfile.mkdtemp(prefix="serve_elastic_")
    os.makedirs(run_dir, exist_ok=True)
    kill_plan = ()
    if args.kill_seed is not None:
        # kill step range sized to the per-replica workload so the
        # SIGKILL lands while requests are genuinely in flight
        per_replica = max(1, args.requests // args.workers)
        step_range = (3, max(6, per_replica))
        if args.disagg:
            kill_plan = disagg_kill_plan(
                args.kill_seed, args.workers, args.kills, step_range)
        else:
            kill_plan = seeded_kill_plan(
                args.kill_seed, args.workers, kills=args.kills,
                step_range=step_range)
        print(f"chaos kill plan (seed {args.kill_seed}): {kill_plan}")
    sup = RecoverySupervisor(
        serving_replica, num_workers=args.workers,
        args=(run_dir, args.requests, args.seed),
        kwargs={"ckpt_dir": args.ckpt_dir,
                "step_delay_s": args.step_delay,
                "prefix_caching": args.prefix_cache,
                "speculative_k": args.speculative,
                "kv_dtype": args.kv_dtype,
                "disagg": args.disagg},
        max_restarts=args.restart_budget, kill_plan=kill_plan,
        generation_timeout_s=args.generation_timeout,
        telemetry_dir=args.telemetry_dir)
    result = sup.run()
    for task, served, total in sorted(result.return_values):
        print(f"replica {task}: served {served} this generation "
              f"({total} total on its shard)")
    print(f"done: {sup.restarts_used} restart(s), "
          f"{sup.failures_total} recorded failure(s), "
          f"final generation {sup.generation}")
    print(f"completion logs: {run_dir}/served-*.jsonl")
    if args.telemetry_dir:
        print(f"report: python tools/obs_report.py {args.telemetry_dir}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=24,
                    help="seeded workload size")
    ap.add_argument("--seed", type=int, default=0,
                    help="workload seed (replayable)")
    ap.add_argument("--telemetry-dir", default=None,
                    help="enable telemetry (serve.step/serve.request "
                         "events + recovery timeline)")
    ap.add_argument("--ckpt-dir", default=None,
                    help="restore serving weights down the recovery "
                         "ladder from this CheckpointManager directory")
    ap.add_argument("--write-ckpt", action="store_true",
                    help="first write a seed-deterministic checkpoint "
                         "to --ckpt-dir (exercises the restore path)")
    ap.add_argument("--elastic", action="store_true",
                    help="run N supervised serving replicas (worker "
                         "death -> reform -> re-queue in-flight)")
    ap.add_argument("--workers", type=int, default=1,
                    help="elastic: number of serving replicas")
    ap.add_argument("--run-dir", default=None,
                    help="elastic: completion-log directory "
                         "(default: the telemetry dir)")
    ap.add_argument("--kill-seed", type=int, default=None,
                    help="elastic chaos: SIGKILL replicas on a schedule "
                         "derived from this seed")
    ap.add_argument("--kills", type=int, default=1)
    ap.add_argument("--restart-budget", type=int, default=3)
    ap.add_argument("--generation-timeout", type=float, default=600.0)
    ap.add_argument("--step-delay", type=float, default=0.05,
                    help="elastic: per-step pacing seconds (gives "
                         "step-targeted chaos kills a window to land)")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="enable copy-on-write prefix caching "
                         "(cross-request KV reuse; outputs invariant, "
                         "restarted replicas rebuild the cache cold)")
    ap.add_argument("--speculative", type=int, default=0, metavar="K",
                    help="speculative decoding: K draft tokens per "
                         "slot per step (greedy outputs exactly equal "
                         "non-speculative)")
    ap.add_argument("--kv-dtype", default=None,
                    choices=("f32", "bf16", "int8"),
                    help="KV-pool storage dtype (int8: quantized, "
                         "2x+ slots per chip)")
    ap.add_argument("--disagg", action="store_true",
                    help="elastic: disaggregated prefill/decode — task "
                         "0 prefills and migrates KV blocks to decode "
                         "tasks 1..N-1 over the chunked blob transport "
                         "(needs --workers >= 2)")
    args = ap.parse_args()

    if args.write_ckpt:
        if not args.ckpt_dir:
            ap.error("--write-ckpt requires --ckpt-dir")
        write_checkpoint(args.ckpt_dir)
    if args.elastic:
        run_elastic(args)
    else:
        run_local(args)


if __name__ == "__main__":
    main()
