#!/usr/bin/env python
"""BERT MLM pretraining on synthetic corpus (BASELINE.md config #3).

≙ the reference's BERT-base CollectiveAllReduceStrategy workload: here
the encoder is the flagship transformer in bidirectional mode with
on-device dynamic 80/10/10 masking, sharded over whatever mesh axes you
pick (dp / fsdp / tp), with GSPMD inserting the gradient allreduce.

    python examples/train_bert.py --axes dp=-1 --steps 20
    python examples/train_bert.py --axes dp=2,tp=2 --seq 512
"""

import argparse
import time

import jax

from distributed_tensorflow_tpu.cluster import bootstrap
from distributed_tensorflow_tpu.cluster.topology import make_mesh
from distributed_tensorflow_tpu.models import bert


def parse_axes(spec: str) -> dict:
    return {k: int(v) for k, v in
            (kv.split("=") for kv in spec.split(","))}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--axes", default="dp=-1")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--global-batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--tiny", action="store_true",
                    help="CI-sized model (default on CPU)")
    args = ap.parse_args()

    bootstrap.initialize()
    mesh = make_mesh(parse_axes(args.axes))
    tiny = args.tiny or jax.default_backend() == "cpu"
    cfg = (bert.tiny_bert_config(max_seq_len=args.seq)
           if tiny else bert.bert_config(max_seq_len=args.seq))

    state, step_fn = bert.make_sharded_train_step(
        cfg, mesh, args.global_batch)
    batch = bert.synthetic_corpus(args.global_batch, cfg.max_seq_len,
                                  cfg.vocab_size)

    t0 = None
    for i in range(args.steps):
        state, metrics = step_fn(state, batch)
        if i == 0:
            jax.block_until_ready(metrics["loss"])
            t0 = time.time()
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i}: mlm_loss={float(metrics['loss']):.4f}",
                  flush=True)
    jax.block_until_ready(state["step"])
    if args.steps > 1:
        rate = (args.steps - 1) * args.global_batch / (time.time() - t0)
        print(f"throughput: {rate:,.1f} samples/sec on {mesh.shape}")
    bootstrap.shutdown()


if __name__ == "__main__":
    main()
