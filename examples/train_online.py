#!/usr/bin/env python
"""Online recommender: fault-tolerant streaming training with dynamic
embeddings and a freshness SLO (ROADMAP item 2, the online form of
BASELINE config #4).

Topology (one recovery supervisor, ``--supervised``)::

    task 0            task 1..W          task W+1         task W+2
    trainer/coord     grad worker(s)     ingestor         evaluator
    tables+cursor     remote closures    appends the      restores fresh
    commit ladder     (remote_dispatch)  event log        snapshots,
        |                   ^                |            stamps offset
        +---- async-PS gradients ----+      v            + freshness
        +<------- stream.log (append-only, offset-ordered) ------->+

- The **ingestor** appends seeded Zipf click events to the append-only
  log (input/stream.py); a restarted ingestor truncates the torn tail
  and continues at the next offset.
- The **trainer** tails the log, trains dynamic user/item tables
  (embedding/dynamic.py) plus a small dense tower, and commits model +
  membership + CURSOR atomically every ``--commit-every`` batches —
  exactly-once event application by construction
  (models/online_dlrm.OnlineTrainer). Gradients are computed on the
  grad worker(s) through the async-PS dispatch path
  (coordinator/remote_dispatch.py).
- The **evaluator** polls the checkpoint directory, restores every new
  snapshot, scores a held-out batch (proof the snapshot is servable),
  and stamps it with its stream offset + update→servable freshness
  (``stream.snapshot_published`` — the freshness-SLO feed,
  telemetry/slo.default_online_slos).

``--kill-seed`` SIGKILLs a seed-chosen task (trainer, ingestor, or
evaluator) mid-run; the supervisor reforms the cluster and the run
must finish with zero lost / zero double-applied events and the
freshness SLO re-cleared — gated by ``tools/chaos_sweep.py --online``.
"""

import argparse
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def seeded_online_kill_plan(seed: int, grad_workers: int, *, kills=1):
    """Seed-derived SIGKILLs over the online roles the ISSUE names:
    trainer (task 0), ingestor (task W+1), evaluator (task W+2). The
    after_step budget is per role (trainer heartbeats per applied
    batch, ingestor per produced chunk, evaluator per published
    snapshot)."""
    import random as _random

    from distributed_tensorflow_tpu.resilience import KillSpec
    rng = _random.Random(f"dtx-online-kill:{seed}")
    roles = [(0, (2, 8)),                       # trainer: batches
             (grad_workers + 1, (1, 4)),        # ingestor: chunks
             (grad_workers + 2, (2, 10))]       # evaluator: polls
    victims = rng.sample(roles, k=min(kills, len(roles)))
    return [KillSpec(worker=task, after_step=rng.randrange(*rng_range))
            for task, rng_range in victims]


def _online_cfg(args):
    from distributed_tensorflow_tpu.models.online_dlrm import OnlineConfig
    return OnlineConfig(
        batch_size=args.batch_size,
        initial_capacity=args.initial_capacity,
        max_capacity=args.max_capacity,
        admission_threshold=args.admission_threshold,
        ttl_steps=args.ttl_steps,
        n_users=args.users, n_items=args.items,
        seed=args.seed)


def online_cluster_task(args_dict):
    """One generation of one online-cluster task (module-level so the
    supervisor's spawn machinery pickles it by reference). Role is
    derived from the process id; every role is restartable."""
    import jax

    from distributed_tensorflow_tpu.cluster import bootstrap
    from distributed_tensorflow_tpu.cluster.coordination import (
        CoordinationError, coordination_service)
    from distributed_tensorflow_tpu.telemetry import events as tv_events

    args = argparse.Namespace(**args_dict)
    runtime = bootstrap.initialize()
    if runtime.num_processes > 1:
        # collective backend init (see data_service_worker): every task
        # must touch the backend or the trainer's first jit blocks
        jax.local_devices()
    tdir = os.environ.get(tv_events.ENV_TELEMETRY_DIR)
    if tdir:
        tv_events.configure(tdir, process_id=runtime.process_id)
    agent = coordination_service()
    w = args.grad_workers
    pid = runtime.process_id
    try:
        if pid == 0:
            return _trainer_task(args, runtime, agent)
        if 1 <= pid <= w:
            from distributed_tensorflow_tpu.coordinator import (
                remote_dispatch)
            remote_dispatch.run_worker_loop()
            bootstrap.shutdown()
            return ("grad_worker", pid)
        if pid == w + 1:
            return _ingestor_task(args, runtime, agent)
        return _evaluator_task(args, runtime, agent)
    except CoordinationError:
        # coordinator torn down at job end while this task was mid-RPC
        return (("task", pid), "released")


def _stream_path(args):
    from distributed_tensorflow_tpu.input import stream as stream_lib
    return os.path.join(args.stream_dir, stream_lib.LOG_NAME)


def _ingestor_task(args, runtime, agent):
    """Append the seeded event stream in paced chunks; resumable — a
    reformed ingestor truncates the torn tail and continues from the
    log's end, so offsets stay contiguous and immutable."""
    from distributed_tensorflow_tpu.cluster import bootstrap, elastic
    from distributed_tensorflow_tpu.input import stream as stream_lib
    from distributed_tensorflow_tpu.telemetry import events as tv_events

    cfg = _online_cfg(args)
    path = _stream_path(args)
    writer = stream_lib.StreamWriter.open(path)
    produced = writer.next_offset
    chunks = 0
    t0 = time.perf_counter()
    while produced < args.events:
        n = min(args.chunk, args.events - produced)
        chunk = stream_lib.seeded_events(
            args.seed, produced, n, n_users=cfg.n_users,
            n_items=cfg.n_items, n_dense=cfg.n_dense,
            zipf_a=cfg.zipf_a)
        produced = stream_lib.append_chunk(writer, chunk)
        chunks += 1
        elastic.heartbeat(chunks)
        tv_events.event(
            "stream.produced", offset=produced, chunk=chunks,
            events_per_sec=round(
                produced / max(time.perf_counter() - t0, 1e-9), 1))
        if produced < args.events and args.pace_s > 0:
            time.sleep(args.pace_s)
    writer.close()
    agent.key_value_set("dtx_online/done/ingestor", "1")
    bootstrap.shutdown()
    return ("ingestor", produced)


def _trainer_task(args, runtime, agent):
    from distributed_tensorflow_tpu.cluster import bootstrap, elastic
    from distributed_tensorflow_tpu.coordinator import remote_dispatch
    from distributed_tensorflow_tpu.coordinator.cluster_coordinator \
        import ClusterCoordinator
    from distributed_tensorflow_tpu.models import online_dlrm as od

    cfg = _online_cfg(args)
    coordinator = None
    if args.grad_workers > 0:
        coordinator = ClusterCoordinator(
            remote_worker_ids=list(range(1, args.grad_workers + 1)))
    trainer = od.OnlineTrainer(
        cfg, _stream_path(args), args.ckpt_dir,
        commit_every=args.commit_every, coordinator=coordinator,
        local_dir=args.ckpt_dir.rstrip("/") + ".local",
        agent=agent)
    start = trainer.restore()
    print(f"[gen {runtime.generation}] trainer resumed at offset "
          f"{start} (step {trainer.step})")
    summary = trainer.run(
        args.events, idle_timeout_s=args.idle_timeout,
        heartbeat_fn=elastic.heartbeat,
        on_batch=lambda t: (od.table_stats_event(t)
                            if t.step % args.commit_every == 0
                            else None))
    trainer.sync()
    od.table_stats_event(trainer)
    print(f"[gen {runtime.generation}] trainer done: {summary}")
    # wait for the sidecars to observe the final state before tearing
    # down the coordination service this process hosts
    deadline = time.monotonic() + args.idle_timeout
    pending = {"ingestor", "evaluator"}
    while pending and time.monotonic() < deadline:
        for role in list(pending):
            if agent.key_value_try_get(f"dtx_online/done/{role}") \
                    is not None:
                pending.discard(role)
        if pending:
            time.sleep(0.1)
    if args.grad_workers > 0:
        remote_dispatch.shutdown_workers(
            agent, worker_ids=list(range(1, args.grad_workers + 1)))
    bootstrap.shutdown()
    return ("trainer", summary["offset"], summary["loss_last"])


def _evaluator_task(args, runtime, agent):
    """Serve fresh snapshots: restore every new checkpoint, score it,
    stamp it with stream offset + update→servable freshness."""
    import numpy as np

    from distributed_tensorflow_tpu.checkpoint.checkpoint import (
        Checkpoint, CheckpointCorruptError, latest_checkpoint)
    from distributed_tensorflow_tpu.cluster import bootstrap, elastic
    from distributed_tensorflow_tpu.input import stream as stream_lib
    from distributed_tensorflow_tpu.models import online_dlrm as od
    from distributed_tensorflow_tpu.telemetry import events as tv_events

    cfg = _online_cfg(args)
    ckpt = Checkpoint(single_writer=True,
                      online=od.checkpoint_template(cfg))
    path = _stream_path(args)
    seen: set = set()
    published = 0
    polls = 0
    while True:
        # heartbeat per POLL (not per publish): the evaluator's
        # progress signal — and the chaos plan's step clock — must
        # tick while it waits for the trainer's next commit
        polls += 1
        elastic.heartbeat(polls)
        latest = latest_checkpoint(args.ckpt_dir, "online")
        if latest is None or latest in seen:
            time.sleep(args.eval_poll_s)
            continue
        seen.add(latest)
        try:
            flat = ckpt.restore(latest)
        except (OSError, KeyError, ValueError, CheckpointCorruptError):
            continue           # rotation race / torn write: next poll
        state = od.unpack_restored(flat)
        offset = int(np.asarray(state["offset"]))
        step = int(np.asarray(state["step"]))
        commit_wall = float(np.asarray(state["commit_wall"]))
        loss = od.eval_snapshot(cfg, state)
        now = time.time()
        lag = stream_lib.count_records(path) - offset
        published += 1
        tv_events.event(
            "stream.snapshot_published", offset=offset, step=step,
            freshness_s=round(now - commit_wall, 6),
            lag_events=int(lag), eval_loss=round(loss, 5),
            snapshot=published)
        print(f"[gen {runtime.generation}] snapshot {published}: "
              f"offset {offset} freshness "
              f"{now - commit_wall:.3f}s lag {lag} loss {loss:.4f}")
        if offset >= args.events:
            break
    agent.key_value_set("dtx_online/done/evaluator", "1")
    bootstrap.shutdown()
    return ("evaluator", published)


def run_supervised(args):
    import tempfile

    from distributed_tensorflow_tpu.resilience import RecoverySupervisor

    base = args.stream_dir or tempfile.mkdtemp(prefix="online_")
    args.stream_dir = base
    args.ckpt_dir = args.ckpt_dir or os.path.join(base, "ckpt")
    kill_plan = ()
    if args.kill_seed is not None:
        kill_plan = seeded_online_kill_plan(
            args.kill_seed, args.grad_workers, kills=args.kills)
        print(f"online kill plan (seed {args.kill_seed}): {kill_plan}")
    n_tasks = 1 + args.grad_workers + 2
    sup = RecoverySupervisor(
        online_cluster_task, num_workers=n_tasks,
        args=(vars(args),),
        max_restarts=args.restart_budget, kill_plan=kill_plan,
        generation_timeout_s=args.generation_timeout,
        telemetry_dir=args.telemetry_dir)
    result = sup.run()
    for value in sorted(result.return_values, key=str):
        print(f"task result: {value}")
    print(f"done: {args.events} events through {n_tasks} tasks, "
          f"{sup.restarts_used} restart(s), "
          f"final generation {sup.generation}")
    if args.telemetry_dir:
        print(f"timeline: python tools/obs_report.py "
              f"{args.telemetry_dir}")


def run_local(args):
    """Single-process smoke path: pre-produce the log, train inline
    (no supervisor, no remote dispatch) — the quickest way to watch
    the admission/eviction/growth counters move."""
    import tempfile

    from distributed_tensorflow_tpu.input import stream as stream_lib
    from distributed_tensorflow_tpu.models import online_dlrm as od
    from distributed_tensorflow_tpu.telemetry import events as tv_events

    if args.telemetry_dir:
        tv_events.configure(args.telemetry_dir, process_id=0)
    base = args.stream_dir or tempfile.mkdtemp(prefix="online_")
    args.stream_dir = base
    args.ckpt_dir = args.ckpt_dir or os.path.join(base, "ckpt")
    cfg = _online_cfg(args)
    path = _stream_path(args)
    writer = stream_lib.StreamWriter.open(path)
    while writer.next_offset < args.events:
        n = min(args.chunk, args.events - writer.next_offset)
        stream_lib.append_chunk(writer, stream_lib.seeded_events(
            args.seed, writer.next_offset, n, n_users=cfg.n_users,
            n_items=cfg.n_items, n_dense=cfg.n_dense,
            zipf_a=cfg.zipf_a))
    writer.close()
    trainer = od.OnlineTrainer(cfg, path, args.ckpt_dir,
                               commit_every=args.commit_every)
    trainer.restore()
    summary = trainer.run(args.events, idle_timeout_s=args.idle_timeout)
    print(f"online: {summary}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--events", type=int, default=480,
                    help="total stream events (the run's end condition)")
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--chunk", type=int, default=48,
                    help="ingestor append-chunk size")
    ap.add_argument("--pace-s", type=float, default=0.25,
                    help="ingestor pause between chunks (stream pacing)")
    ap.add_argument("--commit-every", type=int, default=3,
                    help="trainer: commit cursor+state every N batches")
    ap.add_argument("--grad-workers", type=int, default=1,
                    help="async-PS grad worker tasks (0 = compute "
                         "gradients in the trainer process)")
    ap.add_argument("--initial-capacity", type=int, default=256)
    ap.add_argument("--max-capacity", type=int, default=1024)
    ap.add_argument("--admission-threshold", type=int, default=2)
    ap.add_argument("--ttl-steps", type=int, default=2048)
    ap.add_argument("--users", type=int, default=50_000)
    ap.add_argument("--items", type=int, default=10_000)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--supervised", action="store_true",
                    help="run the full 4-role topology under the "
                         "recovery supervisor")
    ap.add_argument("--kill-seed", type=int, default=None,
                    help="supervised chaos: SIGKILL a seed-chosen "
                         "trainer/ingestor/evaluator mid-run")
    ap.add_argument("--kills", type=int, default=1)
    ap.add_argument("--restart-budget", type=int, default=3)
    ap.add_argument("--generation-timeout", type=float, default=300.0)
    ap.add_argument("--idle-timeout", type=float, default=60.0,
                    help="trainer: stream idle budget before giving up")
    ap.add_argument("--eval-poll-s", type=float, default=0.3)
    ap.add_argument("--stream-dir", default=None,
                    help="directory holding stream.log (default: tmp)")
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--telemetry-dir", default=None)
    args = ap.parse_args()

    if args.supervised:
        run_supervised(args)
    else:
        run_local(args)


if __name__ == "__main__":
    main()
