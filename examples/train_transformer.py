#!/usr/bin/env python
"""Flagship transformer pretraining across every parallelism axis.

≙ the reference's BERT/Transformer-big multi-worker scripts
(BASELINE.md configs #3/#5), driven through the native SPMD path:
pick a mesh shape, get ONE compiled train step, feed global batches.

    # pure data parallel over all local devices
    python examples/train_transformer.py --axes dp=-1

    # fsdp + tensor parallel
    python examples/train_transformer.py --axes dp=2,fsdp=2,tp=2

    # GPipe pipeline over dp×pp
    python examples/train_transformer.py --axes dp=4,pp=2 --microbatches 4

    # MoE experts over dp×ep
    python examples/train_transformer.py --axes dp=2,ep=4 --moe-experts 4

    # causal sequence parallelism (ring / striped)
    python examples/train_transformer.py --axes dp=4,sp=2 --sp-impl ring
"""

import argparse
import time

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.cluster import bootstrap
from distributed_tensorflow_tpu.cluster.topology import make_mesh
from distributed_tensorflow_tpu.models.transformer import (
    TransformerConfig,
    make_pipelined_train_step,
    make_sharded_train_step,
    synthetic_tokens,
)


def parse_axes(spec: str) -> dict:
    out = {}
    for kv in spec.split(","):
        k, v = kv.split("=")
        out[k] = int(v)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--axes", default="dp=-1",
                    help="mesh axes, e.g. dp=2,fsdp=2,tp=2")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--global-batch", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=None)
    ap.add_argument("--tiny", action="store_true",
                    help="CI-sized model (default on CPU)")
    ap.add_argument("--microbatches", type=int, default=2,
                    help="pipeline microbatches when the mesh has pp")
    ap.add_argument("--schedule", default="gpipe",
                    choices=["gpipe", "1f1b"],
                    help="pipeline schedule (1f1b = interleaved "
                         "one-forward-one-backward, O(stages) "
                         "activation memory)")
    ap.add_argument("--grad-sync", default="auto",
                    choices=["auto", "bucketed", "gspmd"],
                    help="gradient sync on non-pp meshes (auto = "
                         "reverse-order bucketed collectives on >1 "
                         "device pure-dp meshes)")
    ap.add_argument("--moe-experts", type=int, default=0)
    ap.add_argument("--sp-impl", default="ring",
                    choices=["ring", "ulysses", "striped"])
    args = ap.parse_args()

    bootstrap.initialize()                 # no-op single-process
    mesh = make_mesh(parse_axes(args.axes))
    print(f"mesh: {dict(mesh.shape)} on {jax.default_backend()}")

    tiny = args.tiny or jax.default_backend() != "tpu"
    kw = {}
    if args.seq_len:
        kw["max_seq_len"] = args.seq_len
    if args.moe_experts:
        kw["moe_experts"] = args.moe_experts
    if "sp" in mesh.shape and mesh.shape["sp"] > 1:
        kw["sp_impl"] = args.sp_impl
        if tiny and args.sp_impl == "striped":
            kw["sp_attn_impl"] = "interpret"
    cfg = (TransformerConfig.tiny(**kw) if tiny
           else TransformerConfig.transformer_big(**kw))

    if mesh.shape.get("pp", 1) > 1:
        state, step = make_pipelined_train_step(
            cfg, mesh, args.global_batch,
            num_microbatches=args.microbatches,
            schedule=args.schedule)
    else:
        state, step = make_sharded_train_step(cfg, mesh,
                                              args.global_batch,
                                              grad_sync=args.grad_sync)

    tokens = synthetic_tokens(args.global_batch, cfg.max_seq_len,
                              cfg.vocab_size)
    t0 = time.perf_counter()
    for i in range(args.steps):
        state, metrics = step(state, {"tokens": tokens})
        if i % 5 == 0 or i == args.steps - 1:
            loss = float(metrics["loss"])
            print(f"step {i}: loss={loss:.4f}")
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0
    tok = args.steps * args.global_batch * cfg.max_seq_len
    print(f"{tok / dt:,.0f} tokens/s over {args.steps} steps")


if __name__ == "__main__":
    main()
