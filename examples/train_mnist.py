#!/usr/bin/env python
"""Config #1: MNIST CNN under MirroredStrategy semantics (BASELINE.md).

Single-host synchronous data parallelism — the TPU-native counterpart of
the reference's `MirroredStrategy` Keras script. Uses the TF-parity
Strategy API end to end: scope() -> distribute dataset -> run().
"""

import argparse

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.input.dataset import Dataset
from distributed_tensorflow_tpu.models.mnist_cnn import (
    create_train_state, make_train_step, synthetic_data)
from distributed_tensorflow_tpu.parallel.mirrored import MirroredStrategy


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--global-batch", type=int, default=64)
    ap.add_argument("--lr", type=float, default=1e-3)
    args = ap.parse_args()

    strategy = MirroredStrategy()
    print(f"devices: {strategy.num_replicas_in_sync} replicas on "
          f"{jax.default_backend()}")

    data = synthetic_data(4096)
    ds = Dataset.from_tensor_slices(data).shuffle(4096).batch(
        args.global_batch).repeat()
    dist_ds = strategy.experimental_distribute_dataset(ds)

    state, model, tx = create_train_state(jax.random.PRNGKey(0),
                                          learning_rate=args.lr)
    train_step = make_train_step(model, tx)

    it = iter(dist_ds)
    for step in range(args.steps):
        batch = next(it)
        state, metrics = strategy.run_step(train_step, state, batch) \
            if hasattr(strategy, "run_step") else train_step_distributed(
                strategy, train_step, state, batch)
        if step % 20 == 0 or step == args.steps - 1:
            print(f"step {step}: loss={float(metrics['loss']):.4f} "
                  f"acc={float(metrics['accuracy']):.3f}")
    print("done")


def train_step_distributed(strategy, train_step, state, batch):
    """SPMD path: batch is already sharded over the mesh; params
    replicated; one jit step (≙ Strategy.run on TPU, SURVEY §3.4)."""
    import functools
    if not hasattr(strategy, "_compiled_step"):
        strategy._compiled_step = jax.jit(train_step)
    return strategy._compiled_step(state, batch)


if __name__ == "__main__":
    main()
