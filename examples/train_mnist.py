#!/usr/bin/env python
"""Config #1: MNIST CNN under MirroredStrategy semantics (BASELINE.md).

Single-host synchronous data parallelism — the TPU-native counterpart of
the reference's `MirroredStrategy` Keras script, on the NATIVE path
(SURVEY §3.4): distribute dataset -> replicate state -> one compiled
SPMD step via `strategy.compile_step`. For the TF-parity
scope()/run()/merge_call surface, see tests/test_strategy.py and the
conformance suite (testing/strategy_conformance.py); for the Keras-style
`Model.fit` layer, see distributed_tensorflow_tpu/training.

``--elastic`` instead runs the job as an N-worker cluster under the
recovery supervisor (resilience/supervisor.py): worker processes train
data-parallel with periodic checkpoints; if one dies (try
``--kill-seed``) the supervisor kills the stragglers, reforms the
cluster under a fresh generation, and the job resumes from the last
intact checkpoint. Render the run with ``tools/obs_report.py
<telemetry-dir>`` to see the recovery timeline.

``--data-service`` runs the DISAGGREGATED-INPUT topology (ISSUE 12):
task 0 trains and dispatches FILE splits, tasks 1..M are input
workers executing the registered pipeline under heartbeat-backed
leases over the coordination KV; ``--kill-seed`` SIGKILLs input
workers mid-epoch and the epoch's exactly-once split delivery must
survive (gated by ``tools/chaos_sweep.py --data``).
"""

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

#: deterministic synthetic sample pool shared by every worker/generation
_POOL = 512


# ---------------------------------------------------------------------------
# Disaggregated data service (ISSUE 12): task 0 = trainer + dispatcher,
# tasks 1..M = input workers, all under one recovery supervisor
# ---------------------------------------------------------------------------

def _npz_reader(path):
    """Per-file reader of the split files ``write_mnist_split_files``
    lays down — module-level so the split pipeline factory pickles by
    reference into input-worker processes."""
    import numpy as np
    with np.load(path) as z:
        images, labels = z["image"], z["label"]
    for i in range(len(labels)):
        yield {"image": images[i], "label": labels[i]}


def mnist_split_pipeline(files):
    """The registered per-split pipeline (SplitProvider.from_factory):
    unbatched examples; the trainer batches (batch composition follows
    split-completion order, the element MULTISET is deterministic)."""
    from distributed_tensorflow_tpu.input.dataset import Dataset
    return Dataset.from_files(list(files), _npz_reader)


def write_mnist_split_files(data_dir, num_files, pool=_POOL):
    """Shard the deterministic synthetic pool into FILE splits."""
    import numpy as np

    from distributed_tensorflow_tpu.models.mnist_cnn import synthetic_data
    data = synthetic_data(pool)
    per = pool // num_files
    os.makedirs(data_dir, exist_ok=True)
    files = []
    for i in range(num_files):
        path = os.path.join(data_dir, f"mnist-{i:03d}.npz")
        sl = slice(i * per, (i + 1) * per)
        np.savez(path, image=data["image"][sl], label=data["label"][sl])
        files.append(path)
    return files


def seeded_input_kill_plan(seed, input_workers, *, kills=1,
                           step_range=(1, 3)):
    """Seed-derived SIGKILLs of INPUT-WORKER tasks (cluster task ids
    1..M; task 0 is the trainer): fire once the victim's heartbeat
    reports >= after_step splits processed — mid-epoch by
    construction."""
    import random as _random

    from distributed_tensorflow_tpu.resilience import KillSpec
    rng = _random.Random(f"dtx-data-kill:{seed}")
    victims = rng.sample(range(input_workers),
                         k=min(kills, input_workers))
    return [KillSpec(worker=1 + v, after_step=rng.randrange(*step_range))
            for v in victims]


def data_service_worker(data_dir, ckpt_dir, epochs, global_batch, lr,
                        input_workers):
    """One generation of one data-service cluster task. Task 0 is the
    trainer (plus the split dispatcher); tasks 1..M are input workers
    executing the registered pipeline over leased FILE splits. All KV
    traffic is generation-namespaced, so a supervisor reform fences
    every straggler of the dead incarnation."""
    import glob as _glob

    from distributed_tensorflow_tpu.cluster import bootstrap, elastic
    from distributed_tensorflow_tpu.cluster.coordination import (
        CoordinationError, coordination_service)
    from distributed_tensorflow_tpu.input import data_service as dsvc
    from distributed_tensorflow_tpu.input.split_provider import (
        SplitProvider)
    from distributed_tensorflow_tpu.telemetry import events as tv_events

    runtime = bootstrap.initialize()
    if runtime.num_processes > 1:
        # The CPU test backend's gloo client creation is COLLECTIVE:
        # every process of the distributed runtime must initialize its
        # backend or the ones that do (the trainer's first jit) block
        # forever in make_cpu_client waiting for the rest. Input
        # workers never run a jax computation, so touch the backend
        # explicitly.
        import jax
        jax.local_devices()
    tdir = os.environ.get(tv_events.ENV_TELEMETRY_DIR)
    if tdir:
        tv_events.configure(tdir, process_id=runtime.process_id)
    agent = coordination_service()
    files = sorted(_glob.glob(os.path.join(data_dir, "*.npz")))
    provider = SplitProvider.from_factory(files, mnist_split_pipeline,
                                          seed=0)
    cfg = dsvc.DataServiceConfig(job="mnist", lease_timeout_s=1.0,
                                 fetch_timeout_s=60.0)
    if runtime.process_id == 0:
        return _data_service_trainer(
            runtime, agent, provider, cfg, ckpt_dir, epochs,
            global_batch, lr, input_workers)
    wid = runtime.process_id - 1
    worker = dsvc.DataInputWorker(
        agent, provider, cfg, worker_id=wid,
        num_workers=input_workers, epochs=epochs,
        heartbeat_fn=elastic.heartbeat)
    try:
        worker.run()
    except CoordinationError:
        pass          # coordinator torn down at job end: released
    bootstrap.shutdown()
    return ("input_worker", wid, worker.splits_processed)


def _data_service_trainer(runtime, agent, provider, cfg, ckpt_dir,
                          epochs, global_batch, lr, input_workers):
    import time as _time

    import jax
    import numpy as np
    import optax

    from distributed_tensorflow_tpu.checkpoint.checkpoint import (
        Checkpoint, CheckpointManager)
    from distributed_tensorflow_tpu.cluster import bootstrap, elastic
    from distributed_tensorflow_tpu.input import data_service as dsvc
    from distributed_tensorflow_tpu.models.mnist_cnn import (
        create_train_state)
    from distributed_tensorflow_tpu.telemetry import events as tv_events
    from distributed_tensorflow_tpu.telemetry import goodput

    ledger = goodput.GoodputLedger()
    goodput.activate(ledger)
    dispatcher = dsvc.DataServiceDispatcher(
        agent, provider, cfg, num_workers=input_workers, epochs=epochs)
    dispatcher.start()
    client = dsvc.DataServiceClient(
        agent, cfg, heartbeat_fn=lambda _s: elastic.heartbeat())

    state, model, tx = create_train_state(jax.random.PRNGKey(0),
                                          learning_rate=lr)
    params, opt_state = state["params"], state["opt_state"]

    def loss_fn(p, images, labels):
        logits = model.apply({"params": p}, images)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))

    @jax.jit
    def apply_fn(p, o, grads):
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o

    leaves, treedef = jax.tree_util.tree_flatten((params, opt_state))
    # single_writer: the trainer alone owns the model state — the
    # input workers are cluster members but never checkpoint, so the
    # SPMD commit barrier would block for its full timeout every save
    ckpt = Checkpoint(single_writer=True, leaves=list(leaves))
    mgr = CheckpointManager(ckpt, ckpt_dir, checkpoint_name="dsvc")
    start_epoch = 0
    res = mgr.restore_latest()
    if res is not None:
        tier, start_epoch, restored = res
        params, opt_state = jax.tree_util.tree_unflatten(
            treedef, [restored[f"leaves/{i}"]
                      for i in range(len(leaves))])
        print(f"[gen {runtime.generation}] trainer resumed at epoch "
              f"{start_epoch} from the {tier} tier")

    loss = float("nan")
    step = 0
    last_wait = client.total_wait_s
    for epoch in range(start_epoch, epochs):
        batch_buf = []
        for el in client.epoch(epoch):
            batch_buf.append(el)
            if len(batch_buf) < global_batch:
                continue
            t0 = _time.perf_counter()
            images = np.stack([b["image"] for b in batch_buf])
            labels = np.stack([b["label"] for b in batch_buf])
            batch_buf = []
            loss, grads = grad_fn(params, images, labels)
            loss = float(loss)
            params, opt_state = apply_fn(params, opt_state, grads)
            jax.block_until_ready(params)
            dur_s = _time.perf_counter() - t0
            # fetch-wait accrued since the previous step prices into
            # the infeed_wait badput bucket (event-walk AND live paths)
            wait_s = client.total_wait_s - last_wait
            last_wait = client.total_wait_s
            elastic.heartbeat(step)
            tv_events.event("train.step", step=step, loss=loss,
                            dur_s=round(dur_s + wait_s, 6),
                            infeed_wait_s=round(wait_s, 6))
            ledger.step_completed(dur_s + wait_s, infeed_s=wait_s)
            step += 1
        refresh = jax.tree_util.tree_flatten((params, opt_state))[0]
        ckpt._objects["leaves"] = list(refresh)
        ledger.enter("ckpt_block")
        mgr.save(checkpoint_number=epoch + 1)
        ledger.enter("idle")
        print(f"[gen {runtime.generation}] epoch {epoch} done: "
              f"loss={loss:.4f} fetch_wait={client.total_wait_s:.2f}s "
              f"reassigned={dispatcher.splits_reassigned}")
    dsvc.signal_shutdown(agent, cfg)
    dsvc.await_shutdown_acks(agent, cfg, input_workers)
    dispatcher.stop()
    ckpt.sync()
    bootstrap.shutdown()
    return (0, start_epoch, loss)


def elastic_worker(ckpt_dir, total_steps, save_every, global_batch, lr,
                   local_dir=None, snapshot_every=None, snapshot_keep=2,
                   step_delay_s=0.0):
    """One generation of one elastic worker: bootstrap from TF_CONFIG,
    restore down the recovery ladder (own host snapshot > peer replica
    > local disk > durable disk), train data-parallel (grads
    allgather-averaged across processes), checkpoint every
    ``save_every`` steps with host snapshots every ``snapshot_every``
    in between, heartbeat every step. The per-worker batch is derived
    from the CURRENT process count (``global_batch // nproc``), so the
    same worker fn runs at any topology the supervisor reforms to.
    Module-level so the supervisor's spawn machinery can pickle it by
    reference."""
    from distributed_tensorflow_tpu.cluster import bootstrap, elastic
    runtime = bootstrap.initialize()
    import jax
    if runtime.num_processes <= 1:
        # a cluster scaled down to ONE trainer (autoscaler donation —
        # examples/shared_fleet.py) never joins a distributed world,
        # but the spawn harness pre-configures gloo collectives, which
        # this jaxlib rejects without a distributed client: reset
        # before the first computation (the serving_replica discipline)
        import contextlib
        with contextlib.suppress(Exception):
            jax.config.update("jax_cpu_collectives_implementation",
                              "none")
    import numpy as np
    import optax
    from jax.experimental import multihost_utils

    from distributed_tensorflow_tpu.checkpoint.checkpoint import (
        Checkpoint, CheckpointManager)
    from distributed_tensorflow_tpu.checkpoint.peer_snapshot import (
        SnapshotStore)
    from distributed_tensorflow_tpu.models.mnist_cnn import (
        create_train_state, synthetic_data)
    from distributed_tensorflow_tpu.telemetry import events as tv_events

    tdir = os.environ.get(tv_events.ENV_TELEMETRY_DIR)
    if tdir:
        tv_events.configure(tdir, process_id=runtime.process_id)
    # live goodput ledger: per-step feeding below prices infeed/ckpt
    # blocking; enter("ckpt_block") names the bucket a stall during a
    # blocking save would accrue to
    from distributed_tensorflow_tpu.telemetry import goodput
    ledger = goodput.GoodputLedger()
    goodput.activate(ledger)

    state, model, tx = create_train_state(jax.random.PRNGKey(0),
                                          learning_rate=lr)
    params, opt_state = state["params"], state["opt_state"]
    data = synthetic_data(_POOL)

    def loss_fn(p, images, labels):
        logits = model.apply({"params": p}, images)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))

    @jax.jit
    def apply_fn(p, o, grads):
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o

    leaves, treedef = jax.tree_util.tree_flatten((params, opt_state))
    ckpt = Checkpoint(leaves=list(leaves))
    # snapshot_every == 0 disables the host/peer memory tiers entirely
    memdir = elastic.peer_memdir()
    store = (SnapshotStore(memdir, keep=snapshot_keep)
             if memdir and snapshot_every != 0 else None)
    mgr = CheckpointManager(ckpt, ckpt_dir, checkpoint_name="elastic",
                            local_dir=local_dir, snapshot_store=store)
    start_step = 0
    res = mgr.restore_latest()
    if res is not None:
        tier, start_step, restored = res
        params, opt_state = jax.tree_util.tree_unflatten(
            treedef, [restored[f"leaves/{i}"] for i in range(len(leaves))])
        print(f"[gen {runtime.generation} p{runtime.process_id}] resumed "
              f"at step {start_step} from the {tier} tier")

    nproc, pid = runtime.num_processes, runtime.process_id
    per_batch = max(1, global_batch // nproc)
    loss = float("nan")
    import time as _time

    def refresh_tracked():
        ckpt._objects["leaves"] = list(
            jax.tree_util.tree_flatten((params, opt_state))[0])

    for step in range(start_step, total_steps):
        elastic.heartbeat(step)
        if step_delay_s:
            # pacing for shared-fleet runs (examples/shared_fleet.py):
            # a trainer sharing the host with serving replicas models a
            # device-bound step so the 1-core container's CPU contention
            # doesn't drown the serving latency signal
            _time.sleep(step_delay_s)
        # Per-step phase attribution (the obs_report/trace_report phase
        # table): compute = local fwd/bwd + optimizer apply, collective
        # = the cross-process gradient allgather (host-driven here, so
        # it is ENTIRELY exposed — overlap_eff 0 by construction; the
        # compiled bucketed path in bench.py measures the overlapped
        # counterpart), ckpt_block = step-loop time blocked on
        # checkpoint capture/commit/snapshot.
        t0 = _time.perf_counter()
        start = (step * global_batch + pid * per_batch) % _POOL
        idx = (np.arange(per_batch) + start) % _POOL
        loss, grads = grad_fn(params, data["image"][idx],
                              data["label"][idx])
        loss = float(loss)               # block: fwd/bwd complete
        t1 = _time.perf_counter()
        if nproc > 1:
            grads = jax.tree_util.tree_map(
                lambda g: np.asarray(
                    multihost_utils.process_allgather(g)).mean(0), grads)
        t2 = _time.perf_counter()
        params, opt_state = apply_fn(params, opt_state, grads)
        jax.block_until_ready(params)
        t3 = _time.perf_counter()
        ckpt_s = 0.0
        if (step + 1) % save_every == 0:
            refresh_tracked()
            ledger.enter("ckpt_block")
            mgr.save(checkpoint_number=step + 1)
            ledger.enter("idle")
            ckpt_s = _time.perf_counter() - t3
        elif (store is not None and snapshot_every
              and (step + 1) % snapshot_every == 0):
            refresh_tracked()
            ledger.enter("ckpt_block")
            mgr.snapshot(step + 1)   # memory-only: the cheap hot tier
            ledger.enter("idle")
            ckpt_s = _time.perf_counter() - t3
        dur_s = _time.perf_counter() - t0
        tv_events.event(
            "train.step", step=step, loss=loss,
            dur_s=round(dur_s, 6),
            compute_s=round((t1 - t0) + (t3 - t2), 6),
            collective_s=round(t2 - t1, 6),
            ckpt_block_s=round(ckpt_s, 6))
        ledger.step_completed(dur_s, ckpt_s=ckpt_s)
        if step % 10 == 0 and pid == 0:
            print(f"[gen {runtime.generation}] step {step}: "
                  f"loss={float(loss):.4f}")
    ckpt.sync()
    bootstrap.shutdown()
    return runtime.process_id, start_step, float(loss)


def run_elastic(args):
    import tempfile

    from distributed_tensorflow_tpu.resilience import (
        RecoverySupervisor, seeded_kill_plan, seeded_shrink_plan)

    ckpt_dir = args.ckpt_dir or tempfile.mkdtemp(prefix="mnist_elastic_")
    local_dir = args.local_ckpt_dir
    if local_dir is None and not args.no_local_tier:
        local_dir = ckpt_dir.rstrip("/") + ".local"
    snapshot_every = args.snapshot_every
    if snapshot_every is None:
        snapshot_every = max(1, args.save_every // 2)
    kill_plan = ()
    if args.kill_seed is not None:
        step_range = (2, max(3, args.steps - 4))
        if args.permanent_kill:
            kill_plan = seeded_shrink_plan(args.kill_seed, args.workers,
                                           step_range=step_range)
        else:
            kill_plan = seeded_kill_plan(args.kill_seed, args.workers,
                                         kills=args.kills,
                                         step_range=step_range)
        print(f"chaos kill plan (seed {args.kill_seed}): {kill_plan}")
    sup = RecoverySupervisor(
        elastic_worker, num_workers=args.workers,
        args=(ckpt_dir, args.steps, args.save_every, args.global_batch,
              args.lr),
        kwargs={"local_dir": local_dir,
                "snapshot_every": 0 if args.no_snapshots
                else snapshot_every},
        max_restarts=args.restart_budget, kill_plan=kill_plan,
        shrink_after=args.shrink_after, min_workers=args.min_workers,
        generation_timeout_s=args.generation_timeout,
        telemetry_dir=args.telemetry_dir)
    result = sup.run()
    for pid, start_step, loss in sorted(result.return_values):
        print(f"worker {pid}: resumed@{start_step} final loss={loss:.4f}")
    print(f"done: {sup.restarts_used} restart(s), "
          f"{sup.failures_total} recorded failure(s), "
          f"final generation {sup.generation}, "
          f"final cluster size {sup.num_workers}")
    if args.telemetry_dir:
        print(f"recovery timeline: python tools/obs_report.py "
              f"{args.telemetry_dir}")


def run_data_service(args):
    import tempfile

    from distributed_tensorflow_tpu.resilience import RecoverySupervisor

    ckpt_dir = args.ckpt_dir or tempfile.mkdtemp(prefix="mnist_dsvc_")
    data_dir = os.path.join(ckpt_dir, "splits")
    files = write_mnist_split_files(data_dir, args.split_files)
    kill_plan = ()
    if args.kill_seed is not None:
        kill_plan = seeded_input_kill_plan(
            args.kill_seed, args.input_workers, kills=args.kills)
        print(f"input-worker kill plan (seed {args.kill_seed}): "
              f"{kill_plan}")
    sup = RecoverySupervisor(
        data_service_worker,
        num_workers=1 + args.input_workers,
        args=(data_dir, ckpt_dir, args.epochs, args.global_batch,
              args.lr, args.input_workers),
        max_restarts=args.restart_budget, kill_plan=kill_plan,
        generation_timeout_s=args.generation_timeout,
        telemetry_dir=args.telemetry_dir)
    result = sup.run()
    for value in sorted(result.return_values, key=str):
        print(f"task result: {value}")
    print(f"done: {len(files)} splits x {args.epochs} epochs over "
          f"{args.input_workers} input worker(s), "
          f"{sup.restarts_used} restart(s), "
          f"final generation {sup.generation}")
    if args.telemetry_dir:
        print(f"recovery timeline: python tools/obs_report.py "
              f"{args.telemetry_dir}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--global-batch", type=int, default=64)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--telemetry-dir", default=None,
                    help="enable telemetry: per-step train.step events "
                         "(JSONL) land here; render with "
                         "tools/obs_report.py")
    ap.add_argument("--elastic", action="store_true",
                    help="run as a multi-worker job under the recovery "
                         "supervisor (worker death -> reform -> resume)")
    ap.add_argument("--workers", type=int, default=2,
                    help="elastic: number of worker processes")
    ap.add_argument("--save-every", type=int, default=10,
                    help="elastic: checkpoint every N steps")
    ap.add_argument("--restart-budget", type=int, default=3,
                    help="elastic: max cluster reforms before "
                         "RecoveryFailedError")
    ap.add_argument("--ckpt-dir", default=None,
                    help="elastic: checkpoint directory (default: tmp)")
    ap.add_argument("--kill-seed", type=int, default=None,
                    help="elastic chaos: SIGKILL workers on a schedule "
                         "derived from this seed")
    ap.add_argument("--kills", type=int, default=1,
                    help="elastic chaos: number of scheduled kills")
    ap.add_argument("--permanent-kill", action="store_true",
                    help="elastic chaos: the seed-chosen worker's "
                         "machine dies for good (kill re-fires every "
                         "generation; pair with --shrink-after)")
    ap.add_argument("--shrink-after", type=int, default=None,
                    help="elastic: after N failed restarts of the same "
                         "task, reform at one fewer worker "
                         "(topology-elastic resharded restore)")
    ap.add_argument("--min-workers", type=int, default=1,
                    help="elastic: never shrink below this many workers")
    ap.add_argument("--local-ckpt-dir", default=None,
                    help="elastic: node-local fast checkpoint tier "
                         "(default: <ckpt-dir>.local)")
    ap.add_argument("--no-local-tier", action="store_true",
                    help="elastic: disable the local disk tier")
    ap.add_argument("--snapshot-every", type=int, default=None,
                    help="elastic: host-snapshot cadence between disk "
                         "saves (default: save-every // 2)")
    ap.add_argument("--no-snapshots", action="store_true",
                    help="elastic: disable host/peer snapshot tiers")
    ap.add_argument("--generation-timeout", type=float, default=600.0,
                    help="elastic: per-generation wall budget (s)")
    ap.add_argument("--data-service", action="store_true",
                    help="run with a disaggregated input service under "
                         "the recovery supervisor: task 0 trains (and "
                         "dispatches FILE splits), tasks 1..M execute "
                         "the input pipeline under heartbeat-backed "
                         "leases (--kill-seed SIGKILLs input workers)")
    ap.add_argument("--input-workers", type=int, default=2,
                    help="data-service: input-worker tasks")
    ap.add_argument("--epochs", type=int, default=2,
                    help="data-service: epochs (each = one exactly-once "
                         "pass over every FILE split)")
    ap.add_argument("--split-files", type=int, default=8,
                    help="data-service: FILE splits the sample pool is "
                         "sharded into")
    args = ap.parse_args()

    if args.data_service:
        run_data_service(args)
        return
    if args.elastic:
        run_elastic(args)
        return

    import jax

    from distributed_tensorflow_tpu import telemetry
    from distributed_tensorflow_tpu.input.dataset import Dataset
    from distributed_tensorflow_tpu.models.mnist_cnn import (
        create_train_state, make_train_step, synthetic_data)
    from distributed_tensorflow_tpu.parallel.mirrored import MirroredStrategy

    exporter = None
    if args.telemetry_dir:
        telemetry.configure(args.telemetry_dir)
        # live scrape: metrics-live.prom in the run dir (plus /metrics
        # when DTX_METRICS_PORT is set)
        exporter = telemetry.MetricsExporter(dir=args.telemetry_dir)

    strategy = MirroredStrategy()
    print(f"devices: {strategy.num_replicas_in_sync} replicas on "
          f"{jax.default_backend()}")

    data = synthetic_data(4096)
    ds = Dataset.from_tensor_slices(data).shuffle(4096).batch(
        args.global_batch).repeat()
    dist_ds = strategy.experimental_distribute_dataset(ds)

    state, model, tx = create_train_state(jax.random.PRNGKey(0),
                                          learning_rate=args.lr)
    # native path (SURVEY §3.4): replicated state + ONE compiled SPMD
    # step; the distributed dataset lands batches sharded over the mesh
    state = strategy.replicate(state)
    step_fn = strategy.compile_step(make_train_step(model, tx))

    from distributed_tensorflow_tpu.training.loops import StepTelemetry
    steps_telemetry = StepTelemetry()
    it = iter(dist_ds)
    for step in range(args.steps):
        state, metrics = step_fn(state, next(it))
        log_step = step % 20 == 0 or step == args.steps - 1
        steps_telemetry.step_completed(
            step, loss=metrics["loss"] if log_step else None)
        if log_step:
            print(f"step {step}: loss={float(metrics['loss']):.4f} "
                  f"acc={float(metrics['accuracy']):.3f}")
    print("done")
    if exporter is not None:
        exporter.stop()
    telemetry.shutdown()


if __name__ == "__main__":
    main()
