#!/usr/bin/env python
"""Config #1: MNIST CNN under MirroredStrategy semantics (BASELINE.md).

Single-host synchronous data parallelism — the TPU-native counterpart of
the reference's `MirroredStrategy` Keras script, on the NATIVE path
(SURVEY §3.4): distribute dataset -> replicate state -> one compiled
SPMD step via `strategy.compile_step`. For the TF-parity
scope()/run()/merge_call surface, see tests/test_strategy.py and the
conformance suite (testing/strategy_conformance.py); for the Keras-style
`Model.fit` layer, see distributed_tensorflow_tpu/training.
"""

import argparse

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu import telemetry
from distributed_tensorflow_tpu.input.dataset import Dataset
from distributed_tensorflow_tpu.models.mnist_cnn import (
    create_train_state, make_train_step, synthetic_data)
from distributed_tensorflow_tpu.parallel.mirrored import MirroredStrategy


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--global-batch", type=int, default=64)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--telemetry-dir", default=None,
                    help="enable telemetry: per-step train.step events "
                         "(JSONL) land here; render with "
                         "tools/obs_report.py")
    args = ap.parse_args()
    if args.telemetry_dir:
        telemetry.configure(args.telemetry_dir)

    strategy = MirroredStrategy()
    print(f"devices: {strategy.num_replicas_in_sync} replicas on "
          f"{jax.default_backend()}")

    data = synthetic_data(4096)
    ds = Dataset.from_tensor_slices(data).shuffle(4096).batch(
        args.global_batch).repeat()
    dist_ds = strategy.experimental_distribute_dataset(ds)

    state, model, tx = create_train_state(jax.random.PRNGKey(0),
                                          learning_rate=args.lr)
    # native path (SURVEY §3.4): replicated state + ONE compiled SPMD
    # step; the distributed dataset lands batches sharded over the mesh
    state = strategy.replicate(state)
    step_fn = strategy.compile_step(make_train_step(model, tx))

    from distributed_tensorflow_tpu.training.loops import StepTelemetry
    steps_telemetry = StepTelemetry()
    it = iter(dist_ds)
    for step in range(args.steps):
        state, metrics = step_fn(state, next(it))
        log_step = step % 20 == 0 or step == args.steps - 1
        steps_telemetry.step_completed(
            step, loss=metrics["loss"] if log_step else None)
        if log_step:
            print(f"step {step}: loss={float(metrics['loss']):.4f} "
                  f"acc={float(metrics['accuracy']):.3f}")
    print("done")
    telemetry.shutdown()


if __name__ == "__main__":
    main()
