"""Config #2 (ResNet-50 classifier) as a VERBATIM reference-style
FUNCTIONAL-API Keras script.

Written exactly the way the reference's ResNet training script is
(SURVEY.md §3.1 / TFK/src/applications/resnet.py style: functional
graph with identity/conv blocks under strategy.scope, compile, fit) —
the ONLY line that differs from the tf_keras original is the import.
Residual connections make this impossible in Sequential; it exercises
keras.Model(inputs, outputs), layers.Add, ZeroPadding2D and
BatchNormalization through the functional shim
(training/functional.py ≙ TFK/src/engine/functional.py:84).

    reference:  import tensorflow as tf; keras = tf.keras
    here:       from distributed_tensorflow_tpu import keras
"""

import numpy as np

import distributed_tensorflow_tpu as tf_distribute
from distributed_tensorflow_tpu import keras

layers = keras.layers


def identity_block(x, filters, kernel_size=3):
    """Standard ResNet identity block (1x1 -> 3x3 -> 1x1 + shortcut)."""
    f1, f2, f3 = filters
    shortcut = x
    x = layers.Conv2D(f1, 1)(x)
    x = layers.BatchNormalization()(x)
    x = layers.Activation("relu")(x)
    x = layers.Conv2D(f2, kernel_size, padding="same")(x)
    x = layers.BatchNormalization()(x)
    x = layers.Activation("relu")(x)
    x = layers.Conv2D(f3, 1)(x)
    x = layers.BatchNormalization()(x)
    x = layers.Add()([x, shortcut])
    return layers.Activation("relu")(x)


def conv_block(x, filters, kernel_size=3, strides=2):
    """ResNet conv block: projection shortcut with stride."""
    f1, f2, f3 = filters
    shortcut = layers.Conv2D(f3, 1, strides=strides)(x)
    shortcut = layers.BatchNormalization()(shortcut)
    x = layers.Conv2D(f1, 1, strides=strides)(x)
    x = layers.BatchNormalization()(x)
    x = layers.Activation("relu")(x)
    x = layers.Conv2D(f2, kernel_size, padding="same")(x)
    x = layers.BatchNormalization()(x)
    x = layers.Activation("relu")(x)
    x = layers.Conv2D(f3, 1)(x)
    x = layers.BatchNormalization()(x)
    x = layers.Add()([x, shortcut])
    return layers.Activation("relu")(x)


def build_resnet50(input_shape=(64, 64, 3), classes=10):
    """ResNet-50: [3, 4, 6, 3] bottleneck stages, keras-application
    style (TFK/src/applications/resnet.py ResNet50 stack)."""
    inputs = keras.Input(shape=input_shape)
    x = layers.ZeroPadding2D(3)(inputs)
    x = layers.Conv2D(64, 7, strides=2)(x)
    x = layers.BatchNormalization()(x)
    x = layers.Activation("relu")(x)
    x = layers.ZeroPadding2D(1)(x)
    x = layers.MaxPooling2D(3, strides=2)(x)

    x = conv_block(x, [64, 64, 256], strides=1)
    for _ in range(2):
        x = identity_block(x, [64, 64, 256])
    x = conv_block(x, [128, 128, 512])
    for _ in range(3):
        x = identity_block(x, [128, 128, 512])
    x = conv_block(x, [256, 256, 1024])
    for _ in range(5):
        x = identity_block(x, [256, 256, 1024])
    x = conv_block(x, [512, 512, 2048])
    for _ in range(2):
        x = identity_block(x, [512, 512, 2048])

    x = layers.GlobalAveragePooling2D()(x)
    outputs = layers.Dense(classes)(x)
    return keras.Model(inputs=inputs, outputs=outputs)


def load_data(n=2048, shape=(64, 64, 3), seed=0):
    """Synthetic ImageNet-shaped data (zero-egress environment); labels
    derived from image statistics so the model can actually fit."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, *shape)).astype("float32")
    y = (np.abs(x.mean(axis=(1, 2, 3))) * 400).astype("int32") % 10
    return (x[: n - 256], y[: n - 256]), (x[n - 256:], y[n - 256:])


def main():
    (x_train, y_train), (x_test, y_test) = load_data()

    strategy = tf_distribute.MirroredStrategy()
    with strategy.scope():
        model = build_resnet50()
        model.compile(
            optimizer=keras.optimizers.SGD(0.05, momentum=0.9),
            loss=keras.losses.SparseCategoricalCrossentropy(
                from_logits=True),
            metrics=["accuracy"],
        )

    model.fit(x_train, y_train, batch_size=64, epochs=2,
              validation_data=(x_test, y_test))
    loss, acc = model.evaluate(x_test, y_test, batch_size=64)
    print(f"eval loss {loss:.4f}  accuracy {acc:.4f}")


if __name__ == "__main__":
    main()
