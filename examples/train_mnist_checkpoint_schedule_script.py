"""Config #1 with CHECKPOINTING + LR SCHEDULE, verbatim reference style.

The reference's production training scripts nearly always combine
``ModelCheckpoint`` + ``model.save`` + a ``keras.optimizers.schedules``
learning-rate schedule (TFK/src/engine/training.py:2779 save;
TFK/src/optimizers/schedules/). This script exercises that surface with
ONLY the import changed:

    reference:  import tensorflow as tf; keras = tf.keras
    here:       from distributed_tensorflow_tpu import keras
"""

import os
import tempfile

import numpy as np

import distributed_tensorflow_tpu as tf_distribute
from distributed_tensorflow_tpu import keras


def load_data(n=4096, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 28, 28, 1)).astype("float32")
    y = (np.abs(x.mean(axis=(1, 2, 3))) * 40).astype("int32") % 10
    return (x[: n - 512], y[: n - 512]), (x[n - 512:], y[n - 512:])


def main():
    (x_train, y_train), (x_test, y_test) = load_data()
    workdir = tempfile.mkdtemp(prefix="mnist_ckpt_")

    strategy = tf_distribute.MirroredStrategy()
    with strategy.scope():
        model = keras.Sequential([
            keras.Input((28, 28, 1)),
            keras.layers.Conv2D(32, 3, padding="same", activation="relu"),
            keras.layers.MaxPooling2D(2),
            keras.layers.Flatten(),
            keras.layers.Dense(128, activation="relu"),
            keras.layers.Dense(10),
        ])
        lr_schedule = keras.optimizers.schedules.ExponentialDecay(
            initial_learning_rate=1e-3, decay_steps=100, decay_rate=0.9)
        model.compile(
            optimizer=keras.optimizers.Adam(lr_schedule),
            loss=keras.losses.SparseCategoricalCrossentropy(
                from_logits=True),
            metrics=["accuracy"],
        )

    checkpoint_cb = keras.callbacks.ModelCheckpoint(
        os.path.join(workdir, "ckpt-{epoch}"), monitor="val_loss",
        save_best_only=True, save_weights_only=False)
    model.fit(x_train, y_train, batch_size=256, epochs=3,
              validation_data=(x_test, y_test), callbacks=[checkpoint_cb])

    model.save(os.path.join(workdir, "final_model"))
    restored = keras.models.load_model(os.path.join(workdir, "final_model"))
    restored.compile(
        optimizer=keras.optimizers.Adam(1e-4),
        loss=keras.losses.SparseCategoricalCrossentropy(from_logits=True),
        metrics=["accuracy"],
    )
    loss, acc = restored.evaluate(x_test, y_test, batch_size=256)
    print(f"restored-model eval loss {loss:.4f}  accuracy {acc:.4f}")


if __name__ == "__main__":
    main()
