"""Config #3 (BERT-style encoder classifier) as a VERBATIM
reference-style FUNCTIONAL-API Keras script.

Written the way the reference's BERT fine-tuning scripts compose an
encoder in keras (functional graph of MultiHeadAttention + residual
Add + LayerNormalization blocks — TFK/src/layers/attention/
multi_head_attention.py); the ONLY line that differs from the tf_keras
original is the import.

    reference:  import tensorflow as tf; keras = tf.keras
    here:       from distributed_tensorflow_tpu import keras
"""

import numpy as np

import distributed_tensorflow_tpu as tf_distribute
from distributed_tensorflow_tpu import keras

layers = keras.layers


def encoder_block(x, d_model, num_heads, ff_dim, dropout=0.1):
    """Post-LN transformer encoder block, keras-tutorial style."""
    attn = layers.MultiHeadAttention(num_heads, d_model // num_heads,
                                     dropout=dropout)(x, x)
    attn = layers.Dropout(dropout)(attn)
    x = layers.LayerNormalization(epsilon=1e-6)(layers.Add()([x, attn]))
    ff = layers.Dense(ff_dim, activation="gelu")(x)
    ff = layers.Dense(d_model)(ff)
    ff = layers.Dropout(dropout)(ff)
    return layers.LayerNormalization(epsilon=1e-6)(
        layers.Add()([x, ff]))


def build_encoder(vocab_size=1000, seq_len=64, d_model=64, num_heads=4,
                  ff_dim=256, num_blocks=2, classes=4):
    inputs = keras.Input(shape=(seq_len,), dtype="int32")
    x = layers.Embedding(vocab_size, d_model)(inputs)
    for _ in range(num_blocks):
        x = encoder_block(x, d_model, num_heads, ff_dim)
    x = layers.GlobalAveragePooling1D()(x)
    x = layers.Dense(d_model, activation="tanh")(x)   # pooler
    outputs = layers.Dense(classes)(x)
    return keras.Model(inputs=inputs, outputs=outputs)


def load_data(n=2048, seq_len=64, vocab=1000, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.integers(1, vocab, size=(n, seq_len)).astype("int32")
    y = (x[:, :8].sum(axis=1) % 4).astype("int32")
    return (x[: n - 256], y[: n - 256]), (x[n - 256:], y[n - 256:])


def main():
    (x_train, y_train), (x_test, y_test) = load_data()

    strategy = tf_distribute.MirroredStrategy()
    with strategy.scope():
        model = build_encoder()
        model.compile(
            optimizer=keras.optimizers.Adam(5e-4),
            loss=keras.losses.SparseCategoricalCrossentropy(
                from_logits=True),
            metrics=["accuracy"],
        )

    model.fit(x_train, y_train, batch_size=64, epochs=3,
              validation_data=(x_test, y_test))
    loss, acc = model.evaluate(x_test, y_test, batch_size=64)
    print(f"eval loss {loss:.4f}  accuracy {acc:.4f}")


if __name__ == "__main__":
    main()
