#!/usr/bin/env python
"""Multi-tenant routed serving: affinity router + supervised replicas.

Runs the full ISSUE-20 stack end to end, twice, on ONE seeded
multi-tenant workload (interactive + batch + a quota-capped tenant,
with an interactive traffic spike):

- **affinity phase** (chaos): N router-fed replicas under the recovery
  supervisor (``serving.replica.routed_replica`` — each tails its
  inbox file, exports live metrics, logs completions), with the router
  (``serving.router.Router``) running as its own process: it paces the
  seeded arrivals, admits under per-tenant quotas + weighted-fair
  priority classes, routes by prefix-cache affinity (least-loaded by
  scraped queue depth as fallback), journals every decision, acks from
  the fleet completion-log union, and re-routes unacked work off
  replicas whose metrics scrape goes stale. ``--kill-seed`` SIGKILLs a
  replica mid-load (supervisor chaos plan) AND SIGKILLs the router at
  a seeded wall time — the respawned router resumes from its journal
  without double-routing.
- **random phase** (clean): the SAME workload through ``--policy
  random`` — the same-seed baseline the affinity hit-rate is gated
  against.

``analyze`` then writes ``router-summary.json``: zero-dropped +
byte-identical-duplicate verdicts (the PR 9 completion-log contract
extended across replicas), affinity-vs-random measured hit rates,
per-tenant admit/reject/shed counts, per-class latency with the
interactive recovery + batch-starvation verdicts, the goodput identity
with the re-route cost priced in ``reroute_replay``, and the
journal's double-route audit. ``tools/chaos_sweep.py --router`` runs
this example across seeds and gates that summary.
"""

import argparse
import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

#: workload shape shared by both phases (and by the chaos sweep):
#: spike multiplies INTERACTIVE arrival rates inside the window
WORKLOAD = dict(duration_s=22.0, spike=(6.0, 12.0, 4.0),
                sessions_per_tenant=6, session_prefix_blocks=3,
                block_size=8, rates={"acme": 2.5, "batchco": 1.2,
                                     "burst": 1.5})

#: chaos variant: arrivals must OUTLAST the gang-restart outage
#: (supervisor respawn + jax re-init + warmup is ~15-20s on a small
#: box) so the recovery window has post-outage samples to judge
CHAOS_WORKLOAD = dict(WORKLOAD, duration_s=44.0,
                      rates={"acme": 1.8, "batchco": 0.8,
                             "burst": 1.2})

#: arrivals this long after the disturbance ends (spike end, or the
#: last respawned replica's warmup under chaos) must meet the tenant
#: SLO again — the backlog needs a drain window first. The chaos lag
#: is longer: an outage parks ~15s of admitted arrivals at the router,
#: and the fleet needs the extra seconds to chew through that backlog
RECOVERY_LAG_S = 5.0
CHAOS_RECOVERY_LAG_S = 10.0


def workload_params(chaos: bool) -> dict:
    return CHAOS_WORKLOAD if chaos else WORKLOAD


def phase_tenants():
    """The three-tenant contract the example serves: a weighted
    interactive tenant, a batch tenant with a long SLO and early
    anti-starvation promotion, and a quota-capped interactive tenant
    whose overrun exercises ``serve.reject cause=quota``."""
    from distributed_tensorflow_tpu.serving.tenancy import TenantConfig
    return (
        TenantConfig("acme", pclass="interactive", weight=2.0,
                     slo_latency_s=2.0),
        TenantConfig("batchco", pclass="batch", weight=1.0,
                     slo_latency_s=15.0, starvation_frac=0.15),
        TenantConfig("burst", pclass="interactive", weight=1.0,
                     quota_tokens_per_s=40.0, quota_burst=80.0,
                     slo_latency_s=2.0),
    )


def router_main(run_dir: str, tdir: str, seed: int, policy: str,
                n_replicas: int, chaos: bool = False,
                tick_s: float = 0.04,
                tick_token_budget: int = 16,
                max_wall_s: float = 240.0):
    """The router process (spawn target; both incarnations run this —
    the second resumes from the journal the first left behind)."""
    from distributed_tensorflow_tpu.serving import replica as rep
    from distributed_tensorflow_tpu.serving import router as rt
    from distributed_tensorflow_tpu.telemetry import events as tv_events

    tv_events.configure(tdir, process_id="router")
    replicas = list(range(n_replicas))

    # wait until every replica's exporter has ticked once (its engine
    # is warm): arrivals must not start while the fleet is compiling
    mfile = {t: os.path.join(rep.replica_metrics_dir(run_dir, t),
                             "metrics-live.prom") for t in replicas}
    deadline = time.time() + 120.0
    while (not all(os.path.exists(p) for p in mfile.values())
           and time.time() < deadline
           and not os.path.exists(os.path.join(run_dir,
                                               "run-epoch.json"))):
        time.sleep(0.05)
    epoch = rep.run_epoch(run_dir)      # first router incarnation wins

    def clock():
        return time.time() - epoch

    def submit(replica, request, meta):
        # line-buffered append; the replica tolerates the torn tail of
        # a mid-write router SIGKILL by rewinding partial lines
        with open(rep.inbox_path(run_dir, replica), "a",
                  buffering=1) as f:
            f.write(json.dumps(rep.request_to_wire(request, meta))
                    + "\n")

    router = rt.Router(replicas=replicas, tenants=phase_tenants(),
                       submit_fn=submit, policy=policy, block_size=8,
                       tick_token_budget=tick_token_budget, seed=seed,
                       run_dir=run_dir, reroute_timeout_s=3.0,
                       clock=clock)
    wl = rt.seeded_tenant_workload(seed, tenants=phase_tenants(),
                                   **workload_params(chaos))
    import collections
    pending = collections.deque(wl)
    seen = {}              # replica -> last scrape mtime
    t_end = time.time() + max_wall_s
    while time.time() < t_end:
        now = clock()
        while pending and pending[0].arrival_s <= now:
            router.offer(pending.popleft())
        depths = {}
        stale = set()
        for t, p in mfile.items():
            try:
                m = os.path.getmtime(p)
            except OSError:
                continue
            seen[t] = m
            if time.time() - m > 1.5:
                stale.add(t)
            else:
                d = rt.parse_queue_depth(p)
                if d is not None:
                    depths[t] = d
        router.observe_depths(depths)
        router.dispatch(stale=stale)
        router.note_completed(rep.completed_ids_all(run_dir))
        router.tick_reroutes(stale=stale)
        if not pending and not router.queued and not router.inflight:
            break
        time.sleep(tick_s)
    router.emit_tenant_summary()
    stats = router.stats()
    stats["drained_clean"] = (not pending and not router.queued
                              and not router.inflight)
    tmp = os.path.join(run_dir, "router-stats.json.tmp")
    with open(tmp, "w") as f:
        json.dump(stats, f, indent=2, default=str)
    os.replace(tmp, os.path.join(run_dir, "router-stats.json"))
    for t in replicas:                  # release the fleet
        with open(rep.inbox_path(run_dir, t), "a", buffering=1) as f:
            f.write(json.dumps({"eof": True}) + "\n")
    router.close()
    tv_events.shutdown()
    print(f"[router] done: {stats['routes']} routed, "
          f"{stats['reroutes']} rerouted, "
          f"{stats['acked']} acked", flush=True)


def run_phase(phase_dir: str, seed: int, policy: str, workers: int,
              kill_seed=None, router_kill_s=None):
    """One phase: supervisor-run replica fleet + router process (killed
    and respawned once when ``router_kill_s`` is set)."""
    import multiprocessing as mp
    import threading

    from distributed_tensorflow_tpu.resilience import (
        RecoverySupervisor, seeded_kill_plan)
    from distributed_tensorflow_tpu.serving.replica import routed_replica

    os.makedirs(phase_dir, exist_ok=True)
    tdir = os.path.join(phase_dir, "telemetry")
    kill_plan = ()
    if kill_seed is not None:
        kill_plan = seeded_kill_plan(kill_seed, workers, kills=1,
                                     step_range=(40, 120))
        print(f"[{os.path.basename(phase_dir)}] replica kill plan "
              f"(seed {kill_seed}): {kill_plan}")

    ctx = mp.get_context("spawn")
    rargs = (phase_dir, tdir, seed, policy, workers,
             kill_seed is not None)
    router_proc = ctx.Process(target=router_main, args=rargs,
                              name="dtx-router")
    router_proc.start()
    router_kills = []

    def _chaos_router():
        time.sleep(router_kill_s)
        if router_proc.is_alive():
            print(f"[chaos] SIGKILL router pid {router_proc.pid} at "
                  f"t+{router_kill_s:.1f}s", flush=True)
            os.kill(router_proc.pid, signal.SIGKILL)
            router_proc.join()
            router_kills.append(time.time())
            r2 = ctx.Process(target=router_main, args=rargs,
                             name="dtx-router-2")
            r2.start()
            router_kills.append(r2)

    killer = None
    if router_kill_s is not None:
        killer = threading.Thread(target=_chaos_router, daemon=True)
        killer.start()

    sup = RecoverySupervisor(
        routed_replica, num_workers=workers, args=(phase_dir, seed),
        kwargs={"step_delay_s": 0.0},
        max_restarts=6, kill_plan=kill_plan,
        generation_timeout_s=300.0, telemetry_dir=tdir)
    result = sup.run()
    if killer is not None:
        killer.join(timeout=60.0)
    # join whichever router incarnation is current
    last = router_kills[-1] if (router_kills
                                and hasattr(router_kills[-1], "join")) \
        else router_proc
    last.join(timeout=90.0)
    if last.is_alive():
        last.terminate()
        last.join(timeout=10.0)
    for task, served, total in sorted(result.return_values):
        print(f"[{os.path.basename(phase_dir)}] replica {task}: "
              f"served {served} this generation")
    return {"restarts": sup.restarts_used,
            "router_killed": bool(router_kills)}


# ---------------------------------------------------------------------------
# analysis
# ---------------------------------------------------------------------------

def _hit_rate(tdir: str) -> "tuple[float, int]":
    """Measured prefix-cache hit rate over a phase's ``serve.prefill``
    events (warmups excluded): hit tokens / prompt tokens."""
    from distributed_tensorflow_tpu.telemetry import events as tv_events
    cached = prompt = 0
    for events in tv_events.read_run(tdir).values():
        for ev in events:
            if ev.get("ev") != "serve.prefill" \
                    or str(ev.get("id", "")).startswith("warmup-"):
                continue
            prompt += int(ev.get("prompt_tokens") or 0)
            cached += int(ev.get("cached_tokens") or 0)
    return (cached / prompt if prompt else 0.0), prompt


def analyze(run_dir: str, seed: int, chaos: bool = False) -> dict:
    """Cross-phase verdicts -> ``router-summary.json`` (the chaos
    sweep's gate surface)."""
    from distributed_tensorflow_tpu.serving import replica as rep
    from distributed_tensorflow_tpu.serving import router as rt
    from distributed_tensorflow_tpu.telemetry import events as tv_events
    from distributed_tensorflow_tpu.telemetry import goodput

    aff = os.path.join(run_dir, "affinity")
    rnd = os.path.join(run_dir, "random")
    tenants = {t.name: t for t in phase_tenants()}
    wl = rt.seeded_tenant_workload(seed, tenants=phase_tenants(),
                                   **workload_params(chaos))

    # ---- zero dropped + byte-identical duplicates (affinity phase) --
    journal = rt.RouterJournal.replay(
        os.path.join(aff, rt.ROUTER_JOURNAL))
    rejected = {r["id"] for r in journal if r["kind"] == "reject"}
    route_counts: dict = {}
    for r in journal:
        if r["kind"] == "route":
            route_counts[r["id"]] = route_counts.get(r["id"], 0) + 1
    double_routes = sum(1 for n in route_counts.values() if n > 1)
    served_tokens: dict = {}
    duplicates = mismatched = 0
    import glob as _glob
    for path in sorted(_glob.glob(os.path.join(aff, "served-*.jsonl"))):
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                rid, toks = rec.get("id"), rec.get("tokens")
                if rid is None:
                    continue
                if rid in served_tokens:
                    duplicates += 1
                    if served_tokens[rid] != toks:
                        mismatched += 1
                else:
                    served_tokens[rid] = toks
    expected = {r.id for r in wl} - rejected
    dropped = sorted(expected - set(served_tokens))

    # ---- per-class latency + recovery/starvation verdicts -----------
    by_class: dict = {}     # pclass -> [(rid, lat)]
    reject_by: dict = {}
    sheds = 0
    spike_end = workload_params(chaos)["spike"][1]
    last_warm_wall = None   # when the LAST (re)spawned replica warmed
    for events in tv_events.read_run(
            os.path.join(aff, "telemetry")).values():
        for ev in events:
            name = ev.get("ev")
            if name == "serve.request" and ev.get("tenant"):
                lat = float(ev.get("dur_s") or 0.0)
                by_class.setdefault(ev.get("pclass"), []).append(
                    (ev.get("id"), lat))
            elif name == "serve.prefill" \
                    and str(ev.get("id", "")).startswith("warmup-"):
                w = float(ev.get("wall") or 0.0)
                if last_warm_wall is None or w > last_warm_wall:
                    last_warm_wall = w
            elif name == "serve.reject":
                key = (ev.get("tenant") or "-",
                       ev.get("cause") or "-")
                reject_by[key] = reject_by.get(key, 0) + 1
            elif name == "router.shed":
                sheds += 1

    def _pct(vals, q):
        if not vals:
            return None
        vals = sorted(vals)
        return vals[min(len(vals) - 1, int(q * (len(vals) - 1)))]

    # recovery window: arrivals after the LAST disturbance settle —
    # the spike end, or (under chaos) the moment the last respawned
    # replica finished its warmup, whichever is later — plus a drain
    # lag. Earlier arrivals carry the honest cost of the outage; the
    # gate is that service RECOVERS, not that kills are free.
    recover_rel = spike_end
    epoch_path = os.path.join(aff, "run-epoch.json")
    if last_warm_wall is not None and os.path.exists(epoch_path):
        with open(epoch_path) as f:
            epoch = float(json.load(f)["epoch"])
        recover_rel = max(recover_rel, last_warm_wall - epoch)
    recover_rel += CHAOS_RECOVERY_LAG_S if chaos else RECOVERY_LAG_S
    arrivals = {r.id: r.arrival_s for r in wl}

    def _window(pclass):
        return [lat for rid, lat in by_class.get(pclass, [])
                if arrivals.get(rid, -1.0) >= recover_rel]

    post = _window("interactive")
    batch_post = _window("batch")
    acme_slo = tenants["acme"].slo_latency_s
    interactive_recovered = (bool(post)
                             and (_pct(post, 0.99) or 9e9) <= acme_slo)
    batch_lats = [lat for _, lat in by_class.get("batch", [])]
    batch_slo = tenants["batchco"].slo_latency_s
    if chaos:
        # outage-spanning batch waits are the outage's cost, not
        # starvation; starvation = batch STILL past its SLO after the
        # fleet recovered
        batch_starved = (bool(batch_post)
                         and (_pct(batch_post, 0.99) or 9e9)
                         > batch_slo)
    else:
        batch_starved = bool(batch_lats) and max(batch_lats) > batch_slo

    # ---- affinity vs random hit rate (same seeded workload) ---------
    hit_aff, ptoks_aff = _hit_rate(os.path.join(aff, "telemetry"))
    hit_rnd, ptoks_rnd = _hit_rate(os.path.join(rnd, "telemetry"))

    # ---- goodput identity with the re-route cost priced -------------
    ledger = goodput.ledger_from_run(os.path.join(aff, "telemetry"))
    wall = ledger.get("wall_s") or 0.0
    identity_frac = (abs(ledger.get("identity_error_s") or 0.0)
                     / wall if wall > 0 else 0.0)

    stats_path = os.path.join(aff, "router-stats.json")
    router_stats = {}
    if os.path.exists(stats_path):
        with open(stats_path) as f:
            router_stats = json.load(f)

    summary = {
        "seed": seed,
        "requests": len(wl),
        "rejected_quota": len(rejected),
        "served_unique": len(served_tokens),
        "dropped": dropped,
        "duplicates": duplicates,
        "duplicates_mismatched": mismatched,
        "double_routes": double_routes,
        "reroutes": router_stats.get("reroutes", 0),
        "route_reasons": router_stats.get("route_reasons", {}),
        "sheds": sheds,
        "rejects_by_tenant_cause": {f"{t}/{c}": n for (t, c), n
                                    in sorted(reject_by.items())},
        "interactive_p50_s": _pct([lat for _, lat in
                                   by_class.get("interactive", [])],
                                  0.5),
        "interactive_p99_s": _pct([lat for _, lat in
                                   by_class.get("interactive", [])],
                                  0.99),
        "batch_p50_s": _pct(batch_lats, 0.5),
        "batch_p99_s": _pct(batch_lats, 0.99),
        "batch_max_s": max(batch_lats) if batch_lats else None,
        "interactive_recovered": interactive_recovered,
        "interactive_recovery_p99_s": _pct(post, 0.99),
        "recovery_window_start_s": round(recover_rel, 2),
        "recovery_samples": {"interactive": len(post),
                             "batch": len(batch_post)},
        "batch_recovery_p99_s": _pct(batch_post, 0.99),
        "batch_starved_past_slo": batch_starved,
        "affinity_hit_rate": round(hit_aff, 4),
        "random_hit_rate": round(hit_rnd, 4),
        "affinity_uplift": round(hit_aff - hit_rnd, 4),
        "prompt_tokens": {"affinity": ptoks_aff, "random": ptoks_rnd},
        "goodput_frac": ledger.get("goodput_frac"),
        "identity_error_frac": round(identity_frac, 6),
        "badput_reroute_replay_s": round(
            ledger["badput_s"].get("reroute_replay", 0.0), 4),
        "badput_recovery_s": round(
            ledger["badput_s"].get("recovery", 0.0), 4),
    }
    out = os.path.join(run_dir, "router-summary.json")
    with open(out + ".tmp", "w") as f:
        json.dump(summary, f, indent=2)
    os.replace(out + ".tmp", out)
    return summary


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--run-dir", required=True)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--kill-seed", type=int, default=None,
                    help="SIGKILL one replica mid-load (supervisor "
                         "chaos plan) AND the router at a seeded wall "
                         "time")
    ap.add_argument("--skip-random", action="store_true",
                    help="skip the random-routing baseline phase")
    args = ap.parse_args()
    os.makedirs(args.run_dir, exist_ok=True)

    router_kill_s = None
    if args.kill_seed is not None:
        import random as _random
        rng = _random.Random(f"dtx-router-kill:{args.kill_seed}")
        # land inside the spike window, after warmup
        router_kill_s = 8.0 + 4.0 * rng.random()

    t0 = time.time()
    info = run_phase(os.path.join(args.run_dir, "affinity"),
                     args.seed, "affinity", args.workers,
                     kill_seed=args.kill_seed,
                     router_kill_s=router_kill_s)
    print(f"[affinity] phase done in {time.time() - t0:.1f}s: {info}")
    if not args.skip_random:
        # the baseline suffers the SAME kill plan — affinity-vs-random
        # is only a fair comparison if both phases lose the same caches
        t1 = time.time()
        info2 = run_phase(os.path.join(args.run_dir, "random"),
                          args.seed, "random", args.workers,
                          kill_seed=args.kill_seed,
                          router_kill_s=router_kill_s)
        print(f"[random] phase done in {time.time() - t1:.1f}s: "
              f"{info2}")
        summary = analyze(args.run_dir, args.seed,
                          chaos=args.kill_seed is not None)
        print(json.dumps(summary, indent=2))
        ok = (not summary["dropped"]
              and summary["duplicates_mismatched"] == 0
              and summary["double_routes"] == 0
              and summary["interactive_recovered"]
              and not summary["batch_starved_past_slo"]
              and summary["affinity_hit_rate"]
              > summary["random_hit_rate"])
        print(f"router verdict: {'OK' if ok else 'VIOLATIONS'}")
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
