// Native data-pipeline core: threaded record reader + prefetch ring.
//
// TPU-native counterpart of the reference's C++ tf.data engine (the
// reference's input pipeline bottoms out in tensorflow/core/data/ C++
// iterators with prefetch + parallel interleave; SURVEY.md §2.7 requires
// native equivalents, not Python stand-ins). Host-side input processing
// must keep TPU infeed saturated without fighting the Python GIL, so the
// hot loop — file IO, shuffling, batch assembly — lives here.
//
// Design:
//  - Fixed-size binary records in one or more files (the on-disk layout
//    a converter writes once; ≙ TFRecord without the varint framing).
//  - Worker threads read+assemble whole batches into reusable buffers.
//  - A bounded MPMC ring hands filled buffers to the consumer (Python via
//    ctypes, zero-copy numpy view), which returns them to a free list.
//  - Per-epoch Fisher-Yates shuffle of the record index (seeded), sharded
//    by (num_shards, shard_index) for multi-host input
//    (≙ AutoShardPolicy.DATA, reference input_ops.py:28).
//
// C ABI only — consumed with ctypes; no pybind11 dependency.

#include <zlib.h>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <map>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

// GZIP (1f 8b) / ZLIB (78 xx) compressed files (≙ TFRecordOptions
// compression_type, tensorflow/python/lib/io/tf_record.py): compressed
// streams cannot be seek-indexed, so such files are inflated ONCE into
// memory at open and both the scan and the worker reads run against
// the buffer. Plain files keep the zero-copy seek/read path.
enum class FileCompression { kNone, kGzip, kZlib };

FileCompression SniffCompression(FILE* f) {
  uint8_t magic[2];
  size_t got = std::fread(magic, 1, 2, f);
  std::fseek(f, 0, SEEK_SET);
  if (got == 2 && magic[0] == 0x1f && magic[1] == 0x8b)
    return FileCompression::kGzip;
  if (got == 2 && magic[0] == 0x78 &&
      (magic[1] == 0x01 || magic[1] == 0x5e || magic[1] == 0x9c ||
       magic[1] == 0xda))
    return FileCompression::kZlib;
  return FileCompression::kNone;
}

bool InflateFile(FILE* f, FileCompression comp, std::vector<uint8_t>* out) {
  std::fseek(f, 0, SEEK_END);
  int64_t csize = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::vector<uint8_t> comp_buf(csize);
  if (std::fread(comp_buf.data(), 1, csize, f) !=
      static_cast<size_t>(csize))
    return false;
  z_stream strm{};
  int window = comp == FileCompression::kGzip ? 16 + MAX_WBITS : MAX_WBITS;
  if (inflateInit2(&strm, window) != Z_OK) return false;
  strm.next_in = comp_buf.data();
  strm.avail_in = static_cast<uInt>(csize);
  out->clear();
  out->resize(std::max<int64_t>(csize * 4, 1 << 16));
  int ret = Z_OK;
  for (;;) {
    strm.next_out = out->data() + strm.total_out;
    strm.avail_out = static_cast<uInt>(out->size() - strm.total_out);
    ret = inflate(&strm, Z_NO_FLUSH);
    if (ret == Z_STREAM_END) break;
    if (ret != Z_OK && ret != Z_BUF_ERROR) { inflateEnd(&strm); return false; }
    if (strm.avail_out == 0) out->resize(out->size() * 2);
    else if (ret == Z_BUF_ERROR) { inflateEnd(&strm); return false; }
  }
  out->resize(strm.total_out);
  inflateEnd(&strm);
  return true;
}

struct Batch {
  std::vector<uint8_t> data;
  std::vector<int64_t> lengths;  // per-row payload bytes (TFRecord mode)
  int64_t epoch = -1;
  int64_t batch_index = -1;
};

// crc32c (Castagnoli, reflected) + the TFRecord mask — for verifying the
// framing of files we index (≙ tensorflow/core/lib/io/record_reader).
struct Crc32c {
  uint32_t table[256];
  Crc32c() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0x82F63B78u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
  }
  uint32_t operator()(const uint8_t* p, size_t n) const {
    uint32_t c = 0xFFFFFFFFu;
    for (size_t i = 0; i < n; ++i) c = table[(c ^ p[i]) & 0xFF] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
  }
  uint32_t Masked(const uint8_t* p, size_t n) const {
    uint32_t c = (*this)(p, n);
    return ((c >> 15) | (c << 17)) + 0xA282EAD8u;
  }
};

class Pipeline {
 public:
  // record_bytes > 0: fixed-size records (row = record_bytes).
  // record_bytes == 0: TFRecord framing — scan each file's
  // length/crc/payload/crc structure to index variable-length records;
  // rows are padded to the longest payload and per-row lengths reported.
  Pipeline(const char** paths, int num_paths, int64_t record_bytes,
           int64_t batch_size, int shuffle, uint64_t seed, int num_threads,
           int64_t queue_depth, int64_t num_shards, int64_t shard_index,
           int drop_remainder, int verify_crc = 0)
      : record_bytes_(record_bytes),
        batch_size_(batch_size),
        shuffle_(shuffle),
        seed_(seed),
        num_shards_(num_shards < 1 ? 1 : num_shards),
        shard_index_(shard_index),
        drop_remainder_(drop_remainder),
        tfrecord_(record_bytes == 0),
        verify_crc_(verify_crc) {
    int64_t max_len = 0;
    for (int i = 0; i < num_paths; ++i) {
      FILE* f = std::fopen(paths[i], "rb");
      if (!f) { ok_ = false; return; }
      FileCompression comp = SniffCompression(f);
      // A VALID plain TFRecord header (length crc32c matches at offset
      // 8) beats any magic-byte coincidence: an uncompressed file
      // whose first record length encodes to 78 01 / 1f 8b would
      // otherwise be misdetected as compressed.
      if (comp != FileCompression::kNone && tfrecord_ &&
          HasValidPlainHeader(f))
        comp = FileCompression::kNone;
      if (comp != FileCompression::kNone) {
        std::vector<uint8_t> raw;
        if (InflateFile(f, comp, &raw)) {
          if (tfrecord_) {
            if (!ScanTFRecordMem(raw, i, verify_crc, &max_len)) {
              std::fclose(f);
              ok_ = false;
              return;
            }
          } else {
            int64_t n = static_cast<int64_t>(raw.size()) / record_bytes_;
            for (int64_t r = 0; r < n; ++r)
              index_.push_back({i, r * record_bytes_, record_bytes_});
          }
          mem_files_[i] = std::move(raw);
        } else {
          // magic-byte false positive on a non-compressed file: fall
          // back to the plain path rather than rejecting a valid file
          std::fseek(f, 0, SEEK_SET);
          comp = FileCompression::kNone;
        }
      }
      if (comp == FileCompression::kNone &&
          mem_files_.find(i) == mem_files_.end()) {
        if (tfrecord_) {
          if (!ScanTFRecord(f, i, verify_crc, &max_len)) {
            std::fclose(f);
            ok_ = false;
            return;
          }
        } else {
          std::fseek(f, 0, SEEK_END);
          int64_t bytes = std::ftell(f);
          int64_t n = bytes / record_bytes_;
          for (int64_t r = 0; r < n; ++r)
            index_.push_back({i, r * record_bytes_, record_bytes_});
        }
      }
      std::fclose(f);
      files_.emplace_back(paths[i]);
    }
    if (tfrecord_) record_bytes_ = max_len;  // row stride = longest payload
    // Static shard over records (≙ DATA autoshard policy).
    std::vector<Entry> mine;
    for (size_t i = shard_index_; i < index_.size(); i += num_shards_)
      mine.push_back(index_[i]);
    index_.swap(mine);
    if (index_.empty() || record_bytes_ <= 0) { ok_ = false; return; }

    int64_t nb = static_cast<int64_t>(index_.size()) / batch_size_;
    if (!drop_remainder_ && index_.size() % batch_size_) ++nb;
    if (nb == 0) { ok_ = false; return; }  // shard < batch: no SIGFPE
    batches_per_epoch_ = nb;

    for (int64_t i = 0; i < queue_depth; ++i) {
      auto* b = new Batch();
      b->data.resize(record_bytes_ * batch_size_);
      b->lengths.resize(batch_size_);
      free_.push_back(b);
    }
    int64_t nt = num_threads < 1 ? 1 : num_threads;
    for (int64_t t = 0; t < nt; ++t)
      workers_.emplace_back([this] { WorkerLoop(); });
  }

  // True iff the file starts with a crc-valid plain TFRecord header.
  static bool HasValidPlainHeader(FILE* f) {
    static const Crc32c crc;
    uint8_t header[12];
    size_t got = std::fread(header, 1, 12, f);
    std::fseek(f, 0, SEEK_SET);
    if (got != 12) return false;
    uint32_t len_crc;
    std::memcpy(&len_crc, header + 8, 4);
    return crc.Masked(header, 8) == len_crc;
  }

  // TFRecord framing: u64le length, u32le masked-crc(length), payload,
  // u32le masked-crc(payload). The scan is seek-only (headers validated,
  // lengths bounds-checked against the file size — a corrupt length
  // cannot index past EOF, OOM the row stride, or wrap negative);
  // payload CRCs are verified by the WORKERS at read time, so dataset
  // bytes are read exactly once and startup never reads the data.
  bool ScanTFRecord(FILE* f, int file_idx, int verify_crc,
                    int64_t* max_len) {
    static const Crc32c crc;
    std::fseek(f, 0, SEEK_END);
    const int64_t fsize = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    uint8_t header[12];
    for (;;) {
      size_t got = std::fread(header, 1, 12, f);
      if (got == 0) return true;          // clean EOF
      if (got != 12) return false;        // truncated header
      uint64_t len;
      uint32_t len_crc;
      std::memcpy(&len, header, 8);
      std::memcpy(&len_crc, header + 8, 4);
      if (verify_crc && crc.Masked(header, 8) != len_crc) return false;
      int64_t payload_off = std::ftell(f);
      int64_t slen = static_cast<int64_t>(len);
      if (slen < 0 || payload_off + slen + 4 > fsize) return false;
      if (std::fseek(f, slen + 4, SEEK_CUR) != 0) return false;
      index_.push_back({file_idx, payload_off, slen});
      if (slen > *max_len) *max_len = slen;
    }
  }

  // Same framing walk over an inflated in-memory file.
  bool ScanTFRecordMem(const std::vector<uint8_t>& buf, int file_idx,
                       int verify_crc, int64_t* max_len) {
    static const Crc32c crc;
    const int64_t fsize = static_cast<int64_t>(buf.size());
    int64_t pos = 0;
    for (;;) {
      if (pos == fsize) return true;      // clean EOF
      if (pos + 12 > fsize) return false;  // truncated header
      uint64_t len;
      uint32_t len_crc;
      std::memcpy(&len, buf.data() + pos, 8);
      std::memcpy(&len_crc, buf.data() + pos + 8, 4);
      if (verify_crc && crc.Masked(buf.data() + pos, 8) != len_crc)
        return false;
      int64_t payload_off = pos + 12;
      int64_t slen = static_cast<int64_t>(len);
      if (slen < 0 || payload_off + slen + 4 > fsize) return false;
      index_.push_back({file_idx, payload_off, slen});
      if (slen > *max_len) *max_len = slen;
      pos = payload_off + slen + 4;
    }
  }

  ~Pipeline() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_free_.notify_all();
    cv_ready_.notify_all();
    for (auto& t : workers_) t.join();
    for (auto* b : free_) delete b;
    for (auto* b : ready_) delete b;
    for (auto* b : lent_) delete b;
  }

  bool ok() const { return ok_; }
  bool failed() const { return failed_; }
  int64_t num_records() const { return static_cast<int64_t>(index_.size()); }
  int64_t batches_per_epoch() const { return batches_per_epoch_; }
  int64_t row_bytes() const { return record_bytes_; }

  // Blocks until the batch with the next sequential batch_index is ready;
  // returns its buffer (caller must Return() it). Delivering strictly in
  // batch order makes the stream deterministic for any num_threads: every
  // in-flight batch owns its buffer, so the next-expected batch can always
  // complete even while later batches sit in ready_. actual_records
  // reports the (possibly short) batch size.
  Batch* Next(int64_t* actual_records, int64_t* epoch) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_ready_.wait(lk, [this] {
      return stop_ || (!ready_.empty() &&
                       ready_.front()->batch_index == next_deliver_);
    });
    if (stop_ &&
        (ready_.empty() || ready_.front()->batch_index != next_deliver_))
      return nullptr;
    Batch* b = ready_.front();
    ready_.pop_front();
    lent_.push_back(b);
    ++next_deliver_;
    *actual_records = last_sizes_[b];
    *epoch = b->epoch;
    return b;
  }

  void Return(Batch* b) {
    std::lock_guard<std::mutex> lk(mu_);
    lent_.erase(std::find(lent_.begin(), lent_.end(), b));
    free_.push_back(b);
    cv_free_.notify_one();
  }

 private:
  struct Entry { int file; int64_t offset; int64_t length; };

  void WorkerLoop() {
    // Each worker owns a FILE* per input file (no seek contention).
    std::vector<FILE*> fps;
    for (auto& p : files_) fps.push_back(std::fopen(p.c_str(), "rb"));

    while (true) {
      Batch* buf = nullptr;
      int64_t my_batch, my_epoch, count;
      std::vector<Entry> picks;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_free_.wait(lk, [this] { return stop_ || !free_.empty(); });
        if (stop_) break;
        buf = free_.back();
        free_.pop_back();
        my_batch = next_batch_++;
        my_epoch = my_batch / batches_per_epoch_;
        if (epoch_order_.empty() || shuffled_epoch_ != my_epoch)
          ShuffleEpochLocked(my_epoch);
        // Resolve record picks while the epoch order is still this
        // epoch's (another worker may reshuffle right after we unlock).
        int64_t start = (my_batch % batches_per_epoch_) * batch_size_;
        count = std::min<int64_t>(batch_size_, num_records() - start);
        picks.resize(count);
        for (int64_t i = 0; i < count; ++i)
          picks[i] = index_[epoch_order_[start + i]];
      }
      static const Crc32c crc;
      bool bad = false;
      for (int64_t i = 0; i < count; ++i) {
        uint8_t* row = buf->data.data() + i * record_bytes_;
        auto mem = mem_files_.find(picks[i].file);
        if (mem != mem_files_.end()) {
          // inflated (gzip/zlib) file: copy from the in-memory buffer
          const std::vector<uint8_t>& src = mem->second;
          std::memcpy(row, src.data() + picks[i].offset, picks[i].length);
          if (tfrecord_ && verify_crc_) {
            uint32_t data_crc;
            std::memcpy(&data_crc,
                        src.data() + picks[i].offset + picks[i].length, 4);
            if (crc.Masked(row, picks[i].length) != data_crc) bad = true;
          }
        } else {
          FILE* f = fps[picks[i].file];
          std::fseek(f, picks[i].offset, SEEK_SET);
          size_t got = std::fread(row, 1, picks[i].length, f);
          if (static_cast<int64_t>(got) != picks[i].length) { bad = true; }
          if (tfrecord_ && verify_crc_ && !bad) {
            // payload crc sits right after the payload; data's in hand —
            // verify here so dataset bytes are read exactly once
            uint32_t data_crc;
            if (std::fread(&data_crc, 1, 4, f) != 4 ||
                crc.Masked(row, picks[i].length) != data_crc)
              bad = true;
          }
        }
        if (picks[i].length < record_bytes_)
          std::memset(row + picks[i].length, 0,
                      record_bytes_ - picks[i].length);
        buf->lengths[i] = picks[i].length;
      }
      if (bad) {
        std::lock_guard<std::mutex> lk(mu_);
        failed_ = true;
        stop_ = true;
        cv_ready_.notify_all();
        cv_free_.notify_all();
        break;
      }
      {
        std::lock_guard<std::mutex> lk(mu_);
        buf->epoch = my_epoch;
        buf->batch_index = my_batch;
        last_sizes_[buf] = count;
        // Insert in batch order so consumers see a deterministic stream.
        auto it = ready_.begin();
        while (it != ready_.end() && (*it)->batch_index < my_batch) ++it;
        ready_.insert(it, buf);
      }
      cv_ready_.notify_all();
    }
    for (FILE* f : fps)
      if (f) std::fclose(f);
  }

  void ShuffleEpochLocked(int64_t epoch) {
    epoch_order_.resize(index_.size());
    for (size_t i = 0; i < index_.size(); ++i) epoch_order_[i] = i;
    if (shuffle_) {
      std::mt19937_64 rng(seed_ + 0x9e3779b97f4a7c15ull * (epoch + 1));
      for (size_t i = index_.size() - 1; i > 0; --i) {
        std::uniform_int_distribution<size_t> d(0, i);
        std::swap(epoch_order_[i], epoch_order_[d(rng)]);
      }
    }
    shuffled_epoch_ = epoch;
  }

  std::vector<std::string> files_;
  std::map<int, std::vector<uint8_t>> mem_files_;  // inflated gzip/zlib
  std::vector<Entry> index_;
  std::vector<size_t> epoch_order_;
  int64_t shuffled_epoch_ = -1;

  int64_t record_bytes_, batch_size_;
  bool tfrecord_ = false;
  int verify_crc_ = 0;
  int shuffle_;
  uint64_t seed_;
  int64_t num_shards_, shard_index_;
  int drop_remainder_;
  int64_t batches_per_epoch_ = 0;
  bool ok_ = true;

  std::mutex mu_;
  std::condition_variable cv_free_, cv_ready_;
  std::deque<Batch*> free_;
  std::deque<Batch*> ready_;   // kept sorted by batch_index
  std::vector<Batch*> lent_;
  std::map<Batch*, int64_t> last_sizes_;
  int64_t next_batch_ = 0;
  int64_t next_deliver_ = 0;
  bool stop_ = false;
  std::atomic<bool> failed_{false};   // IO error / crc mismatch mid-read

  std::vector<std::thread> workers_;
};

}  // namespace

extern "C" {

void* dtx_pipeline_create(const char** paths, int num_paths,
                          int64_t record_bytes, int64_t batch_size,
                          int shuffle, uint64_t seed, int num_threads,
                          int64_t queue_depth, int64_t num_shards,
                          int64_t shard_index, int drop_remainder) {
  auto* p = new Pipeline(paths, num_paths, record_bytes, batch_size,
                         shuffle, seed, num_threads, queue_depth,
                         num_shards, shard_index, drop_remainder);
  if (!p->ok()) { delete p; return nullptr; }
  return p;
}

int64_t dtx_pipeline_num_records(void* h) {
  return static_cast<Pipeline*>(h)->num_records();
}

int64_t dtx_pipeline_batches_per_epoch(void* h) {
  return static_cast<Pipeline*>(h)->batches_per_epoch();
}

// Returns an opaque batch handle; fills *data/*n_records/*epoch.
void* dtx_pipeline_next(void* h, uint8_t** data, int64_t* n_records,
                        int64_t* epoch) {
  Batch* b = static_cast<Pipeline*>(h)->Next(n_records, epoch);
  if (!b) return nullptr;
  *data = b->data.data();
  return b;
}

void dtx_pipeline_return(void* h, void* batch) {
  static_cast<Pipeline*>(h)->Return(static_cast<Batch*>(batch));
}

void dtx_pipeline_destroy(void* h) { delete static_cast<Pipeline*>(h); }

// -- TFRecord mode (variable-length framed records) -------------------------

void* dtx_tfrecord_create(const char** paths, int num_paths,
                          int64_t batch_size, int shuffle, uint64_t seed,
                          int num_threads, int64_t queue_depth,
                          int64_t num_shards, int64_t shard_index,
                          int drop_remainder, int verify_crc) {
  auto* p = new Pipeline(paths, num_paths, /*record_bytes=*/0, batch_size,
                         shuffle, seed, num_threads, queue_depth,
                         num_shards, shard_index, drop_remainder,
                         verify_crc);
  if (!p->ok()) { delete p; return nullptr; }
  return p;
}

int64_t dtx_pipeline_row_bytes(void* h) {
  return static_cast<Pipeline*>(h)->row_bytes();
}

// 1 if a worker hit an IO error or crc mismatch (the stream stopped
// because the DATA is bad, not because it ended).
int dtx_pipeline_failed(void* h) {
  return static_cast<Pipeline*>(h)->failed() ? 1 : 0;
}

// Like dtx_pipeline_next but also exposes the per-row payload lengths
// (rows are zero-padded to row_bytes).
void* dtx_pipeline_next2(void* h, uint8_t** data, int64_t** lengths,
                         int64_t* n_records, int64_t* epoch) {
  Batch* b = static_cast<Pipeline*>(h)->Next(n_records, epoch);
  if (!b) return nullptr;
  *data = b->data.data();
  *lengths = b->lengths.data();
  return b;
}

}  // extern "C"
