"""Recovery supervisor: detect worker failure → reform → resume.

The piece that turns the detection stack (chaos injection, RetryPolicy,
WorkerHealthTracker, checkpoint integrity, StallDetector, structured
telemetry) into an actual fault-tolerance story: a controlling process
that runs a multi-worker training job, watches it, and — when a worker
dies, is preempted, or stalls — executes a bounded recovery instead of
letting the run end (≙ Elastic Horovod's driver / the reference
failure-handling module's restart-the-job contract, closed-loop).

The recovery protocol, per failure:

1. **Detect.** Poll task exit codes (SIGKILL → negative signal code,
   preemption → :data:`~distributed_tensorflow_tpu.checkpoint.
   failure_handling.EXIT_PREEMPTED`, crash → anything else) and, when
   configured, per-task heartbeat staleness (stall — the supervisor-side
   complement of the in-process StallDetector).
2. **Kill stragglers.** Survivors of a dead peer are typically wedged
   in a collective or barrier against it; they are SIGKILLed rather
   than waited out.
3. **Reform.** The cluster *generation id* is incremented and every
   task is respawned (``multi_process_runner.MultiProcessRunner.reform``:
   per-worker restart under a fresh cluster spec — fresh
   coordination-service ports) with ``DTX_CLUSTER_GENERATION`` bumped,
   so the new incarnation's KV keys and barriers live in a fresh
   namespace (cluster/elastic.py).
4. **Resume.** Restarted workers restore from the latest *intact*
   checkpoint (torn checkpoints are already skipped by
   ``CheckpointManager.latest_checkpoint``) and re-enter their step
   loop. Restart pacing follows a :class:`RetryPolicy` backoff; the
   restart budget is bounded, and exhaustion raises
   :class:`RecoveryFailedError` carrying the full failure history.

Every transition emits ``recovery.*`` telemetry events (plus a
``recovery.recover`` span around each reform), written both to the
supervisor's own ``events-supervisor.jsonl`` under ``telemetry_dir``
and to the process-wide event log when one is configured —
``tools/obs_report.py`` renders them as a recovery timeline.

Chaos: ``kill_plan`` schedules seed-driven SIGKILLs through the
supervisor itself (fired when the victim's heartbeat reaches a target
step), which is how ``tools/chaos_sweep.py --kill`` and the elastic
end-to-end tests drive worker death deterministically.
"""

from __future__ import annotations

import dataclasses
import os
import random
import tempfile
import time
from typing import Callable, Mapping, Sequence

from distributed_tensorflow_tpu.checkpoint.failure_handling import (
    EXIT_PREEMPTED,
)
from distributed_tensorflow_tpu.cluster import elastic
from distributed_tensorflow_tpu.resilience.health import WorkerHealthTracker
from distributed_tensorflow_tpu.resilience.retry import Backoff, RetryPolicy
from distributed_tensorflow_tpu.telemetry import events as _events
from distributed_tensorflow_tpu.testing import multi_process_runner as mpr


@dataclasses.dataclass(frozen=True)
class WorkerFailure:
    """One detected failure (an entry of the recovery history)."""

    generation: int
    task: tuple[str, int]
    kind: str                     # "killed" | "preempted" | "crash" | "stall"
    exitcode: int | None = None
    wall: float = 0.0
    detail: str = ""

    def describe(self) -> str:
        code = "" if self.exitcode is None else f" exit={self.exitcode}"
        extra = f" ({self.detail})" if self.detail else ""
        return (f"gen{self.generation} {self.task[0]}:{self.task[1]} "
                f"{self.kind}{code}{extra}")


class RecoveryFailedError(RuntimeError):
    """The restart budget is exhausted (or recovery is disabled) and the
    job still cannot finish. Carries the full failure ``history`` so the
    operator sees every death that led here, not just the last."""

    def __init__(self, msg: str, history: Sequence[WorkerFailure]):
        super().__init__(msg)
        self.history: list[WorkerFailure] = list(history)


@dataclasses.dataclass(frozen=True)
class KillSpec:
    """One scheduled chaos kill: SIGKILL ``worker`` once its heartbeat
    reports a step >= ``after_step``."""

    worker: int
    after_step: int


def seeded_kill_plan(seed: int, num_workers: int, *, kills: int = 1,
                     step_range: tuple[int, int] = (3, 12)) -> list[KillSpec]:
    """Deterministic kill schedule from a chaos seed (the
    resilience/faults.py seeding discipline: a string-seeded stream that
    is a pure function of the seed, stable across processes/runs)."""
    rng = random.Random(f"dtx-kill:{seed}")
    return [KillSpec(worker=rng.randrange(num_workers),
                     after_step=rng.randrange(*step_range))
            for _ in range(kills)]


class RecoverySupervisor:
    """Run ``worker_fn`` as an elastic multi-worker job that survives
    worker death.

    ``worker_fn`` is one cluster task's whole life for one generation:
    it must be restartable — bootstrap from ``TF_CONFIG``, restore from
    the latest checkpoint, train, checkpoint periodically — and should
    call :func:`cluster.elastic.heartbeat` once per step so the
    supervisor can see progress (stall detection, step-targeted chaos
    kills). Spawn semantics are those of
    :class:`testing.multi_process_runner.MultiProcessRunner`: the fn
    must be module-level (picklable by reference).

    ::

        sup = RecoverySupervisor(worker_fn, num_workers=2,
                                 args=(ckpt_dir, total_steps),
                                 max_restarts=3,
                                 telemetry_dir=run_dir)
        result = sup.run()            # or raises RecoveryFailedError
        values = result.return_values # final generation's returns
    """

    def __init__(self, worker_fn: Callable, *,
                 num_workers: int = 2,
                 args: tuple = (), kwargs: dict | None = None,
                 env: Mapping[str, str] | None = None,
                 devices_per_process: int = 1,
                 max_restarts: int = 3,
                 retry_policy: RetryPolicy | None = None,
                 health: WorkerHealthTracker | None = None,
                 stall_timeout_s: float | None = None,
                 generation_timeout_s: float = 600.0,
                 poll_interval_s: float = 0.05,
                 kill_plan: Sequence[KillSpec] = (),
                 telemetry_dir: str | None = None,
                 work_dir: str | None = None):
        self._fn = worker_fn
        self._num_workers = num_workers
        self._args = args
        self._kwargs = kwargs or {}
        self._env = dict(env or {})
        self._devices = devices_per_process
        self.max_restarts = max_restarts
        self._policy = retry_policy or RetryPolicy(
            max_attempts=max_restarts + 1, initial_backoff_s=0.2,
            backoff_multiplier=2.0, max_backoff_s=10.0)
        self.health = health or WorkerHealthTracker()
        self._stall_timeout_s = stall_timeout_s
        self._generation_timeout_s = generation_timeout_s
        self._poll_s = poll_interval_s
        self._pending_kills: list[KillSpec] = list(kill_plan)
        self._telemetry_dir = telemetry_dir
        self._dir = work_dir or tempfile.mkdtemp(prefix="dtx_supervisor_")
        os.makedirs(self._dir, exist_ok=True)
        self._log: _events.EventLog | None = None
        if telemetry_dir:
            self._log = _events.EventLog(
                os.path.join(telemetry_dir, "events-supervisor.jsonl"),
                process_id="supervisor")
        self.history: list[WorkerFailure] = []
        self.generation = 0
        self.restarts_used = 0
        self._runner: mpr.MultiProcessRunner | None = None

    # -- telemetry --------------------------------------------------------
    def _event(self, name: str, **fields):
        if self._log is not None:
            # recovery transitions are rare and each must survive a
            # supervisor crash: flush per event
            self._log.event(name, **fields)
            self._log.flush()
        else:
            # no supervisor file: fall back to the process-wide log (if
            # any) so in-process callers still see the transitions
            _events.event(name, **fields)

    # -- lifecycle --------------------------------------------------------
    def _child_env(self, generation: int) -> dict[str, str]:
        env = dict(self._env)
        env[elastic.ENV_GENERATION] = str(generation)
        env[elastic.ENV_SUPERVISOR_DIR] = self._dir
        if self._telemetry_dir:
            env.setdefault(_events.ENV_TELEMETRY_DIR, self._telemetry_dir)
        return env

    def _clear_heartbeats(self):
        for i in range(self._num_workers):
            try:
                os.unlink(elastic.heartbeat_path(self._dir, i))
            except OSError:
                pass

    def _heartbeat(self, worker: int) -> tuple[float, int | None] | None:
        """(mtime, step) of a worker's heartbeat file, None if absent."""
        path = elastic.heartbeat_path(self._dir, worker)
        try:
            mtime = os.path.getmtime(path)
            with open(path) as f:
                text = f.read().strip()
            return mtime, int(text) if text else None
        except (OSError, ValueError):
            return None

    @staticmethod
    def _classify(exitcode: int | None) -> str:
        if exitcode is None:
            return "stall"
        if exitcode < 0:
            import signal as _signal
            return ("killed" if -exitcode == _signal.SIGKILL
                    else "preempted" if -exitcode == _signal.SIGTERM
                    else "crash")
        if exitcode == EXIT_PREEMPTED:
            return "preempted"
        return "crash"

    # -- the loop ---------------------------------------------------------
    def run(self) -> mpr.MultiProcessRunnerResult:
        """Run the job to completion, recovering from failures within
        the restart budget. Returns the final generation's result;
        raises :class:`RecoveryFailedError` on budget exhaustion."""
        spec = mpr.create_cluster_spec(num_workers=self._num_workers)
        self._runner = mpr.MultiProcessRunner(
            self._fn, spec, args=self._args, kwargs=self._kwargs,
            env=self._child_env(0), devices_per_process=self._devices,
            timeout=self._generation_timeout_s)
        self._event("recovery.run_start", num_workers=self._num_workers,
                    max_restarts=self.max_restarts,
                    chaos_kills=len(self._pending_kills))
        self._clear_heartbeats()
        self._runner.start()
        self._event("recovery.generation_start", generation=0)
        backoff = Backoff(self._policy)
        try:
            while True:
                failures = self._watch()
                if failures is None:
                    result = self._runner.join(timeout=60,
                                               raise_on_error=False)
                    failures = self._result_failures(result)
                    if not failures:
                        for i in range(self._num_workers):
                            self.health.record_success(i)
                        self._event("recovery.run_complete",
                                    generation=self.generation,
                                    restarts=self.restarts_used)
                        return result
                self._recover(failures, backoff)
        finally:
            self._runner.terminate_all()

    def _result_failures(self, result) -> list[WorkerFailure]:
        return [WorkerFailure(generation=self.generation, task=k,
                              kind=self._classify(t.exitcode),
                              exitcode=t.exitcode, wall=time.time(),
                              detail=(t.error or "")[-300:])
                for k, t in sorted(result.tasks.items())
                if t.exitcode != 0 or t.error is not None]

    def _watch(self) -> list[WorkerFailure] | None:
        """Watch the current generation. Returns failures needing
        recovery, or None when every task exited cleanly."""
        runner = self._runner
        t0 = time.monotonic()
        while True:
            exits = runner.poll()
            bad = {k: c for k, c in exits.items() if c != 0}
            if bad:
                return [WorkerFailure(
                    generation=self.generation, task=k,
                    kind=self._classify(c), exitcode=c, wall=time.time())
                    for k, c in sorted(bad.items())]
            if len(exits) == runner.num_tasks:
                return None
            self._fire_due_kills(exits)
            stalled = self._check_stall(exits, t0)
            if stalled is not None:
                return [stalled]
            if time.monotonic() - t0 > self._generation_timeout_s:
                return [WorkerFailure(
                    generation=self.generation, task=("worker", -1),
                    kind="stall", wall=time.time(),
                    detail=f"generation exceeded "
                           f"{self._generation_timeout_s}s")]
            time.sleep(self._poll_s)

    def _fire_due_kills(self, exits):
        for spec in list(self._pending_kills):
            if ("worker", spec.worker) in exits:
                continue                    # already down — keep waiting
            hb = self._heartbeat(spec.worker)
            if hb is None or hb[1] is None or hb[1] < spec.after_step:
                continue
            self._event("recovery.chaos_kill", generation=self.generation,
                        worker=spec.worker, after_step=spec.after_step,
                        at_step=hb[1])
            self._runner.terminate("worker", spec.worker)
            self._pending_kills.remove(spec)

    def _check_stall(self, exits, t0: float) -> WorkerFailure | None:
        if self._stall_timeout_s is None:
            return None
        now = time.time()
        worst: tuple[float, int] | None = None    # (age, worker)
        for i in range(self._num_workers):
            if ("worker", i) in exits:
                continue                          # finished: not stalled
            hb = self._heartbeat(i)
            # before the first heartbeat, age from generation start
            # (covers spawn + jax import + compile)
            age = (now - hb[0]) if hb is not None \
                else (time.monotonic() - t0)
            if worst is None or age > worst[0]:
                worst = (age, i)
        if worst is not None and worst[0] > self._stall_timeout_s:
            return WorkerFailure(
                generation=self.generation, task=("worker", worst[1]),
                kind="stall", wall=now,
                detail=f"no heartbeat for {worst[0]:.1f}s "
                       f"(budget {self._stall_timeout_s}s)")
        return None

    def _recover(self, failures: list[WorkerFailure],
                 backoff: Backoff):
        """Bounded recovery: record → kill stragglers → (budget
        permitting) back off, bump the generation, reform, un-quarantine
        the restarted lanes."""
        for f in failures:
            self.history.append(f)
            self.health.record_failure(f.task[1])
            self._event("recovery.worker_death", generation=f.generation,
                        task_type=f.task[0], task_id=f.task[1],
                        kind=f.kind, exitcode=f.exitcode, detail=f.detail)
        # a stalled task is still alive; every straggler of the dead
        # generation gets killed before the namespace moves on
        for key in self._runner.alive_tasks():
            self._event("recovery.kill_straggler",
                        generation=self.generation,
                        task_type=key[0], task_id=key[1])
        self._runner.terminate_all()
        if self.restarts_used >= self.max_restarts:
            self._event("recovery.failed", generation=self.generation,
                        restarts=self.restarts_used,
                        failures=len(self.history))
            raise RecoveryFailedError(
                f"restart budget exhausted ({self.restarts_used}/"
                f"{self.max_restarts} restarts used) after "
                f"{len(self.history)} failure(s): "
                + "; ".join(f.describe() for f in self.history[-5:]),
                self.history)
        self.restarts_used += 1
        delay = backoff.next_s()
        self.generation += 1
        span_cm = (self._log.span if self._log is not None
                   else _events.span)
        with span_cm("recovery.recover", generation=self.generation,
                     restart=self.restarts_used, backoff_s=round(delay, 3)):
            if delay > 0:
                time.sleep(delay)
            self._clear_heartbeats()
            self._event("recovery.restart", generation=self.generation,
                        restart=self.restarts_used,
                        budget_left=self.max_restarts - self.restarts_used,
                        backoff_s=round(delay, 3))
            self._runner.reform(
                mpr.create_cluster_spec(num_workers=self._num_workers),
                env=self._child_env(self.generation))
            for f in failures:
                self.health.worker_restarted(f.task[1])
        self._event("recovery.generation_start",
                    generation=self.generation)   # also flushes the span
