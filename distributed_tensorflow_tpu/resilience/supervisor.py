"""Recovery supervisor: detect worker failure → reform → resume.

The piece that turns the detection stack (chaos injection, RetryPolicy,
WorkerHealthTracker, checkpoint integrity, StallDetector, structured
telemetry) into an actual fault-tolerance story: a controlling process
that runs a multi-worker training job, watches it, and — when a worker
dies, is preempted, or stalls — executes a bounded recovery instead of
letting the run end (≙ Elastic Horovod's driver / the reference
failure-handling module's restart-the-job contract, closed-loop).

The recovery protocol, per failure:

1. **Detect.** Poll task exit codes (SIGKILL → negative signal code,
   preemption → :data:`~distributed_tensorflow_tpu.checkpoint.
   failure_handling.EXIT_PREEMPTED`, crash → anything else) and, when
   configured, per-task heartbeat staleness (stall — the supervisor-side
   complement of the in-process StallDetector).
2. **Kill stragglers.** Survivors of a dead peer are typically wedged
   in a collective or barrier against it; they are SIGKILLed rather
   than waited out.
3. **Reform.** The cluster *generation id* is incremented and every
   task is respawned (``multi_process_runner.MultiProcessRunner.reform``:
   per-worker restart under a fresh cluster spec — fresh
   coordination-service ports) with ``DTX_CLUSTER_GENERATION`` bumped,
   so the new incarnation's KV keys and barriers live in a fresh
   namespace (cluster/elastic.py).
4. **Resume.** Restarted workers restore down the recovery ladder —
   own host snapshot > peer replica (checkpoint/peer_snapshot.py) >
   local disk > durable disk (``CheckpointManager.restore_latest``;
   torn checkpoints are already skipped) — and re-enter their step
   loop. Restart pacing follows a :class:`RetryPolicy` backoff; the
   restart budget is bounded, and exhaustion raises
   :class:`RecoveryFailedError` carrying the (bounded) failure history.
5. **Shrink** (optional, ``shrink_after``): when the SAME task slot has
   failed that many consecutive restarts, the machine is treated as
   gone for good — the cluster reforms at N-1 workers
   (``recovery.reshard`` event) and the topology-elastic restore
   stitches the N-worker checkpoint onto the smaller cluster instead
   of burning the remaining budget re-spawning into the hole.

The supervisor also owns each worker machine's *memdir* (the stand-in
for node RAM holding host/peer snapshots, ``cluster.elastic.
peer_memdir``): a slot whose failure means machine death (SIGKILL,
preemption) gets its memdir wiped; a stall or in-process crash keeps
it, so the respawned worker restores from its own host tier.

Every transition emits ``recovery.*`` telemetry events (plus a
``recovery.recover`` span around each reform), written both to the
supervisor's own ``events-supervisor.jsonl`` under ``telemetry_dir``
and to the process-wide event log when one is configured —
``tools/obs_report.py`` renders them as a recovery timeline.

Chaos: ``kill_plan`` schedules seed-driven SIGKILLs through the
supervisor itself (fired when the victim's heartbeat reaches a target
step), which is how ``tools/chaos_sweep.py --kill`` and the elastic
end-to-end tests drive worker death deterministically.

Beyond failure recovery, the supervisor is also the fleet's *resource
actuator* (ROADMAP item 5): :meth:`RecoverySupervisor.request_scale`
resizes the job on purpose through the SAME reform machinery a failure
uses — drain (optional), generation bump, reform at the new size,
topology-elastic restore — without touching the restart budget. Scale
generations are recorded (``scale.applied`` events +
``scale_generations``) so the goodput ledger prices their reform gaps
into the ``scale_transition`` badput bucket instead of ``recovery``.
An ``autoscaler`` hook (resilience/autoscaler.py) is ticked from the
watch loop, closing SLO burn -> scale decision -> reform in one place;
scale actions serialize behind the reform lock, so a decision arriving
mid-recovery is deferred to the next healthy tick, never lost.
"""

from __future__ import annotations

import dataclasses
import os
import random
import tempfile
import threading
import time
from typing import Callable, Mapping, Sequence

from distributed_tensorflow_tpu.checkpoint.failure_handling import (
    EXIT_PREEMPTED,
)
from distributed_tensorflow_tpu.cluster import elastic
from distributed_tensorflow_tpu.resilience import heartbeats as _hb
from distributed_tensorflow_tpu.resilience.health import WorkerHealthTracker
from distributed_tensorflow_tpu.resilience.retry import Backoff, RetryPolicy
from distributed_tensorflow_tpu.telemetry import events as _events
from distributed_tensorflow_tpu.testing import multi_process_runner as mpr


@dataclasses.dataclass(frozen=True)
class WorkerFailure:
    """One detected failure (an entry of the recovery history)."""

    generation: int
    task: tuple[str, int]
    kind: str                     # "killed" | "preempted" | "crash" | "stall"
    exitcode: int | None = None
    wall: float = 0.0
    detail: str = ""

    def describe(self) -> str:
        code = "" if self.exitcode is None else f" exit={self.exitcode}"
        extra = f" ({self.detail})" if self.detail else ""
        return (f"gen{self.generation} {self.task[0]}:{self.task[1]} "
                f"{self.kind}{code}{extra}")


class RecoveryFailedError(RuntimeError):
    """The restart budget is exhausted (or recovery is disabled) and the
    job still cannot finish. Carries the full failure ``history`` so the
    operator sees every death that led here, not just the last."""

    def __init__(self, msg: str, history: Sequence[WorkerFailure]):
        super().__init__(msg)
        self.history: list[WorkerFailure] = list(history)


@dataclasses.dataclass(frozen=True)
class KillSpec:
    """One scheduled chaos kill: SIGKILL ``worker`` once its heartbeat
    reports a step >= ``after_step``. A ``permanent`` spec models a
    machine that is gone for good: it re-fires in EVERY generation
    (once per generation) until the supervisor's shrink policy removes
    the slot."""

    worker: int
    after_step: int
    permanent: bool = False


def seeded_kill_plan(seed: int, num_workers: int, *, kills: int = 1,
                     step_range: tuple[int, int] = (3, 12)) -> list[KillSpec]:
    """Deterministic kill schedule from a chaos seed (the
    resilience/faults.py seeding discipline: a string-seeded stream that
    is a pure function of the seed, stable across processes/runs)."""
    rng = random.Random(f"dtx-kill:{seed}")
    return [KillSpec(worker=rng.randrange(num_workers),
                     after_step=rng.randrange(*step_range))
            for _ in range(kills)]


def seeded_shrink_plan(seed: int, num_workers: int, *,
                       step_range: tuple[int, int] = (3, 12)
                       ) -> list[KillSpec]:
    """A permanent-loss schedule: one seed-chosen worker's machine dies
    for good (its kill re-fires every generation), forcing the
    supervisor down the shrink path — reform at N-1 with a resharded
    restore."""
    rng = random.Random(f"dtx-shrink:{seed}")
    return [KillSpec(worker=rng.randrange(num_workers),
                     after_step=rng.randrange(*step_range),
                     permanent=True)]


class RecoverySupervisor:
    """Run ``worker_fn`` as an elastic multi-worker job that survives
    worker death.

    ``worker_fn`` is one cluster task's whole life for one generation:
    it must be restartable — bootstrap from ``TF_CONFIG``, restore from
    the latest checkpoint, train, checkpoint periodically — and should
    call :func:`cluster.elastic.heartbeat` once per step so the
    supervisor can see progress (stall detection, step-targeted chaos
    kills). Spawn semantics are those of
    :class:`testing.multi_process_runner.MultiProcessRunner`: the fn
    must be module-level (picklable by reference).

    ::

        sup = RecoverySupervisor(worker_fn, num_workers=2,
                                 args=(ckpt_dir, total_steps),
                                 max_restarts=3,
                                 telemetry_dir=run_dir)
        result = sup.run()            # or raises RecoveryFailedError
        values = result.return_values # final generation's returns
    """

    def __init__(self, worker_fn: Callable, *,
                 num_workers: int = 2,
                 args: tuple = (), kwargs: dict | None = None,
                 env: Mapping[str, str] | None = None,
                 devices_per_process: int = 1,
                 max_restarts: int = 3,
                 retry_policy: RetryPolicy | None = None,
                 health: WorkerHealthTracker | None = None,
                 stall_timeout_s: float | None = None,
                 heartbeat_grace_s: float | None = None,
                 generation_timeout_s: float = 600.0,
                 poll_interval_s: float = 0.05,
                 kill_plan: Sequence[KillSpec] = (),
                 max_failure_history: int = 256,
                 shrink_after: int | None = None,
                 min_workers: int = 1,
                 max_workers: int | None = None,
                 telemetry_dir: str | None = None,
                 work_dir: str | None = None,
                 heartbeats=None,
                 runner_factory=None,
                 cluster_spec_fn=None,
                 kv_gc=None,
                 autoscaler=None,
                 drain_on_scale: bool = False,
                 drain_timeout_s: float = 15.0,
                 drain_scale_down_mode: str = "full"):
        """Knobs beyond the obvious:

        - ``stall_timeout_s`` — heartbeat *staleness* budget: a worker
          whose newest heartbeat is older than this is declared stalled
          (None disables supervisor-side stall detection).
        - ``heartbeat_grace_s`` — separate budget for a worker that has
          not heartbeat at all yet this generation (spawn + imports +
          first compile are much slower than a steady-state step);
          defaults to ``stall_timeout_s``. Both budgets are per
          construction — nothing is hard-coded inside the loop.
        - ``max_failure_history`` — cap on retained
          :class:`WorkerFailure` entries: a long flapping run keeps the
          NEWEST this-many failures (``failures_total`` still counts
          them all), so supervisor memory stays bounded.
        - ``shrink_after`` — the shrink policy: after this many
          consecutive failed restarts of the SAME task slot, stop
          re-spawning into the hole — reform at N-1 workers (never
          below ``min_workers``) and let the topology-elastic restore
          reshard the checkpoint onto the smaller cluster. ``None``
          disables shrinking (restart budget semantics unchanged).
        - ``heartbeats`` — the liveness transport, a
          :class:`resilience.heartbeats.HeartbeatSource`-shaped object
          (``read_all``/``clear``/``generation``). Default: the
          per-task heartbeat FILES under the supervisor scratch dir.
          ``ShardedKVHeartbeats`` swaps in per-shard summary keys over
          the coordination KV so the watch loop polls O(N/shard)
          keys instead of O(N) files — the fleet-scale detect path
          (bench.py --fleet measures detect latency vs N through it).
        - ``runner_factory`` / ``cluster_spec_fn`` — how generations
          are spawned: default the real spawn-process
          ``MultiProcessRunner`` + fresh-port cluster specs; the
          simulated-fleet harness (testing/fleet_sim.py) injects an
          in-process thread runner and a portless spec so hundreds of
          workers drive THIS loop unchanged.
        - ``kv_gc`` — a :class:`cluster.kv_gc.GenerationGC`: at every
          reform the supervisor notes the outgoing generation's last
          heartbeat (the GC's grace anchor) and the watch loop sweeps
          dead generations' KV namespaces once their grace window
          elapses (``recovery.kv_gc`` event per sweep).
        - ``autoscaler`` — an object with ``tick(supervisor)`` called
          once per watch tick while the generation is healthy
          (resilience/autoscaler.py: the SLO-burn policy engine or the
          shared-fleet capacity arbiter). Its decisions land through
          :meth:`request_scale`; a tick that raises degrades to a
          ``scale.error`` event, never kills the job.
        - ``max_workers`` — upper clamp for :meth:`request_scale`
          (``min_workers`` is the lower clamp, shared with the shrink
          policy). ``drain_on_scale`` — before a scale reform, write
          per-task drain flags (cluster/elastic.drain_path) and give
          the generation ``drain_timeout_s`` to exit on its own;
          serving replicas use it to finish in-flight sequences so a
          scale-down drops zero requests. ``drain_scale_down_mode``
          picks the flag written on scale-DOWN: ``full`` (finish
          everything admitted before exiting) or ``migrate`` (export
          live KV blocks to the handoff namespace and exit now — the
          successor generation adopts them with zero replayed decode
          steps; serving/replica.py ``_drain``). Scale-up always
          drains ``fast``: the capacity is wanted immediately.
        """
        self._fn = worker_fn
        self._num_workers = num_workers
        self._args = args
        self._kwargs = kwargs or {}
        self._env = dict(env or {})
        self._devices = devices_per_process
        self.max_restarts = max_restarts
        self._policy = retry_policy or RetryPolicy(
            max_attempts=max_restarts + 1, initial_backoff_s=0.2,
            backoff_multiplier=2.0, max_backoff_s=10.0)
        self.health = health or WorkerHealthTracker()
        self._stall_timeout_s = stall_timeout_s
        self._heartbeat_grace_s = (heartbeat_grace_s
                                   if heartbeat_grace_s is not None
                                   else stall_timeout_s)
        self._generation_timeout_s = generation_timeout_s
        self._poll_s = poll_interval_s
        # chaos kills as mutable records: permanent specs re-fire once
        # per generation until their slot is shrunk away
        self._kills: list[dict] = [{"spec": s, "fired_gen": None}
                                   for s in kill_plan]
        self.max_failure_history = max_failure_history
        self.shrink_after = shrink_after
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.autoscaler = autoscaler
        self._drain_on_scale = drain_on_scale
        self._drain_timeout_s = drain_timeout_s
        self._drain_scale_down_mode = drain_scale_down_mode
        #: serializes generation-replacing actions (failure recovery
        #: AND scale reforms): a scale request landing while a recovery
        #: holds this lock stays pending and is applied at the next
        #: healthy watch tick — deferred, never lost
        self._reform_lock = threading.RLock()
        self._scale_lock = threading.Lock()
        self._pending_scale: "tuple[int, str] | None" = None
        self._stop_requested = threading.Event()
        self.scales_applied = 0
        #: generations created by scale actions (not failures) — the
        #: goodput ledger prices their reform gaps as scale_transition
        self.scale_generations: set[int] = set()
        self._fail_streak: dict[int, int] = {}
        self._hb_seen: dict[int, int | None] = {}
        self._telemetry_dir = telemetry_dir
        self._dir = work_dir or tempfile.mkdtemp(prefix="dtx_supervisor_")
        os.makedirs(self._dir, exist_ok=True)
        self._hb = heartbeats or _hb.FileHeartbeatSource(self._dir)
        self._runner_factory = runner_factory or mpr.MultiProcessRunner
        self._spec_fn = (cluster_spec_fn or
                         (lambda n: mpr.create_cluster_spec(num_workers=n)))
        self.kv_gc = kv_gc
        self._log: _events.EventLog | None = None
        if telemetry_dir:
            self._log = _events.EventLog(
                os.path.join(telemetry_dir, "events-supervisor.jsonl"),
                process_id="supervisor")
        self.history: list[WorkerFailure] = []
        self.failures_total = 0
        self.generation = 0
        self.restarts_used = 0
        self._runner: mpr.MultiProcessRunner | None = None
        self._exporter = None

    # -- live health export -----------------------------------------------
    def _health_lines(self) -> "list[str]":
        """Exporter extra lines: the fleet goodput/badput ledger (and,
        for serving jobs, SLO burn) recomputed from the run's event
        files on every export tick — the workers' logs are
        line-buffered, so this is the live fleet surface one scrape
        (or ``metrics-live.prom`` read) sees."""
        from distributed_tensorflow_tpu.telemetry import (
            events as tv_events, goodput, slo as tv_slo)
        events_by_pid = tv_events.read_run(self._telemetry_dir)
        ledger = goodput.ledger_from_events(events_by_pid)
        lines = goodput.prometheus_lines(ledger)
        records = tv_slo.records_from_events(events_by_pid)
        if records:
            span = ((records[-1]["wall"] - records[0]["wall"])
                    if len(records) > 1 else 1.0)
            slos = tv_slo.default_serving_slos(
                windows=tv_slo.windows_for_span(max(span, 1e-3)))
            mon = tv_slo.SLOMonitor(slos)
            for r in records:
                mon.observe(r)
            lines += mon.prometheus_lines()
        return lines

    def _start_exporter(self):
        if self._telemetry_dir is None:
            return
        from distributed_tensorflow_tpu.telemetry import exporter
        try:
            self._exporter = exporter.MetricsExporter(
                dir=self._telemetry_dir, interval_s=1.0,
                extra_fn=self._health_lines,
                labels={"job": "supervisor"})
        except OSError:
            self._exporter = None       # port taken: file export only
                                        # would also have failed — skip

    def _stop_exporter(self):
        if self._exporter is not None:
            self._exporter.stop()
            self._exporter = None

    @property
    def num_workers(self) -> int:
        """Current cluster size (shrinks under the shrink policy)."""
        return self._num_workers

    # -- telemetry --------------------------------------------------------
    def _event(self, name: str, **fields):
        if self._log is not None:
            # recovery transitions are rare and each must survive a
            # supervisor crash: flush per event
            self._log.event(name, **fields)
            self._log.flush()
        else:
            # no supervisor file: fall back to the process-wide log (if
            # any) so in-process callers still see the transitions
            _events.event(name, **fields)

    # -- lifecycle --------------------------------------------------------
    def _child_env(self, generation: int) -> dict[str, str]:
        env = dict(self._env)
        env[elastic.ENV_GENERATION] = str(generation)
        env[elastic.ENV_SUPERVISOR_DIR] = self._dir
        if self._telemetry_dir:
            env.setdefault(_events.ENV_TELEMETRY_DIR, self._telemetry_dir)
        return env

    def _clear_heartbeats(self, clear_n: int | None = None):
        self._hb_seen: dict[int, int | None] = {}
        self._hb.generation = self.generation
        # a scale-down leaves heartbeat files of removed slots behind —
        # clear the LARGER of the old/new sizes so they cannot read as
        # live workers later
        self._hb.clear(clear_n if clear_n is not None
                       else self._num_workers)

    @staticmethod
    def _classify(exitcode: int | None) -> str:
        if exitcode is None:
            return "stall"
        if exitcode < 0:
            import signal as _signal
            return ("killed" if -exitcode == _signal.SIGKILL
                    else "preempted" if -exitcode == _signal.SIGTERM
                    else "crash")
        if exitcode == EXIT_PREEMPTED:
            return "preempted"
        return "crash"

    # -- the loop ---------------------------------------------------------
    def run(self) -> mpr.MultiProcessRunnerResult:
        """Run the job to completion, recovering from failures within
        the restart budget. Returns the final generation's result;
        raises :class:`RecoveryFailedError` on budget exhaustion."""
        spec = self._spec_fn(self._num_workers)
        self._runner = self._runner_factory(
            self._fn, spec, args=self._args, kwargs=self._kwargs,
            env=self._child_env(0), devices_per_process=self._devices,
            timeout=self._generation_timeout_s)
        self._event("recovery.run_start", num_workers=self._num_workers,
                    max_restarts=self.max_restarts,
                    chaos_kills=len(self._kills))
        self._start_exporter()
        self._clear_heartbeats()
        self._runner.start()
        self._event("recovery.generation_start", generation=0)
        backoff = Backoff(self._policy)
        try:
            while True:
                failures = self._watch()
                if failures == "scale":
                    self._apply_scale()
                    continue
                if failures == "stop":
                    self._event("recovery.run_stopped",
                                generation=self.generation,
                                restarts=self.restarts_used)
                    self._runner.terminate_all()
                    return self._runner.join(timeout=30,
                                             raise_on_error=False)
                if failures is None:
                    result = self._runner.join(timeout=60,
                                               raise_on_error=False)
                    failures = self._result_failures(result)
                    if not failures:
                        for i in range(self._num_workers):
                            self.health.record_success(i)
                        self._event("recovery.run_complete",
                                    generation=self.generation,
                                    restarts=self.restarts_used)
                        return result
                self._recover(failures, backoff)
        finally:
            self._runner.terminate_all()
            self._stop_exporter()

    def _result_failures(self, result) -> list[WorkerFailure]:
        return [WorkerFailure(generation=self.generation, task=k,
                              kind=self._classify(t.exitcode),
                              exitcode=t.exitcode, wall=time.time(),
                              detail=(t.error or "")[-300:])
                for k, t in sorted(result.tasks.items())
                if t.exitcode != 0 or t.error is not None]

    def _watch(self) -> "list[WorkerFailure] | None | str":
        """Watch the current generation. Returns failures needing
        recovery, None when every task exited cleanly, ``"scale"``
        when a scale request is pending (the run loop applies it), or
        ``"stop"`` after :meth:`request_stop`.

        Heartbeats are read from the source ONCE per tick (``read_all``
        — for the sharded KV source that is O(N/shard) key reads) and
        the one batch feeds clock-sync telemetry, chaos-kill targeting
        and stall detection alike."""
        runner = self._runner
        t0 = time.monotonic()
        while True:
            exits = runner.poll()
            bad = {k: c for k, c in exits.items() if c != 0}
            if bad:
                return [WorkerFailure(
                    generation=self.generation, task=k,
                    kind=self._classify(c), exitcode=c, wall=time.time())
                    for k, c in sorted(bad.items())]
            if len(exits) == runner.num_tasks:
                return None
            hbs = self._hb.read_all(self._num_workers)
            self._observe_heartbeats(hbs)
            self._fire_due_kills(exits, hbs)
            stalled = self._check_stall(exits, t0, hbs)
            if stalled is not None:
                return [stalled]
            if self._stop_requested.is_set():
                return "stop"
            if self.autoscaler is not None:
                # the closed loop: SLO burn / goodput -> decision ->
                # request_scale, all on this tick. A policy bug logs,
                # it never kills the supervised job.
                try:
                    self.autoscaler.tick(self)
                except Exception as e:       # noqa: BLE001
                    self._event("scale.error",
                                generation=self.generation,
                                error=repr(e)[:300])
            with self._scale_lock:
                pending = self._pending_scale
            if pending is not None:
                return "scale"
            if self.kv_gc is not None:
                swept = self.kv_gc.maybe_sweep(current_gen=self.generation)
                if swept:
                    self._event("recovery.kv_gc",
                                generation=self.generation, swept=swept)
            if time.monotonic() - t0 > self._generation_timeout_s:
                return [WorkerFailure(
                    generation=self.generation, task=("worker", -1),
                    kind="stall", wall=time.time(),
                    detail=f"generation exceeded "
                           f"{self._generation_timeout_s}s")]
            time.sleep(self._poll_s)

    def _observe_heartbeats(self, hbs):
        """Telemetry-only: record one ``clock.hb`` event per fresh
        worker heartbeat, pairing the worker's self-reported wall clock
        with the heartbeat's observation time (this process's clock
        domain — the file mtime for file heartbeats). These pairs are
        how the trace assembler
        (telemetry/trace.estimate_clock_offsets) aligns the
        supervisor's recovery timeline with the workers' step
        timelines. No-op without a telemetry log."""
        if self._log is None:
            return
        for i, hb in hbs.items():
            if (hb[1] is not None and hb[2] is not None
                    and hb[1] != self._hb_seen.get(i)):
                self._hb_seen[i] = hb[1]
                self._event("clock.hb", generation=self.generation,
                            worker=i, step=hb[1],
                            worker_wall=hb[2], mtime=hb[0])

    def _fire_due_kills(self, exits, hbs):
        for rec in list(self._kills):
            spec = rec["spec"]
            if rec["fired_gen"] is not None and (
                    not spec.permanent
                    or rec["fired_gen"] >= self.generation):
                continue                    # spent (or already fired
            if spec.worker >= self._num_workers:   # this generation)
                self._kills.remove(rec)     # slot shrunk away: retire
                continue
            if ("worker", spec.worker) in exits:
                continue                    # already down — keep waiting
            hb = hbs.get(spec.worker)
            if hb is None or hb[1] is None or hb[1] < spec.after_step:
                continue
            self._event("recovery.chaos_kill", generation=self.generation,
                        worker=spec.worker, after_step=spec.after_step,
                        at_step=hb[1], permanent=spec.permanent)
            self._runner.terminate("worker", spec.worker)
            rec["fired_gen"] = self.generation
            if not spec.permanent:
                self._kills.remove(rec)

    def _check_stall(self, exits, t0: float, hbs) -> WorkerFailure | None:
        if self._stall_timeout_s is None:
            return None
        now = time.time()
        # (overage, age, budget, worker): worst = largest budget overrun
        worst: tuple[float, float, float, int] | None = None
        for i in range(self._num_workers):
            if ("worker", i) in exits:
                continue                          # finished: not stalled
            hb = hbs.get(i)
            # before the first heartbeat, age from generation start
            # against the (typically larger) heartbeat_grace_s budget —
            # spawn + jax import + first compile are not a stall
            if hb is not None:
                age, budget = now - hb[0], self._stall_timeout_s
            else:
                age, budget = (time.monotonic() - t0,
                               self._heartbeat_grace_s)
            over = age - budget
            if worst is None or over > worst[0]:
                worst = (over, age, budget, i)
        if worst is not None and worst[0] > 0:
            return WorkerFailure(
                generation=self.generation, task=("worker", worst[3]),
                kind="stall", wall=now,
                detail=f"no heartbeat for {worst[1]:.3f}s "
                       f"(budget {worst[2]}s)")
        return None

    # -- elastic resizing (the resource-manager surface) ------------------
    def request_scale(self, num_workers: int, *,
                      reason: str = "scale") -> "int | None":
        """Ask for an elastic resize to ``num_workers`` (clamped to
        ``[min_workers, max_workers]``). Thread-safe and asynchronous:
        the watch loop applies it at its next healthy tick through the
        same generation-bump + reform machinery a failure recovery
        uses — behind the reform lock, so a request landing mid-recovery
        is deferred, never lost, and never consumes the restart budget.
        Returns the accepted (clamped) target, or None for a no-op."""
        target = max(self.min_workers, int(num_workers))
        if self.max_workers is not None:
            target = min(target, self.max_workers)
        with self._scale_lock:
            if target == self._num_workers and self._pending_scale is None:
                return None
            self._pending_scale = (target, reason)
        return target

    def request_stop(self) -> None:
        """Ask the run loop to end the job at its next watch tick
        (``recovery.run_stopped``): the shared-fleet supervisor uses it
        to wind the training job down once the serving workload is
        done. The returned result carries whatever each task had
        produced; no recovery is attempted."""
        self._stop_requested.set()

    def _drain_generation(self, mode: str = "fast") -> int:
        """Write per-task drain flags (``mode``: ``fast`` = finish
        running work only, ``full`` = finish everything admitted — see
        cluster/elastic.drain_mode) and give the running generation up
        to ``drain_timeout_s`` to exit on its own (serving replicas
        finish and log — zero dropped requests). Returns how many
        tasks exited before the deadline; stragglers are terminated by
        the caller."""
        n = self._num_workers
        for i in range(n):
            try:
                with open(elastic.drain_path(self._dir, i), "w") as f:
                    f.write(mode)
            except OSError:
                pass
        deadline = time.monotonic() + self._drain_timeout_s
        while time.monotonic() < deadline:
            exits = self._runner.poll()
            if len(exits) >= self._runner.num_tasks:
                break
            time.sleep(self._poll_s)
        return len(self._runner.poll())

    def _clear_drains(self, n: int):
        for i in range(n):
            try:
                os.unlink(elastic.drain_path(self._dir, i))
            except OSError:
                pass

    def _apply_scale(self):
        """Apply the pending scale request: (drain ->) terminate ->
        generation bump -> reform at the new size. The new generation
        is recorded in ``scale_generations`` and announced with a
        ``scale.applied`` event so the goodput ledger prices the gap
        as ``scale_transition``, not ``recovery``."""
        with self._scale_lock:
            pending, self._pending_scale = self._pending_scale, None
        if pending is None:
            return
        target, reason = pending
        with self._reform_lock:
            old_n = self._num_workers
            if target == old_n:
                return
            direction = "up" if target > old_n else "down"
            drained = 0
            if self._drain_on_scale:
                # scale-up wants the capacity NOW (queued work
                # re-shards); scale-down happens at low load, so
                # completing the admitted queue ("full") — or handing
                # live KV to the successor ("migrate", zero replay) —
                # keeps those requests off the respawn gap's tail
                drained = self._drain_generation(
                    self._drain_scale_down_mode
                    if direction == "down" else "fast")
            self._runner.terminate_all()
            if self.kv_gc is not None:
                hbs = self._hb.read_all(old_n)
                last = max((h[0] for h in hbs.values()),
                           default=time.time())
                self.kv_gc.note_generation_end(self.generation, last)
            self.generation += 1
            self.scale_generations.add(self.generation)
            self.scales_applied += 1
            if direction == "down":
                # removed slots: retire their exporter label series
                # (role change / repurposed machine — the ghost-series
                # dedup, exporter.retire_worker) and forget their fail
                # streaks; memdirs stay — the machine is donated, not
                # dead, and may come back on a scale-up
                for i in range(target, old_n):
                    if self._exporter is not None:
                        self._exporter.retire_worker(i)
                self._fail_streak = {w: s for w, s in
                                     self._fail_streak.items()
                                     if w < target}
            self._num_workers = target
            self._clear_heartbeats(clear_n=max(old_n, target))
            self._clear_drains(max(old_n, target))
            self._runner.reform(
                self._spec_fn(target),
                env=self._child_env(self.generation),
                allow_resize=True)
            # emitted AFTER the reform so the event's wall is the
            # instant the new capacity is actually spawning — the
            # honest end of the actuation latency chaos_sweep --spike
            # and bench --autoscale measure
            self._event("scale.applied", generation=self.generation,
                        from_workers=old_n, to_workers=target,
                        reason=reason, direction=direction,
                        drained=drained)
        self._event("recovery.generation_start",
                    generation=self.generation)

    #: failure kinds that mean the MACHINE behind the slot lost its
    #: memory (peer-snapshot memdir wiped): a SIGKILL stands in for
    #: node death and a preemption reclaims the VM. A stall or an
    #: in-process crash leaves the machine — and its memdir — alive.
    _MACHINE_LOST_KINDS = frozenset({"killed", "preempted"})

    def _record_failures(self, failures: list[WorkerFailure]):
        import shutil

        from distributed_tensorflow_tpu.cluster import elastic
        failed_ids = set()
        for f in failures:
            self.history.append(f)
            self.failures_total += 1
            self.health.record_failure(f.task[1])
            if f.task[1] >= 0:
                failed_ids.add(f.task[1])
                self._fail_streak[f.task[1]] = \
                    self._fail_streak.get(f.task[1], 0) + 1
            if f.kind in self._MACHINE_LOST_KINDS and f.task[1] >= 0:
                shutil.rmtree(
                    elastic.peer_memdir_path(self._dir, f.task[1]),
                    ignore_errors=True)
            self._event("recovery.worker_death", generation=f.generation,
                        task_type=f.task[0], task_id=f.task[1],
                        kind=f.kind, exitcode=f.exitcode, detail=f.detail)
        # bounded memory on flapping runs: keep only the newest entries
        if len(self.history) > self.max_failure_history:
            del self.history[:-self.max_failure_history]
        # a slot that did NOT fail this round broke its streak
        for wid in list(self._fail_streak):
            if wid not in failed_ids:
                self._fail_streak[wid] = 0

    def _maybe_shrink(self) -> int | None:
        """Apply the shrink policy; returns the removed task id (or
        None). The worst repeat offender's slot is dropped, higher slots
        renumber down, and their machines' memdirs follow them."""
        import shutil

        from distributed_tensorflow_tpu.cluster import elastic
        if self.shrink_after is None or self._num_workers <= \
                self.min_workers:
            return None
        over = {w: n for w, n in self._fail_streak.items()
                if n >= self.shrink_after}
        if not over:
            return None
        removed = max(over, key=lambda w: (over[w], -w))
        shutil.rmtree(elastic.peer_memdir_path(self._dir, removed),
                      ignore_errors=True)
        for i in range(removed + 1, self._num_workers):
            src = elastic.peer_memdir_path(self._dir, i)
            dst = elastic.peer_memdir_path(self._dir, i - 1)
            shutil.rmtree(dst, ignore_errors=True)
            if os.path.isdir(src):
                os.replace(src, dst)
        self._fail_streak = {
            (w - 1 if w > removed else w): n
            for w, n in self._fail_streak.items() if w != removed}
        for rec in list(self._kills):       # chaos plan follows the
            w = rec["spec"].worker          # machines, not the slots
            if w == removed:
                self._kills.remove(rec)     # the dead machine is gone
            elif w > removed:
                rec["spec"] = dataclasses.replace(rec["spec"],
                                                  worker=w - 1)
        self._num_workers -= 1
        return removed

    def _recover(self, failures: list[WorkerFailure],
                 backoff: Backoff):
        """Bounded recovery: record → kill stragglers → (budget
        permitting) back off, bump the generation, maybe shrink,
        reform, un-quarantine the restarted lanes. Holds the reform
        lock end to end — a scale request arriving mid-recovery stays
        pending until the next healthy watch tick."""
        with self._reform_lock:
            self._recover_locked(failures, backoff)

    def _recover_locked(self, failures: list[WorkerFailure],
                        backoff: Backoff):
        self._record_failures(failures)
        # a stalled task is still alive; every straggler of the dead
        # generation gets killed before the namespace moves on
        for key in self._runner.alive_tasks():
            self._event("recovery.kill_straggler",
                        generation=self.generation,
                        task_type=key[0], task_id=key[1])
        self._runner.terminate_all()
        if self.restarts_used >= self.max_restarts:
            self._event("recovery.failed", generation=self.generation,
                        restarts=self.restarts_used,
                        failures=self.failures_total)
            raise RecoveryFailedError(
                f"restart budget exhausted ({self.restarts_used}/"
                f"{self.max_restarts} restarts used) after "
                f"{self.failures_total} failure(s): "
                + "; ".join(f.describe() for f in self.history[-5:]),
                self.history)
        self.restarts_used += 1
        delay = backoff.next_s()
        if self.kv_gc is not None:
            # anchor the dying generation's GC grace window on the last
            # heartbeat anyone in it produced (stragglers get the full
            # grace past this instant before their keys are swept)
            hbs = self._hb.read_all(self._num_workers)
            last = max((h[0] for h in hbs.values()),
                       default=time.time())
            self.kv_gc.note_generation_end(self.generation, last)
        self.generation += 1
        removed = self._maybe_shrink()
        if removed is not None:
            self._event("recovery.reshard", generation=self.generation,
                        removed_task=removed,
                        old_workers=self._num_workers + 1,
                        new_workers=self._num_workers,
                        streak=self.shrink_after)
        span_cm = (self._log.span if self._log is not None
                   else _events.span)
        with span_cm("recovery.recover", generation=self.generation,
                     restart=self.restarts_used, backoff_s=round(delay, 3)):
            if delay > 0:
                time.sleep(delay)
            self._clear_heartbeats()
            self._event("recovery.restart", generation=self.generation,
                        restart=self.restarts_used,
                        budget_left=self.max_restarts - self.restarts_used,
                        backoff_s=round(delay, 3),
                        num_workers=self._num_workers)
            self._runner.reform(
                self._spec_fn(self._num_workers),
                env=self._child_env(self.generation),
                allow_resize=removed is not None)
            for f in failures:
                if 0 <= f.task[1] < self._num_workers:
                    self.health.worker_restarted(f.task[1])
        self._event("recovery.generation_start",
                    generation=self.generation)   # also flushes the span
