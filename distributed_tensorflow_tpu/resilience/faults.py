"""Deterministic, seed-driven fault injection (the chaos layer).

The resilience claims of this framework — closures survive worker death
(coordinator/cluster_coordinator.py), checkpoints survive torn commits
(checkpoint/checkpoint.py), training survives preemption
(checkpoint/failure_handling.py) — are only claims until the failure
paths actually run. This registry lets tests (and `tools/chaos_sweep.py`)
fire those paths on command, reproducibly.

Model: production code is instrumented with named **injection sites**::

    faults.fire("coord.barrier", tag=name, exc=BarrierTimeoutError,
                msg="injected barrier timeout")

A site consults the installed :class:`FaultSchedule`; a matching
:class:`FaultRule` makes the site raise (``exc``), sleep (``delay``), or
hand a :class:`FaultDecision` back to the caller (``corrupt`` /
``signal`` — the call site implements the site-specific damage, e.g. a
torn shard file). With no schedule installed — the production default —
``fire`` is a single module-global ``None`` check: zero overhead, no
locks, no allocation.

Instrumented sites:

========================  ====================================================
``coord.kv_get``          CoordinationServiceAgent.key_value_get (tag=key)
``coord.barrier``         CoordinationServiceAgent.barrier (tag=barrier name)
``dispatch.wait``         RemoteLane.wait (tag=worker id)
``closure.execute``       Worker._process_closure (tag=worker index)
``checkpoint.commit``     Checkpoint._commit (tag=path; ``corrupt`` tears a
                          shard file after the index commits)
``preemption.signal``     PreemptionCheckpointHandler.run (tag=process id;
                          ``signal`` delivers a synthetic preemption notice)
``input.prefetch``        Dataset.prefetch / fetch-to-device background
                          worker, once per element (tag=stage name) — a
                          ``raise`` here models a decode/IO failure inside
                          the host input pipeline; it must surface on the
                          consumer, never hang the queue
``peer.exchange``         peer_snapshot.exchange (tag=process id) — a
                          ``raise`` models losing the ring-replica
                          transfer at a snapshot boundary; training and
                          the disk tiers must be unaffected
``serve.step``            serving/engine.InferenceEngine.step (tag=step
                          index) — fires BEFORE any scheduler/cache
                          mutation, so a ``raise`` models a transient
                          serving-step failure the replica retries
                          without losing or double-serving a request
``fleet.step``            testing/fleet_sim.py simulated-worker step
                          (tag=worker pid; per-tag hit counter == the
                          worker's step number) — ``raise`` crashes the
                          worker, ``delay`` stalls it past the
                          supervisor's staleness budget, ``signal``
                          partitions it (KV ops and heartbeats
                          suppressed for a window); the seeded
                          fault plans of bench.py --fleet and
                          tools/fleet_sweep.py are rules on this site
``data.dispatch``         input/data_service.DataServiceDispatcher.tick
                          (tag=job) — a ``raise`` fails one dispatch
                          round; the background loop must absorb it
                          and the next tick must re-derive assignment
``data.fetch``            input/data_service.DataServiceClient split
                          fetch (tag=split id) — a ``raise`` models a
                          transient payload-read failure the trainer
                          retries under its decorrelated RetryPolicy
``data.worker_step``      input/data_service.DataInputWorker per
                          split-processing attempt (tag=worker id) —
                          ``raise`` crashes the input worker mid-epoch,
                          ``delay`` stalls it past the lease budget;
                          either must end in the dispatcher re-issuing
                          the lease and an exactly-once epoch
``offload.spill``         parallel/offload.ActivationSpillStore.put,
                          once per spilled 1F1B cycle (tag=``c<cycle>``)
                          — a ``raise`` fails the device->host
                          activation copy; the store retries once, and
                          a double failure must surface as a clean
                          ``OffloadSpillError`` on the cycle that needs
                          the lost stash entry — never a hang, never
                          silently wrong activations
                          (tools/chaos_sweep.py --offload)
========================  ====================================================

Determinism: hit counters are kept per ``(site, tag)`` **and** per site
globally; a rule with ``tag`` set evaluates against the per-tag counter
(deterministic regardless of thread interleaving across lanes), a rule
without evaluates against the site-global counter. Probabilistic rules
draw from a dedicated ``random.Random`` stream seeded by
``(schedule seed, rule index, site, tag)`` — one site's draw sequence is
a pure function of its own hit sequence, never of what other sites did
in between. Every firing is appended to an event log
(:func:`events`) so a run can be compared bit-for-bit against a replay.

Activation: programmatic (``install``/``inject``) or via the
``DTX_FAULT_SCHEDULE`` environment variable holding the JSON schedule
(or ``@/path/to/schedule.json``) — the env form reaches spawned
multi-process children for free.
"""

from __future__ import annotations

import contextlib
import dataclasses
import fnmatch
import json
import os
import random
import threading
import time


class FaultInjected(RuntimeError):
    """Default exception for a ``raise`` fault at a site that did not
    supply its own exception class."""


@dataclasses.dataclass(frozen=True)
class FaultRule:
    """One injection rule.

    ``site`` is an ``fnmatch`` pattern over site names (``"coord.*"``).
    Trigger selection (all optional, combined with AND):

    - ``hits``: fire only on these 1-based hit indices;
    - ``every``: fire on every Nth hit;
    - ``probability``: fire with this per-hit probability (seeded,
      deterministic);
    - ``max_fires``: stop firing after this many firings;
    - ``tag``: only fire for this tag value (e.g. one worker id), and
      count hits per tag instead of per site.

    ``action``: ``raise`` | ``delay`` | ``corrupt`` | ``signal``.
    ``delay_s`` applies to ``delay``.
    """

    site: str
    action: str = "raise"
    hits: tuple[int, ...] | None = None
    every: int | None = None
    probability: float | None = None
    max_fires: int | None = None
    delay_s: float = 0.0
    tag: str | None = None

    _ACTIONS = ("raise", "delay", "corrupt", "signal")

    def __post_init__(self):
        if self.action not in self._ACTIONS:
            raise ValueError(f"unknown fault action {self.action!r} "
                             f"(expected one of {self._ACTIONS})")
        if self.hits is not None:
            object.__setattr__(self, "hits", tuple(int(h) for h in self.hits))
        if self.tag is not None:
            object.__setattr__(self, "tag", str(self.tag))

    def to_dict(self) -> dict:
        out = {"site": self.site, "action": self.action}
        for k in ("hits", "every", "probability", "max_fires", "tag"):
            v = getattr(self, k)
            if v is not None:
                out[k] = list(v) if isinstance(v, tuple) else v
        if self.delay_s:
            out["delay_s"] = self.delay_s
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "FaultRule":
        d = dict(d)
        if "p" in d:                      # short alias in hand-written JSON
            d["probability"] = d.pop("p")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown fault rule keys {sorted(unknown)}")
        if "hits" in d and d["hits"] is not None:
            d["hits"] = tuple(d["hits"])
        return cls(**d)


@dataclasses.dataclass(frozen=True)
class FaultSchedule:
    """An ordered rule list plus the seed all probabilistic draws derive
    from. The first matching rule per hit wins."""

    rules: tuple[FaultRule, ...] = ()
    seed: int = 0

    def __post_init__(self):
        object.__setattr__(self, "rules", tuple(self.rules))

    def to_json(self) -> str:
        return json.dumps({"seed": self.seed,
                           "rules": [r.to_dict() for r in self.rules]})

    @classmethod
    def from_json(cls, text: str) -> "FaultSchedule":
        if text.startswith("@"):
            with open(text[1:]) as f:
                text = f.read()
        d = json.loads(text)
        return cls(seed=int(d.get("seed", 0)),
                   rules=tuple(FaultRule.from_dict(r)
                               for r in d.get("rules", ())))


@dataclasses.dataclass(frozen=True)
class FaultDecision:
    """What a site was told to do (returned for corrupt/signal; raise and
    delay are consumed inside :func:`fire`)."""

    site: str
    tag: str | None
    hit: int
    rule_index: int
    action: str
    delay_s: float = 0.0


class FaultRegistry:
    """Live injection state for one installed schedule: hit counters,
    per-rule fire counts, seeded RNG streams, and the event log."""

    def __init__(self, schedule: FaultSchedule):
        self.schedule = schedule
        self._lock = threading.Lock()
        self._hits: dict[tuple[str, str | None], int] = {}
        self._fires: dict[int, int] = {}
        self._rngs: dict[tuple[int, str, str | None], random.Random] = {}
        self._events: list[tuple] = []

    def _rng(self, rule_index: int, site: str,
             tag: str | None) -> random.Random:
        key = (rule_index, site, tag)
        rng = self._rngs.get(key)
        if rng is None:
            # str seeds hash via sha512 (stable across processes/runs)
            rng = random.Random(
                f"{self.schedule.seed}:{rule_index}:{site}:{tag}")
            self._rngs[key] = rng
        return rng

    def fire(self, site: str, tag=None, exc=None,
             msg: str | None = None) -> FaultDecision | None:
        tag = None if tag is None else str(tag)
        with self._lock:
            gh = self._hits.get((site, None), 0) + 1
            self._hits[(site, None)] = gh
            th = gh
            if tag is not None:
                th = self._hits.get((site, tag), 0) + 1
                self._hits[(site, tag)] = th
            decision = None
            for idx, rule in enumerate(self.schedule.rules):
                if not fnmatch.fnmatchcase(site, rule.site):
                    continue
                if rule.tag is not None and rule.tag != tag:
                    continue
                h = th if rule.tag is not None else gh
                if rule.max_fires is not None and \
                        self._fires.get(idx, 0) >= rule.max_fires:
                    continue
                if rule.hits is not None and h not in rule.hits:
                    continue
                if rule.every is not None and h % rule.every != 0:
                    continue
                if rule.probability is not None and \
                        self._rng(idx, site, tag).random() >= rule.probability:
                    continue
                self._fires[idx] = self._fires.get(idx, 0) + 1
                decision = FaultDecision(site=site, tag=tag, hit=h,
                                         rule_index=idx, action=rule.action,
                                         delay_s=rule.delay_s)
                self._events.append((site, tag, h, rule.action, idx))
                break
        if decision is None:
            return None
        # Telemetry: every firing is visible in the run's structured
        # event log + fleet metric rollups (chaos runs are exactly the
        # runs an operator later reconstructs from telemetry).
        from distributed_tensorflow_tpu.telemetry import events as _tv_events
        from distributed_tensorflow_tpu.telemetry import registry as _tv_reg
        _tv_reg.counter("resilience/faults_fired",
                        "chaos-layer fault firings").increment()
        _tv_events.event("fault.fired", site=site, tag=tag,
                         hit=decision.hit, action=decision.action)
        if decision.action == "delay":
            time.sleep(decision.delay_s)
            return decision
        if decision.action == "raise":
            cls = exc or FaultInjected
            raise cls(msg or f"injected fault at {site!r} "
                             f"(hit {decision.hit})")
        return decision                   # corrupt / signal: caller's job

    def events(self) -> list[tuple]:
        """(site, tag, hit, action, rule_index) per firing, in order."""
        with self._lock:
            return list(self._events)


_REGISTRY: FaultRegistry | None = None
_INSTALL_LOCK = threading.Lock()


def active() -> bool:
    """True when a schedule is installed (the chaos layer is live)."""
    return _REGISTRY is not None


def install(schedule: FaultSchedule) -> FaultRegistry:
    """Install ``schedule`` process-wide; returns the live registry."""
    global _REGISTRY
    with _INSTALL_LOCK:
        _REGISTRY = FaultRegistry(schedule)
        return _REGISTRY


def clear():
    """Remove any installed schedule (back to the zero-overhead path)."""
    global _REGISTRY
    with _INSTALL_LOCK:
        _REGISTRY = None


@contextlib.contextmanager
def inject(schedule: FaultSchedule):
    """Scoped installation: ``with faults.inject(schedule) as registry:``.
    Restores whatever was installed before on exit."""
    global _REGISTRY
    with _INSTALL_LOCK:
        prev = _REGISTRY
        registry = FaultRegistry(schedule)
        _REGISTRY = registry
    try:
        yield registry
    finally:
        with _INSTALL_LOCK:
            _REGISTRY = prev


def fire(site: str, *, tag=None, exc=None,
         msg: str | None = None) -> FaultDecision | None:
    """Injection-site entry point. No schedule installed -> ``None``
    immediately (the hot-path guarantee); otherwise consult the registry
    and raise / sleep / return a decision per the matching rule."""
    reg = _REGISTRY
    if reg is None:
        return None
    return reg.fire(site, tag=tag, exc=exc, msg=msg)


def events() -> list[tuple]:
    """Firing log of the installed registry ([] when none installed)."""
    reg = _REGISTRY
    return reg.events() if reg is not None else []


# Env activation: a schedule in DTX_FAULT_SCHEDULE (JSON, or @/path) is
# live from import — the route by which spawned multi-process children
# inherit the chaos configuration.
_env = os.environ.get("DTX_FAULT_SCHEDULE")
if _env:
    install(FaultSchedule.from_json(_env))
del _env
