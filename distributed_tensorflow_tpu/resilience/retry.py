"""Unified retry policy: exponential backoff + jitter + deadline.

One policy object replaces the hand-rolled retry loops that had grown in
``coordinator/cluster_coordinator.py`` (per-worker resource creation:
fixed 3 attempts, resubmit between attempts) and
``coordinator/remote_dispatch.py`` (fast-fail backoff pacing inside
``RemoteLane.wait``) — ≙ the reference's single
``WorkerPreemptionHandler.wait_on_failure`` path
(cluster_coordinator.py:879) being the only place retry timing lives.

The policy is deliberately dumb about *what* is retryable: callers pass
the exception classification (``WorkerPreemptionError``,
``CoordinationError``, ...) so this module needs no imports from the
layers it serves.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Retry configuration + execution.

    - ``max_attempts``: total attempts (first try included);
    - ``initial_backoff_s`` * ``backoff_multiplier``^(n-1), capped at
      ``max_backoff_s``, slept between attempts (0 = no sleep);
    - ``jitter``: fraction j in [0, 1] — each backoff is scaled by a
      uniform draw from [1-j, 1+j] (decorrelates retry storms);
    - ``deadline_s``: overall budget from the first attempt; when
      exceeded the last exception is re-raised instead of retrying;
    - ``retryable``: default exception classes ``call`` retries on;
    - ``seed``: seeds the jitter stream (None = nondeterministic).
    """

    max_attempts: int = 3
    initial_backoff_s: float = 0.0
    backoff_multiplier: float = 2.0
    max_backoff_s: float = 30.0
    jitter: float = 0.0
    deadline_s: float | None = None
    retryable: tuple = (Exception,)
    seed: int | None = None

    def is_retryable(self, exc: BaseException, retryable=None) -> bool:
        return isinstance(exc, tuple(retryable or self.retryable))

    def backoff_s(self, attempt: int,
                  rng: random.Random | None = None) -> float:
        """Backoff after the ``attempt``-th failure (1-based)."""
        if self.initial_backoff_s <= 0:
            return 0.0
        d = min(self.initial_backoff_s
                * self.backoff_multiplier ** (attempt - 1),
                self.max_backoff_s)
        if self.jitter and rng is not None:
            d *= 1.0 - self.jitter + 2.0 * self.jitter * rng.random()
        return min(d, self.max_backoff_s)

    def call(self, fn: Callable, *, retryable=None,
             on_retry: Callable[[BaseException, int], None] | None = None):
        """Run ``fn()`` under this policy.

        On a retryable exception with attempts (and deadline budget)
        remaining: call ``on_retry(exc, attempt_number)`` (e.g. to
        resubmit work), sleep the backoff, try again. Exhaustion
        re-raises the LAST exception unchanged — callers that want a
        summary error catch and wrap it.
        """
        retry_on = tuple(retryable or self.retryable)
        rng = random.Random(self.seed) if self.jitter else None
        deadline = (time.monotonic() + self.deadline_s
                    if self.deadline_s is not None else None)
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn()
            except BaseException as e:
                if not isinstance(e, retry_on):
                    raise
                if attempt >= self.max_attempts:
                    raise
                if deadline is not None and time.monotonic() >= deadline:
                    raise
                if on_retry is not None:
                    on_retry(e, attempt)
                d = self.backoff_s(attempt, rng)
                if deadline is not None:
                    d = min(d, max(deadline - time.monotonic(), 0.0))
                if d > 0:
                    time.sleep(d)


class Backoff:
    """Stateful backoff pacer for open-ended loops (poll/wait paths that
    are bounded by liveness or deadline rather than attempt count).

    ``sleep(max_s=...)`` sleeps the next backoff in the policy's
    schedule, clamped to ``max_s``; ``reset()`` restarts the schedule
    after a success.
    """

    def __init__(self, policy: RetryPolicy, seed: int | None = None):
        self.policy = policy
        self._rng = (random.Random(policy.seed if seed is None else seed)
                     if policy.jitter else None)
        self._attempt = 0

    def next_s(self) -> float:
        self._attempt += 1
        return self.policy.backoff_s(self._attempt, self._rng)

    def sleep(self, max_s: float | None = None) -> float:
        d = self.next_s()
        if max_s is not None:
            d = min(d, max_s)
        if d > 0:
            time.sleep(d)
        return d

    def reset(self):
        self._attempt = 0
