"""Unified retry policy: exponential backoff + jitter + deadline.

One policy object replaces the hand-rolled retry loops that had grown in
``coordinator/cluster_coordinator.py`` (per-worker resource creation:
fixed 3 attempts, resubmit between attempts) and
``coordinator/remote_dispatch.py`` (fast-fail backoff pacing inside
``RemoteLane.wait``) — ≙ the reference's single
``WorkerPreemptionHandler.wait_on_failure`` path
(cluster_coordinator.py:879) being the only place retry timing lives.

The policy is deliberately dumb about *what* is retryable: callers pass
the exception classification (``WorkerPreemptionError``,
``CoordinationError``, ...) so this module needs no imports from the
layers it serves.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Retry configuration + execution.

    - ``max_attempts``: total attempts (first try included);
    - ``initial_backoff_s`` * ``backoff_multiplier``^(n-1), capped at
      ``max_backoff_s``, slept between attempts (0 = no sleep);
    - ``jitter``: fraction j in [0, 1] — each backoff is scaled by a
      uniform draw from [1-j, 1+j] (decorrelates retry storms);
    - ``decorrelated``: full decorrelated jitter (the AWS
      exponential-backoff-and-jitter scheme): each backoff is a fresh
      uniform draw from ``[initial_backoff_s, 3 * previous_backoff]``,
      capped at ``max_backoff_s``. Where multiplicative ``jitter``
      spreads N simultaneous retriers over a ±j band around the SAME
      schedule — after a coordinator blip they still arrive in loose
      waves — decorrelated draws spread them over the whole
      [initial, cap] range within a couple of attempts, which is what
      keeps an N-worker fleet's retry storm off the KV (the
      thundering-herd case the fleet harness sweeps). Deterministic
      per retrier under ``seed`` (give each worker its own seed);
    - ``deadline_s``: overall budget from the first attempt; when
      exceeded the last exception is re-raised instead of retrying;
    - ``retryable``: default exception classes ``call`` retries on;
    - ``seed``: seeds the jitter stream (None = nondeterministic).
    """

    max_attempts: int = 3
    initial_backoff_s: float = 0.0
    backoff_multiplier: float = 2.0
    max_backoff_s: float = 30.0
    jitter: float = 0.0
    decorrelated: bool = False
    deadline_s: float | None = None
    retryable: tuple = (Exception,)
    seed: int | None = None

    def is_retryable(self, exc: BaseException, retryable=None) -> bool:
        return isinstance(exc, tuple(retryable or self.retryable))

    def _needs_rng(self) -> bool:
        return bool(self.jitter) or self.decorrelated

    def backoff_s(self, attempt: int,
                  rng: random.Random | None = None,
                  prev_s: float = 0.0) -> float:
        """Backoff after the ``attempt``-th failure (1-based).
        ``prev_s`` is the previous backoff actually used — the state
        decorrelated jitter chains on (0.0 for the first)."""
        if self.initial_backoff_s <= 0:
            return 0.0
        if self.decorrelated and rng is not None:
            lo = self.initial_backoff_s
            hi = max(3.0 * (prev_s if prev_s > 0 else lo), lo)
            return min(rng.uniform(lo, hi), self.max_backoff_s)
        d = min(self.initial_backoff_s
                * self.backoff_multiplier ** (attempt - 1),
                self.max_backoff_s)
        if self.jitter and rng is not None:
            d *= 1.0 - self.jitter + 2.0 * self.jitter * rng.random()
        return min(d, self.max_backoff_s)

    def call(self, fn: Callable, *, retryable=None,
             on_retry: Callable[[BaseException, int], None] | None = None):
        """Run ``fn()`` under this policy.

        On a retryable exception with attempts (and deadline budget)
        remaining: call ``on_retry(exc, attempt_number)`` (e.g. to
        resubmit work), sleep the backoff, try again. Exhaustion
        re-raises the LAST exception unchanged — callers that want a
        summary error catch and wrap it.
        """
        retry_on = tuple(retryable or self.retryable)
        rng = random.Random(self.seed) if self._needs_rng() else None
        deadline = (time.monotonic() + self.deadline_s
                    if self.deadline_s is not None else None)
        attempt = 0
        prev_d = 0.0
        while True:
            attempt += 1
            try:
                return fn()
            except BaseException as e:
                if not isinstance(e, retry_on):
                    raise
                if attempt >= self.max_attempts:
                    raise
                if deadline is not None and time.monotonic() >= deadline:
                    raise
                if on_retry is not None:
                    on_retry(e, attempt)
                d = self.backoff_s(attempt, rng, prev_s=prev_d)
                prev_d = d
                if deadline is not None:
                    d = min(d, max(deadline - time.monotonic(), 0.0))
                if d > 0:
                    time.sleep(d)


class Backoff:
    """Stateful backoff pacer for open-ended loops (poll/wait paths that
    are bounded by liveness or deadline rather than attempt count).

    ``sleep(max_s=...)`` sleeps the next backoff in the policy's
    schedule, clamped to ``max_s``; ``reset()`` restarts the schedule
    after a success.
    """

    def __init__(self, policy: RetryPolicy, seed: int | None = None):
        self.policy = policy
        self._rng = (random.Random(policy.seed if seed is None else seed)
                     if policy._needs_rng() else None)
        self._attempt = 0
        self._prev = 0.0

    def next_s(self) -> float:
        self._attempt += 1
        d = self.policy.backoff_s(self._attempt, self._rng,
                                  prev_s=self._prev)
        self._prev = d
        return d

    def sleep(self, max_s: float | None = None) -> float:
        d = self.next_s()
        if max_s is not None:
            d = min(d, max_s)
        if d > 0:
            time.sleep(d)
        return d

    def reset(self):
        self._attempt = 0
        self._prev = 0.0
