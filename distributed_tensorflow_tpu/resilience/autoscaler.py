"""SLO-driven autoscaling and goodput-aware capacity arbitration.

The closed loop (ROADMAP item 5): PR 10 made the fleet *measurable*
(multi-window SLO burn rates, the goodput/badput ledger) — this module
makes it *act*. Three layers, bottom up:

- :class:`Autoscaler` — the pure policy engine. Every tick it turns the
  live ``serve.request`` completion stream into burn rates
  (telemetry/slo.burn_windows) and emits a :class:`ScaleDecision`:
  **up** when both burn windows fire ``fire_consecutive`` ticks in a
  row, **down** when every window has stayed under ``clear_burn`` for
  ``clear_hold_s`` (the hysteresis), never more often than
  ``cooldown_s``. No side effects — fully unit-testable with a fake
  clock.
- :class:`CapacityArbiter` — arbitration over a FIXED worker budget
  shared by one training job and one serving job. Ticked from the
  serving supervisor's watch loop (``RecoverySupervisor(autoscaler=)``),
  it actuates decisions as a small state machine: a scale-up first asks
  the *training* supervisor to donate a worker
  (``request_scale(n-1, reason="donate_to_serving")`` — the PR 7
  topology-elastic shrink path, so the trainer resumes N-1-sharded from
  warm tiers, no cold restart), waits for the donation to land, then
  grows serving; a scale-down drains the serving replica
  (drain-before-stop: zero dropped requests) and hands the capacity
  back (``reason="reclaim"``). Decisions and outcomes are
  ``scale.decision`` events; applied reforms are ``scale.applied``;
  the live split is exported as ``fleet/capacity/*`` gauges.
- :class:`SharedFleetSupervisor` — the runnable composition: two
  :class:`~distributed_tensorflow_tpu.resilience.supervisor.
  RecoverySupervisor` instances over disjoint telemetry subdirs
  (``<dir>/train`` + ``<dir>/serve``, each a self-contained run dir),
  the arbiter wired as the serving supervisor's autoscaler, and a root
  metrics exporter whose scrape carries both jobs' goodput ledgers and
  the capacity gauges. Every transition is priced: scale generations'
  reform gaps land in the ``scale_transition`` badput bucket
  (telemetry/goodput.py), so ``wall == goodput + Σ badput`` holds
  through the whole maneuver and the decision's cost is auditable.

Verified the way this repo always does: ``tools/chaos_sweep.py
--spike`` drives seeded traffic spikes through a real shared fleet
(examples/shared_fleet.py) and gates scale-up firing, SLO recovery,
the ledger identity (±1%) and capacity return; ``bench.py
--autoscale`` captures the measured spike table (AUTOSCALE_r*.json,
regression-gated inverted by tools/bench_trend.py).
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
import threading
import time

from distributed_tensorflow_tpu.resilience.supervisor import (
    RecoverySupervisor,
)
from distributed_tensorflow_tpu.telemetry import events as tv_events
from distributed_tensorflow_tpu.telemetry import registry as tv_registry
from distributed_tensorflow_tpu.telemetry import slo as tv_slo


def _default_slo() -> tv_slo.SLO:
    # short-run burn windows (8s/2s @ 2x): bench/chaos runs last tens
    # of seconds, not 30 days; production deployments pass their own
    # SLO with the SRE presets (slo.DEFAULT_BURN_WINDOWS)
    return tv_slo.SLO("p99_latency", "latency", objective=0.99,
                      threshold_s=0.5, windows=((8.0, 2.0, 2.0),))


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """The closed loop's knobs (the README "Autoscaling" table).

    ``slo`` supplies the burn thresholds (its window triples are the
    ``(long_s, short_s, max_burn)`` pairs that must BOTH fire);
    ``fire_consecutive`` debounces scale-ups, ``clear_hold_s`` +
    ``clear_burn`` are the scale-down hysteresis, ``cooldown_s`` paces
    actions, ``min/max_replicas`` bound serving and ``train_floor``
    bounds how far training can be drained."""

    min_replicas: int = 1
    max_replicas: int = 8
    train_floor: int = 1
    fire_consecutive: int = 2
    clear_burn: float = 1.0
    clear_hold_s: float = 5.0
    cooldown_s: float = 8.0
    scale_step: int = 1
    interval_s: float = 0.5
    #: minimum completions inside the SHORT window for a burn reading
    #: to count as firing — with two data points, one contention blip
    #: reads as burn 50x; no evidence is no alarm (the SRE
    #: low-traffic rule), and sizing this just under the spike's
    #: completion rate makes startup jitter physically unable to fire
    min_evidence: int = 3
    slo: tv_slo.SLO = dataclasses.field(default_factory=_default_slo)


@dataclasses.dataclass(frozen=True)
class ScaleDecision:
    """One policy verdict (also the payload of ``scale.decision``)."""

    direction: str                       # "up" | "down"
    target: int
    reason: str                          # "slo_burn" | "burn_clear"
    wall: float
    burn_long: "float | None" = None
    burn_short: "float | None" = None
    firing: bool = False
    #: decision provenance (multi-tenant serving): the tenant whose
    #: per-tenant burn fired — None for fleet-level verdicts or
    #: single-tenant deployments
    tenant: "str | None" = None

    def to_fields(self) -> dict:
        return {"direction": self.direction, "target": self.target,
                "reason": self.reason,
                "burn_long": (round(self.burn_long, 4)
                              if self.burn_long is not None else None),
                "burn_short": (round(self.burn_short, 4)
                               if self.burn_short is not None else None),
                "firing": self.firing, "tenant": self.tenant}


def serving_records_fn(run_dir: str):
    """Live completion-record feed from a telemetry run directory: the
    replicas' event files are line-buffered and the reader tolerates
    torn tails, so this is safe to poll mid-run every tick."""
    def _read() -> list:
        try:
            return tv_slo.records_from_events(tv_events.read_run(run_dir))
        except Exception:                # noqa: BLE001 — mid-write race
            return []
    return _read


class Autoscaler:
    """The pure policy engine: burn windows in, :class:`ScaleDecision`
    out. Stateful only in the ways the policy needs (fire streak,
    clear timer, cooldown); all clocks injectable."""

    def __init__(self, policy: "AutoscalePolicy | None" = None, *,
                 records_fn=None, tenants=None, clock=time.time):
        self.policy = policy or AutoscalePolicy()
        self._records_fn = records_fn
        #: TenantConfig set (serving/tenancy.py): when given, each
        #: decision also evaluates PER-TENANT burn (each tenant's own
        #: threshold/objective over the policy's windows) and names the
        #: worst-burning firing tenant — decision provenance for the
        #: multi-tenant router
        self._tenants = tuple(tenants) if tenants else ()
        self._clock = clock
        self._last_decide: "float | None" = None
        self._fire_streak = 0
        self._clear_since: "float | None" = None
        self._cooldown_until: "float | None" = None
        #: last evaluation (burns, firing, record count) — the live
        #: surface capacity gauges and health lines render
        self.last_eval: "dict | None" = None

    def action_applied(self, now: "float | None" = None):
        """Note an applied scale action: starts the cooldown and resets
        the debounce/hysteresis timers (the world just changed — old
        evidence is stale)."""
        now = now if now is not None else self._clock()
        self._cooldown_until = now + self.policy.cooldown_s
        self._fire_streak = 0
        self._clear_since = None

    def decide(self, n_replicas: int, *, records: "list | None" = None,
               now: "float | None" = None) -> "ScaleDecision | None":
        """One policy tick. Throttled to ``interval_s``; returns None
        when nothing should change."""
        p = self.policy
        now = now if now is not None else self._clock()
        if (self._last_decide is not None
                and now - self._last_decide < p.interval_s):
            return None
        self._last_decide = now
        if records is None:
            records = self._records_fn() if self._records_fn else []
        windows = tv_slo.burn_windows(records, p.slo, now=now)

        def _evidence(w) -> int:
            lo = now - w["short_s"]
            return sum(1 for r in records
                       if isinstance(r.get("wall"), (int, float))
                       and lo < r["wall"] <= now)

        firing = any(w["firing"] and _evidence(w) >= p.min_evidence
                     for w in windows)
        bl = windows[0]["burn_long"] if windows else None
        bs = windows[0]["burn_short"] if windows else None
        tenant, tenant_evals = self._tenant_burns(records, now)
        self.last_eval = {"wall": now, "burn_long": bl, "burn_short": bs,
                          "firing": firing, "records": len(records),
                          "tenant": tenant, "tenants": tenant_evals}
        if firing:
            self._fire_streak += 1
            self._clear_since = None
        else:
            self._fire_streak = 0
            # "clear" = every window's burns under clear_burn; a window
            # with NO traffic is clear too (idle capacity must flow
            # back — that is the whole point of the reclaim path)
            clear = all(
                (w["burn_short"] is None
                 or w["burn_short"] < p.clear_burn)
                and (w["burn_long"] is None
                     or w["burn_long"] < p.clear_burn)
                for w in windows)
            if clear:
                if self._clear_since is None:
                    self._clear_since = now
            else:
                self._clear_since = None
        if self._cooldown_until is not None and now < self._cooldown_until:
            return None
        if (self._fire_streak >= p.fire_consecutive
                and n_replicas < p.max_replicas):
            return ScaleDecision(
                "up", min(p.max_replicas, n_replicas + p.scale_step),
                "slo_burn", now, bl, bs, firing, tenant=tenant)
        if (self._clear_since is not None
                and now - self._clear_since >= p.clear_hold_s
                and n_replicas > p.min_replicas):
            return ScaleDecision(
                "down", max(p.min_replicas, n_replicas - p.scale_step),
                "burn_clear", now, bl, bs, firing)
        return None

    def _tenant_burns(self, records: list, now: float):
        """Per-tenant burn attribution: each tenant's records evaluated
        against ITS OWN threshold/objective over the policy's windows.
        Returns ``(worst_firing_tenant_or_None, {name: eval})``."""
        if not self._tenants:
            return None, None
        p = self.policy
        by_t: dict = {}
        for r in records:
            t = r.get("tenant")
            if t:
                by_t.setdefault(t, []).append(r)
        evals: dict = {}
        worst = None
        for cfg in self._tenants:
            recs = by_t.get(cfg.name)
            if not recs:
                continue
            t_slo = tv_slo.SLO(f"{cfg.name}/p99_latency", "latency",
                               objective=cfg.slo_objective,
                               threshold_s=cfg.slo_latency_s,
                               windows=p.slo.windows)
            wins = tv_slo.burn_windows(recs, t_slo, now=now)

            def _ev(w, recs=recs) -> int:
                lo = now - w["short_s"]
                return sum(1 for r in recs
                           if isinstance(r.get("wall"), (int, float))
                           and lo < r["wall"] <= now)

            t_firing = any(w["firing"] and _ev(w) >= p.min_evidence
                           for w in wins)
            t_bs = wins[0]["burn_short"] if wins else None
            evals[cfg.name] = {
                "burn_short": (round(t_bs, 4) if t_bs is not None
                               else None),
                "firing": t_firing, "records": len(recs),
                "share": round(len(recs) / len(records), 4)
                if records else None}
            if t_firing and t_bs is not None and (
                    worst is None
                    or t_bs > evals[worst]["burn_short"]):
                worst = cfg.name
        return worst, evals


class CapacityArbiter:
    """Fixed-budget arbitration between one training job and one
    serving job, actuated through their recovery supervisors.

    Wire it as the SERVING supervisor's ``autoscaler=`` — every watch
    tick calls :meth:`tick`, which runs the policy engine and drives a
    small state machine:

    ======================  =============================================
    ``idle``                ask the engine; on **up**: grow directly if
                            the budget has slack (training finished /
                            never started), else ask training to donate
                            (``awaiting_donation``); on **down**: shrink
                            serving (``applying_down``)
    ``awaiting_donation``   training shrink landed → grow serving
                            (``applying_up``)
    ``applying_up/down``    serving reform landed → (down only) hand the
                            freed capacity back to training
                            (``reason="reclaim"``), start the cooldown
    ======================  =============================================

    A state stuck longer than ``state_timeout_s`` (e.g. training wedged
    in its own recovery) reverts to ``idle`` with a ``scale.decision``
    outcome ``timeout`` — the loop re-evaluates rather than deadlocks.
    An **up** decision with training already at ``train_floor`` is
    outcome ``blocked`` (and starts a cooldown so it is re-examined,
    not spammed). The live split exports as ``fleet/capacity/*``
    gauges.
    """

    def __init__(self, engine: Autoscaler, *, budget: int,
                 train_sup: "RecoverySupervisor | None" = None,
                 train_floor: "int | None" = None,
                 state_timeout_s: float = 60.0, reg=None):
        self.engine = engine
        self.budget = budget
        self.train_sup = train_sup
        self.train_floor = (train_floor if train_floor is not None
                            else engine.policy.train_floor)
        self.state_timeout_s = state_timeout_s
        #: set by the shared-fleet supervisor when the training job
        #: exits (its workers stop counting against the budget)
        self.train_done = train_sup is None
        self._state = "idle"
        self._state_since: "float | None" = None
        self._pending: "ScaleDecision | None" = None
        self._expect_train: "int | None" = None
        self._train_baseline = (train_sup.num_workers
                                if train_sup is not None else 0)
        self.decisions = 0
        reg = reg or tv_registry.get_registry()
        self._g_budget = reg.gauge("fleet/capacity/budget")
        self._g_train = reg.gauge("fleet/capacity/train_workers")
        self._g_serve = reg.gauge("fleet/capacity/serve_replicas")
        self._g_burn = reg.gauge("fleet/capacity/burn_short")
        self._g_budget.set(budget)
        self._reg = reg
        self._g_tenant: dict = {}

    # -- helpers -----------------------------------------------------------
    def _train_n(self) -> int:
        if self.train_sup is None or self.train_done:
            return 0
        return self.train_sup.num_workers

    def _emit(self, serve_sup, decision: ScaleDecision, outcome: str):
        serve_sup._event("scale.decision", outcome=outcome,
                         state=self._state,
                         train_workers=self._train_n(),
                         serve_replicas=serve_sup.num_workers,
                         budget=self.budget, **decision.to_fields())

    def _enter(self, state: str, now: float):
        self._state = state
        self._state_since = now

    # -- the tick ----------------------------------------------------------
    def tick(self, serve_sup):
        now = self.engine._clock()
        self._g_train.set(self._train_n())
        self._g_serve.set(serve_sup.num_workers)
        ev = self.engine.last_eval
        if ev and ev.get("burn_short") is not None:
            self._g_burn.set(round(ev["burn_short"], 4))
        if ev and ev.get("tenants"):
            # per-tenant capacity view: burn + share of recent
            # completions, exported as fleet/tenant/<name>/* gauges
            for name, te in ev["tenants"].items():
                for field in ("burn_short", "share"):
                    if te.get(field) is None:
                        continue
                    key = f"fleet/tenant/{name}/{field}"
                    g = self._g_tenant.get(key)
                    if g is None:
                        g = self._g_tenant[key] = self._reg.gauge(key)
                    g.set(te[field])
        if self._state != "idle" and self._state_since is not None \
                and now - self._state_since > self.state_timeout_s:
            if self._pending is not None:
                self._emit(serve_sup, self._pending, "timeout")
            self.engine.action_applied(now)
            self._pending = None
            self._enter("idle", now)
        if self._state == "idle":
            d = self.engine.decide(serve_sup.num_workers, now=now)
            if d is None:
                return
            self.decisions += 1
            if d.direction == "up":
                self._begin_up(serve_sup, d, now)
            else:
                self._begin_down(serve_sup, d, now)
        elif self._state == "awaiting_donation":
            if (self.train_done
                    or self.train_sup.num_workers <= self._expect_train):
                serve_sup.request_scale(self._pending.target,
                                        reason="slo_burn")
                self._enter("applying_up", now)
        elif self._state == "applying_up":
            if serve_sup.num_workers >= self._pending.target:
                self.engine.action_applied(now)
                self._emit(serve_sup, self._pending, "applied")
                self._pending = None
                self._enter("idle", now)
        elif self._state == "applying_down":
            if serve_sup.num_workers <= self._pending.target:
                # capacity released: hand it back to training (never
                # past its baseline size or the budget)
                if not self.train_done and self.train_sup is not None:
                    reclaim = min(self._train_baseline,
                                  self.budget - serve_sup.num_workers)
                    if reclaim > self.train_sup.num_workers:
                        self.train_sup.request_scale(reclaim,
                                                     reason="reclaim")
                self.engine.action_applied(now)
                self._emit(serve_sup, self._pending, "applied")
                self._pending = None
                self._enter("idle", now)

    def _begin_up(self, serve_sup, d: ScaleDecision, now: float):
        serve_n = serve_sup.num_workers
        train_n = self._train_n()
        need = d.target - serve_n
        free = self.budget - serve_n - train_n
        if free >= need:
            # budget slack (training finished or was never this big):
            # grow directly, no donation needed
            self._emit(serve_sup, d, "requested")
            serve_sup.request_scale(d.target, reason="slo_burn")
            self._pending = d
            self._enter("applying_up", now)
            return
        donate_to = train_n - (need - free)
        if donate_to >= self.train_floor and self.train_sup is not None:
            self._emit(serve_sup, d, "donate")
            self.train_sup.request_scale(donate_to,
                                         reason="donate_to_serving")
            self._expect_train = donate_to
            self._pending = d
            self._enter("awaiting_donation", now)
            return
        # training is at its floor: the fleet is genuinely out of
        # capacity — record the blocked decision and cool down so the
        # loop re-examines instead of spamming
        self._emit(serve_sup, d, "blocked")
        self.engine.action_applied(now)

    def _begin_down(self, serve_sup, d: ScaleDecision, now: float):
        self._emit(serve_sup, d, "requested")
        serve_sup.request_scale(d.target, reason="burn_clear")
        self._pending = d
        self._enter("applying_down", now)


@dataclasses.dataclass
class FleetRunResult:
    """What one :meth:`SharedFleetSupervisor.run` produced."""

    serve_result: object = None
    train_result: object = None
    train_error: "BaseException | None" = None
    train_stopped: bool = False
    serve_scales: int = 0
    train_scales: int = 0
    final_serve_replicas: int = 0
    final_train_workers: int = 0


class SharedFleetSupervisor:
    """One fixed worker budget, two supervised jobs, one closed loop.

    ``telemetry_dir`` grows two self-contained run dirs —
    ``train/`` and ``serve/`` (each with its own supervisor event log,
    so generation numbering and the goodput ledger stay per-job) — and
    a root ``metrics-live.prom`` carrying both ledgers, the SLO burn
    and the ``fleet/capacity/*`` gauges. ``train_fn``/``serve_fn`` are
    ordinary supervisor worker fns (module-level, restartable); extra
    per-supervisor knobs pass through ``train_sup_kwargs`` /
    ``serve_sup_kwargs`` (the simulated fleet injects thread runners
    here — testing/fleet_sim.py).

    The serving job defines the run's span: when it completes,
    ``stop_training_when_served`` (default) winds the training job down
    via ``request_stop`` — on a real fleet the trainer would simply
    keep running; on this harness the demo must end."""

    def __init__(self, *, budget: int,
                 train_fn, serve_fn,
                 train_workers: int, serve_replicas: int,
                 train_args: tuple = (), train_kwargs: "dict | None" = None,
                 serve_args: tuple = (), serve_kwargs: "dict | None" = None,
                 policy: "AutoscalePolicy | None" = None,
                 telemetry_dir: "str | None" = None,
                 records_fn=None, clock=time.time,
                 stop_training_when_served: bool = True,
                 train_join_timeout_s: float = 120.0,
                 train_sup_kwargs: "dict | None" = None,
                 serve_sup_kwargs: "dict | None" = None):
        if train_workers + serve_replicas > budget:
            raise ValueError(
                f"initial split {train_workers}+{serve_replicas} "
                f"exceeds the budget {budget}")
        self.budget = budget
        self.policy = policy or AutoscalePolicy()
        self.telemetry_dir = telemetry_dir or tempfile.mkdtemp(
            prefix="dtx_fleet_")
        self.train_dir = os.path.join(self.telemetry_dir, "train")
        self.serve_dir = os.path.join(self.telemetry_dir, "serve")
        os.makedirs(self.train_dir, exist_ok=True)
        os.makedirs(self.serve_dir, exist_ok=True)
        self._stop_training_when_served = stop_training_when_served
        self._train_join_timeout_s = train_join_timeout_s
        self.train_sup = RecoverySupervisor(
            train_fn, num_workers=train_workers,
            args=train_args, kwargs=train_kwargs,
            telemetry_dir=self.train_dir,
            min_workers=self.policy.train_floor,
            max_workers=train_workers,
            **(train_sup_kwargs or {}))
        self.engine = Autoscaler(
            self.policy,
            records_fn=records_fn or serving_records_fn(self.serve_dir),
            clock=clock)
        self.arbiter = CapacityArbiter(
            self.engine, budget=budget, train_sup=self.train_sup,
            train_floor=self.policy.train_floor)
        self.serve_sup = RecoverySupervisor(
            serve_fn, num_workers=serve_replicas,
            args=serve_args, kwargs=serve_kwargs,
            telemetry_dir=self.serve_dir,
            min_workers=self.policy.min_replicas,
            max_workers=self.policy.max_replicas,
            autoscaler=self.arbiter,
            drain_on_scale=True,
            # scale-downs hand live KV to the successor generation
            # instead of replaying decode from the prompt — the
            # preempt_replay badput of a shrink drops to ~0
            # (serving/migrate.py; override via serve_sup_kwargs)
            **{"drain_scale_down_mode": "migrate",
               **(serve_sup_kwargs or {})})

    def _health_lines(self) -> "list[str]":
        """Root-exporter extra lines: both jobs' goodput ledgers (the
        scale_transition bucket included) plus the live burn."""
        from distributed_tensorflow_tpu.telemetry import goodput
        lines: "list[str]" = []
        for role, d in (("train", self.train_dir),
                        ("serve", self.serve_dir)):
            try:
                ledger = goodput.ledger_from_run(d)
                if ledger["wall_s"] > 0:
                    lines += goodput.prometheus_lines(
                        ledger, prefix=f"dtx_{role}_")
            except Exception:            # noqa: BLE001 — mid-run races
                pass
        ev = self.engine.last_eval
        if ev:
            for k in ("burn_long", "burn_short"):
                if ev.get(k) is not None:
                    lines.append(f"# TYPE dtx_fleet_slo_{k} gauge")
                    lines.append(f"dtx_fleet_slo_{k} {ev[k]:.6f}")
        return lines

    def run(self) -> FleetRunResult:
        from distributed_tensorflow_tpu.telemetry import exporter
        root_exp = None
        try:
            root_exp = exporter.MetricsExporter(
                dir=self.telemetry_dir, interval_s=1.0,
                extra_fn=self._health_lines, labels={"job": "fleet"})
        except OSError:
            pass
        out = FleetRunResult()
        train_box: dict = {}

        def _train():
            try:
                train_box["result"] = self.train_sup.run()
            except BaseException as e:   # noqa: BLE001
                train_box["error"] = e
            finally:
                self.arbiter.train_done = True

        t = threading.Thread(target=_train, daemon=True,
                             name="fleet-train")
        t.start()
        try:
            out.serve_result = self.serve_sup.run()
        finally:
            if t.is_alive() and self._stop_training_when_served:
                self.train_sup.request_stop()
                out.train_stopped = True
            t.join(self._train_join_timeout_s)
            if root_exp is not None:
                root_exp.stop()
        out.train_result = train_box.get("result")
        out.train_error = train_box.get("error")
        out.serve_scales = self.serve_sup.scales_applied
        out.train_scales = self.train_sup.scales_applied
        out.final_serve_replicas = self.serve_sup.num_workers
        out.final_train_workers = self.train_sup.num_workers
        if out.train_error is not None and not out.train_stopped:
            raise out.train_error
        return out
