"""SLO-gated canary rollout with auto-rollback.

The last leg of the live-rollout loop (README "Live rollout"): the
engine can hot-swap weights in place (serving/engine.py
``load_version``) and every completion record carries its
``model_version`` — this module decides *when* each replica moves.

:class:`RolloutController` is a pure policy state machine in the mold
of :class:`~distributed_tensorflow_tpu.resilience.autoscaler.
Autoscaler` (injectable clock, no side effects in :meth:`decide`,
ticked from the supervisor watch loop via the same ``autoscaler=``
hook). It ramps a static traffic split replica-by-replica:

- the FIRST replica moves to the target version immediately — that is
  the canary;
- every subsequent move is gated: the canary's per-version SLO burn
  (telemetry/slo.burn_windows over records filtered by
  ``model_version``) must stay clear for ``clear_hold_s`` with at
  least ``min_evidence`` completions in the short window — no
  evidence is no promotion (a canary serving nothing proves nothing);
- the canary firing while the BASELINE version is *not* firing, for
  ``fire_consecutive`` consecutive ticks, is the version's fault →
  **rollback**: every replica is reassigned to the base version
  (replicas pin-restore it — ``InferenceEngine.load_version(base)``
  via ``restore_latest(at_step=)``). Both versions burning together
  reads as an infrastructure problem, not the candidate's — the
  controller holds.

The actuation surface is deliberately dumb: an atomically-rewritten
JSON assignment file (replica name → snapshot step) that serving
replicas poll between steps, so the controller works unchanged across
process boundaries and survives replica restarts (a respawned replica
reads the file and adopts the current assignment — the restart-
adoption path tests/test_rollout.py covers). Decisions are
``rollout.decision`` events; the target version's availability is one
``rollout.publish`` event, which telemetry/slo.py's servable-freshness
accounting closes per replica at that replica's ``serve.swap`` —
freshness ends when the weights *serve*, not when the file lands.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

from distributed_tensorflow_tpu import telemetry
from distributed_tensorflow_tpu.telemetry import slo as tv_slo


def _default_slo() -> tv_slo.SLO:
    # short-run burn windows (8s/2s @ 2x), same scale as the
    # autoscaler's: canary verdicts in a tens-of-seconds harness run;
    # production passes its own SLO with the SRE presets
    return tv_slo.SLO("rollout_p99_latency", "latency", objective=0.99,
                      threshold_s=0.5, windows=((8.0, 2.0, 2.0),))


def version_step(version) -> "int | None":
    """Snapshot step out of a ``model_version`` string
    (``"<step>@<digest>"``); None for anything unparseable."""
    if not isinstance(version, str):
        return None
    head = version.split("@", 1)[0]
    try:
        return int(head)
    except ValueError:
        return None


def read_assignment(path: str) -> "dict | None":
    """The replica side: current assignment file, or None while the
    controller hasn't written one yet (serve the base version)."""
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


@dataclasses.dataclass(frozen=True)
class RolloutPolicy:
    """The canary's knobs (the README "Live rollout" table).

    ``slo`` supplies the burn thresholds applied PER VERSION;
    ``fire_consecutive`` debounces rollback, ``clear_hold_s`` +
    ``clear_burn`` gate each advance, ``min_evidence`` is the
    low-traffic rule (burn over fewer completions than this is
    neither an alarm nor an all-clear), ``cooldown_s`` paces actions
    so a fresh swap's warmup can't trip the next verdict."""

    fire_consecutive: int = 2
    clear_hold_s: float = 3.0
    clear_burn: float = 1.0
    cooldown_s: float = 2.0
    interval_s: float = 0.25
    min_evidence: int = 3
    slo: tv_slo.SLO = dataclasses.field(default_factory=_default_slo)


@dataclasses.dataclass(frozen=True)
class RolloutDecision:
    """One verdict (also the payload of ``rollout.decision``)."""

    action: str                  # "advance" | "promote" | "rollback"
    replica: "str | None"        # the replica moved (advance only)
    step: int                    # the step the action assigns
    reason: str
    wall: float
    canary_burn_short: "float | None" = None
    canary_burn_long: "float | None" = None
    base_burn_short: "float | None" = None
    evidence: int = 0

    def to_fields(self) -> dict:
        f = {"action": self.action, "replica": self.replica,
             "step": self.step, "reason": self.reason,
             "evidence": self.evidence}
        for k in ("canary_burn_short", "canary_burn_long",
                  "base_burn_short"):
            v = getattr(self, k)
            f[k] = round(v, 4) if v is not None else None
        return f


class RolloutController:
    """Replica-by-replica ramp from ``base_step`` to ``target_step``
    with SLO-gated advances and burn-triggered rollback (module
    docstring has the rules).

    Pure core: :meth:`decide` takes ``(now, records)`` and mutates
    only controller state — fully unit-testable with a fake clock and
    synthetic records. :meth:`tick` is the supervisor adapter
    (``RecoverySupervisor(autoscaler=ctrl)``): it pulls live records,
    runs one decision, rewrites the assignment file atomically and
    emits the events."""

    def __init__(self, replicas, *, base_step: int, target_step: int,
                 policy: "RolloutPolicy | None" = None,
                 records_fn=None, clock=time.time,
                 assignment_path: "str | None" = None,
                 published_wall: "float | None" = None):
        if not replicas:
            raise ValueError("rollout needs at least one replica")
        self.replicas = [str(r) for r in replicas]
        self.base_step = int(base_step)
        self.target_step = int(target_step)
        self.policy = policy or RolloutPolicy()
        self.published_wall = published_wall
        self._records_fn = records_fn
        self._clock = clock
        self.assignment_path = assignment_path
        #: replica -> snapshot step it should serve
        self.assignment = {r: self.base_step for r in self.replicas}
        #: "baseline" -> "ramping" -> "promoted" | "rolled_back"
        self.state = "baseline"
        self.moved: "list[str]" = []
        self.decisions: "list[RolloutDecision]" = []
        self.last_eval: "dict | None" = None
        self._last_decide: "float | None" = None
        self._fire_streak = 0
        self._clear_since: "float | None" = None
        self._cooldown_until: "float | None" = None
        self._published = False
        self._seq = 0

    # -- pure policy -------------------------------------------------------
    @property
    def done(self) -> bool:
        return self.state in ("promoted", "rolled_back")

    def _records_for(self, records, step: int) -> list:
        return [r for r in records
                if version_step(r.get("model_version")) == step]

    def _evidence(self, records, window, now: float) -> int:
        lo = now - window["short_s"]
        return sum(1 for r in records
                   if isinstance(r.get("wall"), (int, float))
                   and lo < r["wall"] <= now)

    def _acted(self, d: RolloutDecision, now: float):
        self.decisions.append(d)
        self._fire_streak = 0
        self._clear_since = None
        self._cooldown_until = now + self.policy.cooldown_s

    def decide(self, *, now: "float | None" = None,
               records: "list | None" = None) -> "RolloutDecision | None":
        """One policy tick; None when nothing should change."""
        p = self.policy
        now = now if now is not None else self._clock()
        if self.done:
            return None
        if (self._last_decide is not None
                and now - self._last_decide < p.interval_s):
            return None
        self._last_decide = now
        if records is None:
            records = self._records_fn() if self._records_fn else []
        if self.state == "baseline":
            # the canary itself moves ungated — there is no evidence
            # about a version nothing serves — but only once the fleet
            # IS serving: before the first completions land, a "canary"
            # would just be a replica adopting the target at startup,
            # proving nothing about a live swap
            if len(records) < self.policy.min_evidence:
                return None
            rep = self.replicas[0]
            self.assignment[rep] = self.target_step
            self.moved.append(rep)
            self.state = "ramping"
            d = RolloutDecision("advance", rep, self.target_step,
                                "canary_start", now)
            self._acted(d, now)
            return d
        canary = self._records_for(records, self.target_step)
        base = self._records_for(records, self.base_step)
        cw = tv_slo.burn_windows(canary, p.slo, now=now)
        bw = tv_slo.burn_windows(base, p.slo, now=now)
        ev = max((self._evidence(canary, w, now) for w in cw), default=0)
        canary_firing = any(
            w["firing"] and self._evidence(canary, w, now) >= p.min_evidence
            for w in cw)
        base_firing = any(
            w["firing"] and self._evidence(base, w, now) >= p.min_evidence
            for w in bw)
        cbl = cw[0]["burn_long"] if cw else None
        cbs = cw[0]["burn_short"] if cw else None
        bbs = bw[0]["burn_short"] if bw else None
        self.last_eval = {"wall": now, "canary_burn_long": cbl,
                          "canary_burn_short": cbs,
                          "base_burn_short": bbs, "evidence": ev,
                          "canary_firing": canary_firing,
                          "base_firing": base_firing}
        if canary_firing and not base_firing:
            # the candidate's fault: baseline traffic is healthy under
            # the same SLO at the same instant
            self._fire_streak += 1
            self._clear_since = None
        elif canary_firing:
            # both versions burning: infrastructure, not the version —
            # hold (neither rollback progress nor promotion credit)
            self._clear_since = None
        else:
            self._fire_streak = 0
            clear = ev >= p.min_evidence and all(
                (w["burn_short"] is None or w["burn_short"] < p.clear_burn)
                and (w["burn_long"] is None or w["burn_long"] < p.clear_burn)
                for w in cw)
            if clear:
                if self._clear_since is None:
                    self._clear_since = now
            else:
                self._clear_since = None
        if self._cooldown_until is not None and now < self._cooldown_until:
            return None
        if self._fire_streak >= p.fire_consecutive:
            self.assignment = {r: self.base_step for r in self.replicas}
            self.state = "rolled_back"
            d = RolloutDecision("rollback", None, self.base_step,
                                "slo_burn", now, cbs, cbl, bbs, ev)
            self._acted(d, now)
            return d
        if (self._clear_since is not None
                and now - self._clear_since >= p.clear_hold_s):
            remaining = [r for r in self.replicas if r not in self.moved]
            if remaining:
                rep = remaining[0]
                self.assignment[rep] = self.target_step
                self.moved.append(rep)
                d = RolloutDecision("advance", rep, self.target_step,
                                    "burn_clear", now, cbs, cbl, bbs, ev)
            else:
                # every replica already serves the target and the burn
                # held clear once more: the rollout is complete
                self.state = "promoted"
                d = RolloutDecision("promote", None, self.target_step,
                                    "burn_clear", now, cbs, cbl, bbs, ev)
            self._acted(d, now)
            return d
        return None

    # -- actuation ---------------------------------------------------------
    def write_assignment(self, path: "str | None" = None):
        """Atomically rewrite the assignment file replicas poll."""
        path = path or self.assignment_path
        if path is None:
            return
        self._seq += 1
        data = {"assignment": dict(self.assignment),
                "base_step": self.base_step,
                "target_step": self.target_step,
                "published_wall": self.published_wall,
                "state": self.state, "seq": self._seq}
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(data, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def tick(self, sup=None):
        """One supervisor watch tick (the ``autoscaler=`` hook): emit
        the one-time publish, run the policy, actuate + record any
        decision."""
        now = self._clock()
        if not self._published:
            self._published = True
            if self.published_wall is None:
                self.published_wall = now
            fresh = max(0.0, now - self.published_wall)
            fields = dict(step=self.target_step,
                          base_step=self.base_step,
                          freshness_s=round(fresh, 6))
            if sup is not None and hasattr(sup, "_event"):
                sup._event("rollout.publish", **fields)
            else:
                telemetry.event("rollout.publish", **fields)
            self.write_assignment()
        d = self.decide(now=now)
        if d is None:
            return
        self.write_assignment()
        fields = dict(state=self.state,
                      moved=len(self.moved), total=len(self.replicas),
                      **d.to_fields())
        if sup is not None and hasattr(sup, "_event"):
            sup._event("rollout.decision", **fields)
        else:
            telemetry.event("rollout.decision", **fields)
