"""Heartbeat transport for the recovery supervisor, pluggable + sharded.

The supervisor's failure detector needs one thing per watch tick: the
freshest ``(observed_at, step, worker_wall)`` triple for every worker.
Historically that was hard-wired to per-task heartbeat FILES under the
supervisor scratch dir (cluster/elastic.heartbeat) — three separate
O(N) file scans per poll tick (stall check, chaos kills, clock-sync
telemetry). This module makes the transport a :class:`HeartbeatSource`
the supervisor reads ONCE per tick:

- :class:`FileHeartbeatSource` — the existing file protocol, unchanged
  on disk (workers keep writing ``heartbeat-<task>`` files); the
  supervisor just stops re-scanning it three times.
- :class:`ShardedKVHeartbeats` — the fleet-scale transport over the
  coordination KV (≙ the reference WorkerService's grpc heartbeat
  fan-in, SURVEY §L5c/d): workers write per-worker keys
  ``fleet/hb/<shard>/<pid>``, the lowest LIVE pid of each shard folds
  its shard's keys into one summary key ``fleet/hbsum/<shard>`` as part
  of its own step loop, and the supervisor polls only the N/S summary
  keys. Steady-state supervisor cost drops from O(N) reads per tick to
  O(N/S); detection latency for an individual death is unchanged (the
  summary carries every member's own wall clock). A dead REDUCER makes
  its whole shard's summary go stale — the reader then falls back to
  enumerated per-member reads *for that shard only* (O(S)), so reducer
  death degrades one shard's read cost, never detection correctness.

Legacy-jaxlib discipline (cluster/coordination.py): heartbeat values
are strings, point reads only (``try_get`` per key — never a directory
read), keys overwritten in place. Generation-namespacing comes free
from the agent: a dead generation's heartbeats are invisible to the
new one, and the lifecycle GC (cluster/kv_gc.py) sweeps them.
"""

from __future__ import annotations

import json
import os
import time

from distributed_tensorflow_tpu.cluster import elastic

#: Default key namespace. The data service
#: (input/data_service.py) rides the same transport under its own
#: prefix (``data/<job>``) so input-worker liveness and trainer-fleet
#: liveness never share keys.
_DEFAULT_PREFIX = "fleet"


def hb_key(shard: int, pid: int, *, prefix: str = _DEFAULT_PREFIX) -> str:
    """Per-worker heartbeat key (written by the worker every step)."""
    return f"{prefix}/hb/{shard}/{pid}"


def sum_key(shard: int, *, prefix: str = _DEFAULT_PREFIX) -> str:
    """Per-shard summary key (written by the shard's reducer)."""
    return f"{prefix}/hbsum/{shard}"


def shard_of(pid: int, shard_size: int) -> int:
    return pid // shard_size


def shard_members(shard: int, shard_size: int,
                  num_workers: int) -> range:
    lo = shard * shard_size
    return range(lo, min(lo + shard_size, num_workers))


def num_shards(num_workers: int, shard_size: int) -> int:
    return -(-num_workers // shard_size)


class ShardedHeartbeatPublisher:
    """Worker-side: write this worker's heartbeat key; when this worker
    anchors its shard (lowest member pid), also fold the shard into the
    summary key. One or ``1 + shard_size`` KV ops per beat."""

    def __init__(self, agent, *, pid: int | None = None,
                 num_workers: int | None = None, shard_size: int = 32,
                 summarize_every: int = 1,
                 key_prefix: str = _DEFAULT_PREFIX):
        self.agent = agent
        self.pid = pid if pid is not None else agent.process_id
        self.num_workers = (num_workers if num_workers is not None
                            else agent.num_processes)
        self.shard_size = shard_size
        self.key_prefix = key_prefix
        self.shard = shard_of(self.pid, shard_size)
        self.is_reducer = (self.pid ==
                           shard_members(self.shard, shard_size,
                                         self.num_workers)[0])
        self.summarize_every = max(1, summarize_every)
        self._beats = 0

    def beat(self, step: int):
        """Publish liveness (and maybe the shard summary) for one step."""
        self.agent.key_value_set(
            hb_key(self.shard, self.pid, prefix=self.key_prefix),
            f"{int(step)} {time.time():.6f}")
        self._beats += 1
        if self.is_reducer and self._beats % self.summarize_every == 0:
            self.summarize()

    def summarize(self):
        """Fold this shard's member keys into the summary key."""
        members = {}
        for m in shard_members(self.shard, self.shard_size,
                               self.num_workers):
            raw = self.agent.key_value_try_get(
                hb_key(self.shard, m, prefix=self.key_prefix))
            if raw is None:
                continue
            parsed = _parse_hb(raw)
            if parsed is not None:
                members[str(m)] = parsed
        if members:
            self.agent.key_value_set(
                sum_key(self.shard, prefix=self.key_prefix),
                json.dumps(members))


def _parse_hb(raw: bytes) -> "list | None":
    """``b\"<step> <wall>\"`` -> [step, wall] (None when torn)."""
    try:
        parts = raw.decode().split()
        return [int(parts[0]), float(parts[1])]
    except (ValueError, IndexError, UnicodeDecodeError):
        return None


class FileHeartbeatSource:
    """The historical per-task heartbeat files (cluster/elastic.py) as a
    batched source: one scan per supervisor tick."""

    def __init__(self, supervisor_dir: str):
        self.dir = supervisor_dir
        self.generation = 0               # files are generation-agnostic

    def clear(self, num_workers: int):
        for i in range(num_workers):
            try:
                os.unlink(elastic.heartbeat_path(self.dir, i))
            except OSError:
                pass

    def read(self, worker: int) \
            -> "tuple[float, int | None, float | None] | None":
        path = elastic.heartbeat_path(self.dir, worker)
        try:
            mtime = os.path.getmtime(path)
            with open(path) as f:
                parts = f.read().split()
            step = int(parts[0]) if parts and parts[0].isdigit() else None
            wall = (float(parts[-1])
                    if parts and "." in parts[-1] else None)
            return mtime, step, wall
        except (OSError, ValueError):
            return None

    def read_all(self, num_workers: int) \
            -> "dict[int, tuple[float, int | None, float | None]]":
        out = {}
        for i in range(num_workers):
            hb = self.read(i)
            if hb is not None:
                out[i] = hb
        return out


class ShardedKVHeartbeats:
    """Supervisor-side sharded reader (and the matching worker factory).

    ``read_all`` polls the per-shard summary keys; a shard whose
    summary is missing or wholly stale (older than
    ``summary_stale_s`` — reducer death) falls back to enumerated
    per-member reads for that shard. The returned triples use each
    worker's self-reported wall clock as the observation time (the
    KV has no mtimes; in the in-process harness worker and supervisor
    share a clock, and on a real fleet the trace assembler's clock
    alignment applies — telemetry/trace.py).
    """

    def __init__(self, agent, *, shard_size: int = 32,
                 summary_stale_s: float = 2.0,
                 key_prefix: str = _DEFAULT_PREFIX):
        self.agent = agent
        self.shard_size = shard_size
        self.summary_stale_s = summary_stale_s
        self.key_prefix = key_prefix
        self.generation = 0
        #: ops accounting for the cost curves: summary reads vs
        #: fallback member reads per read_all
        self.reads_summary = 0
        self.reads_fallback = 0

    def publisher(self, pid: int, num_workers: int,
                  summarize_every: int = 1) -> ShardedHeartbeatPublisher:
        return ShardedHeartbeatPublisher(
            self.agent, pid=pid, num_workers=num_workers,
            shard_size=self.shard_size, summarize_every=summarize_every,
            key_prefix=self.key_prefix)

    def clear(self, num_workers: int):
        # Nothing to unlink: a reform bumps the generation, and the new
        # namespace starts empty; the dead generation's keys are the
        # lifecycle GC's job (cluster/kv_gc.py).
        pass

    def _read_shard_fallback(self, shard: int, num_workers: int,
                             out: dict):
        for m in shard_members(shard, self.shard_size, num_workers):
            raw = self.agent.key_value_try_get(
                hb_key(shard, m, prefix=self.key_prefix))
            self.reads_fallback += 1
            if raw is None:
                continue
            parsed = _parse_hb(raw)
            if parsed is not None:
                out[m] = (parsed[1], parsed[0], parsed[1])

    def read_all(self, num_workers: int) \
            -> "dict[int, tuple[float, int | None, float | None]]":
        out: dict = {}
        now = time.time()
        with elastic.generation_override(self.generation):
            for shard in range(num_shards(num_workers, self.shard_size)):
                raw = self.agent.key_value_try_get(
                    sum_key(shard, prefix=self.key_prefix))
                self.reads_summary += 1
                summary = None
                if raw is not None:
                    try:
                        summary = json.loads(raw.decode())
                    except (ValueError, UnicodeDecodeError):
                        summary = None
                if summary:
                    freshest = max(v[1] for v in summary.values())
                    if now - freshest <= self.summary_stale_s:
                        for m, (step, wall) in summary.items():
                            out[int(m)] = (wall, step, wall)
                        continue
                # missing/torn/stale summary (dead or lagging reducer):
                # enumerate THIS shard's members directly
                self._read_shard_fallback(shard, num_workers, out)
        return out
