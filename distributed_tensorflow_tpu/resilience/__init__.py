"""Resilience: deterministic fault injection + unified retry/health policies.

Three parts (see each module's docstring):

- :mod:`.faults` — seed-driven chaos layer; named injection sites in the
  coordination, dispatch, and checkpoint stacks raise/delay/corrupt on a
  reproducible schedule (zero overhead when no schedule is installed);
- :mod:`.retry` — the single :class:`RetryPolicy` (exponential backoff,
  jitter, deadline, retryable classification) behind every retry loop;
- :mod:`.health` — per-worker failure tracking and quarantine feeding
  the coordinator's closure re-scheduling.
"""

from distributed_tensorflow_tpu.resilience import faults
from distributed_tensorflow_tpu.resilience.faults import (
    FaultDecision,
    FaultInjected,
    FaultRegistry,
    FaultRule,
    FaultSchedule,
)
from distributed_tensorflow_tpu.resilience.retry import Backoff, RetryPolicy
from distributed_tensorflow_tpu.resilience.health import WorkerHealthTracker
