"""Resilience: fault injection, retry/health policies, recovery.

Four parts (see each module's docstring):

- :mod:`.faults` — seed-driven chaos layer; named injection sites in the
  coordination, dispatch, and checkpoint stacks raise/delay/corrupt on a
  reproducible schedule (zero overhead when no schedule is installed);
- :mod:`.retry` — the single :class:`RetryPolicy` (exponential backoff,
  jitter, deadline, retryable classification) behind every retry loop;
- :mod:`.health` — per-worker failure tracking and quarantine feeding
  the coordinator's closure re-scheduling;
- :mod:`.heartbeats` — the supervisor's pluggable liveness transport:
  per-task files (default) or fleet-scale sharded KV summaries
  (supervisor polls O(N/shard) keys per tick);
- :mod:`.supervisor` — the recovery supervisor closing the loop: it
  restarts dead workers, reforms the cluster under a fresh generation
  (cluster/elastic.py), and resumes from the last intact checkpoint;
- :mod:`.autoscaler` — the resource-management loop on top: SLO-burn
  policy engine, fixed-budget training↔serving capacity arbitration,
  and the shared-fleet supervisor composing two supervised jobs.
"""

from distributed_tensorflow_tpu.resilience import faults, heartbeats
from distributed_tensorflow_tpu.resilience.heartbeats import (
    FileHeartbeatSource,
    ShardedHeartbeatPublisher,
    ShardedKVHeartbeats,
)
from distributed_tensorflow_tpu.resilience.faults import (
    FaultDecision,
    FaultInjected,
    FaultRegistry,
    FaultRule,
    FaultSchedule,
)
from distributed_tensorflow_tpu.resilience.retry import Backoff, RetryPolicy
from distributed_tensorflow_tpu.resilience.health import WorkerHealthTracker
from distributed_tensorflow_tpu.resilience.supervisor import (
    KillSpec,
    RecoveryFailedError,
    RecoverySupervisor,
    WorkerFailure,
    seeded_kill_plan,
    seeded_shrink_plan,
)
from distributed_tensorflow_tpu.resilience.autoscaler import (
    Autoscaler,
    AutoscalePolicy,
    CapacityArbiter,
    ScaleDecision,
    SharedFleetSupervisor,
)
from distributed_tensorflow_tpu.resilience.rollout import (
    RolloutController,
    RolloutDecision,
    RolloutPolicy,
)
