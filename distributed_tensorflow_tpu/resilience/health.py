"""Per-worker health tracking with quarantine.

Feeds the coordinator's dispatch loop (coordinator/cluster_coordinator.py):
a lane whose worker keeps failing closures is quarantined — it stops
pulling work for ``quarantine_s`` so closures drain through healthy
lanes instead of ping-ponging off the same dying worker (≙ the
reference's wait_on_failure backoff keeping a failing worker out of
rotation, cluster_coordinator.py:879 — generalized to a policy).

Liveness guard: the tracker refuses to quarantine the LAST healthy
worker — with everyone else down, a flaky lane still beats no lane, and
the queue can never deadlock with work pending and all lanes benched.
"""

from __future__ import annotations

import dataclasses
import threading
import time

from distributed_tensorflow_tpu.telemetry import registry as _telemetry


@dataclasses.dataclass
class _WorkerHealth:
    consecutive_failures: int = 0
    total_failures: int = 0
    total_successes: int = 0
    quarantined_until: float | None = None
    quarantine_count: int = 0


class WorkerHealthTracker:
    """Failure bookkeeping for a set of workers.

    ``record_failure``/``record_success`` from dispatch; ``is_quarantined``
    gates pulling work. ``failure_threshold`` consecutive failures =>
    quarantined for ``quarantine_s`` (a success clears everything).
    """

    def __init__(self, failure_threshold: int = 3,
                 quarantine_s: float = 5.0,
                 time_fn=time.monotonic):
        self.failure_threshold = failure_threshold
        self.quarantine_s = quarantine_s
        self._now = time_fn
        self._lock = threading.Lock()
        self._workers: dict[int, _WorkerHealth] = {}
        # registry export: counters are process-cumulative (shared across
        # tracker instances); the per-worker detail rides a snapshot
        # collector (latest tracker wins — one live tracker per process)
        reg = _telemetry.get_registry()
        self._failures_total = reg.counter(
            "resilience/worker_failures_total")
        self._quarantines_total = reg.counter(
            "resilience/quarantines_total")
        reg.register_collector("resilience/health", self._collect)

    def _collect(self) -> dict:
        snap = self.snapshot()
        return {"healthy_workers": len(self.healthy_workers()),
                "quarantined_workers": sum(
                    1 for h in snap.values() if h["quarantined"])}

    def register(self, worker_id: int):
        with self._lock:
            self._workers.setdefault(worker_id, _WorkerHealth())

    def _healthy_ids_locked(self) -> list[int]:
        now = self._now()
        return [w for w, h in self._workers.items()
                if h.quarantined_until is None or h.quarantined_until <= now]

    def record_failure(self, worker_id: int) -> bool:
        """Returns True if this failure newly quarantined the worker."""
        self._failures_total.increment()
        with self._lock:
            h = self._workers.setdefault(worker_id, _WorkerHealth())
            h.consecutive_failures += 1
            h.total_failures += 1
            if h.consecutive_failures < self.failure_threshold:
                return False
            healthy = self._healthy_ids_locked()
            if healthy == [worker_id]:
                return False          # never bench the last healthy lane
            h.quarantined_until = self._now() + self.quarantine_s
            h.quarantine_count += 1
            h.consecutive_failures = 0
        self._quarantines_total.increment()
        return True

    def record_success(self, worker_id: int):
        with self._lock:
            h = self._workers.setdefault(worker_id, _WorkerHealth())
            h.consecutive_failures = 0
            h.total_successes += 1
            h.quarantined_until = None

    def worker_restarted(self, worker_id: int):
        """Supervisor-confirmed restart (a new cluster generation): the
        process behind this lane is fresh, so the quarantine and the
        consecutive-failure streak no longer describe it — clear both.
        Lifetime totals (``total_failures``, ``quarantine_count``) are
        kept: they describe the lane's history, not its current
        incarnation."""
        with self._lock:
            h = self._workers.setdefault(worker_id, _WorkerHealth())
            h.consecutive_failures = 0
            h.quarantined_until = None

    def is_quarantined(self, worker_id: int) -> bool:
        with self._lock:
            h = self._workers.get(worker_id)
            if h is None or h.quarantined_until is None:
                return False
            if h.quarantined_until <= self._now():
                h.quarantined_until = None     # quarantine expired
                return False
            return True

    def is_healthy(self, worker_id: int) -> bool:
        return not self.is_quarantined(worker_id)

    def healthy_workers(self) -> list[int]:
        with self._lock:
            return sorted(self._healthy_ids_locked())

    def snapshot(self) -> dict[int, dict]:
        """Introspection / metrics export."""
        with self._lock:
            now = self._now()
            return {
                w: {"consecutive_failures": h.consecutive_failures,
                    "total_failures": h.total_failures,
                    "total_successes": h.total_successes,
                    "quarantine_count": h.quarantine_count,
                    "quarantined": (h.quarantined_until is not None
                                    and h.quarantined_until > now)}
                for w, h in self._workers.items()}
