"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has NO sequence-parallel support (SURVEY.md §5.7: no ring
attention, Ulysses, or blockwise attention anywhere in its tree — only the
raw ``collective_permute``/``all_to_all`` ops, reference
tensorflow/python/tpu/ops/tpu_ops.py:111/:43). Long-context training is a
capability gap the TPU-native framework fills as a first-class feature:

- **Ring attention** (`ring_attention`): each device holds a sequence
  chunk of Q/K/V; K/V blocks rotate around the "sp" ring via
  ``jax.lax.ppermute`` over ICI while each device accumulates flash-style
  online softmax over the blocks it sees. Memory stays O(S/n) per device;
  comm overlaps compute under XLA latency hiding. Causal masking is
  applied per block pair; compute is NOT skipped for future blocks (the
  ring synchronizes every step, so wall-clock is set by the last rank
  regardless — a load-balanced "striped" schedule is future work).

- **Ulysses** (`ulysses_attention`): all-to-all re-shard — heads gather
  the full sequence, attention runs locally per head subset, then
  re-shard back. Better when n_heads >= ring size and ICI all-to-all
  bandwidth beats ring latency.

Both are pure shard_map-region functions: call them inside
``shard_map``/``pjit`` with the sequence axis sharded over "sp".
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from distributed_tensorflow_tpu.ops.attention import (
    DEFAULT_MASK_VALUE, flash_attention, mha_reference)


def _local_attn_stats(q, k, v, *, sm_scale, mask=None):
    """Local attention block returning (out_unnormalized, m, l) for
    online-softmax combination across ring steps.

    q: (b, h, sq, d); k/v: (b, h, sk, d). mask: broadcastable (sq, sk).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if mask is not None:
        s = jnp.where(mask, s, DEFAULT_MASK_VALUE)
    m = jnp.max(s, axis=-1, keepdims=True)          # (b,h,sq,1)
    # Guard fully-masked rows (exp would overflow at MASK - MASK).
    m_safe = jnp.maximum(m, -1e30)
    p = jnp.exp(s - m_safe)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)          # (b,h,sq,1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return o, m_safe, l


def ring_attention(q, k, v, *, axis_name: str = "sp", causal: bool = False,
                   sm_scale: float | None = None):
    """Ring attention over the ``axis_name`` mesh axis (shard_map region).

    Inputs are the LOCAL sequence chunks (b, h, s_local, d); output is the
    local chunk of the attention result, numerically identical to full
    attention over the gathered sequence.

    ≙ capability gap in the reference (SURVEY.md §5.7); comm primitive ≙
    collective_permute (tpu_ops.py:111) lowered to XLA CollectivePermute
    over ICI.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[2]

    perm = [(i, (i + 1) % n) for i in range(n)]     # ring: i -> i+1

    def mask_for(src_idx):
        """Causal mask between my q chunk and the k chunk from src_idx."""
        if not causal:
            return None
        q_ids = my_idx * s_local + jax.lax.broadcasted_iota(
            jnp.int32, (s_local, s_local), 0)
        k_ids = src_idx * s_local + jax.lax.broadcasted_iota(
            jnp.int32, (s_local, s_local), 1)
        return q_ids >= k_ids

    # Online-softmax accumulators.
    o_acc = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32)
    m_acc = jnp.full(q.shape[:3] + (1,), -jnp.inf, jnp.float32)
    l_acc = jnp.zeros(q.shape[:3] + (1,), jnp.float32)

    k_cur, v_cur = k, v
    for step in range(n):
        src_idx = (my_idx - step) % n                # owner of current k/v
        # Future blocks (src_idx > my_idx under causal) are excluded by
        # mask_for: the all-False mask yields o_b=0, l_b=0 and a very
        # negative m_b, which contribute exactly zero through the
        # alpha/beta combine below.
        o_b, m_b, l_b = _local_attn_stats(q, k_cur, v_cur,
                                          sm_scale=sm_scale,
                                          mask=mask_for(src_idx))

        m_new = jnp.maximum(m_acc, m_b)
        # exp(-inf - -inf) guard: where both -inf, keep 0 contribution.
        alpha = jnp.where(jnp.isinf(m_acc) & (m_acc < 0), 0.0,
                          jnp.exp(m_acc - jnp.where(jnp.isinf(m_new),
                                                    0.0, m_new)))
        beta = jnp.where(jnp.isinf(m_b) & (m_b < 0), 0.0,
                         jnp.exp(m_b - jnp.where(jnp.isinf(m_new),
                                                 0.0, m_new)))
        o_acc = o_acc * alpha + o_b * beta
        l_acc = l_acc * alpha + l_b * beta
        m_acc = m_new

        if step != n - 1:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)

    l_safe = jnp.where(l_acc == 0.0, 1.0, l_acc)
    return (o_acc / l_safe).astype(q.dtype)


def ulysses_attention(q, k, v, *, axis_name: str = "sp",
                      causal: bool = False,
                      sm_scale: float | None = None,
                      attn_fn: Callable | None = None):
    """Ulysses-style SP: all-to-all from sequence-sharded to head-sharded,
    run full-sequence attention on the local head subset, all-to-all back.

    Inputs (b, h, s_local, d) sequence-sharded; requires h % axis_size == 0.
    ≙ all_to_all op surface (reference tpu_ops.py:43) used for an SP scheme
    the reference never implemented.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    n = jax.lax.psum(1, axis_name)
    h = q.shape[1]
    assert h % n == 0, f"heads {h} not divisible by sp={n}"

    def to_heads(x):
        # (b, h, s/n, d) -> n chunks of heads, gather seq:
        # all_to_all splits axis 1 (heads) and concats axis 2 (seq).
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    def to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)   # (b, h/n, S, d)
    if attn_fn is None:
        out = mha_reference(qh, kh, vh, causal=causal, sm_scale=sm_scale)
    else:
        out = attn_fn(qh, kh, vh, causal=causal, sm_scale=sm_scale)
    return to_seq(out)


def make_ring_attention(mesh: Mesh, *, axis_name: str = "sp",
                        causal: bool = False, impl: str = "ring",
                        spec: P | None = None):
    """Wrap ring/ulysses attention in shard_map for (b, h, S, d) global
    arrays whose sequence axis is sharded over ``axis_name``.

    ``spec`` describes the full (b, h, S, d) sharding — pass the model's
    batch/head shardings too when calling inside a dp×tp×sp jit, so
    shard_map only ring-communicates over ``axis_name``.
    """
    fn = ring_attention if impl == "ring" else ulysses_attention

    if spec is None:
        spec = P(None, None, axis_name, None)

    @functools.partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_rep=False)
    def sharded(q, k, v):
        return fn(q, k, v, axis_name=axis_name, causal=causal)

    return sharded
