"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has NO sequence-parallel support (SURVEY.md §5.7: no ring
attention, Ulysses, or blockwise attention anywhere in its tree — only the
raw ``collective_permute``/``all_to_all`` ops, reference
tensorflow/python/tpu/ops/tpu_ops.py:111/:43). Long-context training is a
capability gap the TPU-native framework fills as a first-class feature:

- **Ring attention**: each device holds a sequence chunk of Q/K/V; K/V
  blocks rotate around the "sp" ring via ``jax.lax.ppermute`` over ICI
  while each device accumulates online softmax over the blocks it sees.
  Memory stays O(S/n) per device. Two per-step compute paths:
  `ring_flash_attention` (the TPU default) runs the Pallas flash kernel
  per block and ``lax.cond``-skips fully-masked causal future blocks
  outright; `ring_attention` is the unfused reference-math form kept for
  CPU CI and numerics cross-checks. For causal workloads,
  `striped_flash_attention` (impl="striped") distributes tokens
  round-robin so every step is triangular on every rank — the
  load-balanced schedule whose critical path is ~n/2 block-equivalents
  instead of the contiguous layout's n on the last rank.

- **Ulysses** (`ulysses_attention`): all-to-all re-shard — heads gather
  the full sequence, attention runs locally per head subset, then
  re-shard back. Better when n_heads >= ring size and ICI all-to-all
  bandwidth beats ring latency.

Both are pure shard_map-region functions: call them inside
``shard_map``/``pjit`` with the sequence axis sharded over "sp".
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from distributed_tensorflow_tpu.ops.attention import (
    DEFAULT_MASK_VALUE, flash_attention, mha_reference)
from distributed_tensorflow_tpu.ops import attention as _attn


def _local_attn_stats(q, k, v, *, sm_scale, mask=None):
    """Local attention block returning (out_unnormalized, m, l) for
    online-softmax combination across ring steps.

    q: (b, h, sq, d); k/v: (b, h, sk, d). mask: broadcastable (sq, sk).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if mask is not None:
        s = jnp.where(mask, s, DEFAULT_MASK_VALUE)
    m = jnp.max(s, axis=-1, keepdims=True)          # (b,h,sq,1)
    # Guard fully-masked rows (exp would overflow at MASK - MASK).
    m_safe = jnp.maximum(m, -1e30)
    p = jnp.exp(s - m_safe)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)          # (b,h,sq,1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return o, m_safe, l


def ring_attention(q, k, v, *, axis_name: str = "sp", causal: bool = False,
                   sm_scale: float | None = None):
    """Ring attention over the ``axis_name`` mesh axis (shard_map region).

    Inputs are the LOCAL sequence chunks (b, h, s_local, d); output is the
    local chunk of the attention result, numerically identical to full
    attention over the gathered sequence.

    ≙ capability gap in the reference (SURVEY.md §5.7); comm primitive ≙
    collective_permute (tpu_ops.py:111) lowered to XLA CollectivePermute
    over ICI.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[2]

    perm = [(i, (i + 1) % n) for i in range(n)]     # ring: i -> i+1

    def mask_for(src_idx):
        """Causal mask between my q chunk and the k chunk from src_idx."""
        if not causal:
            return None
        q_ids = my_idx * s_local + jax.lax.broadcasted_iota(
            jnp.int32, (s_local, s_local), 0)
        k_ids = src_idx * s_local + jax.lax.broadcasted_iota(
            jnp.int32, (s_local, s_local), 1)
        return q_ids >= k_ids

    # Online-softmax accumulators.
    o_acc = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32)
    m_acc = jnp.full(q.shape[:3] + (1,), -jnp.inf, jnp.float32)
    l_acc = jnp.zeros(q.shape[:3] + (1,), jnp.float32)

    k_cur, v_cur = k, v
    for step in range(n):
        src_idx = (my_idx - step) % n                # owner of current k/v
        # Future blocks (src_idx > my_idx under causal) are excluded by
        # mask_for: the all-False mask yields o_b=0, l_b=0 and a very
        # negative m_b, which contribute exactly zero through the
        # alpha/beta combine below.
        o_b, m_b, l_b = _local_attn_stats(q, k_cur, v_cur,
                                          sm_scale=sm_scale,
                                          mask=mask_for(src_idx))

        m_new = jnp.maximum(m_acc, m_b)
        # exp(-inf - -inf) guard: where both -inf, keep 0 contribution.
        alpha = jnp.where(jnp.isinf(m_acc) & (m_acc < 0), 0.0,
                          jnp.exp(m_acc - jnp.where(jnp.isinf(m_new),
                                                    0.0, m_new)))
        beta = jnp.where(jnp.isinf(m_b) & (m_b < 0), 0.0,
                         jnp.exp(m_b - jnp.where(jnp.isinf(m_new),
                                                 0.0, m_new)))
        o_acc = o_acc * alpha + o_b * beta
        l_acc = l_acc * alpha + l_b * beta
        m_acc = m_new

        if step != n - 1:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)

    l_safe = jnp.where(l_acc == 0.0, 1.0, l_acc)
    return (o_acc / l_safe).astype(q.dtype)


# ---------------------------------------------------------------------------
# Flash ring attention: the Pallas kernel as the per-step compute, with
# causal work-skipping (fully-masked future blocks are lax.cond-skipped).
# ---------------------------------------------------------------------------

def _combine_stats(o_acc, lse_acc, o_b, lse_b):
    """Merge one block's (normalized out, lse) into the accumulators —
    the cross-block online-softmax recombination: given per-block
    normalized outputs, o = Σ o_b·exp(lse_b − lse_tot).

    The flash kernel stores lse = +inf as its EMPTY-row sentinel (a q row
    whose every k was masked, e.g. strict steps at tiny local seq); an
    empty row contributes nothing, which is exactly lse = -inf here."""
    lse_acc = jnp.where(jnp.isposinf(lse_acc), -jnp.inf, lse_acc)
    lse_b = jnp.where(jnp.isposinf(lse_b), -jnp.inf, lse_b)
    lse_new = jnp.logaddexp(lse_acc, lse_b)
    alpha = jnp.where(jnp.isneginf(lse_acc), 0.0,
                      jnp.exp(lse_acc - jnp.where(jnp.isneginf(lse_new),
                                                  0.0, lse_new)))
    beta = jnp.where(jnp.isneginf(lse_b), 0.0,
                     jnp.exp(lse_b - jnp.where(jnp.isneginf(lse_new),
                                               0.0, lse_new)))
    o_new = o_acc * alpha[..., None] + o_b.astype(jnp.float32) \
        * beta[..., None]
    return o_new, lse_new


# Shared ring machinery: the contiguous and striped schedules differ
# ONLY in their per-step block functions; the rotation loops, the
# online-softmax accumulation, and the rotating dk/dv gradient
# accumulators (which land each chunk's gradient home after a full
# circuit) are identical and live here once.

def _ring_fwd_loop(q, k, v, axis_name, step_block):
    """step_block(step, src, me, (k, v)) -> (o_block, lse_block)."""
    n = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    k_cur, v_cur = k, v
    o_acc = lse_acc = None
    for step in range(n):
        src = (me - step) % n
        o_b, lse_b = step_block(step, src, me, (k_cur, v_cur))
        if step == 0:
            o_acc = o_b.astype(jnp.float32)
            lse_acc = jnp.where(jnp.isposinf(lse_b), -jnp.inf, lse_b)
        else:
            o_acc, lse_acc = _combine_stats(o_acc, lse_acc, o_b, lse_b)
        if step != n - 1:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
    return o_acc.astype(q.dtype), lse_acc


def _ring_bwd_loop(q, k, v, axis_name, step_block_bwd):
    """step_block_bwd(step, src, me, (k, v)) -> (dq, dk, dv) per block;
    dk/dv accumulators rotate alongside their chunks, plus one final hop
    home (the chunk visiting device d at the last step belongs to
    d+1)."""
    n = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    k_cur, v_cur = k, v
    dq = jnp.zeros(q.shape, jnp.float32)
    dk_acc = jnp.zeros(k.shape, jnp.float32)
    dv_acc = jnp.zeros(v.shape, jnp.float32)
    for step in range(n):
        src = (me - step) % n
        dqb, dkb, dvb = step_block_bwd(step, src, me, (k_cur, v_cur))
        dq = dq + dqb.astype(jnp.float32)
        dk_acc = dk_acc + dkb.astype(jnp.float32)
        dv_acc = dv_acc + dvb.astype(jnp.float32)
        if step != n - 1:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
            dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
            dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
    dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
    dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
    return (dq.astype(q.dtype), dk_acc.astype(k.dtype),
            dv_acc.astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_flash(q, k, v, axis_name, causal, sm_scale, block_q, block_k,
                interpret):
    out, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal, sm_scale,
                                  block_q, block_k, interpret)
    return out


def _ring_step_fwd(q, sm_scale, causal, block_q, block_k, interpret):
    """Contiguous-schedule per-step forward: diagonal causal at step 0,
    full blocks for past chunks, skipped kernels for future chunks."""
    b, h, s, d = q.shape

    def block(kv, block_causal):
        return _attn._flash_forward(q, kv[0], kv[1], sm_scale,
                                    block_causal, block_q, block_k,
                                    interpret)

    def skip(kv):
        return (jnp.zeros((b, h, s, d), q.dtype),
                jnp.full((b, h, s), -jnp.inf, jnp.float32))

    def step_block(step, src, me, kv):
        if step == 0:
            return block(kv, causal)     # my own chunk: causal diagonal
        if not causal:
            return block(kv, False)
        # past chunks (src < me) are FULL blocks; future chunks are
        # fully masked — skip the kernel entirely
        return jax.lax.cond(src < me, lambda o: block(o, False), skip, kv)

    return step_block


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, sm_scale, block_q,
                         block_k, interpret):
    return _ring_fwd_loop(
        q, k, v, axis_name,
        _ring_step_fwd(q, sm_scale, causal, block_q, block_k, interpret))


def _ring_flash_fwd(q, k, v, axis_name, causal, sm_scale, block_q, block_k,
                    interpret):
    out, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal, sm_scale,
                                    block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, causal, sm_scale, block_q, block_k,
                    interpret, res, g):
    """Ring backward: per-block flash backward against the GLOBAL lse
    (p = exp(s − lse_global) is exact)."""
    q, k, v, out, lse = res

    def block_bwd(ops, block_causal):
        return _attn._flash_backward(
            (q, ops[0], ops[1], out, lse), g, sm_scale=sm_scale,
            causal=block_causal, block_q=block_q, block_k=block_k,
            interpret=interpret)

    def skip(ops):
        return (jnp.zeros_like(q), jnp.zeros_like(k), jnp.zeros_like(v))

    def step_block(step, src, me, kv):
        if step == 0:
            return block_bwd(kv, causal)
        if not causal:
            return block_bwd(kv, False)
        return jax.lax.cond(src < me, lambda o: block_bwd(o, False), skip,
                            kv)

    return _ring_bwd_loop(q, k, v, axis_name, step_block)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_flash_attention(q, k, v, *, axis_name: str = "sp",
                         causal: bool = False,
                         sm_scale: float | None = None,
                         block_q: int = 512, block_k: int = 1024,
                         interpret: bool = False):
    """Ring attention with the Pallas flash kernel as per-step compute
    (shard_map region fn, like :func:`ring_attention`).

    vs the unfused ring: O(block) memory instead of per-step (s_q, s_k)
    fp32 logits, MXU-fused inner loops, and causal future blocks are
    skipped outright instead of computed-and-masked. Numerics match
    ``ring_attention``/full attention (same online-softmax recombination).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    return _ring_flash(q, k, v, axis_name, causal, sm_scale, block_q,
                       block_k, interpret)


# ---------------------------------------------------------------------------
# Striped (load-balanced) ring attention: tokens are distributed
# round-robin (token t on device t mod n), so EVERY ring step is a
# near-triangular block on EVERY device — the causal work is balanced,
# unlike contiguous chunks where rank r does r+1 full blocks and the
# last rank sets the wall-clock (Striped Attention, Brandon et al.).
# ---------------------------------------------------------------------------

def stripe_layout(x, n: int, axis: int = 2):
    """Contiguous layout -> striped: row j*n + r moves to stripe r slot j
    (device r's local row j holds global position j*n + r). A global op:
    under a sequence-sharded jit, GSPMD lowers it to an all-to-all."""
    s = x.shape[axis]
    if s % n:
        raise ValueError(f"seq {s} not divisible by stripes {n}")
    shape = x.shape[:axis] + (s // n, n) + x.shape[axis + 1:]
    perm = list(range(len(shape)))
    perm[axis], perm[axis + 1] = perm[axis + 1], perm[axis]
    return x.reshape(shape).transpose(perm).reshape(x.shape)


def unstripe_layout(x, n: int, axis: int = 2):
    """Inverse of :func:`stripe_layout`."""
    s = x.shape[axis]
    shape = x.shape[:axis] + (n, s // n) + x.shape[axis + 1:]
    perm = list(range(len(shape)))
    perm[axis], perm[axis + 1] = perm[axis + 1], perm[axis]
    return x.reshape(shape).transpose(perm).reshape(x.shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _striped_flash(q, k, v, axis_name, sm_scale, block_q, block_k,
                   interpret):
    out, _ = _striped_fwd_impl(q, k, v, axis_name, sm_scale, block_q,
                               block_k, interpret)
    return out


# Causal-mask derivation for stripes: local row j has global position
# j*n + me, a visiting row i has i*n + src, so q >= k  <=>
# j >= i + (src > me) — i.e. kernel causal_offset 0 (src <= me) or
# -1 (src > me, strict). The cond predicates below are exactly
# `src > me`.


def _striped_fwd_impl(q, k, v, axis_name, sm_scale, block_q, block_k,
                      interpret):
    def step_block(step, src, me, kv):
        return jax.lax.cond(
            src > me,
            lambda ops: _attn._flash_forward(
                q, ops[0], ops[1], sm_scale, True, block_q, block_k,
                interpret, causal_offset=-1),
            lambda ops: _attn._flash_forward(
                q, ops[0], ops[1], sm_scale, True, block_q, block_k,
                interpret, causal_offset=0),
            kv)

    return _ring_fwd_loop(q, k, v, axis_name, step_block)


def _striped_fwd(q, k, v, axis_name, sm_scale, block_q, block_k,
                 interpret):
    out, lse = _striped_fwd_impl(q, k, v, axis_name, sm_scale, block_q,
                                 block_k, interpret)
    return out, (q, k, v, out, lse)


def _striped_bwd(axis_name, sm_scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res

    def step_block(step, src, me, kv):
        return jax.lax.cond(
            src > me,
            lambda o: _attn._flash_backward(
                (q, o[0], o[1], out, lse), g, sm_scale=sm_scale,
                causal=True, block_q=block_q, block_k=block_k,
                interpret=interpret, causal_offset=-1),
            lambda o: _attn._flash_backward(
                (q, o[0], o[1], out, lse), g, sm_scale=sm_scale,
                causal=True, block_q=block_q, block_k=block_k,
                interpret=interpret, causal_offset=0),
            kv)

    return _ring_bwd_loop(q, k, v, axis_name, step_block)


_striped_flash.defvjp(_striped_fwd, _striped_bwd)


def striped_flash_attention(q, k, v, *, axis_name: str = "sp",
                            sm_scale: float | None = None,
                            block_q: int = 512, block_k: int = 1024,
                            interpret: bool = False):
    """Striped causal ring attention (shard_map region fn): inputs must
    be in STRIPE layout (:func:`stripe_layout` — device r holds global
    positions r, r+n, r+2n, ...). Every step is a triangular block on
    every device, so the ring's critical path is ~n/2 block-equivalents
    instead of the contiguous schedule's n on the last rank."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    return _striped_flash(q, k, v, axis_name, sm_scale, block_q, block_k,
                          interpret)


def ulysses_attention(q, k, v, *, axis_name: str = "sp",
                      causal: bool = False,
                      sm_scale: float | None = None,
                      attn_fn: Callable | None = None):
    """Ulysses-style SP: all-to-all from sequence-sharded to head-sharded,
    run full-sequence attention on the local head subset, all-to-all back.

    Inputs (b, h, s_local, d) sequence-sharded; requires h % axis_size == 0.
    ≙ all_to_all op surface (reference tpu_ops.py:43) used for an SP scheme
    the reference never implemented.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    n = jax.lax.psum(1, axis_name)
    h = q.shape[1]
    assert h % n == 0, f"heads {h} not divisible by sp={n}"

    def to_heads(x):
        # (b, h, s/n, d) -> n chunks of heads, gather seq:
        # all_to_all splits axis 1 (heads) and concats axis 2 (seq).
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    def to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)   # (b, h/n, S, d)
    if attn_fn is None:
        # full-sequence local attention: this is exactly the hot path the
        # flash kernel exists for (auto: pallas on TPU)
        attn_fn = flash_attention
    out = attn_fn(qh, kh, vh, causal=causal, sm_scale=sm_scale)
    return to_seq(out)


_ATTN_IMPLS = ("flash", "unfused", "interpret")


def _resolve_attn_impl(attn_impl: str | None) -> str:
    if attn_impl is not None:
        if attn_impl not in _ATTN_IMPLS:
            raise ValueError(f"attn_impl={attn_impl!r}; expected one of "
                             f"{_ATTN_IMPLS} (or None = auto)")
        return attn_impl
    return "flash" if jax.default_backend() == "tpu" else "unfused"


def make_ring_attention(mesh: Mesh, *, axis_name: str = "sp",
                        causal: bool = False, impl: str = "ring",
                        spec: P | None = None,
                        attn_impl: str | None = None,
                        block_q: int = 512, block_k: int = 1024):
    """Wrap ring/ulysses attention in shard_map for (b, h, S, d) global
    arrays whose sequence axis is sharded over ``axis_name``.

    ``spec`` describes the full (b, h, S, d) sharding — pass the model's
    batch/head shardings too when calling inside a dp×tp×sp jit, so
    shard_map only ring-communicates over ``axis_name``.

    ``attn_impl`` selects the per-step compute: "flash" (Pallas kernel +
    causal work-skipping), "unfused" (the reference-math ring), or
    "interpret" (Pallas in interpreter mode — CPU CI). None = auto:
    flash on TPU, unfused elsewhere.
    """
    if impl not in ("ring", "ulysses", "striped"):
        raise ValueError(f"impl={impl!r}; expected one of "
                         f"('ring', 'ulysses', 'striped')")
    attn_impl = _resolve_attn_impl(attn_impl)
    if spec is None:
        spec = P(None, None, axis_name, None)

    if impl == "striped":
        if not causal:
            raise ValueError("striped attention is a causal schedule; "
                             "use impl='ring' for bidirectional")
        if attn_impl == "unfused":
            raise ValueError(
                "striped attention is built on the flash kernel; pass "
                "attn_impl='flash' (TPU) or 'interpret' (CPU CI), or use "
                "impl='ring' for the unfused path")
        n = mesh.shape[axis_name]

        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(spec, spec, spec), out_specs=spec,
                           check_vma=False)
        def region(q, k, v):
            return striped_flash_attention(
                q, k, v, axis_name=axis_name, block_q=block_q,
                block_k=block_k, interpret=attn_impl == "interpret")

        def striped_global(q, k, v):
            # Relayout to stripes (an all-to-all over sp under GSPMD),
            # run the balanced ring, restore the contiguous layout.
            # NOTE: this drop-in wrapper pays 4 relayouts per call; at
            # the long sequences SP targets, attention compute (S²/n)
            # dwarfs the relayout bandwidth (4·S·D). Models wanting the
            # zero-relayout form can stripe tokens ONCE at the input and
            # call striped_flash_attention directly per layer.
            qs, ks, vs = (stripe_layout(t, n) for t in (q, k, v))
            return unstripe_layout(region(qs, ks, vs), n)

        return striped_global

    if impl == "ring":
        if attn_impl in ("flash", "interpret"):
            def fn(q, k, v):
                return ring_flash_attention(
                    q, k, v, axis_name=axis_name, causal=causal,
                    block_q=block_q, block_k=block_k,
                    interpret=attn_impl == "interpret")
        else:
            def fn(q, k, v):
                return ring_attention(q, k, v, axis_name=axis_name,
                                      causal=causal)
    else:
        if attn_impl in ("flash", "interpret"):
            attn_fn = functools.partial(
                flash_attention, block_q=block_q, block_k=block_k,
                implementation=("interpret" if attn_impl == "interpret"
                                else "pallas"))
        else:
            attn_fn = mha_reference

        def fn(q, k, v):
            return ulysses_attention(q, k, v, axis_name=axis_name,
                                     causal=causal, attn_fn=attn_fn)

    @functools.partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    def sharded(q, k, v):
        return fn(q, k, v)

    return sharded
