"""Parallelism: strategies, collectives, distributed values, SP/TP/PP.

TPU-native counterpart of the reference's ``tensorflow/python/distribute/``
package (SURVEY.md §2.1–§2.3, §2.8).
"""
