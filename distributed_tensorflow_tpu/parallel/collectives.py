"""Typed collective operations lowered to XLA collectives over ICI/DCN.

TPU-native replacement of the reference's three-piece communication backend
(SURVEY.md §5.8): NCCL (core/nccl/nccl_manager.h), the C++ collective
framework (core/framework/collective.h — RingReducer et al.), and the grpc
remote-access plane. Here every collective is an XLA HLO op emitted inside a
single compiled SPMD program; the compiler picks the algorithm and schedules
it on ICI (or DCN across slices), so there is no runtime executor, no group /
instance-key rendezvous protocol, and no per-tensor RPC.

The six-type taxonomy mirrors the reference's ``CollectiveType`` enum
(reference: tensorflow/core/framework/collective.h:45-53):
REDUCTION, BROADCAST, GATHER, PERMUTE, ALL_TO_ALL, REDUCE_SCATTER.

Functions in this module must run inside an SPMD context that binds the mesh
axis name — i.e. under ``jax.shard_map`` (or ``Strategy.run``). Outside SPMD,
use ``cross_device_ops`` which wraps these in compiled programs.
"""

from __future__ import annotations

import enum
import dataclasses
import functools
import threading
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


class CollectiveType(enum.Enum):
    """≙ tensorflow/core/framework/collective.h:45-53."""

    REDUCTION = "reduction"
    BROADCAST = "broadcast"
    GATHER = "gather"
    PERMUTE = "permute"
    ALL_TO_ALL = "all_to_all"
    REDUCE_SCATTER = "reduce_scatter"


class ReduceOp(enum.Enum):
    """≙ tf.distribute.ReduceOp plus the nccl_ops.py op set
    (all_sum/all_prod/all_min/all_max, nccl_ops.py:29+)."""

    SUM = "sum"
    MEAN = "mean"
    PROD = "prod"
    MIN = "min"
    MAX = "max"

    @classmethod
    def from_any(cls, op) -> "ReduceOp":
        if isinstance(op, cls):
            return op
        return cls(str(op).lower())


class CommunicationImplementation(enum.Enum):
    """≙ collective_util.py:41-43 ``CommunicationImplementation``.

    AUTO/RING/NCCL are kept for config compatibility; on TPU all three lower
    to XLA collectives — the compiler owns algorithm choice the way
    ``communication_hint`` used to pick RingReducer vs NcclManager. ICI is
    the honest TPU name for the fast path.
    """

    AUTO = "AUTO"
    RING = "RING"
    NCCL = "NCCL"
    ICI = "ICI"


@dataclasses.dataclass(frozen=True)
class CommunicationOptions:
    """≙ collective_util.Options (collective_util.py:117).

    ``bytes_per_pack`` feeds gradient-bucket packing in cross_device_ops
    (same role as CollectiveReplicaLauncher's pack-by-size,
    cross_device_utils.py:436-449); ``timeout_seconds`` maps to the
    coordination-service barrier timeout rather than a per-collective
    timeout, because in-program XLA collectives cannot individually time out.
    """

    bytes_per_pack: int = 0
    timeout_seconds: float | None = None
    implementation: CommunicationImplementation = CommunicationImplementation.AUTO

    def merge(self, other: "CommunicationOptions | None") -> "CommunicationOptions":
        """≙ collective_util.py:139 Options.merge."""
        if other is None:
            return self
        return CommunicationOptions(
            bytes_per_pack=other.bytes_per_pack or self.bytes_per_pack,
            timeout_seconds=(other.timeout_seconds
                             if other.timeout_seconds is not None
                             else self.timeout_seconds),
            implementation=(other.implementation
                            if other.implementation
                            is not CommunicationImplementation.AUTO
                            else self.implementation),
        )


class CollectiveKeys:
    """Group/instance key bookkeeping (≙ cross_device_utils.py:173).

    XLA needs no instance keys — collective matching is positional within the
    single program — but the coordinator/PS path still uses keys to name
    host-side rendezvous (e.g. per-variable update channels), and tests use
    them to assert launch ordering, so the bookkeeping survives.
    """

    def __init__(self, group_key_start: int = 1):
        self._group_key = group_key_start
        self._instance_keys: dict[int, int] = {}
        self._lock = threading.Lock()

    def get_group_key(self, devices: Sequence) -> int:
        with self._lock:
            key = self._group_key
            self._group_key += 1
            self._instance_keys[key] = 0
            return key

    def get_instance_key(self, group_key: int) -> int:
        with self._lock:
            if group_key not in self._instance_keys:
                raise ValueError(f"Unknown group key {group_key}")
            self._instance_keys[group_key] += 1
            return self._instance_keys[group_key]


# ---------------------------------------------------------------------------
# In-SPMD collective functions (must be called under an axis binding).
# These are the op surface ≙ tensorflow/python/ops/collective_ops.py and
# tensorflow/python/tpu/ops/tpu_ops.py (SURVEY §2.2/§2.6), lowered to XLA.
# ---------------------------------------------------------------------------

AxisName = str | Sequence[str]


def all_reduce(x, axis_name: AxisName, op: ReduceOp | str = ReduceOp.SUM):
    """REDUCTION: ≙ collective_ops.all_reduce_v2 (collective_ops.py:95),
    nccl_ops.all_sum (nccl_ops.py:29), tpu_ops.cross_replica_sum
    (tpu_ops.py:92). Lowers to HLO AllReduce on ICI."""
    op = ReduceOp.from_any(op)
    if op is ReduceOp.SUM:
        return lax.psum(x, axis_name)
    if op is ReduceOp.MEAN:
        return lax.pmean(x, axis_name)
    if op is ReduceOp.MAX:
        return lax.pmax(x, axis_name)
    if op is ReduceOp.MIN:
        return lax.pmin(x, axis_name)
    if op is ReduceOp.PROD:
        # no pprod primitive; gather contributions and multiply (correct for
        # zero/negative values, unlike the log-sum-exp trick)
        gathered = lax.all_gather(x, axis_name, axis=0, tiled=False)
        return jnp.prod(gathered, axis=0)
    raise ValueError(f"Unsupported reduce op {op}")


def all_gather(x, axis_name: AxisName, axis: int = 0, tiled: bool = True):
    """GATHER: ≙ collective_ops.all_gather_v2 (collective_ops.py:200).
    ``tiled=True`` concatenates along ``axis`` (TF semantics); ``False``
    stacks a fresh leading axis."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: AxisName, axis: int = 0,
                   op: ReduceOp | str = ReduceOp.SUM):
    """REDUCE_SCATTER: ≙ CollectiveType::REDUCE_SCATTER (collective.h:53).
    The building block of FSDP gradient sync."""
    op = ReduceOp.from_any(op)
    if op not in (ReduceOp.SUM, ReduceOp.MEAN):
        raise ValueError("reduce_scatter supports SUM and MEAN")
    out = lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)
    if op is ReduceOp.MEAN:
        out = out / lax.psum(1, axis_name)
    return out


def combined_axis_index(axis_names: AxisName):
    """Row-major flat index over one or several mesh axes."""
    if isinstance(axis_names, str):
        return lax.axis_index(axis_names)
    idx = 0
    for name in axis_names:
        idx = idx * lax.axis_size(name) + lax.axis_index(name)
    return idx


def broadcast(x, axis_name: AxisName, source: int = 0):
    """BROADCAST: ≙ collective_ops.broadcast_send_v2/recv_v2
    (collective_ops.py:314/:392). One source shard wins; implemented as a
    masked psum so it stays a single fused collective."""
    idx = combined_axis_index(axis_name)
    mask = (idx == source).astype(x.dtype)
    return lax.psum(x * mask, axis_name)


def permute(x, axis_name: str, perm: Sequence[tuple[int, int]]):
    """PERMUTE: ≙ tpu_ops.collective_permute (tpu_ops.py:111) /
    core Permuter (permuter.h). ``perm`` is (source, dest) pairs; devices not
    named as a dest receive zeros."""
    return lax.ppermute(x, axis_name, perm=list(perm))


def permute_shift(x, axis_name: str, shift: int = 1):
    """Ring shift helper built on PERMUTE — the ring-attention data motion."""
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm=perm)


def all_to_all(x, axis_name: AxisName, split_axis: int, concat_axis: int,
               tiled: bool = True):
    """ALL_TO_ALL: ≙ collective_ops.all_to_all_v2 (collective_ops.py:501),
    tpu_ops.all_to_all (tpu_ops.py:43). The Ulysses sequence<->head
    re-sharding primitive."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


def axis_index(axis_name: AxisName):
    """Replica id along ``axis_name`` (≙ replica_id_in_sync_group)."""
    return lax.axis_index(axis_name)


def axis_size(axis_name: str) -> int:
    return lax.axis_size(axis_name)


# ---------------------------------------------------------------------------
# Hierarchical reduction (≙ HierarchicalCopyAllReduce, cross_device_ops.py:997
# and _build_nccl_hybrid, v1/all_reduce.py:710).
# ---------------------------------------------------------------------------

def _hierarchical_flat(flat, inner_axis: str, outer_axis: str):
    """scatter(inner) -> reduce(outer) -> gather(inner) on a 1-D vector."""
    size = flat.shape[0]
    n_inner = lax.axis_size(inner_axis)
    pad = (-size) % n_inner
    if pad:
        flat = jnp.pad(flat, (0, pad))
    shard = lax.psum_scatter(flat, inner_axis, scatter_dimension=0, tiled=True)
    shard = lax.psum(shard, outer_axis)
    full = lax.all_gather(shard, inner_axis, axis=0, tiled=True)
    return full[:size]


def hierarchical_all_reduce(x, inner_axis: str, outer_axis: str,
                            op: ReduceOp | str = ReduceOp.SUM,
                            *, chunks: int = 1):
    """Two-level allreduce: reduce-scatter on the fast inner axis (ICI),
    allreduce the shard on the slow outer axis (DCN), all-gather back on the
    inner axis.

    This is the TPU-native form of the reference's hierarchical GPU reduce
    (cross_device_utils.py:55 ``aggregate_gradients_using_hierarchical_copy``
    with its hard-coded 2-group DMA topology, and the NCCL-hybrid graph
    builder v1/all_reduce.py:710): each DCN hop moves only 1/|inner| of the
    bytes. XLA emits the same decomposition for a flat psum over both axes on
    multi-slice topologies, but the explicit form lets the Transformer
    2-slice config (BASELINE.md #5) control it and lets tests assert the
    traffic split.

    ``chunks > 1`` splits the vector into that many independent
    scatter->reduce->gather chains, so the slow outer (DCN) hop of chunk
    *i* can overlap the fast inner (ICI) phases of chunk *i+1* instead of
    the three phases serializing end-to-end — async dispatch across the
    hybrid mesh. The per-element arithmetic is unchanged (chunking only
    partitions the vector), so results are bit-identical to ``chunks=1``.
    """
    op = ReduceOp.from_any(op)
    orig_shape = x.shape
    orig_size = x.size
    flat = x.reshape(-1)
    chunks = max(1, min(int(chunks), orig_size or 1))
    if chunks == 1:
        full = _hierarchical_flat(flat, inner_axis, outer_axis)
    else:
        seg = -(-orig_size // chunks)          # ceil division
        parts = [flat[i * seg:(i + 1) * seg] for i in range(chunks)]
        full = jnp.concatenate(
            [_hierarchical_flat(p, inner_axis, outer_axis)
             for p in parts if p.shape[0]])
    out = full.reshape(orig_shape)
    if op is ReduceOp.MEAN:
        out = out / (lax.axis_size(inner_axis) * lax.axis_size(outer_axis))
    elif op is not ReduceOp.SUM:
        raise ValueError("hierarchical_all_reduce supports SUM and MEAN")
    return out


# ---------------------------------------------------------------------------
# Reverse-order bucketed gradient collectives (≙ the reference's
# NcclAllReduce gradient packing: CollectiveReplicaLauncher pack-by-size,
# cross_device_utils.py:436-449 / group_by_size :679 — plus Horovod-style
# fusion-buffer scheduling in reverse layer order).
# ---------------------------------------------------------------------------

# Default fusion-buffer size when packing is enabled but unconfigured
# (CommunicationOptions.bytes_per_pack == 0). Same order of magnitude as
# Horovod's 64 MB fusion buffer / DDP's 25 MB bucket, sized down for the
# smaller per-bucket latency of ICI.
DEFAULT_BYTES_PER_PACK = 4 * 1024 * 1024


def plan_buckets(sizes: Sequence[int], dtypes: Sequence,
                 bytes_per_pack: int, *, reverse: bool = False
                 ) -> list[list[int]]:
    """Greedy size-bucketing of flattened-tensor indices.

    Buckets NEVER mix dtypes: concatenating bf16 and f32 leaves into one
    buffer would silently upcast (and double the bf16 wire bytes), so a
    dtype change always closes the current bucket. A bucket closes once
    its byte count reaches ``bytes_per_pack`` — a leaf landing exactly on
    the boundary is included and the next leaf starts a fresh bucket.
    ``bytes_per_pack=0`` packs everything (per dtype run) into one bucket.

    ``reverse=True`` emits buckets in reverse leaf order — last-layer
    gradients are produced FIRST by backprop, so their bucket's collective
    can launch while earlier layers are still differentiating (the
    Horovod/DDP overlap idiom; the reference gets the same effect from its
    gradient tape firing allreduces in completion order).
    """
    n = len(sizes)
    order = range(n - 1, -1, -1) if reverse else range(n)
    buckets: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    cur_dtype = None
    for i in order:
        dt = jnp.dtype(dtypes[i])
        if cur and dt != cur_dtype:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_dtype = dt
        cur_bytes += int(sizes[i]) * dt.itemsize
        if bytes_per_pack and cur_bytes >= bytes_per_pack:
            buckets.append(cur)
            cur, cur_bytes = [], 0
    if cur:
        buckets.append(cur)
    return buckets


class GradientBucketer:
    """Packs a gradient pytree into size-bounded single-dtype buckets and
    reduces each bucket as ONE collective, scheduled in reverse layer
    order so reduction overlaps the remaining backward pass.

    Must run inside an SPMD context binding ``axis_names`` (shard_map /
    Strategy.run). On a hybrid mesh pass ``outer_axis``/``inner_axis``
    (e.g. "dcn"/"dp"): each bucket then takes the hierarchical
    scatter->DCN-reduce->gather path, and because buckets are independent
    chains the DCN hop of one bucket overlaps the ICI phases of the next
    (the async hybrid dispatch of ISSUE 6).

    Equivalent wire behavior to the reference's
    ``CollectiveReplicaLauncher`` pack path (cross_device_utils.py:436);
    results are bit-identical to per-leaf ``psum`` — packing concatenates
    buffers but never changes any element's reduction.
    """

    def __init__(self, axis_names: AxisName,
                 *, bytes_per_pack: int = DEFAULT_BYTES_PER_PACK,
                 reverse: bool = True,
                 outer_axis: str | None = None,
                 inner_axis: str | None = None):
        self.axis_names = ((axis_names,) if isinstance(axis_names, str)
                           else tuple(axis_names))
        self.bytes_per_pack = int(bytes_per_pack)
        self.reverse = bool(reverse)
        if (outer_axis is None) != (inner_axis is None):
            raise ValueError("outer_axis and inner_axis must be set "
                             "together (hybrid mesh) or both omitted")
        self.outer_axis = outer_axis
        self.inner_axis = inner_axis

    def plan(self, leaves: Sequence) -> list[list[int]]:
        sizes = [int(np.prod(jnp.shape(x))) if jnp.shape(x) else 1
                 for x in leaves]
        dtypes = [jnp.result_type(x) for x in leaves]
        return plan_buckets(sizes, dtypes, self.bytes_per_pack,
                            reverse=self.reverse)

    def _reduce_flat(self, flat, op: ReduceOp):
        if self.outer_axis is not None:
            return hierarchical_all_reduce(
                flat, inner_axis=self.inner_axis,
                outer_axis=self.outer_axis, op=op)
        return all_reduce(flat, self.axis_names, op)

    def plan_summary(self, leaves: Sequence) -> "list[dict]":
        """Human/bench-readable view of :meth:`plan`: one dict per
        bucket with ``{"leaves": n, "bytes": b, "dtype": name}`` in
        launch order. ``tools/trace_report.py`` and bench rows report
        these so the overlap numbers can be checked against the actual
        bucket schedule."""
        sizes = [int(np.prod(jnp.shape(x))) if jnp.shape(x) else 1
                 for x in leaves]
        dtypes = [jnp.result_type(x) for x in leaves]
        out = []
        for bucket in plan_buckets(sizes, dtypes, self.bytes_per_pack,
                                   reverse=self.reverse):
            dt = jnp.dtype(dtypes[bucket[0]])
            out.append({"leaves": len(bucket),
                        "bytes": sum(sizes[i] * dt.itemsize
                                     for i in bucket),
                        "dtype": dt.name})
        return out

    def all_reduce(self, tree, op: ReduceOp | str = ReduceOp.SUM):
        """Bucketed allreduce of a pytree (the gradient-sync shape)."""
        op = ReduceOp.from_any(op)
        if op not in (ReduceOp.SUM, ReduceOp.MEAN):
            raise ValueError("GradientBucketer supports SUM and MEAN")
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        out: list = [None] * len(leaves)
        for bucket in self.plan(leaves):
            flat = jnp.concatenate(
                [jnp.ravel(jnp.asarray(leaves[i])) for i in bucket])
            reduced = self._reduce_flat(flat, op)
            off = 0
            for i in bucket:
                shape = jnp.shape(leaves[i])
                size = int(np.prod(shape)) if shape else 1
                out[i] = jnp.reshape(reduced[off:off + size], shape)
                off += size
        return jax.tree_util.tree_unflatten(treedef, out)


def simulate_overlap(ready_s: Sequence[float], dur_s: Sequence[float],
                     backward_end_s: float | None = None) -> dict:
    """Model the overlapped bucket schedule and account its win.

    ``ready_s[i]`` is when backprop has produced bucket *i*'s gradients
    (so its collective may launch); ``dur_s[i]`` is that bucket's
    reduction time. Buckets run on ONE communication channel in launch
    order (the wire serializes), each starting at
    ``max(ready, previous bucket's finish)`` — the Horovod/DDP fusion
    buffer model. ``backward_end_s`` defaults to the last ready time.

    Returns::

        {"serial_s":   sum of dur_s (what an unoverlapped tail sync
                       would add to the step),
         "exposed_s":  how far the last bucket finishes past the end of
                       backward — the part that actually extends the
                       critical path,
         "overlap_eff": 1 - exposed/serial (1.0 = fully hidden),
         "finish_s":   per-bucket finish times}

    This is the hand-checkable counterpart of the *measured* overlap
    efficiency (bench.py times the full / sync-free / collective-only
    steps); tests pin this model against a hand-computed 2-bucket
    schedule.
    """
    if len(ready_s) != len(dur_s):
        raise ValueError(f"{len(ready_s)} ready times vs "
                         f"{len(dur_s)} durations")
    finish: list[float] = []
    t = 0.0
    for ready, dur in zip(ready_s, dur_s):
        t = max(float(ready), t) + float(dur)
        finish.append(t)
    serial = float(sum(dur_s))
    bwd_end = (float(backward_end_s) if backward_end_s is not None
               else (max(ready_s) if ready_s else 0.0))
    exposed = max(0.0, (finish[-1] if finish else 0.0) - bwd_end)
    eff = None
    if serial > 0:
        eff = max(0.0, min(1.0, 1.0 - exposed / serial))
    return {"serial_s": serial, "exposed_s": exposed,
            "overlap_eff": eff, "finish_s": finish}


# ---------------------------------------------------------------------------
# Host-level compiled collectives over a mesh (outside SPMD).
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=128)
def _compiled_mesh_reduce(mesh, axis_names: tuple, op: ReduceOp):
    from jax.sharding import PartitionSpec as P

    def f(x):
        return all_reduce(x, axis_names, op)

    return jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=P(axis_names), out_specs=P(),
        check_vma=False))


def mesh_all_reduce(mesh, x, axis_names: Sequence[str] | str,
                    op: ReduceOp | str = ReduceOp.SUM):
    """Reduce a host array whose leading axis spans ``axis_names`` of
    ``mesh``. Used by CrossDeviceOps for eager-style reductions."""
    if isinstance(axis_names, str):
        axis_names = (axis_names,)
    return _compiled_mesh_reduce(mesh, tuple(axis_names),
                                 ReduceOp.from_any(op))(x)
