"""CentralStorageStrategy: variables on the host, compute on the mesh.

≙ tensorflow/python/distribute/central_storage_strategy.py (~200 LoC,
SURVEY.md §2.1/§2.8): one physical copy of every variable on the
parameter device (host CPU), compute replicated across local
accelerators, replica writes aggregated before applying.

TPU-native form: variables are :class:`AggregatingVariable`s pinned to
host memory; each compiled step pulls them in (H2D on dispatch — the PS
read) and the write-back re-pins the single updated copy. The SPMD
run/aggregation machinery is the shared Strategy core.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh

from distributed_tensorflow_tpu.cluster import topology as topo_lib
from distributed_tensorflow_tpu.parallel.ps_values import (
    AggregatingVariable,
    _default_parameter_device,
)
from distributed_tensorflow_tpu.parallel.strategy import Strategy
from distributed_tensorflow_tpu.parallel.values import (
    VariableAggregation,
    VariableSynchronization,
)


class CentralStorageStrategy(Strategy):
    """Variables on one parameter device; replicas on the mesh."""

    def __init__(self, mesh: Mesh | None = None, parameter_device=None):
        super().__init__(mesh=mesh,
                         data_axis_names=(topo_lib.DATA_AXIS,))
        self._parameter_device = (parameter_device
                                  or _default_parameter_device())

    @property
    def parameter_device(self):
        return self._parameter_device

    def gradient_bucketer(self):
        # Variables live on the parameter device, not replicated on the
        # mesh — gradient aggregation happens on write-back through
        # AggregatingVariable, not as an in-program collective.
        return None

    def create_variable(self, value, *, name=None, trainable=True,
                        synchronization=VariableSynchronization.AUTO,
                        aggregation=VariableAggregation.NONE, dtype=None):
        if synchronization is VariableSynchronization.ON_READ:
            return super().create_variable(
                value, name=name, trainable=trainable,
                synchronization=synchronization, aggregation=aggregation,
                dtype=dtype)
        var = AggregatingVariable(
            value, device=self._parameter_device, name=name,
            trainable=trainable, aggregation=aggregation, dtype=dtype)
        self._variables.append(var)
        return var
