"""PS-placed values: AggregatingVariable and per-worker caching.

≙ tensorflow/python/distribute/ps_values.py (963 LoC — SURVEY.md §2.3):
``AggregatingVariable`` (one physical copy on a parameter device, writes
from replica context aggregated before applying) and ``CachingVariable``
(a read-mostly per-worker cache of a PS variable).

TPU-native mapping: the "parameter device" is a HOME DEVICE the variable
is pinned to (host CPU for central storage, a designated chip for V1-style
round-robin PS placement). Compute steps pull the value in (one transfer
per step — the PS read), and write-back re-pins to the home device. The
cross-replica write aggregation itself is enforced by Strategy.run's
on-write machinery (strategy.py), exactly like MirroredVariable — the
difference is placement, not math.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.parallel.values import (
    DistributedVariable,
    VariableAggregation,
    VariableSynchronization,
)


def _default_parameter_device():
    """Host CPU: the reference's central-storage parameter device."""
    try:
        return jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        return jax.local_devices()[0]


class AggregatingVariable(DistributedVariable):
    """Single-copy variable on a parameter device with aggregated writes.

    ≙ ps_values.AggregatingVariable: replica-context assigns aggregate
    across replicas (MEAN by default) and apply once to the single copy.
    """

    def __init__(self, value, *, device=None, name=None, trainable=True,
                 aggregation: VariableAggregation = VariableAggregation.MEAN,
                 dtype=None):
        self._home_device = device or _default_parameter_device()
        value = jax.device_put(jnp.asarray(value, dtype=dtype),
                               self._home_device)
        super().__init__(
            value, name=name, trainable=trainable,
            synchronization=VariableSynchronization.ON_WRITE,
            aggregation=(aggregation
                         if aggregation is not VariableAggregation.NONE
                         else VariableAggregation.MEAN),
            dtype=dtype)

    @property
    def device(self):
        return self._home_device

    def _set_raw(self, value):
        # Strategy.run write-back: the updated value must come HOME (the
        # point of central storage — one copy on the parameter device).
        self._value = jax.device_put(value, self._home_device)


class CachingVariable:
    """Read-mostly cache of a PS variable (≙ ps_values.CachingVariable).

    ``read_value`` serves the cached copy; ``update_cache`` re-reads the
    source. Writes pass through to the source variable and refresh the
    cache.
    """

    def __init__(self, source: DistributedVariable):
        self._source = source
        self._cache = source.read_value()

    @property
    def name(self):
        return self._source.name

    @property
    def shape(self):
        return self._source.shape

    @property
    def dtype(self):
        return self._source.dtype

    def read_value(self):
        return self._cache

    @property
    def value(self):
        return self._cache

    def update_cache(self):
        self._cache = self._source.read_value()
        return self._cache

    def assign(self, value):
        self._source.assign(value)
        return self.update_cache()

    def assign_add(self, delta):
        self._source.assign_add(delta)
        return self.update_cache()

    def __array__(self, dtype=None):
        import numpy as np
        arr = np.asarray(self._cache)
        return arr.astype(dtype) if dtype is not None else arr
