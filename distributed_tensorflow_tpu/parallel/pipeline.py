"""Pipeline parallelism over the "pp" mesh axis (GPipe schedule).

The reference has NO pipeline parallelism (SURVEY.md §2.8: "nothing in
TF/python/distribute/; delegated to GPipe/Mesh-TF out-of-tree"). The
TPU-native framework provides it as a first-class schedule:

- Stage parameters are stacked on a leading axis and sharded over "pp"
  (each device holds exactly its stage's weights — no duplication).
- Microbatches flow stage-to-stage via ``jax.lax.ppermute`` over ICI,
  the canonical neighbor-exchange on a TPU torus.
- The whole schedule is a ``lax.scan`` over ticks inside ``shard_map``,
  so XLA sees one compiled loop body; autodiff through ppermute/scan
  gives the backward pipeline (reverse schedule) for free.

Bubble fraction is (n_stages-1)/(n_micro+n_stages-1) — standard GPipe.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def pipeline_apply(stage_fn: Callable, params_local, x_microbatches,
                   *, axis_name: str = "pp"):
    """Run a GPipe pipeline inside a shard_map region.

    stage_fn(params, x) -> y: one stage's computation (same shape in/out).
    params_local: this device's stage parameters (leading "pp" axis
        already sliced away by shard_map).
    x_microbatches: (n_micro, mb, ...) — replicated across pp; stage 0
        injects microbatch t at tick t.

    Returns (n_micro, mb, ...) outputs of the LAST stage, valid on every
    device (psum-broadcast at the end).
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    n_micro = x_microbatches.shape[0]
    mb_shape = x_microbatches.shape[1:]
    n_ticks = n_micro + n_stages - 1

    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        state, outputs = carry
        # Stage 0 injects microbatch t (clamped; injections past n_micro
        # are garbage that never reaches collection).
        inject = jax.lax.dynamic_index_in_dim(
            x_microbatches, jnp.minimum(t, n_micro - 1), axis=0,
            keepdims=False)
        state = jnp.where(stage == 0, inject, state)
        state = stage_fn(params_local, state)
        # Last stage collects microbatch t-(n_stages-1) at tick t.
        out_idx = t - (n_stages - 1)
        collect = (stage == n_stages - 1) & (out_idx >= 0)
        outputs = jax.lax.cond(
            collect,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, state.astype(o.dtype), jnp.maximum(out_idx, 0), axis=0),
            lambda o: o,
            outputs)
        state = jax.lax.ppermute(state, axis_name, perm)
        return (state, outputs), None

    state0 = jnp.zeros(mb_shape, x_microbatches.dtype)
    outputs0 = jnp.zeros((n_micro,) + mb_shape, x_microbatches.dtype)
    (_, outputs), _ = jax.lax.scan(tick, (state0, outputs0),
                                   jnp.arange(n_ticks))
    # Broadcast the last stage's outputs to every device.
    outputs = jnp.where(stage == n_stages - 1, outputs, 0.0)
    return jax.lax.psum(outputs, axis_name)


def make_pipelined_fn(mesh: Mesh, stage_fn: Callable, *,
                      axis_name: str = "pp",
                      param_spec: P | None = None,
                      data_spec: P | None = None):
    """shard_map wrapper: (stacked_params, x_microbatches) -> outputs.

    stacked_params: pytree with leading axis n_stages, sharded over "pp".
    x_microbatches: (n_micro, mb, ...), replicated over "pp" (shard other
        mesh axes via ``data_spec``).
    """
    n_stages = mesh.shape[axis_name]
    if param_spec is None:
        param_spec = P(axis_name)
    if data_spec is None:
        data_spec = P()

    def run(stacked_params, x_mb):
        def inner(params_local, x_local):
            # shard_map leaves the (sliced) leading stage axis of size 1.
            params_local = jax.tree_util.tree_map(
                lambda p: jnp.squeeze(p, axis=0), params_local)
            return pipeline_apply(stage_fn, params_local, x_local,
                                  axis_name=axis_name)

        return shard_map(
            inner, mesh=mesh,
            in_specs=(param_spec, data_spec),
            out_specs=data_spec,
            check_vma=False)(stacked_params, x_mb)

    return run


def stack_stage_params(per_stage_params: list):
    """[stage0_tree, stage1_tree, ...] -> one tree with leading pp axis."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *per_stage_params)


def place_stacked_params(stacked, mesh: Mesh, axis_name: str = "pp"):
    """Device_put the stacked params so each pp rank owns its stage."""
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh, P(axis_name))), stacked)
