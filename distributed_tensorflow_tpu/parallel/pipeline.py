"""Pipeline parallelism over the "pp" mesh axis (GPipe and 1F1B).

The reference has NO pipeline parallelism (SURVEY.md §2.8: "nothing in
TF/python/distribute/; delegated to GPipe/Mesh-TF out-of-tree"). The
TPU-native framework provides it as a first-class schedule:

- Stage parameters are stacked on a leading axis and sharded over "pp"
  (each device holds exactly its stage's weights — no duplication).
- Microbatches flow stage-to-stage via ``jax.lax.ppermute`` over ICI,
  the canonical neighbor-exchange on a TPU torus.
- The whole schedule is a ``lax.scan`` over ticks inside ``shard_map``,
  so XLA sees one compiled loop body.

Two schedules (pick via :func:`bubble_fraction` / the transformer's
``make_pipelined_train_step(schedule=...)``):

- **GPipe** (:func:`pipeline_apply`): forward pipeline under autodiff;
  the reverse schedule falls out of differentiating ppermute/scan.
  Bubble fraction (S-1)/(M+S-1); activation memory O(M) — autodiff
  stashes every microbatch's residuals until the backward phase.
- **1F1B** (:func:`pipeline_1f1b_value_and_grad`): PipeDream-flush
  one-forward-one-backward — the backward of microbatch m starts the
  cycle its forward reaches the last stage and interleaves with the
  remaining forwards, so at most min(M, 2S-1) microbatch inputs are
  stashed (activations rematerialized per stage on the backward).
  In this lockstep SPMD realization the schedule spans M+2(S-1)
  fwd+bwd cycles — bubble fraction 2(S-1)/(M+2(S-1)) — trading GPipe's
  O(M) activation memory for O(S); on asynchronous hardware the same
  order realizes the classic (S-1)/(M+S-1) bubble with t_f-granular
  warmup.
- **Interleaved 1F1B**
  (:func:`pipeline_interleaved_1f1b_value_and_grad`): Megatron-style
  virtual pipeline stages — each of the W workers holds v
  NON-adjacent model chunks (worker k owns model stages k, W+k, …,
  (v-1)W+k), so a microbatch crosses every worker v times and the
  warmup/drain ramps shrink by ~1/v. In the lockstep realization the
  schedule spans Mv + vW + W - 2 cycles — bubble fraction
  (vW + W - 2)/(Mv + vW + W - 2), strictly below plain 1F1B's
  2(W-1)/(M+2(W-1)) for v >= 2 — at the cost of v× more
  stage-boundary traffic and a v-chunk parameter gather per cycle.
  v=1 degenerates to plain 1F1B exactly. Requires M % W == 0
  (microbatches flow in groups of W per chunk).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def pipeline_apply(stage_fn: Callable, params_local, x_microbatches,
                   *, axis_name: str = "pp"):
    """Run a GPipe pipeline inside a shard_map region.

    stage_fn(params, x) -> y: one stage's computation (same shape in/out).
    params_local: this device's stage parameters (leading "pp" axis
        already sliced away by shard_map).
    x_microbatches: (n_micro, mb, ...) — replicated across pp; stage 0
        injects microbatch t at tick t.

    Returns (n_micro, mb, ...) outputs of the LAST stage, valid on every
    device (psum-broadcast at the end).
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    n_micro = x_microbatches.shape[0]
    mb_shape = x_microbatches.shape[1:]
    n_ticks = n_micro + n_stages - 1

    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        state, outputs = carry
        # Stage 0 injects microbatch t (clamped; injections past n_micro
        # are garbage that never reaches collection).
        inject = jax.lax.dynamic_index_in_dim(
            x_microbatches, jnp.minimum(t, n_micro - 1), axis=0,
            keepdims=False)
        state = jnp.where(stage == 0, inject, state)
        state = stage_fn(params_local, state)
        # Last stage collects microbatch t-(n_stages-1) at tick t.
        out_idx = t - (n_stages - 1)
        collect = (stage == n_stages - 1) & (out_idx >= 0)
        outputs = jax.lax.cond(
            collect,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, state.astype(o.dtype), jnp.maximum(out_idx, 0), axis=0),
            lambda o: o,
            outputs)
        state = jax.lax.ppermute(state, axis_name, perm)
        return (state, outputs), None

    state0 = jnp.zeros(mb_shape, x_microbatches.dtype)
    outputs0 = jnp.zeros((n_micro,) + mb_shape, x_microbatches.dtype)
    (_, outputs), _ = jax.lax.scan(tick, (state0, outputs0),
                                   jnp.arange(n_ticks))
    # Broadcast the last stage's outputs to every device.
    outputs = jnp.where(stage == n_stages - 1, outputs, 0.0)
    return jax.lax.psum(outputs, axis_name)


def bubble_fraction(n_stages: int, n_micro: int,
                    schedule: str = "gpipe", *,
                    interleave: int = 1) -> float:
    """Idle fraction of the pipeline schedule (docstring formulas).

    ``n_stages`` counts WORKERS (pp ranks). For ``schedule=
    "interleaved"`` each worker holds ``interleave`` virtual chunks, so
    the model has ``n_stages * interleave`` stages total and the bubble
    is (vW + W - 2)/(Mv + vW + W - 2) — strictly below plain 1F1B's for
    v >= 2, equal at v=1.
    """
    s, m = int(n_stages), int(n_micro)
    v = int(interleave)
    if v < 1:
        raise ValueError(f"interleave must be >= 1, got {v}")
    if schedule == "gpipe":
        return (s - 1) / (m + s - 1)
    if schedule == "1f1b":
        return 2 * (s - 1) / (m + 2 * (s - 1))
    if schedule == "interleaved":
        return (v * s + s - 2) / (m * v + v * s + s - 2)
    raise ValueError(f"unknown schedule {schedule!r}")


def schedule_table(n_stages: int, n_micro: int, schedule: str = "gpipe",
                   *, interleave: int = 1) -> "list[dict]":
    """Flat unit-of-work table of one pipeline step.

    Each entry is ``{"worker", "cycle", "lane", "mb", "stage"}`` — one
    microbatch's forward or backward of one MODEL stage on one worker at
    one lockstep cycle. ``lane`` is ``"fwd"``, ``"bwd"``, or
    ``"fwd+bwd"`` (GPipe's fused sweep, where the reverse schedule is
    implicit under autodiff); ``stage`` is the model-stage index, which
    equals the worker for non-interleaved schedules and ``chunk *
    n_workers + worker`` for interleaved. Feed the result to
    :func:`validate_schedule`; :func:`schedule_spans` renders the same
    table as per-worker busy intervals.
    """
    s, m = int(n_stages), int(n_micro)
    v = int(interleave)
    if s < 1 or m < 1 or v < 1:
        raise ValueError(
            f"need n_stages>=1, n_micro>=1, interleave>=1, got {s}/{m}/{v}")
    table: list[dict] = []
    if schedule == "gpipe":
        for k in range(s):
            for j in range(m):
                table.append({"worker": k, "cycle": j + k,
                              "lane": "fwd+bwd", "mb": j, "stage": k})
    elif schedule == "1f1b":
        for k in range(s):
            for j in range(m):
                table.append({"worker": k, "cycle": j + k,
                              "lane": "fwd", "mb": j, "stage": k})
                table.append({"worker": k, "cycle": j + 2 * s - 2 - k,
                              "lane": "bwd", "mb": j, "stage": k})
    elif schedule == "interleaved":
        if m % s != 0:
            raise ValueError(
                f"interleaved needs n_micro % n_workers == 0, got {m}/{s}")
        w = s
        for k in range(w):
            for j in range(v):
                for g in range(m // w):
                    for r in range(w):
                        mb = g * w + r
                        table.append({
                            "worker": k,
                            "cycle": g * v * w + j * w + r + k,
                            "lane": "fwd", "mb": mb, "stage": j * w + k})
                        table.append({
                            "worker": k,
                            "cycle": (v * w - 1) + g * v * w
                            + (v - 1 - j) * w + r + (w - 1 - k),
                            "lane": "bwd", "mb": mb, "stage": j * w + k})
    else:
        raise ValueError(f"unknown schedule {schedule!r}")
    return table


def validate_schedule(table: "list[dict]") -> "list[str]":
    """Physical-validity check of a :func:`schedule_table`.

    Verifies (a) no worker runs two units in the same (cycle, lane) — a
    ``fwd+bwd`` entry books both lanes; (b) every (microbatch, model
    stage) runs exactly one forward, and exactly one backward when the
    schedule has explicit backward entries; (c) dependencies — a
    microbatch's forward of stage s+1 is strictly after its forward of
    stage s, its backward of stage s strictly after its backward of
    stage s+1, and the last stage's backward no earlier than its own
    forward (same cycle allowed: the lockstep body writes the forward
    then reads it in the backward sub-tick).

    Returns human-readable violations; an empty list means valid.
    """
    problems: list[str] = []
    if not table:
        return ["empty schedule"]
    booked: set = set()
    for e in table:
        lanes = ("fwd", "bwd") if e["lane"] == "fwd+bwd" else (e["lane"],)
        for lane in lanes:
            key = (e["worker"], e["cycle"], lane)
            if key in booked:
                problems.append(
                    f"worker {e['worker']} double-booked: cycle "
                    f"{e['cycle']} lane {lane}")
            booked.add(key)
    occ: dict = {}
    for e in table:
        lane = "fwd" if e["lane"] == "fwd+bwd" else e["lane"]
        occ.setdefault((e["mb"], e["stage"], lane), []).append(e["cycle"])
    n_stage = max(e["stage"] for e in table) + 1
    mbs = sorted({e["mb"] for e in table})
    has_bwd = any(e["lane"] == "bwd" for e in table)
    for mb in mbs:
        for st in range(n_stage):
            fwd = occ.get((mb, st, "fwd"), [])
            if len(fwd) != 1:
                problems.append(
                    f"mb {mb} stage {st}: {len(fwd)} fwd units (want 1)")
                continue
            if st > 0:
                prev = occ.get((mb, st - 1, "fwd"), [])
                if prev and fwd[0] < prev[0] + 1:
                    problems.append(
                        f"mb {mb}: fwd stage {st} at cycle {fwd[0]} not "
                        f"after stage {st - 1} at {prev[0]}")
            if not has_bwd:
                continue
            bwd = occ.get((mb, st, "bwd"), [])
            if len(bwd) != 1:
                problems.append(
                    f"mb {mb} stage {st}: {len(bwd)} bwd units (want 1)")
                continue
            if st == n_stage - 1 and bwd[0] < fwd[0]:
                problems.append(
                    f"mb {mb}: last-stage bwd at cycle {bwd[0]} before "
                    f"its fwd at {fwd[0]}")
            nxt = occ.get((mb, st + 1, "bwd"), [])
            if nxt and bwd[0] < nxt[0] + 1:
                problems.append(
                    f"mb {mb}: bwd stage {st} at cycle {bwd[0]} not "
                    f"after stage {st + 1} at {nxt[0]}")
    return problems


def schedule_spans(n_stages: int, n_micro: int, schedule: str = "gpipe",
                   *, t_cycle_s: float = 1.0,
                   interleave: int = 1) -> "list[list[dict]]":
    """Analytic per-stage busy spans of one pipeline step.

    The compiled schedule runs as ONE fused XLA program — individual
    stage activity is invisible to host-side telemetry — so the trace
    renders the schedule's *analytic* timeline instead: per stage, the
    list of busy intervals ``{"t0": s, "t1": s, "kind": "fwd"|"bwd"|
    "fwd+bwd"}`` in units of ``t_cycle_s`` (one pipeline cycle; for
    1F1B a cycle holds one forward AND one backward, for GPipe's
    forward sweep one forward — measured step time / total cycles gives
    the real scale). ``tools/trace_report.py --pipeline`` turns these
    into synthetic stage tracks next to the measured spans.

    The derived idle share matches :func:`bubble_fraction` exactly
    (regression-tested), so the rendered bubbles are the formula, drawn.
    """
    s, m = int(n_stages), int(n_micro)
    if s < 1 or m < 1:
        raise ValueError(f"need n_stages>=1 and n_micro>=1, got {s}/{m}")
    spans: list[list[dict]] = [[] for _ in range(s)]

    def busy(stage: int, tick: int, kind: str):
        spans[stage].append({"t0": tick * t_cycle_s,
                             "t1": (tick + 1) * t_cycle_s, "kind": kind})

    if schedule == "gpipe":
        # forward sweep: stage k runs microbatch j at tick j + k; the
        # autodiff reverse schedule mirrors it (same bubble), so one
        # sweep of m + s - 1 ticks IS the schedule's shape.
        for k in range(s):
            for j in range(m):
                busy(k, j + k, "fwd+bwd")
    elif schedule == "1f1b":
        # lockstep realization (pipeline_1f1b_value_and_grad): cycle c
        # runs forward f = c - k on stage k and backward
        # b = c - (2 * s - 2 - k); m + 2 * (s - 1) cycles total.
        for k in range(s):
            for c in range(m + 2 * (s - 1)):
                f, b = c - k, c - (2 * s - 2 - k)
                fwd, bwd = 0 <= f < m, 0 <= b < m
                if fwd or bwd:
                    busy(k, c, "fwd+bwd" if fwd and bwd
                         else "fwd" if fwd else "bwd")
    elif schedule == "interleaved":
        # rows index WORKERS; render from the unit-of-work table so the
        # executable decode arithmetic and the drawn timeline share one
        # source of truth.
        cells: dict = {}
        for e in schedule_table(s, m, "interleaved", interleave=interleave):
            cells.setdefault((e["worker"], e["cycle"]), set()).add(e["lane"])
        for (k, c), lanes in sorted(cells.items()):
            busy(k, c, "fwd+bwd" if len(lanes) == 2 else next(iter(lanes)))
    else:
        raise ValueError(f"unknown schedule {schedule!r}")
    return spans


def schedule_idle_fraction(spans: "list[list[dict]]") -> float:
    """Idle share of a :func:`schedule_spans` timeline: 1 - busy time /
    (stages x makespan). A cycle running only one of its two lanes
    (``fwd`` or ``bwd`` alone in the lockstep 1F1B model) counts
    half-busy. Equals :func:`bubble_fraction` by construction
    (regression-tested in tests/test_pipeline.py)."""
    if not spans:
        return 0.0
    end = max((sp["t1"] for row in spans for sp in row), default=0.0)
    if end <= 0:
        return 0.0
    busy = sum((sp["t1"] - sp["t0"])
               * (1.0 if sp["kind"] == "fwd+bwd" else 0.5)
               for row in spans for sp in row)
    return 1.0 - busy / (len(spans) * end)


def pipeline_1f1b_value_and_grad(stage_fn: Callable, head_fn: Callable,
                                 params_local, head_params,
                                 x_microbatches, targets_microbatches,
                                 *, axis_name: str = "pp",
                                 batch_axes: tuple = ()):
    """1F1B (PipeDream-flush) schedule: loss and grads in ONE interleaved
    forward/backward pipeline sweep. Must run inside a shard_map region
    binding ``axis_name``.

    stage_fn(params, x) -> y: one stage (same shape in/out).
    head_fn(head_params, y, target) -> scalar: per-microbatch loss on the
        LAST stage's output (executed masked on other stages — SPMD).
    params_local: this device's stage parameters (pp axis sliced away).
    head_params: replicated head/loss parameters.
    x_microbatches / targets_microbatches: (n_micro, mb, ...) replicated
        over pp (shard other axes outside).
    batch_axes: data-parallel axes also bound in this region; loss and
        parameter grads are additionally pmean'd over them (global-mean
        objective) and input grads scaled to match.

    Schedule (cycle c, stage s of S, microbatch count M): forward of
    microbatch f = c - s, then backward of b = c - (2S-2-s); the
    backward of each microbatch starts the cycle its forward reaches the
    last stage. Stage inputs are stashed in a min(M, 2S-1)-slot ring and
    rematerialized via ``jax.vjp`` on the backward — O(S) activation
    memory vs GPipe's O(M). Bubble fraction 2(S-1)/(M+2(S-1)) in this
    lockstep realization (see module docstring).

    Returns ``(loss, stage_param_grads_local, head_param_grads,
    x_microbatch_grads)`` — loss is the mean over microbatches (and
    ``batch_axes``), stage grads stay per-device (pp-sharded), head and
    input grads are valid on every device.
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    M = x_microbatches.shape[0]
    mb_shape = x_microbatches.shape[1:]
    S = n_stages
    K = max(1, min(M, 2 * S - 1))
    C = M + 2 * (S - 1)

    perm_fwd = [(i, (i + 1) % S) for i in range(S)]
    perm_bwd = [(i, (i - 1) % S) for i in range(S)]
    x_dtype = x_microbatches.dtype
    is_last = stage == S - 1

    def cycle(carry, c):
        fwd_in, bwd_in, stash, gparams, ghead, gx, loss_sum = carry

        # -- forward sub-tick: microbatch f = c - stage ------------------
        f = c - stage
        active_f = (f >= 0) & (f < M)
        inject = jax.lax.dynamic_index_in_dim(
            x_microbatches, jnp.clip(f, 0, M - 1), axis=0, keepdims=False)
        fwd_in = jnp.where(stage == 0, inject, fwd_in)
        slot_f = jnp.where(active_f, jnp.mod(f, K), 0)
        stash = jnp.where(
            active_f,
            jax.lax.dynamic_update_index_in_dim(
                stash, fwd_in.astype(stash.dtype), slot_f, axis=0),
            stash)
        out = stage_fn(params_local, fwd_in)
        next_fwd_in = jax.lax.ppermute(out, axis_name, perm_fwd)

        # -- backward sub-tick: microbatch b = c - (2S-2-stage) ----------
        b = c - (2 * S - 2 - stage)
        active_b = (b >= 0) & (b < M)
        slot_b = jnp.where(active_b, jnp.mod(b, K), 0)
        binp = jax.lax.dynamic_index_in_dim(stash, slot_b, axis=0,
                                            keepdims=False).astype(x_dtype)
        out_b, stage_vjp = jax.vjp(stage_fn, params_local, binp)
        tgt = jax.lax.dynamic_index_in_dim(
            targets_microbatches, jnp.clip(b, 0, M - 1), axis=0,
            keepdims=False)
        loss_b, head_vjp = jax.vjp(
            lambda hp, y: head_fn(hp, y, tgt), head_params, out_b)
        dhead, dy = head_vjp(jnp.asarray(1.0 / M, loss_b.dtype))
        g_out = jnp.where(is_last, dy, bwd_in)
        g_out = jnp.where(active_b, g_out, jnp.zeros_like(g_out))
        dparams, dx = stage_vjp(g_out)
        gparams = jax.tree_util.tree_map(jnp.add, gparams, dparams)
        take_head = is_last & active_b
        ghead = jax.tree_util.tree_map(
            lambda a, d: a + jnp.where(take_head, d, 0), ghead, dhead)
        loss_sum = loss_sum + jnp.where(
            take_head, loss_b.astype(jnp.float32), 0.0)
        take_x = (stage == 0) & active_b
        gx = jnp.where(
            take_x,
            jax.lax.dynamic_update_index_in_dim(
                gx, dx.astype(gx.dtype), jnp.clip(b, 0, M - 1), axis=0),
            gx)
        next_bwd_in = jax.lax.ppermute(dx, axis_name, perm_bwd)

        return (next_fwd_in, next_bwd_in, stash, gparams, ghead, gx,
                loss_sum), None

    carry0 = (
        jnp.zeros(mb_shape, x_dtype),                        # fwd_in
        jnp.zeros(mb_shape, x_dtype),                        # bwd_in
        jnp.zeros((K,) + mb_shape, x_dtype),                 # stash
        jax.tree_util.tree_map(jnp.zeros_like, params_local),
        jax.tree_util.tree_map(
            lambda p: jnp.zeros(jnp.shape(p), jnp.result_type(p)),
            head_params),
        jnp.zeros((M,) + mb_shape, x_dtype),                 # gx
        jnp.zeros((), jnp.float32),                          # loss_sum
    )
    (_, _, _, gparams, ghead, gx, loss_sum), _ = jax.lax.scan(
        cycle, carry0, jnp.arange(C))

    # loss/head grads live on the last stage, input grads on stage 0:
    # psum broadcasts each to every pp rank (single contributors).
    loss = jax.lax.psum(loss_sum, axis_name) / M
    ghead = jax.tree_util.tree_map(
        lambda g: jax.lax.psum(g, axis_name), ghead)
    gx = jax.lax.psum(gx, axis_name)
    if batch_axes:
        loss = jax.lax.pmean(loss, batch_axes)
        gparams = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, batch_axes), gparams)
        ghead = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, batch_axes), ghead)
        n_batch = 1
        for a in batch_axes:
            n_batch *= jax.lax.psum(1, a)
        gx = gx / n_batch
    return loss, gparams, ghead, gx


def pipeline_interleaved_1f1b_value_and_grad(
        stage_fn: Callable, head_fn: Callable, params_chunks, head_params,
        x_microbatches, targets_microbatches, *, n_chunks: int,
        axis_name: str = "pp", batch_axes: tuple = ()):
    """Interleaved 1F1B (virtual pipeline stages): loss and grads in one
    lockstep sweep. Must run inside a shard_map region binding
    ``axis_name``.

    Worker k of W holds ``n_chunks`` (= v) NON-adjacent model chunks on
    the leading axis of ``params_chunks``: chunk j is model stage
    ``j*W + k``, so a microbatch crosses every worker v times and the
    warmup/drain ramps shrink by ~1/v. Same rings as plain 1F1B
    (forward i->i+1, backward i->i-1) — chunk-boundary hops are the
    same wrap-around hop plain 1F1B already makes, and the schedule
    identities guarantee every wrapped value is either consumed exactly
    one cycle later or masked (stage-0 injection on the forward ring,
    head cotangent on the backward ring).

    Cycle c decode (mixed radix, worker k): forward unit q = c - k ->
    group g = q // (vW), chunk j = (q % vW) // W, offset r = q % W,
    microbatch m = g*W + r of model stage j*W + k; backward unit
    q' = c - (vW-1) - (W-1-k) with the chunk index mirrored
    (j = v-1 - (q' % vW) // W). Stage inputs live in a
    min(Mv, 2vW-1)-slot ring keyed by forward unit number. Requires
    M % W == 0. Total cycles Mv + vW + W - 2 — bubble fraction
    (vW + W - 2)/(Mv + vW + W - 2); v=1 degenerates to plain 1F1B
    exactly (same cycles, same arithmetic).

    Returns ``(loss, chunk_param_grads_local, head_param_grads,
    x_microbatch_grads)`` — chunk grads keep the leading v axis,
    per-worker (pp-sharded); everything else as in
    :func:`pipeline_1f1b_value_and_grad`.
    """
    W = jax.lax.psum(1, axis_name)
    k = jax.lax.axis_index(axis_name)
    v = int(n_chunks)
    if v < 1:
        raise ValueError(f"n_chunks must be >= 1, got {v}")
    M = x_microbatches.shape[0]
    if M % W != 0:
        raise ValueError(
            f"interleaved 1F1B needs n_micro % n_workers == 0, "
            f"got {M} % {W}")
    mb_shape = x_microbatches.shape[1:]
    S_tot = v * W
    K = max(1, min(M * v, 2 * S_tot - 1))
    C = M * v + S_tot + W - 2

    perm_fwd = [(i, (i + 1) % W) for i in range(W)]
    perm_bwd = [(i, (i - 1) % W) for i in range(W)]
    x_dtype = x_microbatches.dtype

    def chunk_params(j):
        return jax.tree_util.tree_map(
            lambda p: jax.lax.dynamic_index_in_dim(
                p, j, axis=0, keepdims=False), params_chunks)

    def cycle(carry, c):
        fwd_in, bwd_in, stash, gparams, ghead, gx, loss_sum = carry

        # -- forward sub-tick -------------------------------------------
        q = c - k
        active_f = (q >= 0) & (q < M * v)
        qc = jnp.clip(q, 0, M * v - 1)
        j_f = (qc % S_tot) // W
        m_f = (qc // S_tot) * W + qc % W
        inject = jax.lax.dynamic_index_in_dim(
            x_microbatches, jnp.clip(m_f, 0, M - 1), axis=0, keepdims=False)
        fwd_in = jnp.where((k == 0) & (j_f == 0), inject, fwd_in)
        slot_f = jnp.where(active_f, qc % K, 0)
        stash = jnp.where(
            active_f,
            jax.lax.dynamic_update_index_in_dim(
                stash, fwd_in.astype(stash.dtype), slot_f, axis=0),
            stash)
        out = stage_fn(chunk_params(j_f), fwd_in)
        next_fwd_in = jax.lax.ppermute(out, axis_name, perm_fwd)

        # -- backward sub-tick ------------------------------------------
        q2 = c - (S_tot - 1) - (W - 1 - k)
        active_b = (q2 >= 0) & (q2 < M * v)
        q2c = jnp.clip(q2, 0, M * v - 1)
        j_b = (v - 1) - (q2c % S_tot) // W
        m_b = (q2c // S_tot) * W + q2c % W
        # forward unit that stashed this chunk's input
        n_b = (q2c // S_tot) * S_tot + j_b * W + q2c % W
        slot_b = jnp.where(active_b, n_b % K, 0)
        binp = jax.lax.dynamic_index_in_dim(stash, slot_b, axis=0,
                                            keepdims=False).astype(x_dtype)
        out_b, stage_vjp = jax.vjp(stage_fn, chunk_params(j_b), binp)
        tgt = jax.lax.dynamic_index_in_dim(
            targets_microbatches, jnp.clip(m_b, 0, M - 1), axis=0,
            keepdims=False)
        loss_b, head_vjp = jax.vjp(
            lambda hp, y: head_fn(hp, y, tgt), head_params, out_b)
        dhead, dy = head_vjp(jnp.asarray(1.0 / M, loss_b.dtype))
        is_head = (k == W - 1) & (j_b == v - 1)
        g_out = jnp.where(is_head, dy, bwd_in)
        g_out = jnp.where(active_b, g_out, jnp.zeros_like(g_out))
        dparams, dx = stage_vjp(g_out)
        gparams = jax.tree_util.tree_map(
            lambda a, d: a.at[jnp.clip(j_b, 0, v - 1)].add(d),
            gparams, dparams)
        take_head = is_head & active_b
        ghead = jax.tree_util.tree_map(
            lambda a, d: a + jnp.where(take_head, d, 0), ghead, dhead)
        loss_sum = loss_sum + jnp.where(
            take_head, loss_b.astype(jnp.float32), 0.0)
        take_x = (k == 0) & (j_b == 0) & active_b
        gx = jnp.where(
            take_x,
            jax.lax.dynamic_update_index_in_dim(
                gx, dx.astype(gx.dtype), jnp.clip(m_b, 0, M - 1), axis=0),
            gx)
        next_bwd_in = jax.lax.ppermute(dx, axis_name, perm_bwd)

        return (next_fwd_in, next_bwd_in, stash, gparams, ghead, gx,
                loss_sum), None

    carry0 = (
        jnp.zeros(mb_shape, x_dtype),                        # fwd_in
        jnp.zeros(mb_shape, x_dtype),                        # bwd_in
        jnp.zeros((K,) + mb_shape, x_dtype),                 # stash
        jax.tree_util.tree_map(jnp.zeros_like, params_chunks),
        jax.tree_util.tree_map(
            lambda p: jnp.zeros(jnp.shape(p), jnp.result_type(p)),
            head_params),
        jnp.zeros((M,) + mb_shape, x_dtype),                 # gx
        jnp.zeros((), jnp.float32),                          # loss_sum
    )
    (_, _, _, gparams, ghead, gx, loss_sum), _ = jax.lax.scan(
        cycle, carry0, jnp.arange(C))

    loss = jax.lax.psum(loss_sum, axis_name) / M
    ghead = jax.tree_util.tree_map(
        lambda g: jax.lax.psum(g, axis_name), ghead)
    gx = jax.lax.psum(gx, axis_name)
    if batch_axes:
        loss = jax.lax.pmean(loss, batch_axes)
        gparams = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, batch_axes), gparams)
        ghead = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, batch_axes), ghead)
        n_batch = 1
        for a in batch_axes:
            n_batch *= jax.lax.psum(1, a)
        gx = gx / n_batch
    return loss, gparams, ghead, gx


def make_interleaved_1f1b_fn(mesh: Mesh, stage_fn: Callable,
                             head_fn: Callable, *, n_chunks: int,
                             axis_name: str = "pp",
                             param_spec: P | None = None,
                             data_spec: P | None = None):
    """shard_map wrapper for
    :func:`pipeline_interleaved_1f1b_value_and_grad`. Stacked params
    carry axes ``(n_workers, n_chunks, ...)`` with the leading worker
    axis sharded over ``axis_name``; grads come back in the same
    layout."""
    if param_spec is None:
        param_spec = P(axis_name)
    if data_spec is None:
        data_spec = P()
    batch_axes = tuple(
        a for a in jax.tree_util.tree_leaves(
            tuple(data_spec), is_leaf=lambda x: isinstance(x, str))
        if isinstance(a, str) and a in mesh.shape)

    def run(stacked_params, head_params, x_mb, targets_mb):
        def inner(params_local, head_params, x_local, t_local):
            params_local = jax.tree_util.tree_map(
                lambda p: jnp.squeeze(p, axis=0), params_local)
            loss, gp, gh, gx = pipeline_interleaved_1f1b_value_and_grad(
                stage_fn, head_fn, params_local, head_params,
                x_local, t_local, n_chunks=n_chunks, axis_name=axis_name,
                batch_axes=batch_axes)
            gp = jax.tree_util.tree_map(
                lambda g: jnp.expand_dims(g, axis=0), gp)
            return loss, gp, gh, gx

        return shard_map(
            inner, mesh=mesh,
            in_specs=(param_spec, P(), data_spec, data_spec),
            out_specs=(P(), param_spec, P(), data_spec),
            check_vma=False)(stacked_params, head_params, x_mb, targets_mb)

    return run


def make_1f1b_fn(mesh: Mesh, stage_fn: Callable, head_fn: Callable, *,
                 axis_name: str = "pp",
                 param_spec: P | None = None,
                 data_spec: P | None = None):
    """shard_map wrapper for :func:`pipeline_1f1b_value_and_grad`:
    ``(stacked_params, head_params, x_microbatches, targets) ->
    (loss, stacked_param_grads, head_grads, x_grads)``. Same stacking
    conventions as :func:`make_pipelined_fn`."""
    if param_spec is None:
        param_spec = P(axis_name)
    if data_spec is None:
        data_spec = P()
    batch_axes = tuple(
        a for a in jax.tree_util.tree_leaves(
            tuple(data_spec), is_leaf=lambda x: isinstance(x, str))
        if isinstance(a, str) and a in mesh.shape)

    def run(stacked_params, head_params, x_mb, targets_mb):
        def inner(params_local, head_params, x_local, t_local):
            params_local = jax.tree_util.tree_map(
                lambda p: jnp.squeeze(p, axis=0), params_local)
            loss, gp, gh, gx = pipeline_1f1b_value_and_grad(
                stage_fn, head_fn, params_local, head_params,
                x_local, t_local, axis_name=axis_name,
                batch_axes=batch_axes)
            gp = jax.tree_util.tree_map(
                lambda g: jnp.expand_dims(g, axis=0), gp)
            return loss, gp, gh, gx

        return shard_map(
            inner, mesh=mesh,
            in_specs=(param_spec, P(), data_spec, data_spec),
            out_specs=(P(), param_spec, P(), data_spec),
            check_vma=False)(stacked_params, head_params, x_mb, targets_mb)

    return run


def make_pipelined_fn(mesh: Mesh, stage_fn: Callable, *,
                      axis_name: str = "pp",
                      param_spec: P | None = None,
                      data_spec: P | None = None):
    """shard_map wrapper: (stacked_params, x_microbatches) -> outputs.

    stacked_params: pytree with leading axis n_stages, sharded over "pp".
    x_microbatches: (n_micro, mb, ...), replicated over "pp" (shard other
        mesh axes via ``data_spec``).
    """
    n_stages = mesh.shape[axis_name]
    if param_spec is None:
        param_spec = P(axis_name)
    if data_spec is None:
        data_spec = P()

    def run(stacked_params, x_mb):
        def inner(params_local, x_local):
            # shard_map leaves the (sliced) leading stage axis of size 1.
            params_local = jax.tree_util.tree_map(
                lambda p: jnp.squeeze(p, axis=0), params_local)
            return pipeline_apply(stage_fn, params_local, x_local,
                                  axis_name=axis_name)

        return shard_map(
            inner, mesh=mesh,
            in_specs=(param_spec, data_spec),
            out_specs=data_spec,
            check_vma=False)(stacked_params, x_mb)

    return run


def stack_stage_params(per_stage_params: list):
    """[stage0_tree, stage1_tree, ...] -> one tree with leading pp axis."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *per_stage_params)


def place_stacked_params(stacked, mesh: Mesh, axis_name: str = "pp"):
    """Device_put the stacked params so each pp rank owns its stage."""
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh, P(axis_name))), stacked)
