"""Mixture-of-Experts with expert parallelism over the "ep" mesh axis.

The reference has NO expert parallelism (SURVEY.md §2.8: EP "out of scope
unless fork adds it" — nothing in its distribute layer). TPU-native MoE
here uses the Mesh-TF/GSPMD dispatch formulation: a capacity-bounded
one-hot dispatch tensor turns token routing into two einsums, and
sharding expert weights + expert-major activations over "ep" makes GSPMD
lower the dispatch/combine einsums to all-to-alls over ICI — the same
communication pattern hand-written EP frameworks schedule manually.

Layer: Switch-style top-1 routing (optionally top-2), fp32 router,
load-balancing auxiliary loss (Shazeer et al.), capacity factor with
token dropping.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn
from flax.linen import partitioning as nn_partitioning

param_with_axes = nn_partitioning.param_with_axes

# Logical axes for MoE; merge with a model's rules as needed.
MOE_AXIS_RULES = (
    ("expert", "ep"),
    ("expert_mlp", None),
    ("expert_embed", None),
)


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    d_model: int = 64
    d_ff: int = 128
    capacity_factor: float = 1.25
    top_k: int = 1
    aux_loss_weight: float = 0.01
    dtype: Any = jnp.float32
    # Mesh for the expert-sharding constraints: this flax/jax pairing
    # only honors logical constraints when the mesh is passed explicitly
    # (``with mesh:`` does not set the abstract-mesh context flax
    # checks) — see models/transformer.py with_sharding_constraint.
    mesh: Any = None


class MoELayer(nn.Module):
    """Switch-style MoE FFN. Call: (B, S, D) -> ((B, S, D), aux_loss)."""
    cfg: MoEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, S, D = x.shape
        E = cfg.num_experts
        T = B * S
        C = max(1, int(cfg.capacity_factor * T * cfg.top_k / E))

        tokens = x.reshape(T, D)

        router_w = param_with_axes(
            "router", nn.initializers.normal(0.02), (D, E), jnp.float32,
            axes=("expert_embed", "expert"))
        logits = jnp.dot(tokens.astype(jnp.float32), router_w)   # (T, E)
        probs = jax.nn.softmax(logits, axis=-1)

        # Top-k expert choice per token.
        gate_vals, expert_idx = jax.lax.top_k(probs, cfg.top_k)  # (T, k)

        # Capacity-bounded position of each token within its expert:
        # rank tokens per expert by (k-slot, arrival order) — all 1st
        # choices fill an expert's slots before any 2nd choice does
        # (Mesh-TF/Switch formulation), so pass k's positions are offset
        # by the per-expert token counts from passes < k.
        combine = jnp.zeros((T, E, C), jnp.float32)
        aux_me = jnp.mean(probs, axis=0)                         # (E,)
        frac_tokens = jnp.zeros((E,), jnp.float32)
        prior_count = jnp.zeros((E,), jnp.float32)
        for k in range(cfg.top_k):
            e_k = expert_idx[:, k]                               # (T,)
            onehot = jax.nn.one_hot(e_k, E, dtype=jnp.float32)   # (T, E)
            pos = (jnp.cumsum(onehot, axis=0) - 1.0
                   + prior_count[None, :]) * onehot              # (T, E)
            pos_k = jnp.sum(pos, axis=-1)                        # (T,)
            prior_count = prior_count + jnp.sum(onehot, axis=0)
            keep = pos_k < C
            gate = gate_vals[:, k] * keep
            pos_oh = jax.nn.one_hot(pos_k.astype(jnp.int32), C,
                                    dtype=jnp.float32)           # (T, C)
            combine = combine + (gate[:, None, None]
                                 * onehot[:, :, None]
                                 * pos_oh[:, None, :])
            frac_tokens = frac_tokens + jnp.mean(onehot, axis=0)
        dispatch = (combine > 0).astype(x.dtype)                 # (T, E, C)

        # Load-balancing aux loss (Switch Transformer eq. 4).
        aux_loss = (cfg.aux_loss_weight * E
                    * jnp.sum(frac_tokens / cfg.top_k * aux_me))

        wi = param_with_axes("wi", nn.initializers.normal(D ** -0.5),
                             (E, D, cfg.d_ff), jnp.float32,
                             axes=("expert", "expert_embed", "expert_mlp"))
        wo = param_with_axes("wo", nn.initializers.normal(cfg.d_ff ** -0.5),
                             (E, cfg.d_ff, D), jnp.float32,
                             axes=("expert", "expert_mlp", "expert_embed"))

        # Dispatch: (T,D),(T,E,C) -> (E,C,D). Expert-major tensors are
        # ep-sharded; GSPMD inserts the all-to-all over ICI.
        expert_in = jnp.einsum("td,tec->ecd", tokens, dispatch)
        expert_in = nn_partitioning.with_sharding_constraint(
            expert_in, ("expert", None, None), mesh=cfg.mesh)
        h = jnp.einsum("ecd,edf->ecf", expert_in, wi.astype(x.dtype))
        h = nn.gelu(h)
        expert_out = jnp.einsum("ecf,efd->ecd", h, wo.astype(x.dtype))
        expert_out = nn_partitioning.with_sharding_constraint(
            expert_out, ("expert", None, None), mesh=cfg.mesh)

        # Combine back to token order, weighted by gates.
        out = jnp.einsum("ecd,tec->td", expert_out,
                         combine.astype(x.dtype))
        return out.reshape(B, S, D), aux_loss
