"""ShardedVariable and partitioners.

TPU-native counterpart of tensorflow/python/distribute/sharded_variable.py
(SURVEY.md §2.3): first-axis div-sharding of large (embedding) variables.
The reference materializes N separate ``tf.Variable`` shards placed
round-robin on parameter servers (parameter_server_strategy_v2.py:872); here
a ShardedVariable is ONE ``jax.Array`` sharded on axis 0 across a mesh axis
— XLA partitions the lookup/apply, and per-shard views are still addressable
for the PS/coordinator path and for sharded checkpointing.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.parallel.values import (
    DistributedVariable,
    VariableAggregation,
    VariableSynchronization,
)


class Partitioner:
    """Base partitioner (≙ sharded_variable.py:47 ``Partitioner``).

    Callable: ``partitioner(shape, dtype) -> list[int]`` with one entry per
    axis; exactly one axis may have >1 partitions (axis-0 div sharding, the
    reference's supported form).
    """

    def __call__(self, shape, dtype) -> list[int]:
        raise NotImplementedError

    @staticmethod
    def _dtype_size(dtype) -> int:
        return jnp.dtype(dtype).itemsize


class FixedShardsPartitioner(Partitioner):
    """≙ sharded_variable.py:84."""

    def __init__(self, num_shards: int):
        self.num_shards = num_shards

    def __call__(self, shape, dtype) -> list[int]:
        result = [1] * len(shape)
        result[0] = min(self.num_shards, shape[0])
        return result


class MinSizePartitioner(Partitioner):
    """≙ sharded_variable.py:115: as many shards as possible while keeping
    each shard at least ``min_shard_bytes``."""

    def __init__(self, min_shard_bytes: int = 256 << 10, max_shards: int = 1):
        if min_shard_bytes < 1:
            raise ValueError("min_shard_bytes must be positive")
        self.min_shard_bytes = min_shard_bytes
        self.max_shards = max_shards

    def __call__(self, shape, dtype) -> list[int]:
        total = math.prod(shape) * self._dtype_size(dtype)
        shards = min(self.max_shards, max(1, total // self.min_shard_bytes),
                     shape[0] if shape else 1)
        result = [1] * len(shape)
        result[0] = max(1, int(shards))
        return result


class MaxSizePartitioner(Partitioner):
    """≙ sharded_variable.py:176: as few shards as possible while keeping
    each shard at most ``max_shard_bytes``."""

    def __init__(self, max_shard_bytes: int, max_shards: int | None = None):
        if max_shard_bytes < 1:
            raise ValueError("max_shard_bytes must be positive")
        self.max_shard_bytes = max_shard_bytes
        self.max_shards = max_shards

    def __call__(self, shape, dtype) -> list[int]:
        total = math.prod(shape) * self._dtype_size(dtype)
        shards = max(1, -(-total // self.max_shard_bytes))  # ceil div
        if self.max_shards is not None:
            shards = min(shards, self.max_shards)
        shards = min(shards, shape[0] if shape else 1)
        result = [1] * len(shape)
        result[0] = int(shards)
        return result


class ShardedVariable(DistributedVariable):
    """Axis-0 sharded variable (≙ sharded_variable.py:843).

    ``shard_axis_name`` picks the mesh axis the rows are divided over. The
    number of *logical* shards (``num_shards``, from the partitioner) is
    recorded for checkpoint layout parity, but physically XLA divides rows
    evenly over the mesh axis.
    """

    def __init__(self, value, *, mesh: Mesh, shard_axis_name: str,
                 num_shards: int | None = None, name=None,
                 trainable: bool = True, dtype=None):
        if shard_axis_name not in mesh.shape:
            raise ValueError(
                f"axis {shard_axis_name!r} not in mesh {tuple(mesh.shape)}")
        self.shard_axis_name = shard_axis_name
        self.num_shards = num_shards or mesh.shape[shard_axis_name]
        value = jnp.asarray(value, dtype=dtype)
        if value.ndim < 1:
            raise ValueError("ShardedVariable requires rank >= 1")
        self._pad_rows = (-value.shape[0]) % mesh.shape[shard_axis_name]
        self._num_rows = value.shape[0]
        if self._pad_rows:
            value = jnp.pad(value,
                            [(0, self._pad_rows)] + [(0, 0)] * (value.ndim - 1))
        spec = P(shard_axis_name)
        super().__init__(
            value, name=name, mesh=mesh, spec=spec, trainable=trainable,
            synchronization=VariableSynchronization.ON_WRITE,
            aggregation=VariableAggregation.NONE, dtype=dtype)

    @property
    def shape(self):
        # logical (unpadded) shape
        full = self._value.shape
        return (self._num_rows,) + tuple(full[1:])

    def read_value(self) -> jax.Array:
        v = super().read_value()
        if self._pad_rows:
            # gather to replicated before the unpadding slice — a partial
            # slice of a row-sharded array has no unambiguous sharding
            v = jax.device_put(v, NamedSharding(self._mesh, P()))
            v = v[: self._num_rows]
        return v

    def assign(self, value) -> "ShardedVariable":
        value = jnp.asarray(value, dtype=self.dtype)
        if value.shape != self.shape:
            raise ValueError(
                f"assign shape {value.shape} != variable shape {self.shape}")
        if self._pad_rows:
            value = jnp.pad(value,
                            [(0, self._pad_rows)] + [(0, 0)] * (value.ndim - 1))
        value = jax.device_put(value, NamedSharding(self._mesh, self._spec))
        self._value = value
        return self

    @property
    def variables(self) -> list[np.ndarray]:
        """Per-logical-shard views (≙ ShardedVariable.variables) — used by
        the checkpoint layer to save shards as slices of one logical tensor
        (sharded_variable save-slice behavior, SURVEY §5.4)."""
        rows = self.shape[0]
        per = -(-rows // self.num_shards)
        full = np.asarray(self.read_value())
        return [full[i * per: min((i + 1) * per, rows)]
                for i in range(self.num_shards)]

    def embedding_lookup(self, ids) -> jax.Array:
        """Sharded gather (≙ sharded_variable.embedding_lookup,
        sharded_variable.py:995). XLA partitions the gather across the
        shard axis; the result is materialized where the batch needs it
        (replicated by default — pass through jit with sharding constraints
        for a data-sharded result)."""
        try:
            return jnp.take(self._value, ids, axis=0)
        except Exception:
            # eager gather over a row-sharded operand needs an explicit
            # output sharding
            return self._value.at[ids].get(
                out_sharding=NamedSharding(self._mesh, P()))
