"""Host offload of the 1F1B activation stash.

The fused 1F1B schedule (parallel/pipeline.py) keeps a
min(M, 2S-1)-slot ring of stage inputs on DEVICE between a
microbatch's forward and its backward. This module re-realizes the
same schedule as a host-driven loop over cycles so that ring moves to
HOST memory: one jitted cycle program (the cycle index is a traced
scalar — a single compilation serves every cycle) emits each rank's
stashed stage input, the host spills it (``copy_to_host_async`` — the
copy overlaps the next cycle's dispatch, riding the same async-dispatch
machinery the hybrid-mesh collectives use), and re-feeds it exactly
2(S-1-k) cycles later when rank k's backward needs it. Device-side
activation residency drops from O(min(M, 2S-1)) microbatches per rank
to O(1): the current cycle's input and output.

Schedule identities (same as the fused body): rank k runs forward
f = c - k and backward b = c - (2S-2-k) at cycle c; the input of
backward b at rank k was rank k's forward input at cycle b + k =
c - 2(S-1-k). Rank S-1's spill round-trip would be same-cycle, so its
backward reads its own forward input directly in-body and its rows
never touch the store.

The arithmetic inside the cycle program is the fused scan body's,
accumulated in the same order (each per-cycle psum has exactly one
non-zero contributor, and adding zeros is exact in IEEE float), and a
device->host->device round trip preserves bits — so turning the spill
on (host stash) vs off (device stash, ``spill=False``) is bit-identical
end to end, which tests/test_offload.py pins. Against the FUSED
single-jit 1F1B step the losses are bit-identical too, but final
params agree only to float tolerance: the embed-grad scatter-add and
optimizer fuse differently in one whole-step XLA program than in the
split programs here (~1e-9 — the same program-structure artifact the
ZeRO tests document; see parallel/zero.py).

Failure surface: every spill passes the ``offload.spill`` chaos fault
site (resilience/faults.py). A failed spill is retried once; a double
failure is recorded and surfaces as :class:`OffloadSpillError` at the
cycle that needs the lost activation — the consumer sees a clean,
attributable error, never a hang or silently wrong activations
(tools/chaos_sweep.py --offload gates this).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.resilience import faults


class OffloadSpillError(RuntimeError):
    """An activation spill failed (twice) and its consumer needed it."""


class _FailedSpill:
    __slots__ = ("error",)

    def __init__(self, error: BaseException):
        self.error = error


class ActivationSpillStore:
    """Host-side store of per-cycle activation stash entries.

    ``put`` starts an async device->host copy and keeps the handle (the
    host transfer overlaps subsequent device work; ``get`` materializes
    it, by then usually complete). Entries older than the longest
    consumer distance are dropped so host residency is O(S) entries.
    """

    def __init__(self, *, spill: bool = True):
        self.spill = bool(spill)
        self._entries: dict[int, object] = {}
        self.puts = 0
        self.retries = 0
        self.failures = 0
        self.spilled_bytes = 0

    def put(self, cycle: int, value) -> None:
        self.puts += 1
        err: BaseException | None = None
        for attempt in (0, 1):
            try:
                faults.fire("offload.spill", tag=f"c{cycle}")
                if self.spill:
                    value.copy_to_host_async()
                if attempt:
                    self.retries += 1
                self._entries[cycle] = value
                return
            except Exception as e:  # FaultInjected or a real copy failure
                err = e
        self.failures += 1
        self._entries[cycle] = _FailedSpill(err)

    def get(self, cycle: int):
        entry = self._entries.get(cycle)
        if isinstance(entry, _FailedSpill):
            raise OffloadSpillError(
                f"activation stash entry for cycle {cycle} was lost: "
                f"its spill failed twice") from entry.error
        if entry is None:
            raise OffloadSpillError(
                f"activation stash entry for cycle {cycle} is missing "
                f"(already dropped or never spilled)")
        if self.spill:
            arr = np.asarray(entry)
            self.spilled_bytes += arr.nbytes
            return arr
        return entry

    def drop_through(self, cycle: int) -> None:
        """Free every entry with key <= cycle."""
        for key in [k for k in self._entries if k <= cycle]:
            del self._entries[key]

    def __len__(self) -> int:
        return len(self._entries)


class Offloaded1F1B:
    """Host-driven 1F1B with the activation stash spilled to host.

    Same contract as :func:`parallel.pipeline.make_1f1b_fn`:
    ``value_and_grads(stacked_params, head_params, x_microbatches,
    targets_microbatches) -> (loss, stacked_param_grads, head_grads,
    x_grads)``, with stage params stacked on a leading pp-sharded axis.
    ``spill=False`` keeps the stash entries as device arrays (the host
    loop and every compiled program are unchanged — only the residency
    moves), which is the control arm of the on/off bit-identity test.
    """

    def __init__(self, mesh: Mesh, stage_fn: Callable, head_fn: Callable,
                 *, axis_name: str = "pp",
                 param_spec: P | None = None,
                 data_spec: P | None = None,
                 spill: bool = True):
        self.mesh = mesh
        self.axis_name = axis_name
        self.S = mesh.shape[axis_name]
        self.stage_fn = stage_fn
        self.head_fn = head_fn
        self.param_spec = P(axis_name) if param_spec is None else param_spec
        self.data_spec = P() if data_spec is None else data_spec
        self.spill = bool(spill)
        self.batch_axes = tuple(
            a for a in jax.tree_util.tree_leaves(
                tuple(self.data_spec),
                is_leaf=lambda x: isinstance(x, str))
            if isinstance(a, str) and a in mesh.shape)
        # activation arrays (S|M, mb, ...) share the data_spec's
        # microbatch-dim sharding behind their leading axis
        rest = tuple(self.data_spec)[1:]
        self.act_spec = P(axis_name, *rest)
        self._cycle_jit = None
        self._finalize_jit = None
        self.last_stats: dict = {}

    # -- compiled programs -------------------------------------------------

    def _build(self):
        S = self.S
        axis_name = self.axis_name
        stage_fn, head_fn = self.stage_fn, self.head_fn
        batch_axes = self.batch_axes
        perm_fwd = [(i, (i + 1) % S) for i in range(S)]
        perm_bwd = [(i, (i - 1) % S) for i in range(S)]

        def cycle(params_local, head_params, x_mb, t_mb, carry,
                  stash_in, c):
            params_local = jax.tree_util.tree_map(
                lambda p: jnp.squeeze(p, axis=0), params_local)
            fwd_in, bwd_in, gparams, ghead, gx, loss_sum = carry
            fwd_in = jnp.squeeze(fwd_in, axis=0)
            bwd_in = jnp.squeeze(bwd_in, axis=0)
            gparams = jax.tree_util.tree_map(
                lambda g: jnp.squeeze(g, axis=0), gparams)
            stash_loc = jnp.squeeze(stash_in, axis=0)
            stage = jax.lax.axis_index(axis_name)
            M = x_mb.shape[0]
            x_dtype = x_mb.dtype
            is_last = stage == S - 1

            # forward sub-tick (identical to the fused body)
            f = c - stage
            inject = jax.lax.dynamic_index_in_dim(
                x_mb, jnp.clip(f, 0, M - 1), axis=0, keepdims=False)
            fwd_in = jnp.where(stage == 0, inject, fwd_in)
            stash_out = fwd_in  # spilled by the host after this cycle
            out = stage_fn(params_local, fwd_in)
            next_fwd_in = jax.lax.ppermute(out, axis_name, perm_fwd)

            # backward sub-tick: rank S-1's stash round-trip would be
            # same-cycle, so it reads its own forward input directly
            b = c - (2 * S - 2 - stage)
            active_b = (b >= 0) & (b < M)
            binp = jnp.where(is_last, fwd_in,
                             stash_loc.astype(x_dtype))
            out_b, stage_vjp = jax.vjp(stage_fn, params_local, binp)
            tgt = jax.lax.dynamic_index_in_dim(
                t_mb, jnp.clip(b, 0, M - 1), axis=0, keepdims=False)
            loss_b, head_vjp = jax.vjp(
                lambda hp, y: head_fn(hp, y, tgt), head_params, out_b)
            dhead, dy = head_vjp(jnp.asarray(1.0 / M, loss_b.dtype))
            g_out = jnp.where(is_last, dy, bwd_in)
            g_out = jnp.where(active_b, g_out, jnp.zeros_like(g_out))
            dparams, dx = stage_vjp(g_out)
            gparams = jax.tree_util.tree_map(jnp.add, gparams, dparams)
            take_head = is_last & active_b
            # every psum below has exactly ONE non-zero contributor per
            # cycle, so per-cycle reduction == the fused end-of-scan
            # psum bit-for-bit (adding zeros is exact)
            ghead = jax.tree_util.tree_map(
                lambda a, d: a + jax.lax.psum(
                    jnp.where(take_head, d, 0), axis_name), ghead, dhead)
            loss_sum = loss_sum + jax.lax.psum(
                jnp.where(take_head, loss_b.astype(jnp.float32), 0.0),
                axis_name)
            take_x = (stage == 0) & active_b
            dx0 = jax.lax.psum(
                jnp.where(take_x, dx, jnp.zeros_like(dx)), axis_name)
            b0 = c - (2 * S - 2)
            gx = jnp.where(
                (b0 >= 0) & (b0 < M),
                jax.lax.dynamic_update_index_in_dim(
                    gx, dx0.astype(gx.dtype), jnp.clip(b0, 0, M - 1),
                    axis=0),
                gx)
            next_bwd_in = jax.lax.ppermute(dx, axis_name, perm_bwd)

            carry = (jnp.expand_dims(next_fwd_in, 0),
                     jnp.expand_dims(next_bwd_in, 0),
                     jax.tree_util.tree_map(
                         lambda g: jnp.expand_dims(g, 0), gparams),
                     ghead, gx, loss_sum)
            return carry, jnp.expand_dims(stash_out, 0)

        carry_specs = (self.act_spec, self.act_spec, self.param_spec,
                       P(), self.data_spec, P())
        cycle_sm = jax.shard_map(
            cycle, mesh=self.mesh,
            in_specs=(self.param_spec, P(), self.data_spec,
                      self.data_spec, carry_specs, self.act_spec, P()),
            out_specs=(carry_specs, self.act_spec),
            check_vma=False)
        self._cycle_jit = jax.jit(cycle_sm)

        def finalize(carry):
            _, _, gparams, ghead, gx, loss_sum = carry
            loss = loss_sum / gx.shape[0]
            if batch_axes:
                loss = jax.lax.pmean(loss, batch_axes)
                gparams = jax.tree_util.tree_map(
                    lambda g: jax.lax.pmean(g, batch_axes), gparams)
                ghead = jax.tree_util.tree_map(
                    lambda g: jax.lax.pmean(g, batch_axes), ghead)
                n_batch = 1
                for a in batch_axes:
                    n_batch *= jax.lax.psum(1, a)
                gx = gx / n_batch
            return loss, gparams, ghead, gx

        finalize_sm = jax.shard_map(
            finalize, mesh=self.mesh,
            in_specs=(carry_specs,),
            out_specs=(P(), self.param_spec, P(), self.data_spec),
            check_vma=False)
        self._finalize_jit = jax.jit(finalize_sm)

    # -- host loop ---------------------------------------------------------

    def value_and_grads(self, stacked_params, head_params, x_mb, t_mb):
        from distributed_tensorflow_tpu import telemetry

        if self._cycle_jit is None:
            self._build()
        S = self.S
        M = x_mb.shape[0]
        mb_shape = tuple(x_mb.shape[1:])
        C = M + 2 * (S - 1)
        dtype = x_mb.dtype
        act_sharding = NamedSharding(self.mesh, self.act_spec)
        carry = (
            jax.device_put(jnp.zeros((S,) + mb_shape, dtype),
                           act_sharding),
            jax.device_put(jnp.zeros((S,) + mb_shape, dtype),
                           act_sharding),
            jax.tree_util.tree_map(jnp.zeros_like, stacked_params),
            jax.tree_util.tree_map(
                lambda p: jnp.zeros(jnp.shape(p), jnp.result_type(p)),
                head_params),
            jax.device_put(
                jnp.zeros((M,) + mb_shape, dtype),
                NamedSharding(self.mesh, self.data_spec)),
            jnp.zeros((), jnp.float32),
        )
        store = ActivationSpillStore(spill=self.spill)
        for c in range(C):
            stash_in = self._assemble(store, c, S, M, mb_shape, dtype)
            carry, stash_out = self._cycle_jit(
                stacked_params, head_params, x_mb, t_mb, carry,
                stash_in, jnp.asarray(c, jnp.int32))
            store.put(c, stash_out)
            # entries older than the longest consumer distance are dead
            store.drop_through(c - 2 * (S - 1))
        loss, gparams, ghead, gx = self._finalize_jit(carry)
        self.last_stats = {
            "cycles": C, "puts": store.puts, "retries": store.retries,
            "failures": store.failures,
            "spilled_bytes": store.spilled_bytes,
            "resident_entries": len(store)}
        telemetry.event("offload.step", spill=self.spill,
                        **self.last_stats)
        return loss, gparams, ghead, gx

    def _assemble(self, store: ActivationSpillStore, c: int, S: int,
                  M: int, mb_shape: tuple, dtype):
        """Stash rows each rank's backward reads at cycle c: rank k's
        entry was written at cycle c - 2(S-1-k). Rank S-1 reads in-body
        and its row stays zero."""
        if self.spill:
            rows = np.zeros((S,) + mb_shape, jnp.dtype(dtype).name)
            for k in range(S - 1):
                b = c - (2 * S - 2 - k)
                if 0 <= b < M:
                    rows[k] = store.get(c - 2 * (S - 1 - k))[k]
            return rows
        rows = jnp.zeros((S,) + mb_shape, dtype)
        for k in range(S - 1):
            b = c - (2 * S - 2 - k)
            if 0 <= b < M:
                entry = store.get(c - 2 * (S - 1 - k))
                rows = rows.at[k].set(jnp.asarray(entry)[k])
        return rows
