"""OneDeviceStrategy — trivial single-device strategy for API conformance.

≙ tensorflow/python/distribute/one_device_strategy.py (SURVEY.md §2.1).
"""

from __future__ import annotations

import numpy as np
from jax.sharding import Mesh

import jax

from distributed_tensorflow_tpu.parallel.strategy import Strategy


class OneDeviceStrategy(Strategy):
    """All variables and computation on one device (≙ one_device_strategy.py:~40)."""

    def __init__(self, device=None):
        if device is None:
            device = jax.devices()[0]
        elif isinstance(device, str):
            # accept "tpu:0"-style strings for parity with "/gpu:0"
            kind, _, idx = device.lower().rpartition(":")
            idx = int(idx) if idx.isdigit() else 0
            kind = kind.strip("/").replace("device:", "") or None
            devs = jax.devices(kind) if kind not in (None, "") else jax.devices()
            device = devs[idx]
        mesh = Mesh(np.array([device], dtype=object), ("dp",))
        super().__init__(mesh=mesh, data_axis_names=("dp",))
        self.device = device
