"""MirroredStrategy — synchronous data parallelism on local devices.

≙ tensorflow/python/distribute/mirrored_strategy.py:200 (SURVEY.md §2.1).

The reference replicates the graph and runs one Python thread per device
with a merge_call rendezvous (mirrored_run.py:289). Here the strategy is a
thin configuration over the shared SPMD core: a 1-axis mesh over the local
devices, variables replicated (mirrored = replicated NamedSharding), and
``run`` compiling a single program whose gradient sync is an ICI psum.

Gradient sync under ``Model.fit`` uses the strategy's
:meth:`Strategy.gradient_bucketer` by default on >1 device:
reverse-layer-order bucketed allreduce (≙ the reference NcclAllReduce's
pack-by-size, cross_device_utils.py:436) so late-layer buckets reduce
while early layers are still differentiating. Tune the bucket size via
``communication_options.bytes_per_pack`` (0 = the 4 MiB default).
"""

from __future__ import annotations

from typing import Sequence

import jax

from distributed_tensorflow_tpu.cluster import topology as topo_lib
from distributed_tensorflow_tpu.parallel.collectives import CommunicationOptions
from distributed_tensorflow_tpu.parallel.cross_device_ops import CrossDeviceOps
from distributed_tensorflow_tpu.parallel.strategy import Strategy


class MirroredStrategy(Strategy):
    """Sync data-parallel over the given (default: all local) devices."""

    def __init__(self, devices: Sequence | None = None,
                 cross_device_ops: CrossDeviceOps | None = None,
                 communication_options: CommunicationOptions | None = None):
        if devices is None:
            devices = jax.local_devices()
        devices = [self._resolve(d) for d in devices]
        mesh = topo_lib.make_mesh({topo_lib.DATA_AXIS: len(devices)},
                                  devices=devices)
        super().__init__(mesh=mesh, data_axis_names=(topo_lib.DATA_AXIS,),
                         cross_device_ops=cross_device_ops,
                         communication_options=communication_options)

    @staticmethod
    def _resolve(d):
        if not isinstance(d, str):
            return d
        kind, _, idx = d.lower().rpartition(":")
        idx = int(idx) if idx.isdigit() else 0
        kind = kind.strip("/").replace("device:", "")
        devs = jax.devices(kind) if kind else jax.devices()
        return devs[idx]
