"""ZeRO-1/2: optimizer-state (and gradient) sharding over data parallel.

The reference's ``ShardedVariable``/ParameterServer layer
(sharded_variable.py:843) is the ancestral form of training-state
sharding: variables partitioned across stores, each optimizer update
touching only the owning shard. This module is the modern descendant
for a synchronous dp mesh — the ZeRO family (Rajbhandari et al.):

- **ZeRO-1**: gradients are still all-reduced (full grads everywhere,
  bit-identical to the replicated path), but Adam's mu/nu slots exist
  only for this rank's 1/N slice of the parameters. After the sliced
  update, an all-gather over dp rebuilds the full parameters. State
  per device: 4P param bytes + 8P/N slot bytes (f32 slots).
- **ZeRO-2**: the gradient bucket is reduce-scattered instead — each
  rank only ever materializes its grad shard, saving the full-gradient
  buffer as well as the slots.

Exactness by construction: parameters pack into the same dtype-pure
buckets ``GradientBucketer`` uses for gradient sync
(collectives.plan_buckets — packing concatenates, never casts), and
every transform in the AdamW chain (scale_by_adam, add_decayed_weights,
scale-by-lr, apply_updates) is elementwise given the shared step count,
so running ``optax.adamw`` on flat bucket shards produces exactly the
bits the replicated tree update produces for those elements. The
reduce-scatter uses the same packed buffer the bucketed allreduce
would, so ZeRO-2 grads are the replicated grads' own slices
(``lax.psum_scatter`` + /N vs ``pmean``-then-slice is bitwise tested
in tests/test_collectives.py). tests/test_zero.py pins params
bit-identical to replicated Adam after N steps.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.parallel.collectives import (
    DEFAULT_BYTES_PER_PACK, ReduceOp, plan_buckets, reduce_scatter)


class ZeroPartition:
    """Static ZeRO partition plan over a flat list of parameter leaves.

    Leaves pack into the same dtype-pure buckets ``GradientBucketer``
    plans for gradient sync (reverse layer order), each bucket
    flattened to one 1-D vector zero-padded to a multiple of
    ``n_shards``. Rank r owns the r-th equal slice of every bucket.
    Padding elements stay zero under AdamW (zero grad, zero param ->
    zero update), so they are inert forever.
    """

    def __init__(self, leaves: Sequence, n_shards: int, *,
                 bytes_per_pack: int = DEFAULT_BYTES_PER_PACK,
                 reverse: bool = True):
        self.n_shards = int(n_shards)
        if self.n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        self.shapes = [tuple(jnp.shape(x)) for x in leaves]
        self.sizes = [int(np.prod(s)) if s else 1 for s in self.shapes]
        self.dtypes = [jnp.dtype(jnp.result_type(x)) for x in leaves]
        self.buckets = plan_buckets(self.sizes, self.dtypes,
                                    bytes_per_pack, reverse=reverse)
        self.bucket_sizes = [sum(self.sizes[i] for i in b)
                             for b in self.buckets]
        self.padded_sizes = [s + (-s) % self.n_shards
                             for s in self.bucket_sizes]
        self.shard_sizes = [p // self.n_shards for p in self.padded_sizes]
        self.bucket_dtypes = [self.dtypes[b[0]] for b in self.buckets]

    def pack(self, leaves: Sequence) -> list:
        """Leaves -> per-bucket flat padded 1-D vectors."""
        flats = []
        for b, bucket in enumerate(self.buckets):
            flat = jnp.concatenate(
                [jnp.ravel(jnp.asarray(leaves[i])) for i in bucket])
            pad = self.padded_sizes[b] - self.bucket_sizes[b]
            if pad:
                flat = jnp.pad(flat, (0, pad))
            flats.append(flat)
        return flats

    def unpack(self, flats: Sequence) -> list:
        """Per-bucket flat vectors (padded) -> leaves."""
        out: list = [None] * len(self.sizes)
        for b, bucket in enumerate(self.buckets):
            off = 0
            for i in bucket:
                out[i] = jnp.reshape(flats[b][off:off + self.sizes[i]],
                                     self.shapes[i])
                off += self.sizes[i]
        return out

    def shard(self, flats: Sequence, rank) -> list:
        """This rank's slice of each packed bucket (rank may be traced)."""
        return [lax.dynamic_slice_in_dim(f, rank * s, s)
                for f, s in zip(flats, self.shard_sizes)]

    def reduce_scatter_mean(self, leaves: Sequence, axis_name: str) -> list:
        """ZeRO-2 gradient sync: pack each bucket and reduce-scatter it
        over ``axis_name`` — this rank receives only its mean-reduced
        shard; the full gradient bucket never materializes. Bitwise
        equal to pmean-then-slice of the same packed buffer."""
        return [reduce_scatter(f, axis_name, axis=0, op=ReduceOp.MEAN)
                for f in self.pack(leaves)]

    def all_gather_flats(self, shards: Sequence, axis_name: str) -> list:
        return [lax.all_gather(s, axis_name, axis=0, tiled=True)
                for s in shards]

    def shard_templates(self) -> list:
        return [jax.ShapeDtypeStruct((s,), dt)
                for s, dt in zip(self.shard_sizes, self.bucket_dtypes)]

    def summary(self) -> dict:
        return {"n_shards": self.n_shards,
                "buckets": len(self.buckets),
                "elements": sum(self.bucket_sizes),
                "padded_elements": sum(self.padded_sizes),
                "shard_elements": sum(self.shard_sizes)}


def zero_opt_state(tx, partition: ZeroPartition, mesh: Mesh,
                   axes: tuple | None = None):
    """Materialize the sharded optimizer state + shardings + specs.

    The optax state over bucket shards is structurally
    (count, mu=[shards], nu=[shards], ...): every 1-D leaf is one
    rank's slice, laid out globally as a ``shard * N`` vector sharded
    ``P(axes)`` (rank r's slice at offset r); 0-D leaves (the step
    count) are replicated. AdamW's init is zeros everywhere, so the
    global arrays are plain sharded zeros — verified against the real
    ``tx.init`` so a tx with non-zero init state fails loudly.
    """
    axes = tuple(mesh.axis_names) if axes is None else tuple(axes)
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    abstract = jax.eval_shape(tx.init, partition.shard_templates())
    concrete = tx.init([jnp.zeros((s,), dt) for s, dt in
                        zip(partition.shard_sizes, partition.bucket_dtypes)])
    for leaf in jax.tree_util.tree_leaves(concrete):
        if np.any(np.asarray(leaf)):
            raise ValueError(
                "ZeRO sharding supports optimizers whose init state is "
                "all-zero (optax.adamw); got a non-zero init leaf")

    def sharding_of(leaf):
        return NamedSharding(mesh, P() if leaf.ndim == 0 else P(axes))

    shardings = jax.tree_util.tree_map(sharding_of, abstract)
    opt_state = jax.tree_util.tree_map(
        lambda leaf, s: jax.device_put(
            jnp.zeros((leaf.shape[0] * n,) if leaf.ndim else (),
                      leaf.dtype), s),
        abstract, shardings)
    specs = jax.tree_util.tree_map(lambda s: s.spec, shardings,
                                   is_leaf=lambda x: isinstance(
                                       x, NamedSharding))
    return opt_state, shardings, specs


def _local_shape(shape: tuple, spec: P, mesh: Mesh) -> tuple:
    """Per-device block shape of a global array under ``spec``."""
    out = list(shape)
    for d, entry in enumerate(tuple(spec)[:len(shape)]):
        if entry is None:
            continue
        names = (entry,) if isinstance(entry, str) else tuple(entry)
        for a in names:
            size = mesh.shape[a]
            if out[d] % size:
                raise ValueError(
                    f"dim {d} of shape {shape} not divisible by mesh "
                    f"axis {a!r} (size {size}) — ZeRO's shard_map update "
                    f"needs exact divisibility")
            out[d] //= size
    return tuple(out)


def make_zero_update(tx, mesh: Mesh, param_specs, params_abstract, *,
                     axis_name: str = "dp",
                     bytes_per_pack: int = DEFAULT_BYTES_PER_PACK):
    """Build a ZeRO-sharded optimizer step for an arbitrary mesh.

    Returns ``(opt_state, opt_shardings, update_fn)`` where
    ``update_fn(params, grads, opt_state) -> (new_params,
    new_opt_state)`` is a shard_map over the whole mesh, callable from
    inside the caller's jitted train step. Parameters and gradients
    arrive as their mesh-local blocks (per ``param_specs`` — e.g.
    tp-sharded, pp-stage-sharded), the partition is over those LOCAL
    blocks, and only the ``axis_name`` (dp) dimension is ZeRO-sliced:
    each dp rank updates its 1/N of the local blocks and an all-gather
    over dp alone rebuilds them.

    Gradients must already be dp-synced (GSPMD's mean-objective grads,
    or the pipeline schedule's pmean over batch axes): they are sliced,
    never re-reduced. On a mesh without ``axis_name`` the partition is
    trivial (n_shards=1) and the update degenerates to a plain sharded
    optimizer step.
    """
    from distributed_tensorflow_tpu import telemetry

    leaves, treedef = jax.tree_util.tree_flatten(params_abstract)
    spec_leaves = jax.tree_util.tree_leaves(
        param_specs, is_leaf=lambda x: isinstance(x, P))
    if len(spec_leaves) != len(leaves):
        raise ValueError(f"{len(spec_leaves)} param specs for "
                         f"{len(leaves)} param leaves")
    local = [jax.ShapeDtypeStruct(_local_shape(l.shape, s, mesh), l.dtype)
             for l, s in zip(leaves, spec_leaves)]
    n_dp = mesh.shape.get(axis_name, 1)
    partition = ZeroPartition(local, n_dp, bytes_per_pack=bytes_per_pack)
    opt_state, opt_shardings, opt_specs = zero_opt_state(
        tx, partition, mesh)
    telemetry.event("zero.partition", axis=axis_name, **partition.summary())
    has_axis = axis_name in mesh.shape

    def local_update(params_loc, grads_loc, opt_loc):
        pl, td = jax.tree_util.tree_flatten(params_loc)
        gl = jax.tree_util.tree_leaves(grads_loc)
        rank = lax.axis_index(axis_name) if has_axis else 0
        p_shards = partition.shard(partition.pack(pl), rank)
        g_shards = partition.shard(partition.pack(gl), rank)
        updates, new_opt = tx.update(g_shards, opt_loc, p_shards)
        new_shards = optax.apply_updates(p_shards, updates)
        if has_axis:
            flats = partition.all_gather_flats(new_shards, axis_name)
        else:
            flats = new_shards
        new_params = jax.tree_util.tree_unflatten(
            td, partition.unpack(flats))
        return new_params, new_opt

    update_fn = jax.shard_map(
        local_update, mesh=mesh,
        in_specs=(param_specs, param_specs, opt_specs),
        out_specs=(param_specs, opt_specs),
        check_vma=False)
    return opt_state, opt_shardings, update_fn


def zero_state_bytes(n_params: int, n_shards: int, level: int,
                     *, param_bytes: int = 4, slot_bytes: int = 8,
                     grad_bytes: int = 4) -> int:
    """Analytic persistent+transient training-state bytes per device.

    Replicated (level 0): P*(param + grad + slot); ZeRO-1 shards the
    slots; ZeRO-2 shards the gradient buffer too. The measured curve in
    ``bench.py --scaling`` uses real shard shapes — this closed form is
    the sanity line printed next to it.
    """
    if level not in (0, 1, 2):
        raise ValueError(f"level must be 0, 1, or 2, got {level}")
    total = n_params * param_bytes
    total += (n_params * slot_bytes // n_shards if level >= 1
              else n_params * slot_bytes)
    total += (n_params * grad_bytes // n_shards if level >= 2
              else n_params * grad_bytes)
    return total
