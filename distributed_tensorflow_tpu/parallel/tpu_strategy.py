"""TPUStrategy — sync DP plus SPMD model parallelism on TPU.

≙ tensorflow/python/distribute/tpu_strategy.py:243 ``TPUStrategyV2``
(SURVEY.md §2.1, §3.4). The reference's TPUStrategy is the one place where
it already does what this framework does everywhere — trace once, compile
one XLA program, let CrossReplicaSum handle gradients (tpu_strategy.py:1826
``_tpu_function_creator`` wrapping tpu.replicate). Here that is simply the
base Strategy over a mesh that may carry model-parallel axes.

``experimental_split_to_logical_devices`` (tpu_strategy.py:516) — the
reference's manual SPMD annotation — becomes ``split_to_logical_devices``,
a ``jax.lax.with_sharding_constraint`` wrapper.
"""

from __future__ import annotations

from typing import Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.cluster import topology as topo_lib
from distributed_tensorflow_tpu.cluster.resolver import TPUClusterResolver
from distributed_tensorflow_tpu.parallel.strategy import Strategy


class TPUStrategy(Strategy):
    """Synchronous training on TPU over an explicit mesh.

    ``model_axes`` (e.g. ``{"tp": 2}``) reserves mesh axes for model
    parallelism — the ≙ of ``experimental_device_assignment`` with
    ``num_cores_per_replica > 1``.
    """

    def __init__(self, tpu_cluster_resolver: TPUClusterResolver | None = None,
                 mesh: Mesh | None = None,
                 model_axes: dict | None = None):
        self._cluster_resolver = tpu_cluster_resolver
        if mesh is None:
            devices = jax.devices()
            axes = {topo_lib.DATA_AXIS: -1}
            if model_axes:
                axes.update(model_axes)
            mesh = topo_lib.make_mesh(axes, devices=devices)
        super().__init__(mesh=mesh, data_axis_names=(topo_lib.DATA_AXIS,))

    @property
    def cluster_resolver(self) -> TPUClusterResolver | None:
        return self._cluster_resolver

    # -- SPMD annotations (≙ tpu_strategy.py:453/:516) ---------------------
    def assign_to_logical_device(self, tensor, logical_device_id: int):
        """≙ experimental_assign_to_logical_device (tpu_strategy.py:453).
        Under GSPMD the notion collapses to "replicated" placement; kept for
        API parity."""
        return jax.lax.with_sharding_constraint(
            tensor, NamedSharding(self.mesh, P()))

    def split_to_logical_devices(self, tensor, partition_dimensions):
        """≙ experimental_split_to_logical_devices (tpu_strategy.py:516):
        shard ``tensor`` so that dim i is split ``partition_dimensions[i]``
        ways across the mesh's model axes."""
        model_axes = [a for a in self.mesh.axis_names
                      if a not in self.data_axis_names
                      and self.mesh.shape[a] > 1]
        spec = []
        ax_iter = iter(model_axes)
        for nsplit in partition_dimensions:
            if nsplit == 1:
                spec.append(None)
            else:
                try:
                    spec.append(next(ax_iter))
                except StopIteration:
                    raise ValueError(
                        f"Not enough model axes on mesh {tuple(self.mesh.shape)}"
                        f" for partition_dimensions={partition_dimensions}")
        return jax.lax.with_sharding_constraint(
            tensor, NamedSharding(self.mesh, P(*spec)))

    def replicate_to_logical_devices(self, tensor):
        return self.assign_to_logical_device(tensor, 0)


def initialize_tpu_system(resolver: TPUClusterResolver | None = None):
    """≙ tpu_strategy_util.initialize_tpu_system (tpu_strategy_util.py:43).
    PJRT initializes the TPU system at backend creation; this forces backend
    init and returns the detected topology."""
    topo = topo_lib.Topology.detect()
    return topo
