"""Distributed values and variables backed by sharded ``jax.Array``.

TPU-native counterpart of the reference's
tensorflow/python/distribute/values.py (SURVEY.md §2.3):

- ``PerReplica``        ≙ values.py:356 — one value per replica.
- ``Mirrored``          ≙ values.py:436 — identical value on every replica.
- ``DistributedVariable`` ≙ values.py:506 — but instead of N per-device
  ``tf.Variable`` handles kept in sync by the strategy, the state is ONE
  ``jax.Array`` whose ``NamedSharding`` encodes the replication/sharding
  policy. Mirroring is "replicated sharding", not N copies plus a runtime
  that updates each — XLA keeps the copies consistent by construction.
- sync policies         ≙ values.py:1564 (OnRead) / :1705 (OnWrite).

Variables here are host-side mutable containers over immutable device
arrays. Jitted SPMD steps are functional (state pytree in/out) — the
strategy reads variables into the step and writes results back, which is the
single point where "TF variable semantics" meet "JAX functional semantics".
"""

from __future__ import annotations

import enum
import threading
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.parallel.collectives import ReduceOp


class VariableSynchronization(enum.Enum):
    """≙ tf.VariableSynchronization (values.py sync policies)."""

    AUTO = "auto"
    ON_WRITE = "on_write"   # mirrored: every replica holds the same value
    ON_READ = "on_read"     # per-replica state, reduced when read globally


class VariableAggregation(enum.Enum):
    """≙ tf.VariableAggregation."""

    NONE = "none"
    SUM = "sum"
    MEAN = "mean"
    ONLY_FIRST_REPLICA = "only_first_replica"


class DistributedValues:
    """Base for PerReplica/Mirrored (≙ values.py DistributedValues)."""

    def __init__(self, values: Sequence):
        if not values:
            raise ValueError("DistributedValues requires at least one value")
        self._values = tuple(values)

    @property
    def values(self) -> tuple:
        return self._values

    def __len__(self) -> int:
        return len(self._values)

    def __getitem__(self, i):
        return self._values[i]

    def __repr__(self) -> str:
        return f"{type(self).__name__}({list(self._values)!r})"


class PerReplica(DistributedValues):
    """One (possibly different) value per replica (≙ values.py:356)."""


class Mirrored(DistributedValues):
    """Same value on each replica (≙ values.py:436)."""

    @property
    def primary(self):
        return self._values[0]


def _regroup_leaves(structs: Sequence):
    """≙ distribute_utils.regroup: list of per-replica pytrees -> pytree of
    PerReplica leaves."""
    treedef = jax.tree_util.tree_structure(structs[0])
    leaves = [jax.tree_util.tree_leaves(s) for s in structs]
    grouped = [PerReplica(vals) for vals in zip(*leaves)]
    return jax.tree_util.tree_unflatten(treedef, grouped)


def select_replica(replica_id: int, structured):
    """≙ distribute_utils.select_replica."""
    def pick(v):
        return v.values[replica_id] if isinstance(v, DistributedValues) else v
    return jax.tree_util.tree_map(
        pick, structured, is_leaf=lambda v: isinstance(v, DistributedValues))


class DistributedVariable:
    """A named, mutable, sharded training variable (≙ values.py:506).

    The device state is one ``jax.Array`` with a ``NamedSharding`` over the
    strategy's mesh. Policy mapping from the reference:

    - MirroredVariable (values.py:1196): spec ``P()`` — replicated on every
      device; writes happen identically on all (SPMD), so consistency is
      structural, and the reference's cross-replica assign dance
      (values.py OnWrite policy :1705) vanishes.
    - SyncOnReadVariable (values.py:1294): spec with a leading replica axis;
      global reads reduce with ``aggregation``.
    - ShardedVariable (sharded_variable.py:843): axis-0 div sharding — see
      ``sharded_variable.py`` in this package.
    """

    _NAME_LOCK = threading.Lock()
    _UID = 0

    def __init__(self, value, *, name: str | None = None,
                 mesh: Mesh | None = None, spec: P | None = None,
                 trainable: bool = True,
                 synchronization: VariableSynchronization = VariableSynchronization.ON_WRITE,
                 aggregation: VariableAggregation = VariableAggregation.NONE,
                 dtype=None):
        if name is None:
            with DistributedVariable._NAME_LOCK:
                name = f"variable_{DistributedVariable._UID}"
                DistributedVariable._UID += 1
        self.name = name
        self.trainable = trainable
        self.synchronization = synchronization
        self.aggregation = aggregation
        self._mesh = mesh
        self._spec = spec if spec is not None else P()
        value = jnp.asarray(value, dtype=dtype)
        if mesh is not None:
            sharding = NamedSharding(mesh, self._spec)
            value = jax.device_put(value, sharding)
        self._value = value

    # -- reads ------------------------------------------------------------
    @property
    def value(self) -> jax.Array:
        return self._value

    def read_value(self) -> jax.Array:
        if self.synchronization is VariableSynchronization.ON_READ:
            return self._reduce_on_read()
        return self._value

    def _reduce_on_read(self) -> jax.Array:
        # ON_READ state carries a leading per-replica axis (sharded over the
        # data axes); the global read aggregates it (≙ values.py:1294).
        v = self._value
        if self.aggregation is VariableAggregation.SUM:
            return jnp.sum(v, axis=0)
        if self.aggregation is VariableAggregation.MEAN:
            return jnp.mean(v, axis=0)
        if self.aggregation is VariableAggregation.ONLY_FIRST_REPLICA:
            return v[0]
        return v

    def numpy(self) -> np.ndarray:
        return np.asarray(self.read_value())

    @property
    def shape(self):
        return self._value.shape

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def sharding(self):
        return getattr(self._value, "sharding", None)

    @property
    def spec(self) -> P:
        return self._spec

    # -- writes -----------------------------------------------------------
    def assign(self, value) -> "DistributedVariable":
        value = jnp.asarray(value, dtype=self.dtype)
        if value.shape != self._value.shape:
            raise ValueError(
                f"assign shape {value.shape} != variable shape {self._value.shape}")
        if self._mesh is not None:
            value = jax.device_put(value, NamedSharding(self._mesh, self._spec))
        elif isinstance(getattr(self._value, "sharding", None),
                        NamedSharding):
            # Variable built from an already-sharded array: a write must
            # preserve the layout (multi-host restore re-places global
            # host data onto the original sharding — ≙ values.py saveable
            # restore re-placement, :1159).
            value = jax.device_put(value, self._value.sharding)
        # placement tail goes through _set_raw so subclasses with a home
        # device (AggregatingVariable) pin writes without shadowing the
        # overlay-patched assign (strategy.py patches THIS method)
        self._set_raw(value)
        return self

    def assign_add(self, delta) -> "DistributedVariable":
        return self.assign(self._value + jnp.asarray(delta, dtype=self.dtype))

    def assign_sub(self, delta) -> "DistributedVariable":
        return self.assign(self._value - jnp.asarray(delta, dtype=self.dtype))

    # internal fast-path for strategy write-back (already sharded correctly)
    def _set_raw(self, value: jax.Array):
        self._value = value

    def __repr__(self) -> str:
        return (f"DistributedVariable(name={self.name!r}, "
                f"shape={tuple(self.shape)}, dtype={self.dtype}, "
                f"spec={self._spec}, sync={self.synchronization.value})")

    # arithmetic sugar so variables read naturally in host-side math
    def __array__(self, dtype=None):
        arr = np.asarray(self.read_value())
        return arr.astype(dtype) if dtype is not None else arr

    def __add__(self, o): return self.read_value() + o
    def __radd__(self, o): return o + self.read_value()
    def __mul__(self, o): return self.read_value() * o
    def __rmul__(self, o): return o * self.read_value()
    def __sub__(self, o): return self.read_value() - o
    def __rsub__(self, o): return o - self.read_value()


class MirroredVariable(DistributedVariable):
    """Replicated variable (≙ values.py:1196 MirroredVariable)."""

    def __init__(self, value, *, mesh: Mesh | None = None, name=None,
                 trainable: bool = True,
                 aggregation: VariableAggregation = VariableAggregation.MEAN,
                 dtype=None):
        super().__init__(
            value, name=name, mesh=mesh, spec=P(), trainable=trainable,
            synchronization=VariableSynchronization.ON_WRITE,
            aggregation=aggregation, dtype=dtype)


class SyncOnReadVariable(DistributedVariable):
    """Per-replica state reduced on global read (≙ values.py:1294).

    The device value has a leading axis of size ``num_replicas`` sharded
    over the data axes — e.g. batch-norm statistics or per-replica metric
    accumulators.
    """

    def __init__(self, per_replica_value, *, mesh: Mesh,
                 data_axes: tuple = ("dp",), name=None,
                 aggregation: VariableAggregation = VariableAggregation.SUM,
                 dtype=None):
        spec = P(data_axes)
        super().__init__(
            per_replica_value, name=name, mesh=mesh, spec=spec,
            trainable=False,
            synchronization=VariableSynchronization.ON_READ,
            aggregation=aggregation, dtype=dtype)
