"""Cross-device reduction algorithms (host-level API).

TPU-native counterpart of tensorflow/python/distribute/cross_device_ops.py
(SURVEY.md §2.2). The reference builds reduction *graphs* (NCCL op chains,
hierarchical copy trees, collective-V2 launches with instance keys); here
each implementation compiles ONE tiny XLA program over the mesh and lets the
compiler schedule ICI traffic:

- ``ReductionToOneDevice``   ≙ cross_device_ops.py:582 — gather-to-one then
  broadcast; the fallback path.
- ``IciAllReduce``           ≙ ``NcclAllReduce`` (cross_device_ops.py:960):
  batched allreduce with gradient packing (pack-by-size semantics of
  cross_device_utils.py:436-449 / group_by_size :679).
- ``HierarchicalAllReduce``  ≙ ``HierarchicalCopyAllReduce``
  (cross_device_ops.py:997): two-level reduce — fast axis (ICI) scatter,
  slow axis (DCN) reduce, fast axis gather.
- ``select_cross_device_ops`` ≙ cross_device_ops.py:1355.

This layer exists for eager/host-driven use (the coordinator/PS path, tests,
metric aggregation). The training hot path never calls it — gradient
reductions happen inside the jitted SPMD step.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.parallel import collectives
from distributed_tensorflow_tpu.parallel.collectives import (
    CommunicationOptions,
    ReduceOp,
)
from distributed_tensorflow_tpu.parallel.values import (
    DistributedValues,
    Mirrored,
    PerReplica,
)


def _as_per_replica_list(value, num_replicas: int) -> list:
    if isinstance(value, DistributedValues):
        return list(value.values)
    return [value] * num_replicas


class CrossDeviceOps:
    """Abstract reduction API (≙ cross_device_ops.py:252 ``CrossDeviceOps``).

    ``reduce``/``batch_reduce`` consume ``PerReplica`` values (one leaf per
    replica) and return ``Mirrored`` results.
    """

    def __init__(self, mesh: Mesh, axis_names: Sequence[str] = ("dp",),
                 options: CommunicationOptions | None = None):
        self.mesh = mesh
        self.axis_names = tuple(a for a in axis_names if a in mesh.shape)
        if not self.axis_names:
            raise ValueError(f"No reduction axes among {axis_names} on mesh "
                             f"{tuple(mesh.shape)}")
        self.options = options or CommunicationOptions()

    @property
    def num_replicas(self) -> int:
        import math
        return math.prod(self.mesh.shape[a] for a in self.axis_names)

    # -- public API -------------------------------------------------------
    def reduce(self, reduce_op, per_replica_value, options=None) -> Mirrored:
        op = ReduceOp.from_any(reduce_op)
        vals = _as_per_replica_list(per_replica_value, self.num_replicas)
        out = self._reduce_list([vals], op, self.options.merge(options))[0]
        return Mirrored([out] * self.num_replicas)

    def batch_reduce(self, reduce_op, value_list, options=None) -> list:
        """≙ batch_reduce_implementation: reduce many tensors in one launch
        (the gradient-sync shape)."""
        op = ReduceOp.from_any(reduce_op)
        lists = [_as_per_replica_list(v, self.num_replicas) for v in value_list]
        outs = self._reduce_list(lists, op, self.options.merge(options))
        return [Mirrored([o] * self.num_replicas) for o in outs]

    def broadcast(self, value, source_replica: int = 0) -> Mirrored:
        vals = _as_per_replica_list(value, self.num_replicas)
        return Mirrored([vals[source_replica]] * self.num_replicas)

    def gather(self, per_replica_value, axis: int = 0) -> jax.Array:
        """≙ _gather_implementation / _batch_all_gather
        (cross_device_ops.py:1306)."""
        vals = _as_per_replica_list(per_replica_value, self.num_replicas)
        return jnp.concatenate([jnp.asarray(v) for v in vals], axis=axis)

    # -- implementation ---------------------------------------------------
    def _reduce_list(self, lists: list[list], op: ReduceOp,
                     options: CommunicationOptions) -> list:
        raise NotImplementedError


class ReductionToOneDevice(CrossDeviceOps):
    """Sum on one device, then broadcast (≙ cross_device_ops.py:582)."""

    def _reduce_list(self, lists, op, options):
        outs = []
        for vals in lists:
            stacked = jnp.stack([jnp.asarray(v) for v in vals])
            if op is ReduceOp.SUM:
                outs.append(jnp.sum(stacked, axis=0))
            elif op is ReduceOp.MEAN:
                outs.append(jnp.mean(stacked, axis=0))
            elif op is ReduceOp.MAX:
                outs.append(jnp.max(stacked, axis=0))
            elif op is ReduceOp.MIN:
                outs.append(jnp.min(stacked, axis=0))
            else:
                raise ValueError(f"Unsupported op {op}")
        return outs


class IciAllReduce(CrossDeviceOps):
    """Batched allreduce over ICI (≙ NcclAllReduce, cross_device_ops.py:960).

    Packing: tensors are flattened and concatenated into buckets of
    ``options.bytes_per_pack`` (0 = one bucket), reduced as single launches,
    then split back — same wire behavior as the reference's
    aggregate-with-concat path (_do_batch_all_reduce,
    cross_device_ops.py:898) without the Python graph surgery.
    """

    def _reduce_list(self, lists, op, options):
        if op not in (ReduceOp.SUM, ReduceOp.MEAN):
            return ReductionToOneDevice._reduce_list(self, lists, op, options)
        n = len(lists)
        shapes = [np.shape(vals[0]) for vals in lists]
        sizes = [int(np.prod(s)) if s else 1 for s in shapes]
        dtypes = [jnp.asarray(vals[0]).dtype for vals in lists]

        outs: list = [None] * n
        # Tensors keep their own dtype — _pack_buckets never mixes dtypes
        # in a bucket (concatenating bf16+f32 would silently upcast);
        # each bucket is one collective launch.
        for bucket in self._pack_buckets(sizes, options.bytes_per_pack,
                                         dtypes):
            dt = dtypes[bucket[0]]
            flat_per_replica = [
                jnp.concatenate([jnp.ravel(jnp.asarray(lists[i][r]))
                                 for i in bucket])
                for r in range(self.num_replicas)]
            stacked = jnp.stack(flat_per_replica)  # (R, bucket_total)
            integer_mean = (op is ReduceOp.MEAN
                            and not jnp.issubdtype(dt, jnp.inexact))
            if integer_mean:
                stacked = stacked.astype(jnp.float32)
            reduced = self._compiled_allreduce(op)(stacked)
            if integer_mean:
                reduced = reduced.astype(dt)
            off = 0
            for i in bucket:
                outs[i] = jnp.reshape(reduced[off: off + sizes[i]],
                                      shapes[i])
                off += sizes[i]
        return outs

    @staticmethod
    def _pack_buckets(sizes, bytes_per_pack, dtypes):
        """≙ cross_device_utils.group_by_size (cross_device_utils.py:679),
        dtype-aware: a dtype change always closes the current bucket (no
        silent upcast from concatenating mixed-dtype leaves), and a leaf
        landing exactly on ``bytes_per_pack`` closes its bucket with the
        leaf included. ``dtypes`` may be one dtype (applied to all) or a
        per-leaf sequence."""
        if not isinstance(dtypes, (list, tuple)):
            dtypes = [dtypes] * len(sizes)
        return collectives.plan_buckets(sizes, dtypes, bytes_per_pack)

    def _compiled_allreduce(self, op: ReduceOp):
        # cached per-instance (an lru_cache on the method would pin self,
        # the mesh, and compiled executables in a class-level cache forever)
        cache = self.__dict__.setdefault("_fn_cache", {})
        if op in cache:
            return cache[op]
        axes = self.axis_names
        n_total = self.num_replicas

        def f(x):  # x: (R/|axes|, n) local shard of the replica-stacked buf
            out = collectives.all_reduce(jnp.sum(x, axis=0), axes,
                                         ReduceOp.SUM)
            if op is ReduceOp.MEAN:
                out = out / n_total
            return out

        fn = jax.jit(jax.shard_map(
            f, mesh=self.mesh, in_specs=P(axes), out_specs=P(),
            check_vma=False))
        cache[op] = fn
        return fn


# Alias kept for config compatibility with the reference's class name.
NcclAllReduce = IciAllReduce


class HierarchicalAllReduce(CrossDeviceOps):
    """Two-level reduce (≙ HierarchicalCopyAllReduce, cross_device_ops.py:997).

    Requires a 2-axis reduction: ``axis_names = (outer, inner)`` where inner
    is the fast fabric (ICI within a slice) and outer the slow one (DCN
    across slices). Uses reduce-scatter(inner) -> allreduce(outer) ->
    all-gather(inner) so each slow hop carries 1/|inner| of the bytes.
    """

    def __init__(self, mesh, axis_names=("dcn", "dp"), options=None):
        super().__init__(mesh, axis_names, options)
        if len(self.axis_names) != 2:
            raise ValueError("HierarchicalAllReduce needs exactly 2 axes "
                             "(outer/slow, inner/fast)")

    def _reduce_list(self, lists, op, options):
        outer, inner = self.axis_names
        outs = []
        fn = self._compiled(op)
        for vals in lists:
            stacked = jnp.stack([jnp.asarray(v) for v in vals])
            outs.append(fn(stacked))
        return outs

    def _compiled(self, op: ReduceOp):
        cache = self.__dict__.setdefault("_fn_cache", {})
        if op in cache:
            return cache[op]
        outer, inner = self.axis_names
        n_total = self.num_replicas

        def f(x):  # x: (R_local, ...) local shard of the replica-stacked buf
            local = jnp.sum(x, axis=0)
            out = collectives.hierarchical_all_reduce(
                local, inner_axis=inner, outer_axis=outer, op=ReduceOp.SUM)
            if op is ReduceOp.MEAN:
                out = out / n_total
            return out

        fn = jax.jit(jax.shard_map(
            f, mesh=self.mesh, in_specs=P((outer, inner)), out_specs=P(),
            check_vma=False))
        cache[op] = fn
        return fn


def select_cross_device_ops(mesh: Mesh, axis_names: Sequence[str] = ("dp",),
                            options: CommunicationOptions | None = None
                            ) -> CrossDeviceOps:
    """≙ cross_device_ops.select_cross_device_ops (cross_device_ops.py:1355):
    the reference picks NcclAllReduce iff the NCCL kernel is registered;
    here ICI allreduce is always available, and a 2-axis request selects the
    hierarchical form."""
    names = tuple(a for a in axis_names if a in mesh.shape)
    if len(names) == 2 and all(mesh.shape[a] > 1 for a in names):
        return HierarchicalAllReduce(mesh, names, options)
    if sum(mesh.shape[a] for a in names) <= len(names):  # all axes size 1
        return ReductionToOneDevice(mesh, names, options)
    return IciAllReduce(mesh, names, options)
