"""Strategy API: scope / run / reduce over a device mesh.

TPU-native counterpart of tensorflow/python/distribute/distribute_lib.py
(SURVEY.md §2.1): ``Strategy`` (:2026), ``StrategyExtendedV2`` (:2394),
``ReplicaContext`` (:3670), ``Strategy.run`` (:1557), ``reduce`` (:1675),
``scope`` (:1223).

Design shift (SURVEY §7 "Design stance"): the reference's MirroredStrategy
runs one *Python thread per device* with a ``merge_call`` rendezvous
(mirrored_run.py:289) and the grpc worker service moves tensors between
processes. Here ``Strategy.run`` traces the replica function ONCE under
``jax.shard_map`` over the mesh's data axes and compiles a single SPMD
program — the model the reference's own TPUStrategy uses (SURVEY §3.4),
generalized to every strategy. Cross-replica communication inside ``run`` is
an XLA collective; there are no replica threads, no rendezvous, no
per-tensor RPC.

Two ways to use a strategy:

1. **TF-parity path** — ``scope()`` + ``Variable`` + ``run`` + ``reduce``
   with implicit variable capture/write-back, matching tf.distribute
   semantics for porting reference-style training scripts.
2. **Native path** — explicit functional state: ``init_state`` /
   ``compile_step`` return jit-compiled SPMD steps over pytrees (flax/optax
   style). This is the benchmark hot path.
"""

from __future__ import annotations

import contextlib
import math
import threading
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.cluster import topology as topo_lib
from distributed_tensorflow_tpu.parallel import collectives
from distributed_tensorflow_tpu.parallel.collectives import (
    CommunicationOptions,
    ReduceOp,
)
from distributed_tensorflow_tpu.parallel.cross_device_ops import (
    CrossDeviceOps,
    select_cross_device_ops,
)
from distributed_tensorflow_tpu.parallel.values import (
    DistributedValues,
    DistributedVariable,
    Mirrored,
    MirroredVariable,
    PerReplica,
    SyncOnReadVariable,
    VariableAggregation,
    VariableSynchronization,
)

# ---------------------------------------------------------------------------
# Context plumbing (≙ distribution_strategy_context)
# ---------------------------------------------------------------------------

_CTX = threading.local()


def _strategy_stack() -> list:
    if not hasattr(_CTX, "stack"):
        _CTX.stack = []
    return _CTX.stack


def get_strategy() -> "Strategy":
    stack = _strategy_stack()
    if not stack:
        raise RuntimeError("No strategy in scope; use `with strategy.scope():`")
    return stack[-1]


def has_strategy() -> bool:
    return bool(_strategy_stack())


def get_replica_context() -> "ReplicaContext | None":
    return getattr(_CTX, "replica_context", None)


def in_cross_replica_context() -> bool:
    return has_strategy() and get_replica_context() is None


@contextlib.contextmanager
def _replica_context(ctx: "ReplicaContext | None"):
    prev = getattr(_CTX, "replica_context", None)
    _CTX.replica_context = ctx
    try:
        yield
    finally:
        _CTX.replica_context = prev


# Traced-variable overlay: while an SPMD `run` is being traced, variable
# reads/writes resolve against traced values instead of the host arrays.
# This is the single mechanism replacing TF's FuncGraph variable capture.

@contextlib.contextmanager
def _variable_overlay(overlay: dict):
    prev = getattr(_CTX, "var_overlay", None)
    _CTX.var_overlay = overlay
    try:
        yield
    finally:
        _CTX.var_overlay = prev


def _current_overlay() -> dict | None:
    return getattr(_CTX, "var_overlay", None)


# Patch DistributedVariable read/write paths to consult the overlay.
_orig_value = DistributedVariable.value.fget
_orig_read_value = DistributedVariable.read_value
_orig_assign = DistributedVariable.assign


def _overlay_value(self):
    ov = _current_overlay()
    if ov is not None and id(self) in ov:
        return ov[id(self)]
    return _orig_value(self)


def _overlay_read_value(self):
    ov = _current_overlay()
    if ov is not None and id(self) in ov:
        return ov[id(self)]
    return _orig_read_value(self)


def _overlay_assign(self, value):
    ov = _current_overlay()
    if ov is not None and id(self) in ov:
        ov[id(self)] = jnp.asarray(value, dtype=self.dtype)
        return self
    return _orig_assign(self, value)


def _overlay_assign_add(self, delta):
    ov = _current_overlay()
    if ov is not None and id(self) in ov:
        ov[id(self)] = ov[id(self)] + jnp.asarray(delta, dtype=self.dtype)
        return self
    return _orig_assign(self, _orig_value(self) + jnp.asarray(delta, self.dtype))


def _overlay_assign_sub(self, delta):
    ov = _current_overlay()
    if ov is not None and id(self) in ov:
        ov[id(self)] = ov[id(self)] - jnp.asarray(delta, dtype=self.dtype)
        return self
    return _orig_assign(self, _orig_value(self) - jnp.asarray(delta, self.dtype))


DistributedVariable.value = property(_overlay_value)
DistributedVariable.read_value = _overlay_read_value
DistributedVariable.assign = _overlay_assign
DistributedVariable.assign_add = _overlay_assign_add
DistributedVariable.assign_sub = _overlay_assign_sub


# ---------------------------------------------------------------------------
# ReplicaContext
# ---------------------------------------------------------------------------

class ReplicaContext:
    """Per-replica API inside ``Strategy.run`` (≙ distribute_lib.py:3670).

    Collectives lower to XLA HLO over the bound mesh axes. ``merge_call``
    exists for optimizer-compatibility: under SPMD there are no replica
    threads to rendezvous (mirrored_run.py:433's parked-thread dance), so it
    simply runs ``fn`` in cross-replica context — reductions inside become
    in-program collectives. This is exactly TF's own `_use_merge_call=False`
    escape hatch made the default (mirrored_strategy.py:351).
    """

    def __init__(self, strategy: "Strategy", axis_names: tuple):
        self.strategy = strategy
        self._axis_names = axis_names

    @property
    def num_replicas_in_sync(self) -> int:
        return self.strategy.num_replicas_in_sync

    @property
    def replica_id_in_sync_group(self):
        idx = 0
        for name in self._axis_names:
            idx = idx * jax.lax.axis_size(name) + jax.lax.axis_index(name)
        return idx

    def all_reduce(self, reduce_op, value, options=None):
        op = ReduceOp.from_any(reduce_op)
        return jax.tree_util.tree_map(
            lambda v: collectives.all_reduce(v, self._axis_names, op), value)

    def all_gather(self, value, axis: int = 0, options=None):
        return jax.tree_util.tree_map(
            lambda v: collectives.all_gather(v, self._axis_names, axis=axis),
            value)

    def reduce_scatter(self, value, axis: int = 0, reduce_op=ReduceOp.SUM):
        op = ReduceOp.from_any(reduce_op)
        return jax.tree_util.tree_map(
            lambda v: collectives.reduce_scatter(v, self._axis_names,
                                                 axis=axis, op=op), value)

    def collective_permute(self, value, perm):
        if len(self._axis_names) != 1:
            raise ValueError("collective_permute needs a single replica axis")
        return jax.tree_util.tree_map(
            lambda v: collectives.permute(v, self._axis_names[0], perm), value)

    def all_to_all(self, value, split_axis: int, concat_axis: int):
        return jax.tree_util.tree_map(
            lambda v: collectives.all_to_all(
                v, self._axis_names, split_axis=split_axis,
                concat_axis=concat_axis), value)

    def merge_call(self, merge_fn: Callable, args=(), kwargs=None):
        with _replica_context(None):
            return merge_fn(self.strategy, *args, **(kwargs or {}))


# ---------------------------------------------------------------------------
# StrategyExtended (parity shim)
# ---------------------------------------------------------------------------

class StrategyExtended:
    """≙ StrategyExtendedV2 (distribute_lib.py:2394) — the lower-level API
    Keras-style integrations call."""

    def __init__(self, strategy: "Strategy"):
        self._strategy = strategy

    @property
    def worker_devices(self) -> tuple:
        return tuple(self._strategy.replica_devices)

    @property
    def parameter_devices(self) -> tuple:
        return tuple(self._strategy.replica_devices)

    def reduce_to(self, reduce_op, value, destinations=None, options=None):
        """In replica tracing: lowers to an in-program collective. On host:
        delegates to cross_device_ops."""
        op = ReduceOp.from_any(reduce_op)
        if _current_overlay() is not None or _in_spmd_trace():
            return jax.tree_util.tree_map(
                lambda v: collectives.all_reduce(
                    v, self._strategy.data_axis_names, op), value)
        return self._strategy.cross_device_ops.reduce(op, value,
                                                      options=options)

    def batch_reduce_to(self, reduce_op, value_and_destination_pairs,
                        options=None):
        return [self.reduce_to(reduce_op, v, d, options)
                for v, d in value_and_destination_pairs]

    def call_for_each_replica(self, fn, args=(), kwargs=None):
        return self._strategy.run(fn, args=args, kwargs=kwargs)

    def variable_created_in_scope(self, v) -> bool:
        return any(v is var for var in self._strategy.variables)

    def update(self, var: DistributedVariable, fn, args=(), kwargs=None):
        """≙ StrategyExtended.update: apply ``fn(var, *args)`` once, in
        cross-replica context."""
        with _replica_context(None):
            return fn(var, *args, **(kwargs or {}))


def _in_spmd_trace() -> bool:
    return bool(getattr(_CTX, "in_spmd", False))


@contextlib.contextmanager
def _spmd_trace():
    prev = getattr(_CTX, "in_spmd", False)
    _CTX.in_spmd = True
    try:
        yield
    finally:
        _CTX.in_spmd = prev


# ---------------------------------------------------------------------------
# Strategy
# ---------------------------------------------------------------------------

class Strategy:
    """Base distribution strategy over a ``jax.sharding.Mesh``.

    ≙ tf.distribute.Strategy (distribute_lib.py:2026). Subclasses configure
    the mesh and axis roles; the run/reduce machinery is shared. ``mesh`` may
    have axes beyond the data axes (tp/sp/pp) — ``run`` replicates over
    those by default and model code shards over them with explicit specs.
    """

    def __init__(self, mesh: Mesh | None = None,
                 data_axis_names: Sequence[str] = (topo_lib.DATA_AXIS,),
                 cross_device_ops: CrossDeviceOps | None = None,
                 communication_options: CommunicationOptions | None = None):
        if mesh is None:
            mesh = topo_lib.make_mesh()
        self.mesh = mesh
        self.data_axis_names = tuple(
            a for a in data_axis_names if a in mesh.shape)
        if not self.data_axis_names:
            self.data_axis_names = tuple(mesh.axis_names[:1])
        self.communication_options = (communication_options
                                      or CommunicationOptions())
        self.cross_device_ops = cross_device_ops or select_cross_device_ops(
            mesh, self.data_axis_names, communication_options)
        self.extended = StrategyExtended(self)
        self._variables: list[DistributedVariable] = []
        # Bounded LRU of compiled run() programs. The BOUND is the real
        # protection: each entry's compiled fn closes over its variables,
        # pinning them (and their device arrays) until eviction — so the
        # cache holds at most _run_cache_size programs' worth. Keys use
        # weakref tokens rather than raw id()s for hygiene (an id can be
        # reused by a new object after GC; a weakref cannot compare equal
        # to a different object's ref).
        import collections
        self._run_cache: "collections.OrderedDict" = collections.OrderedDict()
        self._run_cache_size = 128

    # -- basic facts ------------------------------------------------------
    @property
    def num_replicas_in_sync(self) -> int:
        return math.prod(self.mesh.shape[a] for a in self.data_axis_names)

    @property
    def replica_devices(self) -> list:
        return list(self.mesh.devices.flat)

    @property
    def variables(self) -> list[DistributedVariable]:
        return list(self._variables)

    # -- scope ------------------------------------------------------------
    @contextlib.contextmanager
    def scope(self):
        """≙ Strategy.scope (distribute_lib.py:1223): variables created
        inside are placed on the mesh with this strategy's policy."""
        from distributed_tensorflow_tpu.utils.summary import (
            api_gauge, strategy_gauge)
        strategy_gauge.set(type(self).__name__)   # ≙ distribute_lib.py:190
        api_gauge.set("scope")
        _strategy_stack().append(self)
        try:
            yield self
        finally:
            _strategy_stack().pop()

    def create_variable(self, value, *, name=None, trainable=True,
                        synchronization=VariableSynchronization.AUTO,
                        aggregation=VariableAggregation.NONE,
                        dtype=None) -> DistributedVariable:
        if synchronization in (VariableSynchronization.AUTO,
                               VariableSynchronization.ON_WRITE):
            var = MirroredVariable(
                value, mesh=self.mesh, name=name, trainable=trainable,
                aggregation=(aggregation
                             if aggregation is not VariableAggregation.NONE
                             else VariableAggregation.MEAN),
                dtype=dtype)
        else:
            # ON_READ state carries a leading per-replica axis; each
            # replica starts from the init value (≙ values.py:1294).
            # ALWAYS broadcast — the init value is the per-replica value,
            # never a pre-stacked (R, ...) array (callers needing custom
            # per-replica init construct SyncOnReadVariable directly).
            val = jnp.asarray(value, dtype=dtype)
            val = jnp.broadcast_to(
                val, (self.num_replicas_in_sync,) + val.shape)
            var = SyncOnReadVariable(
                val, mesh=self.mesh, data_axes=self.data_axis_names,
                name=name, aggregation=aggregation, dtype=dtype)
        self._variables.append(var)
        return var

    # -- data -------------------------------------------------------------
    def experimental_distribute_dataset(self, dataset, options=None):
        from distributed_tensorflow_tpu.input.dataset import DistributedDataset
        return DistributedDataset(dataset, self, options=options)

    def distribute_datasets_from_function(self, dataset_fn, options=None):
        from distributed_tensorflow_tpu.input.dataset import (
            DistributedDataset, InputContext)
        ctx = InputContext(
            num_input_pipelines=jax.process_count(),
            input_pipeline_id=jax.process_index(),
            num_replicas_in_sync=self.num_replicas_in_sync)
        return DistributedDataset(dataset_fn(ctx), self, options=options)

    def experimental_distribute_values_from_function(self, value_fn):
        """≙ distribute_lib.py experimental_distribute_values_from_function:
        value_fn(ValueContext) -> per-replica value."""
        vals = []
        for rid in range(self.num_replicas_in_sync):
            vals.append(value_fn(ValueContext(rid, self.num_replicas_in_sync)))
        return PerReplica(vals)

    # -- run (TF-parity SPMD path) ----------------------------------------
    def run(self, fn: Callable, args=(), kwargs=None) -> Any:
        """Run ``fn`` once per replica as a single SPMD program
        (≙ Strategy.run, distribute_lib.py:1557 — but via shard_map tracing,
        not per-device threads).

        ``PerReplica``/stacked leaves of ``args`` are split over the data
        axes; other leaves are replicated. Variables created in this
        strategy's scope may be read and assigned inside ``fn``; updates are
        written back after the step. Returns per-replica outputs as
        ``PerReplica`` (scalars and arrays get a leading replica axis while
        stacked).
        """
        kwargs = kwargs or {}
        R = self.num_replicas_in_sync
        axes = self.data_axis_names

        def is_dist(v):
            return isinstance(v, DistributedValues)

        def is_data_sharded(v):
            """A device array already sharded over this mesh's data axes
            (a distributed-dataset batch): each replica gets its local
            shard, matching the reference's per-replica dataset element
            semantics (input_lib.py DistributedIterator)."""
            sh = getattr(v, "sharding", None)
            if not isinstance(v, jax.Array) or \
                    not isinstance(sh, NamedSharding):
                return False
            if sh.mesh.devices.shape != self.mesh.devices.shape or \
                    set(sh.mesh.axis_names) != set(self.mesh.axis_names):
                return False
            spec = sh.spec
            if not spec or spec[0] is None:
                return False
            first = spec[0] if isinstance(spec[0], tuple) else (spec[0],)
            return any(a in axes for a in first)

        flat_args, args_treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=is_dist)
        split_mask = [is_dist(v) for v in flat_args]
        sharded_mask = [not m and is_data_sharded(v)
                        for v, m in zip(flat_args, split_mask)]
        stacked = [
            jnp.stack([jnp.asarray(x) for x in v.values]) if m else
            (v if sh else jnp.asarray(v))
            for v, m, sh in zip(flat_args, split_mask, sharded_mask)]

        variables = self._variables

        def mesh_value(v):
            """Mesh-placed values pass through; values pinned elsewhere
            (AggregatingVariable home devices — central storage) are
            re-placed onto the mesh (the PS read — an async device copy,
            not a blocking host round-trip)."""
            val = _orig_value(v)
            sh = getattr(val, "sharding", None)
            if isinstance(sh, NamedSharding) and sh.mesh == self.mesh:
                return val
            try:
                return jax.device_put(val, NamedSharding(self.mesh, v.spec))
            except Exception:
                return np.asarray(val)     # cross-backend fallback

        var_vals = [mesh_value(v) for v in variables]
        var_specs = [v.spec for v in variables]

        # Cache the traced+compiled program per (fn, structure, shapes):
        # without this the TF-parity path would retrace every step.
        # NOTE: a lambda recreated each call defeats the cache — pass a
        # stable function object in training loops.
        import weakref

        def stable_token(v):
            # weakref tokens cannot alias a new object after GC the way
            # raw id()s can (two refs compare unequal once a referent
            # dies); unweakreffable objects fall back to identity.
            try:
                return weakref.ref(v)
            except TypeError:
                return id(v)

        cache_key = (
            fn, args_treedef, tuple(split_mask), tuple(sharded_mask),
            tuple((x.shape, str(x.dtype)) for x in stacked),
            tuple(stable_token(v) for v in variables),
            tuple((tuple(v.shape), str(v.dtype)) for v in variables),
        )
        cached = self._run_cache.get(cache_key)
        if cached is not None:
            self._run_cache.move_to_end(cache_key)
            new_var_vals, out_stacked = cached(tuple(var_vals), *stacked)
            for v, val in zip(variables, new_var_vals):
                v._set_raw(val)
            return self._unstack_outputs(out_stacked)

        def spmd_fn(var_vals_in, *leaves):
            on_read = [v.synchronization is VariableSynchronization.ON_READ
                       for v in variables]
            var_locals = [jnp.squeeze(val, axis=0) if r else val
                          for v, val, r in zip(variables, var_vals_in, on_read)]
            overlay = {id(v): val for v, val in zip(variables, var_locals)}
            # PerReplica leaves: drop the stacked replica axis (size 1
            # locally). Data-sharded leaves: the local shard IS the
            # replica's sub-batch — pass through.
            local = [jnp.squeeze(v, axis=0) if m else v
                     for v, m in zip(leaves, split_mask)]
            (largs, lkwargs) = jax.tree_util.tree_unflatten(args_treedef, local)
            ctx = ReplicaContext(self, axes)
            # run() implicitly enters the strategy's scope (TF semantics:
            # get_strategy() works inside a replica fn)
            with self.scope(), _spmd_trace(), _variable_overlay(overlay), \
                    _replica_context(ctx):
                out = fn(*largs, **lkwargs)
            new_vals = []
            for v, orig, r in zip(variables, var_locals, on_read):
                cur = overlay[id(v)]
                if r:
                    cur = jnp.expand_dims(cur, 0)
                elif cur is not orig:
                    # assigned in replica context: apply the variable's
                    # cross-replica aggregation (≙ values.py OnWrite policy
                    # :1705 — mirrored writes must agree across replicas)
                    agg = v.aggregation
                    if agg is VariableAggregation.MEAN:
                        cur = collectives.all_reduce(cur, axes, ReduceOp.MEAN)
                    elif agg is VariableAggregation.SUM:
                        cur = collectives.all_reduce(cur, axes, ReduceOp.SUM)
                    elif agg is VariableAggregation.ONLY_FIRST_REPLICA:
                        cur = collectives.broadcast(cur, axes, source=0)
                new_vals.append(cur)
            def stack_leaf(x):
                # fns like `var.assign_add` return the variable itself;
                # resolve it to its (traced) value rather than materializing
                if isinstance(x, DistributedVariable):
                    x = overlay.get(id(x), _orig_value(x))
                return jnp.expand_dims(jnp.asarray(x), 0)

            out_stacked = jax.tree_util.tree_map(
                stack_leaf, out,
                is_leaf=lambda x: isinstance(x, DistributedVariable))
            return tuple(new_vals), out_stacked

        in_specs = (
            [P(axes) if (m or sh) else P()
             for m, sh in zip(split_mask, sharded_mask)])
        shard_fn = jax.jit(jax.shard_map(
            spmd_fn,
            mesh=self.mesh,
            in_specs=(tuple(var_specs),) + tuple(in_specs),
            out_specs=(tuple(var_specs), P(axes)),
            check_vma=False,
        ))
        self._run_cache[cache_key] = shard_fn
        while len(self._run_cache) > self._run_cache_size:
            self._run_cache.popitem(last=False)
        new_var_vals, out_stacked = shard_fn(tuple(var_vals), *stacked)

        for v, val in zip(variables, new_var_vals):
            v._set_raw(val)
        return self._unstack_outputs(out_stacked)

    def _unstack_outputs(self, out_stacked):
        """Split stacked (R, ...) outputs into PerReplica host views. The
        stacked array is replica-sharded; indexing it eagerly is ambiguous
        to GSPMD, so re-place replicated first (outputs of the TF-parity
        path are host-consumed, not hot-path)."""
        R = self.num_replicas_in_sync
        repl = self.replicated_sharding()

        def unstack(x):
            x = jax.device_put(x, repl)
            return PerReplica([x[i] for i in range(R)])
        return jax.tree_util.tree_map(unstack, out_stacked)

    # -- reduce (host side) -----------------------------------------------
    def reduce(self, reduce_op, value, axis=None):
        """≙ Strategy.reduce (distribute_lib.py:1675): reduce a PerReplica
        across replicas (and optionally across ``axis`` within each)."""
        op = ReduceOp.from_any(reduce_op)
        if isinstance(value, DistributedValues):
            vals = [jnp.asarray(v) for v in value.values]
        else:
            vals = [jnp.asarray(value)]
        if axis is not None:
            inner = {ReduceOp.MEAN: jnp.mean, ReduceOp.SUM: jnp.sum,
                     ReduceOp.MAX: jnp.max, ReduceOp.MIN: jnp.min}[op]
            vals = [inner(v, axis=axis) for v in vals]
        stacked = jnp.stack(vals)
        if op is ReduceOp.MEAN:
            return jnp.mean(stacked, axis=0)
        if op is ReduceOp.SUM:
            return jnp.sum(stacked, axis=0)
        if op is ReduceOp.MAX:
            return jnp.max(stacked, axis=0)
        if op is ReduceOp.MIN:
            return jnp.min(stacked, axis=0)
        raise ValueError(f"Unsupported reduce op {op}")

    def gather(self, value, axis: int = 0):
        """≙ Strategy.gather: concatenate per-replica values."""
        if isinstance(value, DistributedValues):
            return jnp.concatenate(
                [jnp.asarray(v) for v in value.values], axis=axis)
        return jnp.asarray(value)

    # -- native functional path -------------------------------------------
    def replicated_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def data_sharding(self, batch_axis: int = 0) -> NamedSharding:
        names = self.data_axis_names
        if isinstance(names, (tuple, list)) and len(names) == 1:
            # single data axis: use the bare name — identical sharding,
            # but P('dp') (the canonical form newer jax normalizes to)
            # instead of the vintage-dependent P(('dp',))
            names = names[0]
        spec = [None] * (batch_axis + 1)
        spec[batch_axis] = names
        return NamedSharding(self.mesh, P(*spec))

    def shard_batch(self, batch):
        """Place a host global-batch pytree on the mesh, sharded on axis 0
        over the data axes (≙ distributed-dataset device placement)."""
        sharding = self.data_sharding()
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(jnp.asarray(x), sharding), batch)

    def replicate(self, tree):
        """Place a pytree fully replicated on the mesh."""
        sharding = self.replicated_sharding()
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(jnp.asarray(x), sharding), tree)

    def init_state(self, init_fn: Callable, *args,
                   sharding_rules=None, **kwargs):
        """Initialize a state pytree on the mesh. ``sharding_rules`` maps the
        state to PartitionSpecs (default: fully replicated = mirrored)."""
        abstract = jax.eval_shape(init_fn, *args, **kwargs)
        if sharding_rules is None:
            out_shardings = jax.tree_util.tree_map(
                lambda _: self.replicated_sharding(), abstract)
        else:
            out_shardings = jax.tree_util.tree_map(
                lambda spec: NamedSharding(self.mesh, spec), sharding_rules,
                is_leaf=lambda s: isinstance(s, P))
        return jax.jit(init_fn, out_shardings=out_shardings)(*args, **kwargs)

    def gradient_bucketer(self):
        """Reverse-order bucketed gradient collectives for this strategy's
        data axes (≙ the reference's NcclAllReduce gradient packing,
        cross_device_utils.py:436-449) — ON by default whenever the
        strategy spans more than one replica. Pack size comes from
        ``CommunicationOptions.bytes_per_pack`` (0 -> the
        ``DEFAULT_BYTES_PER_PACK`` fusion-buffer default). On a hybrid
        dcn×dp reduction the bucketer takes the hierarchical path so the
        cross-slice DCN hop of each bucket overlaps the ICI phases of the
        next. Returns None when there is nothing to reduce (single
        replica); subclasses whose variables live off-mesh (central
        storage, parameter server) also return None.
        """
        if self.num_replicas_in_sync <= 1:
            return None
        axes = self.data_axis_names
        bpp = (self.communication_options.bytes_per_pack
               or collectives.DEFAULT_BYTES_PER_PACK)
        outer = inner = None
        if (len(axes) == 2 and axes[0] == topo_lib.DCN_AXIS
                and all(self.mesh.shape[a] > 1 for a in axes)):
            outer, inner = axes
        return collectives.GradientBucketer(
            axes, bytes_per_pack=bpp, outer_axis=outer, inner_axis=inner)

    def compile_step(self, step_fn: Callable, donate_state: bool = True):
        """Compile ``step_fn(state, batch) -> (state, aux)`` into the SPMD
        hot path: batch sharded over data axes, shardings of ``state``
        propagated by GSPMD, state buffers donated.

        This is the ≙ of the reference's TPUStrategy model (SURVEY §3.4):
        one compiled program per step, Python out of the loop.
        """
        from distributed_tensorflow_tpu.utils.jax_compat import (
            safe_donate_argnums)
        donate = safe_donate_argnums((0,)) if donate_state else ()
        return jax.jit(step_fn, donate_argnums=donate)


class ValueContext:
    """≙ tf.distribute.experimental.ValueContext."""

    def __init__(self, replica_id_in_sync_group: int,
                 num_replicas_in_sync: int):
        self.replica_id_in_sync_group = replica_id_in_sync_group
        self.num_replicas_in_sync = num_replicas_in_sync
