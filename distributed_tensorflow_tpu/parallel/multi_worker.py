"""MultiWorkerMirroredStrategy — synchronous DP across hosts.

≙ tensorflow/python/distribute/collective_all_reduce_strategy.py:57
``CollectiveAllReduceStrategy`` (SURVEY.md §2.1, §3.2).

What the reference's ``_initialize_multi_worker`` (:507) does — parse
TF_CONFIG, start an in-process grpc server, configure the coordination
service, build a CollectiveAllReduce over group_size = hosts x local devices
— maps here to: resolve cluster, ``jax.distributed.initialize`` (coordination
service over DCN), and build ONE global mesh whose data axis spans every
chip in the slice. Gradient allreduce is an XLA collective on ICI; no grpc
data plane exists to configure.

Health checking (≙ ``_check_health`` thread, :990): the TSL coordination
service heartbeats every process; a missing peer fails the job fast, the
same observable behavior as the reference's abort-collectives poisoning
(context.py:1090) with none of the machinery.
"""

from __future__ import annotations

from typing import Sequence

import jax

from distributed_tensorflow_tpu.cluster import bootstrap, topology as topo_lib
from distributed_tensorflow_tpu.cluster.resolver import ClusterResolver
from distributed_tensorflow_tpu.parallel.collectives import (
    CommunicationImplementation,
    CommunicationOptions,
)
from distributed_tensorflow_tpu.parallel.strategy import Strategy


class CollectiveAllReduceStrategy(Strategy):
    """Multi-worker sync data parallelism over the global device set."""

    def __init__(self, cluster_resolver: ClusterResolver | None = None,
                 communication_options: CommunicationOptions | None = None,
                 mesh=None):
        # ≙ _initialize_multi_worker: connect control plane first.
        self._runtime = bootstrap.initialize(cluster_resolver)
        self._cluster_resolver = cluster_resolver
        if mesh is None:
            mesh = topo_lib.make_mesh(
                {topo_lib.DATA_AXIS: len(jax.devices())})
        # A hybrid (multi-slice) mesh reduces over dcn×dp so the
        # gradient bucketer takes the hierarchical path: per-bucket
        # reduce-scatter on ICI, the small cross-slice hop on DCN
        # overlapping the next bucket's ICI phases (≙ the reference's
        # CollectiveAllReduce with hierarchical copy on multi-NIC hosts).
        data_axes = topo_lib.data_axes(mesh) or (topo_lib.DATA_AXIS,)
        super().__init__(mesh=mesh, data_axis_names=data_axes,
                         communication_options=communication_options)

    @property
    def cluster_resolver(self) -> ClusterResolver | None:
        return self._cluster_resolver

    @property
    def task_type(self) -> str | None:
        return getattr(self._cluster_resolver, "task_type", None)

    @property
    def task_id(self) -> int | None:
        return getattr(self._cluster_resolver, "task_id", None)

    def check_health(self, timeout_s: float = 30.0) -> bool:
        """≙ context.check_collective_ops_peer_health (context.py:1105)
        + the reference's fail-fast peer-health path
        (collective_all_reduce_strategy.py:990). A coordination-service
        barrier WITH a timeout: a hung or dead peer returns False within
        ``timeout_s`` instead of blocking forever.

        The barrier name comes from a CLUSTER-WIDE atomic counter (not a
        local one): a missed or timed-out round must not desync the
        names processes wait on in later rounds.
        """
        from distributed_tensorflow_tpu.cluster.coordination import (
            coordination_service)
        agent = coordination_service()
        if not agent.is_distributed:
            return True
        try:
            # every participant bumps; the round id = value // world size
            # is identical across processes once all have entered
            n = agent.key_value_increment("dtx_health_check/seq", 1)
            round_id = (n - 1) // agent.num_processes
            agent.barrier(f"dtx_health_check/{round_id}",
                          timeout_s=timeout_s)
            return True
        except Exception:
            return False


# The user-facing alias, matching tf.distribute.MultiWorkerMirroredStrategy.
class MultiWorkerMirroredStrategy(CollectiveAllReduceStrategy):
    pass
