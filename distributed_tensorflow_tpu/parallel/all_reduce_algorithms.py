"""Explicit all-reduce algorithm library: ring, recursive halving-doubling,
shuffle.

≙ tensorflow/python/distribute/v1/all_reduce.py (1,282 LoC — SURVEY.md
§2.2 "the algorithmic spec worth porting"): ``build_ring_all_reduce``
(:250), ``build_recursive_hd_all_reduce`` (:422),
``build_shuffle_all_reduce`` (:554). The reference builds these as
per-device graph fragments with explicit send/recv edges; the TPU-native
forms are shard_map-region functions over ``ppermute``/``all_to_all`` —
the same chunk schedules, expressed as SPMD steps XLA compiles onto ICI.

Default training paths should keep using ``psum`` (XLA picks the
topology-optimal algorithm for the mesh); this library is the
explicit-control option the reference ships — for experimentation,
algorithm research, and validating XLA's choices against known
schedules.

All functions are per-shard region fns: call inside ``shard_map`` with
the value REPLICATED per device (classic allreduce semantics, one
contribution per device), e.g.::

    out = shard_map(lambda x: ring_all_reduce(x, "dp"),
                    mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                    check_vma=False)(stacked_contributions)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _axis_size(axis_name: str) -> int:
    return jax.lax.psum(1, axis_name)


def _chunk(chunks, idx):
    """chunks[idx] with a traced index."""
    return jax.lax.dynamic_index_in_dim(chunks, idx, axis=0,
                                        keepdims=False)


def _set_chunk(chunks, value, idx):
    return jax.lax.dynamic_update_index_in_dim(chunks, value, idx, axis=0)


def ring_all_reduce(x, axis_name: str = "dp"):
    """Bandwidth-optimal ring allreduce (≙ build_ring_all_reduce :250).

    Phase 1 — reduce-scatter: n-1 steps; at step s each device forwards
    the partial sum it received and adds its OWN contribution for that
    chunk. Phase 2 — all-gather: n-1 steps circulating the fully-reduced
    chunks. Each device sends 2(n-1)/n of the payload total: the classic
    ring bound.
    """
    n = _axis_size(axis_name)
    if n == 1:
        return x
    me = jax.lax.axis_index(axis_name)
    shape = x.shape
    flat = x.reshape(-1)
    pad = (-flat.size) % n
    flat = jnp.pad(flat, (0, pad))
    chunks = flat.reshape(n, -1)            # my contribution, chunked
    fwd = [(i, (i + 1) % n) for i in range(n)]

    # Reduce-scatter: device d starts the accumulation of chunk d; after
    # n-1 hops (each adding the local contribution of the chunk in
    # flight) device d holds the FULL sum of chunk (d+1) mod n.
    buf = _chunk(chunks, me)
    for s in range(n - 1):
        buf = jax.lax.ppermute(buf, axis_name, fwd)
        buf = buf + _chunk(chunks, (me - s - 1) % n)

    # All-gather: circulate the reduced chunks.
    out = _set_chunk(chunks, buf, (me + 1) % n)
    for s in range(n - 1):
        buf = jax.lax.ppermute(buf, axis_name, fwd)
        out = _set_chunk(out, buf, (me - s) % n)
    return out.reshape(-1)[:x.size].reshape(shape)


def recursive_hd_all_reduce(x, axis_name: str = "dp"):
    """Recursive halving-doubling (≙ build_recursive_hd_all_reduce :422):
    latency-optimal for power-of-two world sizes — 2·log2(n) steps of
    pairwise exchange at distance 1, 2, 4, ...

    Phase 1: reduce-scatter by halving (exchange the half the PEER keeps,
    add the received half). Phase 2: all-gather by doubling.
    """
    n = _axis_size(axis_name)
    if n == 1:
        return x
    if n & (n - 1):
        raise ValueError(f"recursive halving-doubling needs a power-of-2 "
                         f"world size, got {n}")
    me = jax.lax.axis_index(axis_name)
    shape = x.shape
    flat = x.reshape(-1)
    pad = (-flat.size) % n
    work = jnp.pad(flat, (0, pad))

    # Reduce-scatter by halving: log n rounds, peer = me ^ dist; the
    # device whose `dist` bit is 0 keeps the low half, 1 the high half.
    dists = []
    dist = n // 2
    while dist >= 1:
        peer_perm = [(i, i ^ dist) for i in range(n)]
        bit = (me // dist) % 2
        halves = jnp.stack([work[:work.size // 2], work[work.size // 2:]])
        to_keep = _chunk(halves, bit)
        to_send = _chunk(halves, 1 - bit)
        received = jax.lax.ppermute(to_send, axis_name, peer_perm)
        work = to_keep + received
        dists.append(dist)
        dist //= 2

    # All-gather: reverse the rounds, doubling the segment each time.
    for dist in reversed(dists):
        peer_perm = [(i, i ^ dist) for i in range(n)]
        received = jax.lax.ppermute(work, axis_name, peer_perm)
        bit = (me // dist) % 2
        # my segment is the `bit` half of the doubled segment
        work = jnp.where(bit == 0,
                         jnp.concatenate([work, received]),
                         jnp.concatenate([received, work]))
    return work[:flat.size].reshape(shape)


def shuffle_all_reduce(x, axis_name: str = "dp"):
    """Shuffle allreduce (≙ build_shuffle_all_reduce :554): one
    all-to-all scatters chunk c of every device to device c, each device
    reduces its chunk fully, one all-gather returns the results. Two
    steps of n-way traffic — the "shuffle gather" pattern.
    """
    n = _axis_size(axis_name)
    if n == 1:
        return x
    shape = x.shape
    flat = x.reshape(-1)
    pad = (-flat.size) % n
    flat = jnp.pad(flat, (0, pad))
    chunks = flat.reshape(n, -1)
    # all_to_all: device d receives chunk d from everyone -> (n, chunk)
    gathered = jax.lax.all_to_all(chunks, axis_name, split_axis=0,
                                  concat_axis=0, tiled=True)
    reduced = gathered.reshape(n, -1).sum(axis=0)    # my chunk, full sum
    # all-gather the reduced chunks back to everyone
    full = jax.lax.all_gather(reduced, axis_name, axis=0, tiled=True)
    return full[:x.size].reshape(shape)


ALGORITHMS = {
    "ring": ring_all_reduce,
    "recursive_hd": recursive_hd_all_reduce,
    "shuffle": shuffle_all_reduce,
    "xla": lambda x, axis_name="dp": jax.lax.psum(x, axis_name),
}


def all_reduce(x, axis_name: str = "dp", algorithm: str = "xla"):
    """Dispatch by algorithm name (≙ the reference's per-algorithm build
    functions; "xla" = let the compiler choose — the default path)."""
    try:
        fn = ALGORITHMS[algorithm]
    except KeyError:
        raise ValueError(f"algorithm={algorithm!r}; expected one of "
                         f"{sorted(ALGORITHMS)}") from None
    return fn(x, axis_name=axis_name)
