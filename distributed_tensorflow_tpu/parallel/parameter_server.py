"""ParameterServerStrategy — asynchronous training with sharded variables.

≙ tensorflow/python/distribute/parameter_server_strategy_v2.py:77
``ParameterServerStrategyV2`` (SURVEY.md §2.1, §3.3).

TPU-native redesign: the reference places variable shards round-robin on
dedicated PS *processes* (parameter_server_strategy_v2.py:872) and workers
pull them over grpc eager contexts. On TPU the bandwidth hierarchy inverts —
HBM + ICI are far faster than any host — so "parameter serving" becomes
axis-0 sharding of large variables across the mesh (``ShardedVariable``,
XLA partitions lookups), while the *asynchrony* (the actual point of PS
training) lives in the host-side ``ClusterCoordinator``
(coordinator/cluster_coordinator.py in this package): a closure queue
dispatching steps to workers without a global barrier.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
from jax.sharding import Mesh

from distributed_tensorflow_tpu.cluster import topology as topo_lib
from distributed_tensorflow_tpu.cluster.resolver import ClusterResolver
from distributed_tensorflow_tpu.parallel.sharded_variable import (
    FixedShardsPartitioner,
    Partitioner,
    ShardedVariable,
)
from distributed_tensorflow_tpu.parallel.strategy import Strategy
from distributed_tensorflow_tpu.parallel.values import (
    MirroredVariable,
    VariableAggregation,
    VariableSynchronization,
)


class ParameterServerStrategy(Strategy):
    """Async PS training: sharded variables + coordinator-driven dispatch.

    ``variable_partitioner`` decides which variables get axis-0 sharding
    (≙ parameter_server_strategy_v2.py:689 ``_create_variable``: variables
    matching the partitioner become ShardedVariable; small ones stay
    replicated).
    """

    SHARD_AXIS = "ps_shard"

    def __init__(self, cluster_resolver: ClusterResolver | None = None,
                 variable_partitioner: Partitioner | None = None,
                 mesh: Mesh | None = None):
        self._cluster_resolver = cluster_resolver
        if mesh is None:
            n = len(jax.devices())
            mesh = topo_lib.make_mesh(
                [(topo_lib.DATA_AXIS, 1), (self.SHARD_AXIS, n)])
        self.variable_partitioner = (variable_partitioner
                                     or FixedShardsPartitioner(1))
        super().__init__(mesh=mesh, data_axis_names=(topo_lib.DATA_AXIS,))

    @property
    def cluster_resolver(self) -> ClusterResolver | None:
        return self._cluster_resolver

    def gradient_bucketer(self):
        # PS training is asynchronous: gradients apply to sharded
        # variables through the coordinator, never via a sync allreduce.
        return None

    def create_variable(self, value, *, name=None, trainable=True,
                        synchronization=VariableSynchronization.AUTO,
                        aggregation=VariableAggregation.NONE, dtype=None):
        """Shard large variables on axis 0, mirror the rest
        (≙ _create_variable, parameter_server_strategy_v2.py:689)."""
        import jax.numpy as jnp
        arr = jnp.asarray(value, dtype=dtype)
        parts = self.variable_partitioner(arr.shape, arr.dtype) \
            if arr.ndim >= 1 else [1]
        if parts and parts[0] > 1:
            var = ShardedVariable(
                arr, mesh=self.mesh, shard_axis_name=self.SHARD_AXIS,
                num_shards=parts[0], name=name, trainable=trainable)
            self._variables.append(var)
            return var
        return super().create_variable(
            value, name=name, trainable=trainable,
            synchronization=synchronization, aggregation=aggregation,
            dtype=dtype)

    def make_coordinator(self, **kwargs):
        """Build the ClusterCoordinator for this strategy
        (≙ tf.distribute.coordinator.ClusterCoordinator(strategy)).

        In a multi-process runtime the coordinator dispatches closures to
        the cluster's worker PROCESSES over the coordination service
        (coordinator/remote_dispatch.py — ≙ the grpc dispatch in
        cluster_coordinator.py:1027); single-process falls back to local
        device lanes. Worker tasks must run
        ``remote_dispatch.run_worker_loop()``.
        """
        from distributed_tensorflow_tpu.coordinator.cluster_coordinator \
            import ClusterCoordinator
        from distributed_tensorflow_tpu.cluster.coordination import (
            coordination_service)
        agent = coordination_service()
        if agent.is_distributed and "remote_worker_ids" not in kwargs:
            kwargs["remote_worker_ids"] = [
                p for p in range(agent.num_processes)
                if p != agent.process_id]
        return ClusterCoordinator(strategy=self, **kwargs)


# Alias for the V2 name used in reference scripts.
ParameterServerStrategyV2 = ParameterServerStrategy


class ParameterServerStrategyV1(Strategy):
    """Graph-mode-era PS strategy (≙ parameter_server_strategy.py:
    ``ParameterServerStrategyExtended``, SURVEY.md §2.1 row V1).

    V1 places each variable WHOLE on one parameter server, round-robin —
    vs V2's axis-0 sharding. TPU-native: variables are
    :class:`AggregatingVariable`s pinned round-robin across parameter
    devices (host CPU by default, mirroring vars-on-PS-host placement);
    compute runs replicated on the mesh and write-back re-pins the
    single copy, preserving the one-copy-per-variable memory profile.
    """

    def __init__(self, mesh: Mesh | None = None,
                 parameter_devices: Sequence | None = None,
                 cluster_resolver: ClusterResolver | None = None):
        super().__init__(mesh=mesh,
                         data_axis_names=(topo_lib.DATA_AXIS,))
        self._cluster_resolver = cluster_resolver
        if parameter_devices is None:
            from distributed_tensorflow_tpu.parallel.ps_values import (
                _default_parameter_device)
            parameter_devices = [_default_parameter_device()]
        self._parameter_devices = list(parameter_devices)
        self._next_ps = 0

    @property
    def cluster_resolver(self) -> ClusterResolver | None:
        return self._cluster_resolver

    @property
    def parameter_devices(self) -> list:
        return list(self._parameter_devices)

    def create_variable(self, value, *, name=None, trainable=True,
                        synchronization=None, aggregation=None,
                        dtype=None):
        from distributed_tensorflow_tpu.parallel.ps_values import (
            AggregatingVariable)
        from distributed_tensorflow_tpu.parallel.values import (
            VariableAggregation, VariableSynchronization)
        if synchronization is VariableSynchronization.ON_READ:
            # per-replica state is NOT parameter-server-placed
            return super().create_variable(
                value, name=name, trainable=trainable,
                synchronization=synchronization,
                aggregation=aggregation or VariableAggregation.SUM,
                dtype=dtype)
        device = self._parameter_devices[
            self._next_ps % len(self._parameter_devices)]
        self._next_ps += 1          # ≙ round-robin placement (:872)
        var = AggregatingVariable(
            value, device=device, name=name, trainable=trainable,
            aggregation=aggregation or VariableAggregation.MEAN,
            dtype=dtype)
        self._variables.append(var)
        return var
