"""TPU embedding API: sharded tables, per-table optimizers, combiners.

≙ the reference's TPU embedding stack (SURVEY.md §2.6):
tensorflow/python/tpu/tpu_embedding_v2.py:76 ``TPUEmbedding``,
tpu_embedding_v3.py:498 ``TPUEmbeddingV2`` (SparseCore),
tpu_embedding_v2_utils.py (TableConfig/FeatureConfig/optimizers).
"""

from distributed_tensorflow_tpu.embedding.embedding import (  # noqa: F401
    Adagrad,
    Adam,
    FTRL,
    FeatureConfig,
    SGD,
    TableConfig,
    TPUEmbedding,
    apply_gradients,
    create_state,
    lookup,
)
from distributed_tensorflow_tpu.embedding.dynamic import (  # noqa: F401
    CountMinSketch,
    DynamicTable,
    DynamicTableConfig,
    StaticHashTable,
)
