"""TPU-native embedding: mesh-sharded tables + decoupled per-table optimizers.

≙ tensorflow/python/tpu/tpu_embedding_v2.py:76 (``TPUEmbedding``: config,
build, lookup, ``apply_gradients`` decoupled from the dense optimizer) and
tpu_embedding_v3.py:498 (SparseCore: sharded tables, dedup). The reference
splits embedding work onto dedicated hardware (TensorCore host loops /
SparseCore) with an enqueue/dequeue pipeline; on a JAX/XLA TPU the same
capability is expressed directly in the SPMD program:

- Tables live in HBM as ``jax.Array``s row-sharded over the mesh's model
  axis (``NamedSharding(mesh, P(shard_axis, None))``) — XLA partitions the
  gather so each chip looks up only its rows and all-to-alls the results
  over ICI, the SparseCore communication pattern without custom hardware
  scheduling.
- Lookups are pure functions differentiable w.r.t. the tables; the
  backward gather is a scatter-add XLA fuses into the step program (no
  separate enqueue/dequeue phases to keep coherent).
- ``apply_gradients`` is a pure per-table optimizer update with slot
  variables (≙ tpu_embedding_v2_utils.py SGD/Adagrad/Adam/FTRL), fully
  decoupled from the dense optimizer.

Two API layers:
- functional: ``create_state`` / ``lookup`` / ``apply_gradients`` — pure,
  jit/pjit-composable, the idiomatic JAX shape.
- stateful: :class:`TPUEmbedding` mirroring the reference object API
  (``embedding_tables``, ``__call__``, ``apply_gradients``) for parity.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# ---------------------------------------------------------------------------
# Optimizers (≙ tpu_embedding_v2_utils.py: SGD :432, Adagrad :524,
# Adam :854, FTRL :1051 — slot layout kept, math identical)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class _Optimizer:
    learning_rate: float = 0.01

    def slot_names(self) -> tuple:
        return ()

    def init_slots(self, table: jax.Array) -> dict:
        return {}

    def apply(self, table, grad, slots, step):
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class SGD(_Optimizer):
    def apply(self, table, grad, slots, step):
        return table - self.learning_rate * grad, {}


@dataclasses.dataclass(frozen=True)
class Adagrad(_Optimizer):
    initial_accumulator_value: float = 0.1

    def slot_names(self) -> tuple:
        return ("accumulator",)

    def init_slots(self, table) -> dict:
        return {"accumulator": jnp.full_like(
            table, self.initial_accumulator_value)}

    def apply(self, table, grad, slots, step):
        acc = slots["accumulator"] + jnp.square(grad)
        new = table - self.learning_rate * grad * jax.lax.rsqrt(acc + 1e-12)
        return new, {"accumulator": acc}


@dataclasses.dataclass(frozen=True)
class Adam(_Optimizer):
    beta_1: float = 0.9
    beta_2: float = 0.999
    epsilon: float = 1e-7

    def slot_names(self) -> tuple:
        return ("momenta", "velocities")

    def init_slots(self, table) -> dict:
        return {"momenta": jnp.zeros_like(table),
                "velocities": jnp.zeros_like(table)}

    def apply(self, table, grad, slots, step):
        t = step.astype(jnp.float32) + 1.0
        m = self.beta_1 * slots["momenta"] + (1 - self.beta_1) * grad
        v = self.beta_2 * slots["velocities"] + \
            (1 - self.beta_2) * jnp.square(grad)
        m_hat = m / (1 - self.beta_1 ** t)
        v_hat = v / (1 - self.beta_2 ** t)
        new = table - self.learning_rate * m_hat / \
            (jnp.sqrt(v_hat) + self.epsilon)
        return new, {"momenta": m, "velocities": v}


@dataclasses.dataclass(frozen=True)
class FTRL(_Optimizer):
    learning_rate_power: float = -0.5
    initial_accumulator_value: float = 0.1
    l1_regularization_strength: float = 0.0
    l2_regularization_strength: float = 0.0

    def slot_names(self) -> tuple:
        return ("accumulators", "linears")

    def init_slots(self, table) -> dict:
        return {"accumulators": jnp.full_like(
            table, self.initial_accumulator_value),
            "linears": jnp.zeros_like(table)}

    def apply(self, table, grad, slots, step):
        acc, lin = slots["accumulators"], slots["linears"]
        acc_new = acc + jnp.square(grad)
        p = -self.learning_rate_power
        sigma = (acc_new ** p - acc ** p) / self.learning_rate
        lin_new = lin + grad - sigma * table
        quad = acc_new ** p / self.learning_rate \
            + 2 * self.l2_regularization_strength
        l1 = self.l1_regularization_strength
        pre = jnp.clip(lin_new, -l1, l1) - lin_new
        new = jnp.where(jnp.abs(lin_new) > l1, pre / quad,
                        jnp.zeros_like(table))
        return new, {"accumulators": acc_new, "linears": lin_new}


# ---------------------------------------------------------------------------
# Configs (≙ tpu_embedding_v2_utils.py TableConfig :1205 /
# FeatureConfig :1378)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class TableConfig:
    """One logical embedding table.

    ``combiner`` reduces multivalent features: "sum" | "mean" | "sqrtn".
    ``optimizer`` overrides the TPUEmbedding-level optimizer per table.
    """
    vocabulary_size: int
    dim: int
    initializer: Callable | None = None
    optimizer: _Optimizer | None = None
    combiner: str = "mean"
    name: str | None = None

    def __post_init__(self):
        # Loud validation AT CONSTRUCTION (≙ the reference's
        # TableConfig argument checks, tpu_embedding_v2_utils.py:1205):
        # a non-positive vocab/dim would otherwise surface as an opaque
        # XLA shape error deep inside a jitted lookup.
        if not isinstance(self.vocabulary_size, (int, np.integer)) \
                or self.vocabulary_size <= 0:
            raise ValueError(
                f"table {self.name or '<unnamed>'}: vocabulary_size "
                f"must be a positive int, got {self.vocabulary_size!r}")
        if not isinstance(self.dim, (int, np.integer)) or self.dim <= 0:
            raise ValueError(
                f"table {self.name or '<unnamed>'}: dim must be a "
                f"positive int, got {self.dim!r}")
        if self.combiner not in ("sum", "mean", "sqrtn"):
            raise ValueError(f"combiner {self.combiner!r} not in "
                             f"sum/mean/sqrtn")


@dataclasses.dataclass(frozen=True)
class FeatureConfig:
    """One input feature looked up in a (possibly shared) table."""
    table: TableConfig
    max_sequence_length: int = 0       # 0 = combiner-reduced output
    name: str | None = None

    def __post_init__(self):
        if not isinstance(self.table, TableConfig):
            raise ValueError(
                f"feature {self.name or '<unnamed>'}: table must be a "
                f"TableConfig, got {type(self.table).__name__}")
        if not isinstance(self.max_sequence_length, (int, np.integer)) \
                or self.max_sequence_length < 0:
            raise ValueError(
                f"feature {self.name or '<unnamed>'}: "
                f"max_sequence_length must be a non-negative int, got "
                f"{self.max_sequence_length!r}")


def _table_name(table: TableConfig, idx: int) -> str:
    return table.name or f"table_{idx}"


def _unique_tables(feature_config) -> list[TableConfig]:
    """Tables in first-seen order; shared tables appear once
    (≙ tpu_embedding_v2.py table dedup across features)."""
    seen: list[TableConfig] = []
    for fc in jax.tree_util.tree_leaves(
            feature_config,
            is_leaf=lambda x: isinstance(x, FeatureConfig)):
        # identity, not equality: two distinct tables may share a config
        if not any(t is fc.table for t in seen):
            seen.append(fc.table)
    return seen


# ---------------------------------------------------------------------------
# Functional core
# ---------------------------------------------------------------------------

def create_state(feature_config, optimizer: _Optimizer | None = None,
                 *, mesh: Mesh | None = None, shard_axis: str = "tp",
                 rng: jax.Array | None = None) -> dict:
    """Build {tables, slots, step}: tables row-sharded over ``shard_axis``
    when the mesh has it (≙ SparseCore table sharding,
    tpu_embedding_v3.py:498; PS-era axis-0 ShardedVariable otherwise)."""
    rng = jax.random.PRNGKey(0) if rng is None else rng
    tables: dict[str, jax.Array] = {}
    slots: dict[str, dict] = {}
    sharding = None
    if mesh is not None and shard_axis in mesh.shape:
        sharding = NamedSharding(mesh, P(shard_axis, None))
    for i, tc in enumerate(_unique_tables(feature_config)):
        name = _table_name(tc, i)
        if name in tables:
            raise ValueError(f"duplicate table name {name!r}")
        init = tc.initializer or jax.nn.initializers.truncated_normal(0.02)
        rng, sub = jax.random.split(rng)
        rows = _padded_vocab(tc.vocabulary_size, mesh, shard_axis)
        tab = init(sub, (rows, tc.dim), jnp.float32)
        if sharding is not None:
            tab = jax.device_put(tab, sharding)
        tables[name] = tab
        opt = tc.optimizer or optimizer or SGD()
        slots[name] = opt.init_slots(tab)
        if sharding is not None:
            slots[name] = {k: jax.device_put(v, sharding)
                           for k, v in slots[name].items()}
    return {"tables": tables, "slots": slots,
            "step": jnp.zeros((), jnp.int32)}


def _padded_vocab(vocab: int, mesh, shard_axis: str) -> int:
    """Round the row count up to the shard count (≙ the reference's
    shard-even padding, sharded_variable.py partitioner contract)."""
    if mesh is None or shard_axis not in mesh.shape:
        return vocab
    n = mesh.shape[shard_axis]
    return ((vocab + n - 1) // n) * n


def _combine(rows, ids, weights, combiner: str):
    """Reduce multivalent lookups (B, L, D) -> (B, D) with a validity
    mask (ids < 0 are padding) and optional per-id weights
    (≙ the combiner semantics of tpu_embedding_v2.py enqueue)."""
    valid = (ids >= 0).astype(rows.dtype)
    w = valid if weights is None else weights.astype(rows.dtype) * valid
    out = jnp.einsum("bld,bl->bd", rows, w)
    if combiner == "sum":
        return out
    denom = jnp.sum(w if combiner == "mean" else jnp.square(w), axis=-1)
    if combiner == "sqrtn":
        denom = jnp.sqrt(denom)
    return out / jnp.maximum(denom, 1e-12)[:, None]


def lookup(tables: Mapping[str, jax.Array], feature_config, features,
           weights=None, *, dedup: bool = False,
           unique_size: int | None = None):
    """Embedding activations for ``features`` (structure-matching
    ``feature_config``); differentiable w.r.t. ``tables``.

    - 1-D int ids (B,): one row per example -> (B, D).
    - 2-D ids (B, L): multivalent; ids < 0 are padding; reduced by the
      table's combiner -> (B, D) — unless the feature has
      ``max_sequence_length > 0``, which returns (B, L, D) with padded
      rows zeroed (≙ sequence features, tpu_embedding_v2.py).
    - ``dedup``: gather unique ids once and expand (≙ SparseCore dedup,
      tpu_embedding_v3.py). Pass ``unique_size`` (a static bound on the
      distinct ids per batch, e.g. vocab size or an empirical cap) to
      actually shrink the table gather; without it the unique buffer is
      batch-sized and dedup only coalesces duplicate ROW READS (a
      bandwidth win for hot ids, not a FLOP win).
    """
    flat_fc = jax.tree_util.tree_leaves(
        feature_config, is_leaf=lambda x: isinstance(x, FeatureConfig))
    flat_feats = jax.tree_util.tree_leaves(features)
    flat_w = (jax.tree_util.tree_leaves(
        weights, is_leaf=lambda x: x is None or hasattr(x, "shape"))
        if weights is not None else [None] * len(flat_fc))
    if len(flat_fc) != len(flat_feats):
        raise ValueError(
            f"{len(flat_feats)} features for {len(flat_fc)} FeatureConfigs")
    if len(flat_w) != len(flat_fc):
        raise ValueError(
            f"weights must mirror the features structure: got "
            f"{len(flat_w)} weight leaves for {len(flat_fc)} features")
    uniq = _unique_tables(feature_config)
    names = {id(tc): _table_name(tc, i) for i, tc in enumerate(uniq)}

    outs = []
    for fc, ids, w in zip(flat_fc, flat_feats, flat_w):
        table = tables[names[id(fc.table)]]
        ids = jnp.asarray(ids)
        safe = jnp.maximum(ids, 0)
        if dedup:
            rows = _dedup_gather(table, safe, unique_size)
        else:
            rows = table[safe]
        if ids.ndim == 1:
            if w is not None:
                raise ValueError(
                    f"feature {fc.name!r}: weights are only valid for "
                    f"combiner-reduced (2-D) features, not dense 1-D ids "
                    f"(≙ the reference's enqueue validation)")
            outs.append(rows)
        elif fc.max_sequence_length > 0:
            if w is not None:
                raise ValueError(
                    f"feature {fc.name!r}: weights are not supported for "
                    f"sequence features (max_sequence_length > 0)")
            mask = (ids >= 0).astype(rows.dtype)[..., None]
            outs.append(rows * mask)
        else:
            outs.append(_combine(rows, ids, w, fc.table.combiner))
    treedef = jax.tree_util.tree_structure(
        feature_config, is_leaf=lambda x: isinstance(x, FeatureConfig))
    return jax.tree_util.tree_unflatten(treedef, outs)


def _dedup_gather(table, ids, unique_size: int | None = None):
    """Gather with duplicate-id elimination: unique (static size) ->
    one gather -> inverse expand. ``unique_size`` caps the unique buffer
    (static shape under jit); ids beyond the cap fold onto row 0."""
    shape = ids.shape
    flat = ids.reshape(-1)
    size = min(unique_size or flat.shape[0], flat.shape[0])
    vals, inv = jnp.unique(flat, size=size, fill_value=0,
                           return_inverse=True)
    rows = table[vals]
    return rows[inv.reshape(-1)].reshape(*shape, table.shape[-1])


def apply_gradients(state: dict, grads: Mapping[str, jax.Array],
                    feature_config, optimizer: _Optimizer | None = None
                    ) -> dict:
    """Pure per-table update (≙ TPUEmbedding.apply_gradients,
    tpu_embedding_v2.py:754): ``grads`` maps table name -> dense gradient
    (autodiff through ``lookup`` produces exactly this).

    **Zero-lookup tables are a no-op, by contract.** A table absent
    from ``grads`` (or mapped to None) — e.g. a feature that received
    no lookups this step — keeps its weights AND its optimizer slot
    state bit-identical: no Adam moment decay, no FTRL accumulator
    drift on untouched tables. (Rows of a *touched* table follow the
    optimizer's dense semantics, where a zero gradient still decays
    Adam momenta — the reference's behavior; row-sparse no-decay
    updates live in embedding/dynamic.py.) The step counter still
    advances: it is the global step, shared by every table's bias
    correction."""
    uniq = _unique_tables(feature_config)
    tables, slots = dict(state["tables"]), dict(state["slots"])
    for i, tc in enumerate(uniq):
        name = _table_name(tc, i)
        if name not in grads or grads[name] is None:
            continue
        opt = tc.optimizer or optimizer or SGD()
        new_table, new_slots = opt.apply(
            tables[name], grads[name], slots[name], state["step"])
        tables[name] = new_table
        slots[name] = new_slots
    return {"tables": tables, "slots": slots, "step": state["step"] + 1}


# ---------------------------------------------------------------------------
# Stateful wrapper (reference API parity)
# ---------------------------------------------------------------------------

class TPUEmbedding:
    """Object API mirroring the reference (tpu_embedding_v2.py:76).

    Usage::

        emb = TPUEmbedding(feature_config, optimizer=Adagrad(0.1),
                           mesh=mesh)
        activations = emb(features)     # structure matches feature_config
        ...
        emb.apply_gradients(table_grads)

    The instance owns {tables, slots, step} as sharded jax.Arrays;
    ``state``/``load_state`` expose them for checkpointing
    (≙ the reference's checkpoint integration of embedding_tables).
    """

    def __init__(self, feature_config, optimizer: _Optimizer | None = None,
                 *, mesh: Mesh | None = None, shard_axis: str = "tp",
                 rng: jax.Array | None = None):
        self.feature_config = feature_config
        self.optimizer = optimizer
        self.mesh = mesh
        self.shard_axis = shard_axis
        self._state = create_state(feature_config, optimizer, mesh=mesh,
                                   shard_axis=shard_axis, rng=rng)
        self._apply = None

    @property
    def state(self) -> dict:
        return self._state

    def load_state(self, state: dict):
        self._state = state

    @property
    def embedding_tables(self) -> dict:
        """name -> table array (≙ TPUEmbedding.embedding_tables)."""
        return self._state["tables"]

    def __call__(self, features, weights=None, *, dedup: bool = False):
        return lookup(self._state["tables"], self.feature_config, features,
                      weights, dedup=dedup)

    def lookup_fn(self):
        """The pure (tables, features) -> activations fn, for use inside
        a jitted train step (differentiate w.r.t. arg 0)."""
        fc = self.feature_config
        return lambda tables, features, **kw: lookup(tables, fc, features,
                                                     **kw)

    def apply_gradients(self, grads: Mapping[str, jax.Array]):
        if self._apply is None:
            fc, opt = self.feature_config, self.optimizer

            @jax.jit
            def step(state, grads):
                return apply_gradients(state, grads, fc, opt)

            self._apply = step
        self._state = self._apply(self._state, grads)
