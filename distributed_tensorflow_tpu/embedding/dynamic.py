"""Dynamic embedding tables: hash-free id→row membership with
frequency-capped admission, LFU+TTL eviction, and table growth.

Static :class:`~distributed_tensorflow_tpu.embedding.embedding.
TableConfig` tables assume the vocabulary is known up front. An online
recommender's id space is unbounded and Zipf-shaped: most ids are seen
once or twice and never again, a small head carries most of the
traffic. :class:`DynamicTable` gives that workload a bounded-memory
table (ROADMAP item 2):

- **admission** — an id earns a dedicated row only after the
  frequency sketch has seen it ``admission_threshold`` times; colder
  ids share the reserved COLD row (row 0), which still trains (it is
  the learned prior for rare ids).
- **eviction (LFU+TTL)** — when the table is full, TTL-expired rows
  (idle longer than ``ttl_steps``) are evicted least-frequent-first;
  with nothing expired, the LFU row is evicted only when the admission
  candidate's frequency beats it (no thrash between equals).
- **growth** — when the mapped load factor crosses
  ``growth_load_factor`` and ``max_capacity`` allows, the row count
  DOUBLES; trained rows and their optimizer slot values are preserved
  bit-for-bit, new rows join the free list.

The row/slot math reuses the per-table optimizers of
``embedding/embedding.py`` (SGD/Adagrad/Adam/FTRL) applied ROW-SPARSE:
only the rows a batch touched are gathered, updated, and scattered
back — untouched rows' slot state is bit-identical afterwards (no
spurious Adam moment decay, the same contract
``embedding.apply_gradients`` documents for zero-lookup tables).

Membership IS state: :meth:`DynamicTable.state_dict` packs the id→row
map, frequency sketch, per-row LFU/TTL bookkeeping and counters next
to the row/slot arrays under FIXED leaf names, so the table rides the
existing :class:`~distributed_tensorflow_tpu.checkpoint.checkpoint.
Checkpoint` / peer-snapshot machinery unchanged and a recovered
trainer restores *membership*, not just weights.

Row 0 is the shared COLD row; it is never mapped to an id. The jit'd
sparse apply pads its unique-row index buffer with ``capacity`` — out
of bounds, so XLA's scatter drops the padding updates (and the paired
gather clips harmlessly): padding can never perturb a real row.
"""

from __future__ import annotations

import dataclasses
import functools
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu.embedding.embedding import (
    SGD,
    Adagrad,
    _Optimizer,
)

#: Row 0: shared cold row (sub-threshold ids). Never mapped to an id.
COLD_ROW = 0
RESERVED_ROWS = 1


class CountMinSketch:
    """Fixed-memory frequency estimator (conservative overcount): the
    admission filter's memory stays O(width × depth) no matter how many
    distinct ids the stream produces."""

    def __init__(self, width: int = 2048, depth: int = 4, seed: int = 0):
        if width <= 0 or depth <= 0:
            raise ValueError(f"sketch width/depth must be positive, got "
                             f"{width}x{depth}")
        self.width = int(width)
        self.depth = int(depth)
        self.seed = int(seed)
        rng = np.random.default_rng([seed, 0xC0FFEE])
        # odd multipliers -> full-period multiplicative hashing
        self._mul = (rng.integers(1, 2**63, size=depth,
                                  dtype=np.uint64) | np.uint64(1))
        self._add = rng.integers(0, 2**63, size=depth, dtype=np.uint64)
        self.counts = np.zeros((depth, self.width), dtype=np.uint32)
        # flat counter cells touched since mark_clean() — the sketch's
        # contribution to a row-sparse delta snapshot
        self._dirty: set[int] = set()

    def _slots(self, ids: np.ndarray) -> np.ndarray:
        ids = np.asarray(ids, dtype=np.uint64)
        out = np.empty((self.depth, len(ids)), dtype=np.int64)
        for d in range(self.depth):
            h = ids * self._mul[d] + self._add[d]       # mod 2^64
            out[d] = ((h >> np.uint64(31))
                      % np.uint64(self.width)).astype(np.int64)
        return out

    def add(self, ids: np.ndarray):
        slots = self._slots(ids)
        for d in range(self.depth):
            np.add.at(self.counts[d], slots[d], 1)
        flat = (np.arange(self.depth, dtype=np.int64)[:, None]
                * self.width + slots).ravel()
        self._dirty.update(np.unique(flat).tolist())

    # -- delta snapshots --------------------------------------------------
    def delta(self) -> "tuple[np.ndarray, np.ndarray]":
        """(flat indices, values) of every counter cell touched since
        :meth:`mark_clean` — sorted, so two identical dirty sets
        serialize identically."""
        idx = np.asarray(sorted(self._dirty), dtype=np.int64)
        return idx, self.counts.reshape(-1)[idx].copy()

    def apply_delta(self, idx: np.ndarray, vals: np.ndarray):
        flat = self.counts.reshape(-1)
        flat[np.asarray(idx, dtype=np.int64)] = np.asarray(
            vals, dtype=np.uint32)

    def mark_clean(self):
        self._dirty.clear()

    def estimate(self, ids: np.ndarray) -> np.ndarray:
        slots = self._slots(np.atleast_1d(ids))
        ests = np.stack([self.counts[d][slots[d]]
                         for d in range(self.depth)])
        return ests.min(axis=0).astype(np.int64)


@dataclasses.dataclass(frozen=True)
class DynamicTableConfig:
    """One dynamic table. Validation is loud at construction (the
    static TableConfig discipline): mis-sized tables must not surface
    as shape errors deep inside a jitted step."""

    dim: int
    initial_capacity: int = 256
    max_capacity: int | None = None          # default: 4x initial
    admission_threshold: int = 2
    ttl_steps: int = 512
    growth_load_factor: float = 0.85
    optimizer: _Optimizer | None = None      # default Adagrad(0.05)
    name: str = "table"
    seed: int = 0
    sketch_width: int = 2048
    sketch_depth: int = 4

    def __post_init__(self):
        if self.dim <= 0:
            raise ValueError(f"table {self.name!r}: dim must be "
                             f"positive, got {self.dim}")
        if self.initial_capacity <= RESERVED_ROWS:
            raise ValueError(
                f"table {self.name!r}: initial_capacity must exceed "
                f"the {RESERVED_ROWS} reserved rows, got "
                f"{self.initial_capacity}")
        cap = self.max_capacity
        if cap is not None and cap < self.initial_capacity:
            raise ValueError(
                f"table {self.name!r}: max_capacity {cap} < "
                f"initial_capacity {self.initial_capacity}")
        if self.admission_threshold < 1:
            raise ValueError(
                f"table {self.name!r}: admission_threshold must be "
                f">= 1, got {self.admission_threshold}")
        if self.ttl_steps < 1:
            raise ValueError(f"table {self.name!r}: ttl_steps must be "
                             f">= 1, got {self.ttl_steps}")
        if not 0.0 < self.growth_load_factor <= 1.0:
            raise ValueError(
                f"table {self.name!r}: growth_load_factor must be in "
                f"(0, 1], got {self.growth_load_factor}")

    @property
    def capacity_limit(self) -> int:
        return (self.max_capacity if self.max_capacity is not None
                else 4 * self.initial_capacity)


#: Fixed pad width for the jitted re-init scatter: pending admissions
#: flush in chunks of this many rows, so the program compiles once per
#: table shape instead of once per admission count.
_REINIT_PAD = 32


@jax.jit
def _jit_gather(table, idx):
    return table[idx]


@functools.lru_cache(maxsize=32)
def _reinit_fn(opt: _Optimizer):
    """One fused jitted program re-initializing a chunk of admitted
    rows AND their optimizer slots (slot init values are constants
    folded into the program)."""

    @jax.jit
    def reinit(table, slots, idx, fresh):
        table = table.at[idx].set(fresh)
        fresh_slots = opt.init_slots(fresh)
        slots = {k: slots[k].at[idx].set(fresh_slots[k])
                 for k in slots}
        return table, slots

    return reinit


@functools.lru_cache(maxsize=32)
def _sparse_apply_fn(opt: _Optimizer):
    """Jit'd row-sparse optimizer update, one program per optimizer
    (shape changes — batch pad width, table growth — retrace under the
    same jit). ``idx`` entries must be unique except for the padding
    value (the table's row count — out of bounds, so the scatter drops
    those updates and the gather clips)."""

    @jax.jit
    def apply(table, slots, idx, grads, step):
        rows = table[idx]
        row_slots = {k: v[idx] for k, v in slots.items()}
        new_rows, new_slots = opt.apply(rows, grads, row_slots, step)
        table = table.at[idx].set(new_rows)
        slots = {k: slots[k].at[idx].set(new_slots[k]) for k in slots}
        return table, slots

    return apply


class DynamicTable:
    """Bounded-memory id→row embedding table (see module docstring).

    Host-side membership (dict + numpy bookkeeping) decides WHICH row
    an id resolves to; device-side math (jnp rows/slots, jit'd sparse
    apply) trains only the rows a batch touched.
    """

    def __init__(self, cfg: DynamicTableConfig):
        self.cfg = cfg
        self.capacity = cfg.initial_capacity
        self._opt = cfg.optimizer or Adagrad(0.05)
        self.rows = self._init_rows(0, self.capacity)
        self.slots = {k: jnp.asarray(v) for k, v in
                      self._opt.init_slots(self.rows).items()}
        self.sketch = CountMinSketch(cfg.sketch_width, cfg.sketch_depth,
                                     seed=cfg.seed)
        self.id_to_row: dict[int, int] = {}
        self.row_id = np.full(self.capacity, -1, dtype=np.int64)
        self.row_freq = np.zeros(self.capacity, dtype=np.int64)
        self.row_last = np.zeros(self.capacity, dtype=np.int64)
        self._free = list(range(self.capacity - 1, RESERVED_ROWS - 1, -1))
        self.step = 0
        self.admissions = 0
        self.evictions = 0
        self.grows = 0
        self.declined = 0
        # rows whose weights/slots/bookkeeping changed since the last
        # mark_clean() — the row-sparse delta-snapshot feed. A capacity
        # change (growth) invalidates delta-ability entirely:
        # state_delta() returns None until the next full publish.
        self._dirty: set[int] = set()
        self._clean_capacity = self.capacity

    # -- init helpers -----------------------------------------------------
    def _init_rows(self, start: int, n: int) -> jnp.ndarray:
        """Deterministic truncated-normal-ish init for rows
        ``start..start+n-1`` (seeded per row block, so growth and
        re-admission re-initialize reproducibly)."""
        rng = np.random.default_rng([self.cfg.seed, start, n])
        return jnp.asarray(rng.normal(
            0.0, 0.02, size=(n, self.cfg.dim)).astype(np.float32))

    def _flush_reinits(self, pending: "list[tuple[int, int]]"):
        """Re-initialize all rows admitted by ONE translate call
        through the JITTED scatter, padded to :data:`_REINIT_PAD` so
        the program compiles once per table shape (per-admission eager
        scatters were the dominant cost of the ingest hot path; OOB
        padding rows are dropped by the scatter)."""
        if not pending:
            return
        for i in range(0, len(pending), _REINIT_PAD):
            chunk = pending[i:i + _REINIT_PAD]
            idx = np.full(_REINIT_PAD, self.capacity, dtype=np.int32)
            idx[:len(chunk)] = [r for r, _ in chunk]
            fresh = np.zeros((_REINIT_PAD, self.cfg.dim), np.float32)
            for j, (row, adm) in enumerate(chunk):
                fresh[j] = np.random.default_rng(
                    [self.cfg.seed, 0xAD417, row, adm]).normal(
                    0.0, 0.02, size=self.cfg.dim)
            self.rows, self.slots = _reinit_fn(self._opt)(
                self.rows, self.slots, jnp.asarray(idx),
                jnp.asarray(fresh))

    # -- membership -------------------------------------------------------
    @property
    def mapped(self) -> int:
        return len(self.id_to_row)

    @property
    def load_factor(self) -> float:
        return self.mapped / max(1, self.capacity - RESERVED_ROWS)

    def translate(self, ids: np.ndarray, *, train: bool = True
                  ) -> np.ndarray:
        """id -> row index for one batch. With ``train``, feeds the
        frequency sketch, admits ids crossing the threshold (growing or
        evicting as configured), and updates LFU/TTL bookkeeping.
        Unmapped ids resolve to the shared COLD row."""
        ids = np.asarray(ids, dtype=np.int64)
        if train:
            self.sketch.add(ids)
        uniq, counts = np.unique(ids, return_counts=True)
        row_of: dict[int, int] = {}
        ests = self.sketch.estimate(uniq) if train else None
        pending: list[tuple[int, int]] = []
        for j, uid in enumerate(uniq.tolist()):
            row = self.id_to_row.get(uid)
            if row is None and train \
                    and int(ests[j]) >= self.cfg.admission_threshold:
                row = self._admit(uid, int(ests[j]), pending)
            if row is None:
                row = COLD_ROW
            elif train:
                self.row_freq[row] += int(counts[j])
                self.row_last[row] = self.step
                self._dirty.add(row)
            row_of[uid] = row
        self._flush_reinits(pending)
        return np.asarray([row_of[int(i)] for i in ids], dtype=np.int32)

    def _admit(self, uid: int, est: int,
               pending: "list[tuple[int, int]]") -> int | None:
        if not self._free and self.load_factor \
                >= self.cfg.growth_load_factor:
            self._grow()
        if self._free:
            row = self._free.pop()
        else:
            row = self._evict_for(est)
            if row is None:
                self.declined += 1
                return None
        pending.append((row, self.admissions))
        self.id_to_row[uid] = row
        self.row_id[row] = uid
        self.row_freq[row] = est
        self.row_last[row] = self.step
        self.admissions += 1
        self._dirty.add(row)
        return row

    def _evict_for(self, candidate_est: int) -> int | None:
        mapped_rows = np.flatnonzero(self.row_id >= 0)
        if len(mapped_rows) == 0:
            return None
        expired = mapped_rows[
            self.row_last[mapped_rows] < self.step - self.cfg.ttl_steps]
        pool = expired if len(expired) else mapped_rows
        victim = int(pool[np.argmin(self.row_freq[pool])])
        if not len(expired) \
                and int(self.row_freq[victim]) >= candidate_est:
            return None          # LFU victim is hotter: decline, no thrash
        del self.id_to_row[int(self.row_id[victim])]
        self.row_id[victim] = -1
        self.row_freq[victim] = 0
        self.evictions += 1
        self._dirty.add(victim)
        return victim

    def _grow(self):
        new_cap = self.capacity * 2
        if new_cap > self.cfg.capacity_limit:
            return
        add = new_cap - self.capacity
        self.rows = jnp.concatenate(
            [self.rows, self._init_rows(self.capacity, add)])
        grown = self._opt.init_slots(
            jnp.zeros((add, self.cfg.dim), jnp.float32))
        self.slots = {k: jnp.concatenate([v, jnp.asarray(grown[k])])
                      for k, v in self.slots.items()}
        self.row_id = np.concatenate(
            [self.row_id, np.full(add, -1, dtype=np.int64)])
        self.row_freq = np.concatenate(
            [self.row_freq, np.zeros(add, dtype=np.int64)])
        self.row_last = np.concatenate(
            [self.row_last, np.zeros(add, dtype=np.int64)])
        self._free = list(range(new_cap - 1, self.capacity - 1, -1)) \
            + self._free
        self.capacity = new_cap
        self.grows += 1

    # -- device math ------------------------------------------------------
    def gather(self, row_idx: np.ndarray) -> jnp.ndarray:
        return _jit_gather(self.rows, jnp.asarray(row_idx))

    def apply_row_grads(self, row_idx: np.ndarray, grads: np.ndarray,
                        *, pad_to: int | None = None):
        """Row-sparse optimizer update: ``grads[i]`` is the PER-EXAMPLE
        gradient for ``row_idx[i]``; duplicate rows are summed here,
        then the unique rows are updated through the table's optimizer
        and scattered back. Untouched rows (weights AND slots) are
        bit-identical afterwards. ``pad_to`` fixes the unique-row
        buffer width so the jit'd program compiles once per width."""
        row_idx = np.asarray(row_idx)
        uniq, inv = np.unique(row_idx, return_inverse=True)
        agg = np.zeros((len(uniq), self.cfg.dim), dtype=np.float32)
        np.add.at(agg, inv, np.asarray(grads, dtype=np.float32))
        width = pad_to or len(uniq)
        if len(uniq) > width:
            raise ValueError(f"pad_to={width} < {len(uniq)} unique rows")
        # pad with an OUT-OF-BOUNDS row: XLA drops the scatter updates
        # for it, so padding never perturbs a real row (not even slot
        # decay) — works for dynamic AND static tables alike
        idx = np.full(width, self.capacity, dtype=np.int32)
        idx[:len(uniq)] = uniq
        pad_g = np.zeros((width, self.cfg.dim), dtype=np.float32)
        pad_g[:len(uniq)] = agg
        self.rows, self.slots = _sparse_apply_fn(self._opt)(
            self.rows, self.slots, jnp.asarray(idx), jnp.asarray(pad_g),
            jnp.asarray(self.step, jnp.int32))
        self._dirty.update(int(r) for r in uniq)
        self.step += 1

    def end_step(self):
        """Advance the TTL clock without an optimizer update (eval-only
        batches)."""
        self.step += 1

    # -- checkpoint state (fixed leaf names) ------------------------------
    def state_dict(self) -> dict:
        """Two fixed-name leaves: ``rows`` (the trained table) and
        ``aux`` (a packed uint8 array holding slots + MEMBERSHIP —
        id→row map, sketch counts, LFU/TTL bookkeeping, counters), so
        the table checkpoints under any optimizer without the leaf-name
        set changing."""
        aux = {
            "slots": {k: np.asarray(v) for k, v in self.slots.items()},
            "capacity": self.capacity,
            "id_to_row": self.id_to_row,
            "row_id": self.row_id,
            "row_freq": self.row_freq,
            "row_last": self.row_last,
            "free": list(self._free),
            "sketch_counts": self.sketch.counts,
            "step": self.step,
            "counters": (self.admissions, self.evictions, self.grows,
                         self.declined),
        }
        return {"rows": np.asarray(self.rows),
                "aux": np.frombuffer(pickle.dumps(aux, protocol=4),
                                     dtype=np.uint8).copy()}

    def load_state_dict(self, state: dict):
        rows = np.asarray(state["rows"])
        aux = pickle.loads(np.asarray(state["aux"],
                                      dtype=np.uint8).tobytes())
        self.capacity = int(aux["capacity"])
        if rows.shape != (self.capacity, self.cfg.dim):
            raise ValueError(
                f"table {self.cfg.name!r}: restored rows "
                f"{rows.shape} != (capacity {self.capacity}, dim "
                f"{self.cfg.dim})")
        self.rows = jnp.asarray(rows)
        self.slots = {k: jnp.asarray(v)
                      for k, v in aux["slots"].items()}
        self.id_to_row = {int(k): int(v)
                          for k, v in aux["id_to_row"].items()}
        self.row_id = np.asarray(aux["row_id"], dtype=np.int64)
        self.row_freq = np.asarray(aux["row_freq"], dtype=np.int64)
        self.row_last = np.asarray(aux["row_last"], dtype=np.int64)
        self._free = [int(x) for x in aux["free"]]
        self.sketch.counts = np.asarray(aux["sketch_counts"],
                                        dtype=np.uint32)
        self.step = int(aux["step"])
        (self.admissions, self.evictions, self.grows,
         self.declined) = (int(x) for x in aux["counters"])
        self.mark_clean()

    # -- delta snapshots --------------------------------------------------
    @property
    def dirty_rows(self) -> int:
        """Rows touched since the last :meth:`mark_clean`."""
        return len(self._dirty)

    def mark_clean(self):
        """Commit point: what is in the table NOW is what the last
        published snapshot (full or delta) holds."""
        self._dirty.clear()
        self.sketch.mark_clean()
        self._clean_capacity = self.capacity

    def state_delta(self) -> "dict | None":
        """Row-sparse state since the last :meth:`mark_clean`: only the
        dirty rows' weights/slots/bookkeeping, the sketch's dirty
        cells, and the scalars. Returns ``None`` when the table GREW
        since the clean point — every row moved then, so only a full
        snapshot is honest (the publisher falls back to one).

        The free list ships as ``free_len`` alone: between grows it
        only ever shrinks by pops from its end (``_admit``), so the
        clean-point list truncated to ``free_len`` IS the current
        list — a structural invariant the delta format leans on
        (growth, the one operation that prepends, forces a full)."""
        if self.capacity != self._clean_capacity:
            return None
        idx = np.asarray(sorted(self._dirty), dtype=np.int64)
        sk_idx, sk_vals = self.sketch.delta()
        rows = np.asarray(self.rows)
        return {
            "capacity": self.capacity,
            "idx": idx,
            "rows": rows[idx].copy(),
            "slots": {k: np.asarray(v)[idx].copy()
                      for k, v in self.slots.items()},
            "row_id": self.row_id[idx].copy(),
            "row_freq": self.row_freq[idx].copy(),
            "row_last": self.row_last[idx].copy(),
            "free_len": len(self._free),
            "sketch_idx": sk_idx,
            "sketch_vals": sk_vals,
            "step": self.step,
            "counters": (self.admissions, self.evictions, self.grows,
                         self.declined),
        }

    def apply_state_delta(self, delta: dict):
        """Scatter a :meth:`state_delta` onto this table (which must
        hold the delta's parent state — the reconstructor's job to
        guarantee via the crc'd chain). Bit-identical to having taken
        the steps directly: rows/slots scatter on device, bookkeeping
        scatters on host, membership rebuilds from ``row_id``."""
        if int(delta["capacity"]) != self.capacity:
            raise ValueError(
                f"table {self.cfg.name!r}: delta capacity "
                f"{delta['capacity']} != table capacity "
                f"{self.capacity} (chain broken — restore the full "
                f"base first)")
        idx = np.asarray(delta["idx"], dtype=np.int64)
        if len(idx):
            jidx = jnp.asarray(idx)
            self.rows = self.rows.at[jidx].set(
                jnp.asarray(delta["rows"]))
            self.slots = {k: self.slots[k].at[jidx].set(
                jnp.asarray(v)) for k, v in delta["slots"].items()}
            self.row_id[idx] = np.asarray(delta["row_id"],
                                          dtype=np.int64)
            self.row_freq[idx] = np.asarray(delta["row_freq"],
                                            dtype=np.int64)
            self.row_last[idx] = np.asarray(delta["row_last"],
                                            dtype=np.int64)
        self._free = [int(x)
                      for x in self._free[:int(delta["free_len"])]]
        self.sketch.apply_delta(delta["sketch_idx"],
                                delta["sketch_vals"])
        mapped = np.flatnonzero(self.row_id >= 0)
        self.id_to_row = {int(self.row_id[r]): int(r) for r in mapped}
        self.step = int(delta["step"])
        (self.admissions, self.evictions, self.grows,
         self.declined) = (int(x) for x in delta["counters"])
        self.mark_clean()


class StaticHashTable:
    """The conventional baseline: a FIXED table with hash-bucketed
    id→row mapping (collisions and all) — no membership, no admission,
    no eviction, no growth. Same interface as :class:`DynamicTable`
    (``translate``/``gather``/``apply_row_grads``/``state_dict``) so
    the online bench can swap it in for the same-run baseline row."""

    _MIX = np.uint64(0x9E3779B97F4A7C15)

    def __init__(self, dim: int, capacity: int, *,
                 optimizer: _Optimizer | None = None, seed: int = 0,
                 name: str = "static"):
        if dim <= 0 or capacity <= 0:
            raise ValueError(f"table {name!r}: dim and capacity must "
                             f"be positive, got {dim}/{capacity}")
        self.cfg = DynamicTableConfig(
            dim=dim, initial_capacity=max(capacity, RESERVED_ROWS + 1),
            name=name, seed=seed, optimizer=optimizer)
        self.capacity = capacity
        self._opt = optimizer or SGD(0.05)
        rng = np.random.default_rng([seed, capacity])
        self.rows = jnp.asarray(rng.normal(
            0.0, 0.02, size=(capacity, dim)).astype(np.float32))
        self.slots = {k: jnp.asarray(v) for k, v in
                      self._opt.init_slots(self.rows).items()}
        self.step = 0
        self.admissions = self.evictions = self.grows = 0
        self.mapped = capacity
        # the shared apply_row_grads tracks dirty rows (delta
        # snapshots); the static baseline just ignores the set
        self._dirty: set[int] = set()

    def translate(self, ids: np.ndarray, *, train: bool = True
                  ) -> np.ndarray:
        ids = np.asarray(ids, dtype=np.uint64)
        return ((ids * self._MIX) >> np.uint64(33)).astype(np.int64) \
            % self.capacity

    gather = DynamicTable.gather
    apply_row_grads = DynamicTable.apply_row_grads
    end_step = DynamicTable.end_step

    def state_dict(self) -> dict:
        aux = {"slots": {k: np.asarray(v)
                         for k, v in self.slots.items()},
               "capacity": self.capacity, "step": self.step}
        return {"rows": np.asarray(self.rows),
                "aux": np.frombuffer(pickle.dumps(aux, protocol=4),
                                     dtype=np.uint8).copy()}

    def load_state_dict(self, state: dict):
        aux = pickle.loads(np.asarray(state["aux"],
                                      dtype=np.uint8).tobytes())
        self.capacity = int(aux["capacity"])
        self.rows = jnp.asarray(np.asarray(state["rows"]))
        self.slots = {k: jnp.asarray(v)
                      for k, v in aux["slots"].items()}
        self.step = int(aux["step"])
