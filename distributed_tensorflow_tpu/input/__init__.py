"""Distributed input pipeline (SURVEY.md §2.3 input layer)."""

from distributed_tensorflow_tpu.input.dataset import (
    AutoShardPolicy,
    Dataset,
    DistributedDataset,
    InputContext,
    InputOptions,
)
from distributed_tensorflow_tpu.input.example_parser import (
    FixedLenFeature,
    VarLenFeature,
    encode_example,
    example_reader,
    parse_example,
    parse_single_example,
)

__all__ = [
    "AutoShardPolicy", "Dataset", "DistributedDataset", "InputContext",
    "InputOptions", "FixedLenFeature", "VarLenFeature", "encode_example",
    "example_reader", "parse_example", "parse_single_example",
]
