"""Distributed input pipeline (SURVEY.md §2.3 input layer)."""

from distributed_tensorflow_tpu.input.dataset import (
    AUTOTUNE,
    AutoShardPolicy,
    Dataset,
    DistributedDataset,
    InputContext,
    InputOptions,
)
from distributed_tensorflow_tpu.input import image_ops
from distributed_tensorflow_tpu.input.data_service import (
    DataInputWorker,
    DataServiceClient,
    DataServiceConfig,
    DataServiceDispatcher,
)
from distributed_tensorflow_tpu.input.split_provider import SplitProvider
from distributed_tensorflow_tpu.input.stream import (
    StreamCorruptError,
    StreamDataset,
    StreamReader,
    StreamWriter,
)
from distributed_tensorflow_tpu.input.example_parser import (
    FixedLenFeature,
    VarLenFeature,
    encode_example,
    example_reader,
    parse_example,
    parse_single_example,
)

__all__ = [
    "AUTOTUNE", "AutoShardPolicy", "DataInputWorker", "DataServiceClient",
    "DataServiceConfig", "DataServiceDispatcher", "Dataset",
    "DistributedDataset", "InputContext", "InputOptions",
    "FixedLenFeature", "SplitProvider", "StreamCorruptError",
    "StreamDataset", "StreamReader", "StreamWriter", "VarLenFeature",
    "encode_example", "example_reader", "image_ops", "parse_example",
    "parse_single_example",
]
