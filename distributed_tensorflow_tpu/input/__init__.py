"""Distributed input pipeline (SURVEY.md §2.3 input layer)."""
