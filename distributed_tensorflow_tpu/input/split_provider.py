"""FILE-granularity split source for the disaggregated data service.

≙ the reference tf.data-service dispatcher's ``SplitProvider`` (SURVEY
L5b): the dispatcher does not ship *data*, it ships **splits** — units
of input work small enough to lease, re-issue and account exactly-once.
Here a split is one FILE of a file-rooted pipeline, the same granule
``Dataset.shard_files`` already shards statically; the provider owns

- the **split universe** of a job (the root file list, one split per
  file, indexed 0..N-1),
- the **deterministic epoch order** (a seed-keyed permutation per
  epoch, so every dispatcher incarnation — including one reformed
  mid-epoch under a new generation — derives the identical assignment
  stream), and
- the **per-split rebuild**: replaying the pipeline's recorded op
  chain (``Dataset.replay_spec``, the FILE auto-shard machinery) over
  a single-file source, so an input worker produces exactly the
  elements the in-process pipeline would have produced for that file.

Two construction paths:

- :meth:`from_dataset` — in-process (tests, the simulated fleet): the
  recorded op-chain closures are replayed directly.
- :meth:`from_factory` — cross-process: op-chain closures do not
  pickle, so remote input workers get a module-level factory
  ``fn(files) -> Dataset`` resolved by reference (the same
  pickle-by-reference contract the supervisor's spawn machinery uses
  for worker fns).
"""

from __future__ import annotations

import random
from typing import Callable, Sequence

from distributed_tensorflow_tpu.input.dataset import Dataset


class SplitProvider:
    """The split universe + per-split pipeline rebuild of one job."""

    def __init__(self, files: Sequence[str],
                 builder: Callable[[Sequence[str]], Dataset], *,
                 seed: int = 0):
        files = list(files)
        if not files:
            raise ValueError("a data-service job needs >= 1 file "
                             "(one FILE split per file)")
        self.files = files
        self.builder = builder
        self.seed = int(seed)

    # -- construction ------------------------------------------------------
    @classmethod
    def from_dataset(cls, dataset: Dataset, *, seed: int = 0
                     ) -> "SplitProvider":
        """Derive splits from a file-rooted pipeline's recorded op
        chain — exactly what ``shard_files`` replays, but per FILE
        instead of per worker-index stride."""
        files, reader, chain = dataset.replay_spec()

        def builder(subset):
            ds = Dataset.from_files(list(subset), reader)
            for op in reversed(chain):
                ds = op(ds)
            return ds

        return cls(files, builder, seed=seed)

    @classmethod
    def from_factory(cls, files: Sequence[str],
                     factory: Callable[[Sequence[str]], Dataset], *,
                     seed: int = 0) -> "SplitProvider":
        """Cross-process form: ``factory`` must be module-level
        (picklable by reference) and build the full per-split pipeline
        over a file subset."""
        return cls(files, factory, seed=seed)

    # -- the split universe ------------------------------------------------
    @property
    def num_splits(self) -> int:
        return len(self.files)

    def epoch_order(self, epoch: int) -> "list[int]":
        """The deterministic split permutation of one epoch: a pure
        function of ``(seed, epoch)`` (the resilience/faults.py
        string-seeding discipline — stable across processes and runs),
        so a reformed dispatcher re-derives the identical order and a
        straggler's stale assignment can be recognized for what it is."""
        order = list(range(self.num_splits))
        random.Random(f"dtx-data:{self.seed}:{int(epoch)}").shuffle(order)
        return order

    def build(self, split: int) -> Dataset:
        """The per-split pipeline: the recorded chain over ONE file."""
        if not 0 <= split < self.num_splits:
            raise ValueError(
                f"split {split} out of range [0, {self.num_splits})")
        return self.builder([self.files[split]])

    def elements(self, split: int) -> list:
        """Materialize one split's elements (what an input worker
        publishes). Deterministic given the pipeline: the exactly-once
        contract's unit of delivery."""
        return list(self.build(split))
