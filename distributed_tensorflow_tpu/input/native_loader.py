"""Python wrapper for the native C++ data-pipeline core.

≙ the reference's C++ tf.data engine feeding its distributed input layer
(SURVEY.md §2.7 native rows; input auto-sharding ≙ input_ops.py:28 DATA
policy). The hot path — file IO, shuffle, batch assembly, prefetch — runs
in native threads (distributed_tensorflow_tpu/native/pipeline.cc); Python
sees zero-copy numpy views and hands them to ``jax.device_put``.

On-disk format: fixed-size binary records (one structured-dtype numpy
record each); ``write_records`` produces it.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libdtx_pipeline.so")
_SRC_PATH = os.path.join(_NATIVE_DIR, "pipeline.cc")

_lib = None
_lib_lock = threading.Lock()


def _build_so():
    subprocess.run(
        ["g++", "-O3", "-fPIC", "-shared", "-pthread", "-std=c++17",
         "-o", _SO_PATH, _SRC_PATH],
        check=True, capture_output=True)


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_SO_PATH)
                or os.path.getmtime(_SO_PATH) < os.path.getmtime(_SRC_PATH)):
            _build_so()
        lib = ctypes.CDLL(_SO_PATH)
        lib.dtx_pipeline_create.restype = ctypes.c_void_p
        lib.dtx_pipeline_create.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int, ctypes.c_uint64, ctypes.c_int,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int]
        lib.dtx_pipeline_next.restype = ctypes.c_void_p
        lib.dtx_pipeline_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
        lib.dtx_pipeline_return.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.dtx_pipeline_destroy.argtypes = [ctypes.c_void_p]
        lib.dtx_pipeline_num_records.restype = ctypes.c_int64
        lib.dtx_pipeline_num_records.argtypes = [ctypes.c_void_p]
        lib.dtx_pipeline_batches_per_epoch.restype = ctypes.c_int64
        lib.dtx_pipeline_batches_per_epoch.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


def write_records(path: str, array: np.ndarray) -> None:
    """Write a (N, ...) array as N fixed-size records."""
    with open(path, "wb") as f:
        f.write(np.ascontiguousarray(array).tobytes())


class NativeRecordDataset:
    """Iterator of (batch_array, epoch) with native prefetch.

    record_dtype/record_shape describe ONE record; batches come back as
    (batch, *record_shape) arrays. ``num_shards``/``shard_index`` select
    this host's partition (≙ DATA auto-sharding).
    """

    def __init__(self, paths, record_dtype, record_shape, batch_size: int,
                 *, shuffle: bool = True, seed: int = 0,
                 num_threads: int = 4, queue_depth: int = 8,
                 num_shards: int = 1, shard_index: int = 0,
                 drop_remainder: bool = True):
        if isinstance(paths, (str, os.PathLike)):
            paths = [paths]
        self._paths = [os.fspath(p) for p in paths]
        self.record_dtype = np.dtype(record_dtype)
        self.record_shape = tuple(record_shape)
        self.record_bytes = (self.record_dtype.itemsize
                             * int(np.prod(self.record_shape or (1,))))
        self.batch_size = batch_size
        lib = _load()
        arr = (ctypes.c_char_p * len(self._paths))(
            *[p.encode() for p in self._paths])
        self._h = lib.dtx_pipeline_create(
            arr, len(self._paths), self.record_bytes, batch_size,
            int(shuffle), seed, num_threads, queue_depth, num_shards,
            shard_index, int(drop_remainder))
        if not self._h:
            raise FileNotFoundError(
                f"native pipeline failed to open {self._paths} "
                f"(empty shard or missing file)")
        self._lib = lib

    @property
    def num_records(self) -> int:
        return self._lib.dtx_pipeline_num_records(self._h)

    @property
    def batches_per_epoch(self) -> int:
        return self._lib.dtx_pipeline_batches_per_epoch(self._h)

    def next_batch(self):
        """Blocking: returns (array, epoch). The array is a COPY (the
        native buffer is recycled immediately)."""
        data = ctypes.POINTER(ctypes.c_uint8)()
        n = ctypes.c_int64()
        epoch = ctypes.c_int64()
        bh = self._lib.dtx_pipeline_next(
            self._h, ctypes.byref(data), ctypes.byref(n),
            ctypes.byref(epoch))
        if not bh:
            raise StopIteration
        try:
            nbytes = int(n.value) * self.record_bytes
            flat = np.ctypeslib.as_array(data, shape=(nbytes,))
            out = flat.view(self.record_dtype).reshape(
                (int(n.value),) + self.record_shape).copy()
        finally:
            self._lib.dtx_pipeline_return(self._h, bh)
        return out, int(epoch.value)

    def __iter__(self):
        return self

    def __next__(self):
        return self.next_batch()

    def close(self):
        if getattr(self, "_h", None):
            self._lib.dtx_pipeline_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass
