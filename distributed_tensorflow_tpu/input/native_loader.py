"""Python wrapper for the native C++ data-pipeline core.

≙ the reference's C++ tf.data engine feeding its distributed input layer
(SURVEY.md §2.7 native rows; input auto-sharding ≙ input_ops.py:28 DATA
policy). The hot path — file IO, shuffle, batch assembly, prefetch — runs
in native threads (distributed_tensorflow_tpu/native/pipeline.cc); Python
sees zero-copy numpy views and hands them to ``jax.device_put``.

On-disk format: fixed-size binary records (one structured-dtype numpy
record each); ``write_records`` produces it.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libdtx_pipeline.so")
_SRC_PATH = os.path.join(_NATIVE_DIR, "pipeline.cc")

_lib = None
_lib_lock = threading.Lock()


def _build_so():
    subprocess.run(
        ["g++", "-O3", "-fPIC", "-shared", "-pthread", "-std=c++17",
         "-o", _SO_PATH, _SRC_PATH, "-lz"],
        check=True, capture_output=True)


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_SO_PATH)
                or os.path.getmtime(_SO_PATH) < os.path.getmtime(_SRC_PATH)):
            _build_so()
        lib = ctypes.CDLL(_SO_PATH)
        lib.dtx_pipeline_create.restype = ctypes.c_void_p
        lib.dtx_pipeline_create.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int, ctypes.c_uint64, ctypes.c_int,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int]
        lib.dtx_pipeline_next.restype = ctypes.c_void_p
        lib.dtx_pipeline_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
        lib.dtx_pipeline_return.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.dtx_pipeline_destroy.argtypes = [ctypes.c_void_p]
        lib.dtx_pipeline_num_records.restype = ctypes.c_int64
        lib.dtx_pipeline_num_records.argtypes = [ctypes.c_void_p]
        lib.dtx_pipeline_batches_per_epoch.restype = ctypes.c_int64
        lib.dtx_pipeline_batches_per_epoch.argtypes = [ctypes.c_void_p]
        lib.dtx_tfrecord_create.restype = ctypes.c_void_p
        lib.dtx_tfrecord_create.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int64,
            ctypes.c_int, ctypes.c_uint64, ctypes.c_int, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int, ctypes.c_int]
        lib.dtx_pipeline_row_bytes.restype = ctypes.c_int64
        lib.dtx_pipeline_row_bytes.argtypes = [ctypes.c_void_p]
        lib.dtx_pipeline_failed.restype = ctypes.c_int
        lib.dtx_pipeline_failed.argtypes = [ctypes.c_void_p]
        lib.dtx_pipeline_next2.restype = ctypes.c_void_p
        lib.dtx_pipeline_next2.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
        _lib = lib
        return lib


def write_records(path: str, array: np.ndarray) -> None:
    """Write a (N, ...) array as N fixed-size records."""
    with open(path, "wb") as f:
        f.write(np.ascontiguousarray(array).tobytes())


def write_tfrecords(path: str, payloads, compression: str | None = None
                    ) -> None:
    """Write byte payloads in TFRecord framing (length + masked crc32c),
    readable by :class:`NativeTFRecordDataset` and by TensorFlow.
    ``compression``: None | "GZIP" | "ZLIB" (≙ TFRecordOptions
    compression_type, TF/python/lib/io/tf_record.py)."""
    from distributed_tensorflow_tpu.utils.summary import tfrecord_frame
    if compression is None:
        with open(path, "wb") as f:          # streaming: O(one record)
            for p in payloads:
                f.write(tfrecord_frame(bytes(p)))
        return
    if compression == "GZIP":
        import gzip
        with gzip.open(path, "wb") as f:     # streaming
            for p in payloads:
                f.write(tfrecord_frame(bytes(p)))
        return
    if compression == "ZLIB":
        import zlib
        comp = zlib.compressobj()
        with open(path, "wb") as f:
            for p in payloads:
                f.write(comp.compress(tfrecord_frame(bytes(p))))
            f.write(comp.flush())
        return
    raise ValueError(f"compression={compression!r}; expected "
                     f"None, 'GZIP' or 'ZLIB'")


class _NativePipelineBase:
    """Shared lifecycle for the native pipeline handles: path
    normalization, existence checks, counters, iteration protocol,
    close/__del__ and failure propagation (dtx_pipeline_failed)."""

    def _open(self, paths, create_fn):
        if isinstance(paths, (str, os.PathLike)):
            paths = [paths]
        self._paths = [os.fspath(p) for p in paths]
        missing = [p for p in self._paths if not os.path.exists(p)]
        if missing:
            raise FileNotFoundError(f"no such record file(s): {missing}")
        self._lib = _load()
        arr = (ctypes.c_char_p * len(self._paths))(
            *[p.encode() for p in self._paths])
        self._h = create_fn(self._lib, arr, len(self._paths))
        if not self._h:
            raise ValueError(
                f"native pipeline rejected {self._paths} (empty shard, "
                f"shard smaller than a batch, or corrupt framing)")

    @property
    def num_records(self) -> int:
        return self._lib.dtx_pipeline_num_records(self._h)

    @property
    def batches_per_epoch(self) -> int:
        return self._lib.dtx_pipeline_batches_per_epoch(self._h)

    def _check_stream_end(self):
        """nullptr from Next: distinguish data failure from shutdown."""
        if self._lib.dtx_pipeline_failed(self._h):
            raise ValueError(
                f"native pipeline failed mid-stream on {self._paths} "
                f"(IO error or crc mismatch)")
        raise StopIteration

    def __iter__(self):
        return self

    def __next__(self):
        return self.next_batch()

    def close(self):
        if getattr(self, "_h", None):
            self._lib.dtx_pipeline_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass


class NativeRecordDataset(_NativePipelineBase):
    """Iterator of (batch_array, epoch) with native prefetch.

    record_dtype/record_shape describe ONE record; batches come back as
    (batch, *record_shape) arrays. ``num_shards``/``shard_index`` select
    this host's partition (≙ DATA auto-sharding).
    """

    def __init__(self, paths, record_dtype, record_shape, batch_size: int,
                 *, shuffle: bool = True, seed: int = 0,
                 num_threads: int = 4, queue_depth: int = 8,
                 num_shards: int = 1, shard_index: int = 0,
                 drop_remainder: bool = True):
        self.record_dtype = np.dtype(record_dtype)
        self.record_shape = tuple(record_shape)
        self.record_bytes = (self.record_dtype.itemsize
                             * int(np.prod(self.record_shape or (1,))))
        self.batch_size = batch_size
        self._open(paths, lambda lib, arr, n: lib.dtx_pipeline_create(
            arr, n, self.record_bytes, batch_size, int(shuffle), seed,
            num_threads, queue_depth, num_shards, shard_index,
            int(drop_remainder)))

    def next_batch(self):
        """Blocking: returns (array, epoch). The array is a COPY (the
        native buffer is recycled immediately)."""
        data = ctypes.POINTER(ctypes.c_uint8)()
        n = ctypes.c_int64()
        epoch = ctypes.c_int64()
        bh = self._lib.dtx_pipeline_next(
            self._h, ctypes.byref(data), ctypes.byref(n),
            ctypes.byref(epoch))
        if not bh:
            self._check_stream_end()
        try:
            nbytes = int(n.value) * self.record_bytes
            flat = np.ctypeslib.as_array(data, shape=(nbytes,))
            out = flat.view(self.record_dtype).reshape(
                (int(n.value),) + self.record_shape).copy()
        finally:
            self._lib.dtx_pipeline_return(self._h, bh)
        return out, int(epoch.value)


class NativeTFRecordDataset(_NativePipelineBase):
    """Native TFRecord reader with shuffle/shard/prefetch.

    ≙ the reference's C++ RecordReader + tf.data TFRecordDataset
    (tensorflow/core/lib/io/record_reader; SURVEY.md §2.7): the framing
    scan (seek-only, length-bounds-validated), per-epoch shuffle,
    DATA-policy sharding, and threaded batch assembly all run in native
    code (native/pipeline.cc TFRecord mode); payload crc32c is verified
    by the worker threads at read time so dataset bytes are read exactly
    once. Batches surface as a zero-padded (batch, max_record_bytes)
    uint8 array plus per-row lengths; ``next_records`` gives the payloads
    as bytes.
    """

    def __init__(self, paths, batch_size: int, *, shuffle: bool = True,
                 seed: int = 0, num_threads: int = 4, queue_depth: int = 8,
                 num_shards: int = 1, shard_index: int = 0,
                 drop_remainder: bool = True, verify_crc: bool = True):
        self.batch_size = batch_size
        self._open(paths, lambda lib, arr, n: lib.dtx_tfrecord_create(
            arr, n, batch_size, int(shuffle), seed, num_threads,
            queue_depth, num_shards, shard_index, int(drop_remainder),
            int(verify_crc)))
        self.row_bytes = self._lib.dtx_pipeline_row_bytes(self._h)

    def next_batch(self):
        """Blocking: returns (padded_uint8_array, lengths, epoch); the
        arrays are COPIES (native buffers recycle immediately)."""
        data = ctypes.POINTER(ctypes.c_uint8)()
        lengths = ctypes.POINTER(ctypes.c_int64)()
        n = ctypes.c_int64()
        epoch = ctypes.c_int64()
        bh = self._lib.dtx_pipeline_next2(
            self._h, ctypes.byref(data), ctypes.byref(lengths),
            ctypes.byref(n), ctypes.byref(epoch))
        if not bh:
            self._check_stream_end()
        try:
            count = int(n.value)
            flat = np.ctypeslib.as_array(
                data, shape=(count * self.row_bytes,))
            rows = flat.reshape(count, self.row_bytes).copy()
            lens = np.ctypeslib.as_array(lengths, shape=(count,)).copy()
        finally:
            self._lib.dtx_pipeline_return(self._h, bh)
        return rows, lens, int(epoch.value)

    def next_records(self):
        """Blocking: the next batch as a list of payload ``bytes``."""
        rows, lens, epoch = self.next_batch()
        return [rows[i, :lens[i]].tobytes()
                for i in range(rows.shape[0])], epoch
