"""Datasets, auto-sharding, and distributed iteration.

TPU-native counterpart of tensorflow/python/distribute/input_lib.py /
input_ops.py (SURVEY.md §2.3):

- ``Dataset``            — a small functional dataset (tensor slices / files
  / generators, map/shuffle/batch/repeat/shard/prefetch) standing in for
  tf.data on the host; a tf.data.Dataset or any iterable adapts directly.
- ``AutoShardPolicy``    ≙ input_ops.auto_shard_dataset (input_ops.py:28):
  FILE shards the file list across input pipelines, DATA takes every Nth
  element, AUTO prefers FILE when files exist.
- ``DistributedDataset`` ≙ input_lib.DistributedDataset (input_lib.py:729):
  per-worker iterators producing either PerReplica batches (TF-parity
  ``Strategy.run`` path) or globally-sharded ``jax.Array`` batches (native
  jit path), with background host->device prefetch (≙ infeed,
  tpu_feed.py) and ``get_next_as_optional`` partial-batch handling
  (input_lib.py:574).
"""

from __future__ import annotations

import collections
import concurrent.futures
import dataclasses
import enum
import itertools
import math
import os
import queue
import threading
import time
import weakref
from typing import Any, Callable, Iterable, Iterator, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu.resilience import faults
from distributed_tensorflow_tpu.utils import profiler

#: ≙ tf.data.AUTOTUNE: pass as ``num_parallel_calls`` to size the worker
#: pool from measured stage latency instead of hand-picking it.
AUTOTUNE = -1

_stage_counter = itertools.count(1)


def _stage_name(kind: str, name: str | None = None) -> str:
    return f"{kind}:{name}" if name else f"{kind}#{next(_stage_counter)}"


def _worker_cap() -> int:
    """Pool-size ceiling: cpu_count, floored at 2 so decode can overlap
    device compute even on one-core CI hosts."""
    return max(2, os.cpu_count() or 1)


def _check_parallel_calls(num_parallel_calls: int) -> None:
    if num_parallel_calls != AUTOTUNE and num_parallel_calls < 1:
        raise ValueError(
            f"num_parallel_calls must be >= 1 or AUTOTUNE, got "
            f"{num_parallel_calls}")


def _autotune_workers(src_s: float, fn_s: float) -> int:
    """AUTOTUNE's steady-state answer (≙ tf.data's autotune model,
    collapsed to its fixpoint): with upstream inter-arrival time
    ``src_s`` and per-element stage latency ``fn_s``, ``fn_s / src_s``
    concurrent calls keep the stage from being the bottleneck. Clamped
    to [1, :func:`_worker_cap`]; an instant upstream (src_s -> 0) gives
    the cap, an instant stage gives 1."""
    cap = _worker_cap()
    if fn_s <= 0:
        return 1
    return max(1, min(cap, round(fn_s / max(src_s, fn_s / cap, 1e-6))))


class AutoShardPolicy(enum.Enum):
    """≙ tf.data.experimental.AutoShardPolicy (input_ops.py:28)."""

    AUTO = "auto"
    FILE = "file"
    DATA = "data"
    OFF = "off"


@dataclasses.dataclass(frozen=True)
class InputOptions:
    """≙ tf.distribute.InputOptions (distribute_lib.py:1015)."""

    experimental_fetch_to_device: bool = True
    experimental_per_replica_buffer_size: int = 2
    experimental_replication_mode: str = "per_worker"
    auto_shard_policy: AutoShardPolicy = AutoShardPolicy.AUTO


class InputContext:
    """≙ tf.distribute.InputContext (distribute_lib.py:841)."""

    def __init__(self, num_input_pipelines: int = 1,
                 input_pipeline_id: int = 0,
                 num_replicas_in_sync: int = 1):
        self.num_input_pipelines = num_input_pipelines
        self.input_pipeline_id = input_pipeline_id
        self.num_replicas_in_sync = num_replicas_in_sync

    def get_per_replica_batch_size(self, global_batch_size: int) -> int:
        if global_batch_size % self.num_replicas_in_sync:
            raise ValueError(
                f"global batch {global_batch_size} not divisible by "
                f"{self.num_replicas_in_sync} replicas")
        return global_batch_size // self.num_replicas_in_sync


# ---------------------------------------------------------------------------
# Host dataset
# ---------------------------------------------------------------------------

class Dataset:
    """A minimal functional host dataset.

    Sources: ``from_tensor_slices``, ``from_files``, ``from_generator``,
    ``range``. Transforms are lazy and compose: map, filter, shuffle, batch,
    repeat, take, skip, shard, interleave, cache, padded_batch, prefetch
    (+ Dataset.zip).
    Iteration yields numpy pytrees.
    """

    def __init__(self, gen_fn: Callable[[], Iterator], *,
                 files: Sequence[str] | None = None,
                 element_count: int | None = None):
        self._gen_fn = gen_fn
        self._files = list(files) if files else None
        self._element_count = element_count

    # -- sources ----------------------------------------------------------
    @classmethod
    def from_tensor_slices(cls, tensors) -> "Dataset":
        leaves = jax.tree_util.tree_leaves(tensors)
        n = len(np.asarray(leaves[0]))

        def gen():
            arrs = jax.tree_util.tree_map(np.asarray, tensors)
            for i in range(n):
                yield jax.tree_util.tree_map(lambda a: a[i], arrs)

        return cls(gen, element_count=n)

    @classmethod
    def from_generator(cls, gen_fn: Callable[[], Iterator]) -> "Dataset":
        return cls(gen_fn)

    @classmethod
    def from_iterable(cls, it: Iterable) -> "Dataset":
        if isinstance(it, Dataset):
            return it
        # tf.data adapter: duck-typed on as_numpy_iterator
        if hasattr(it, "as_numpy_iterator"):
            return cls(lambda: iter(it.as_numpy_iterator()))
        if callable(it):
            return cls(it)
        materialized = list(it)
        return cls(lambda: iter(materialized),
                   element_count=len(materialized))

    @classmethod
    def from_files(cls, files: Sequence[str],
                   reader: Callable[[str], Iterator]) -> "Dataset":
        """File-based source; keeps the file list visible so AutoShardPolicy
        FILE can shard it (≙ input_ops.py FILE policy)."""
        files = list(files)

        def gen():
            for f in files:
                yield from reader(f)

        ds = cls(gen, files=files)
        ds._reader = reader
        return ds

    @classmethod
    def range(cls, *args) -> "Dataset":
        r = range(*args)
        return cls(lambda: iter(r), element_count=len(r))

    # -- transforms -------------------------------------------------------
    def _derive(self, gen_fn, element_count=None, op=None) -> "Dataset":
        """Derived dataset. ``op`` (Callable[[Dataset], Dataset]) replays
        this transform on a replacement source — shard_files uses the
        recorded chain to re-apply every transform on top of the SHARDED
        file source (tf.data's FILE auto-shard rewrites the source node
        the same way, input_ops.py:28)."""
        ds = Dataset(gen_fn, files=self._files, element_count=element_count)
        if hasattr(self, "_reader"):
            ds._reader = self._reader
        ds._parent = self
        ds._op = op
        return ds

    def map(self, fn: Callable,
            num_parallel_calls: int | None = None,
            name: str | None = None) -> "Dataset":
        """Apply ``fn`` per element. ``num_parallel_calls`` (int or
        :data:`AUTOTUNE`) fans the calls out over an ordered thread
        pool — element order stays BIT-IDENTICAL to the serial path at
        any worker count (≙ tf.data's deterministic ParallelMap). The
        serial default keeps today's zero-overhead generator chain."""
        src = self._gen_fn
        if num_parallel_calls is None:
            return self._derive(lambda: (fn(x) for x in src()),
                                self._element_count, op=lambda d: d.map(fn))
        stats = profiler.StageStats(_stage_name("map", name))

        def gen():
            yield from _parallel_map_iter(src, fn, num_parallel_calls,
                                          stats)

        ds = self._derive(
            gen, self._element_count,
            op=lambda d: d.map(fn, num_parallel_calls, name))
        ds._stage_stats = stats
        return ds

    def filter(self, pred: Callable) -> "Dataset":
        src = self._gen_fn
        return self._derive(lambda: (x for x in src() if pred(x)),
                            op=lambda d: d.filter(pred))

    def shuffle(self, buffer_size: int, seed: int | None = None) -> "Dataset":
        src = self._gen_fn

        def gen():
            rng = np.random.default_rng(seed)
            buf = []
            for x in src():
                buf.append(x)
                if len(buf) >= buffer_size:
                    i = rng.integers(len(buf))
                    buf[i], buf[-1] = buf[-1], buf[i]
                    yield buf.pop()
            rng.shuffle(buf)
            yield from buf

        return self._derive(gen, self._element_count,
                            op=lambda d: d.shuffle(buffer_size, seed))

    def batch(self, batch_size: int, drop_remainder: bool = False) -> "Dataset":
        src = self._gen_fn

        def gen():
            it = src()
            while True:
                chunk = list(itertools.islice(it, batch_size))
                if not chunk:
                    return
                if len(chunk) < batch_size and drop_remainder:
                    return
                yield jax.tree_util.tree_map(
                    lambda *xs: np.stack([np.asarray(x) for x in xs]), *chunk)

        count = None
        if self._element_count is not None:
            count = (self._element_count // batch_size if drop_remainder
                     else -(-self._element_count // batch_size))
        return self._derive(gen, count,
                            op=lambda d: d.batch(batch_size, drop_remainder))

    def padded_batch(self, batch_size: int, padded_shapes=None,
                     padding_values=0, drop_remainder: bool = False
                     ) -> "Dataset":
        """Batch variable-length elements, padding each component to the
        batch max (or to ``padded_shapes``) — ≙ tf.data
        Dataset.padded_batch. Elements are numpy pytrees; ragged leaves
        are padded on EVERY axis to the componentwise maximum."""
        src = self._gen_fn

        def is_shape(x):
            """A per-component shape spec: tuple/list of int, -1, or
            None (both meaning "pad to the batch max", as in tf.data).
            Being the is_leaf predicate keeps inner Nones from being
            dropped by tree flattening."""
            return (isinstance(x, (tuple, list)) and
                    all(i is None or isinstance(i, int) for i in x))

        def resolve(spec, maxshape, ndim):
            if spec is None:
                return maxshape
            spec = tuple(spec)
            if len(spec) != ndim:
                raise ValueError(
                    f"padded_shapes rank {len(spec)} != element rank "
                    f"{ndim}")
            return tuple(m if t is None or t == -1 else t
                         for t, m in zip(spec, maxshape))

        def pad_stack(leaves, target_shape, fill):
            out = []
            for a in leaves:
                pads = [(0, t - s) for s, t in zip(a.shape, target_shape)]
                if any(p[1] < 0 for p in pads):
                    raise ValueError(
                        f"element shape {a.shape} exceeds padded_shapes "
                        f"{target_shape}")
                out.append(np.pad(a, pads, constant_values=fill)
                           if pads else a)
            return np.stack(out)

        def gen():
            it = src()
            shapes_spec = fills = treedef = None    # set from first chunk
            while True:
                chunk = list(itertools.islice(it, batch_size))
                if not chunk:
                    return
                if len(chunk) < batch_size and drop_remainder:
                    return
                leaves_t = [jax.tree_util.tree_leaves(c) for c in chunk]
                if treedef is None:                 # loop-invariant setup
                    treedef = jax.tree_util.tree_structure(chunk[0])
                    n_leaves = len(leaves_t[0])
                    shapes_spec = (jax.tree_util.tree_leaves(
                                       padded_shapes, is_leaf=is_shape)
                                   if padded_shapes is not None
                                   else [None] * n_leaves)
                    if len(shapes_spec) != n_leaves:
                        raise ValueError(
                            f"padded_shapes has {len(shapes_spec)} "
                            f"components; elements have {n_leaves}")
                    fills = (jax.tree_util.tree_leaves(padding_values)
                             if isinstance(padding_values,
                                           (list, tuple, dict))
                             else [padding_values] * n_leaves)
                cols = []
                for li in range(len(leaves_t[0])):
                    col = [np.asarray(leaves_t[ei][li])
                           for ei in range(len(chunk))]
                    maxshape = tuple(
                        max(a.shape[d] for a in col)
                        for d in range(col[0].ndim))
                    target = resolve(shapes_spec[li], maxshape,
                                     col[0].ndim)
                    cols.append(pad_stack(col, target, fills[li]))
                yield jax.tree_util.tree_unflatten(treedef, cols)

        count = None
        if self._element_count is not None:
            count = (self._element_count // batch_size if drop_remainder
                     else -(-self._element_count // batch_size))
        return self._derive(
            gen, count,
            op=lambda d: d.padded_batch(batch_size, padded_shapes,
                                        padding_values, drop_remainder))

    def repeat(self, count: int | None = None) -> "Dataset":
        src = self._gen_fn

        def gen():
            n = 0
            while count is None or n < count:
                yield from src()
                n += 1

        return self._derive(
            gen, None if count is None or self._element_count is None
            else self._element_count * count,
            op=lambda d: d.repeat(count))

    def take(self, n: int) -> "Dataset":
        src = self._gen_fn
        return self._derive(lambda: itertools.islice(src(), n),
                            op=lambda d: d.take(n))

    def skip(self, n: int) -> "Dataset":
        src = self._gen_fn
        return self._derive(lambda: itertools.islice(src(), n, None),
                            op=lambda d: d.skip(n))

    @staticmethod
    def _check_shard_args(num_shards: int, index: int):
        """Shared validation for shard/shard_files (≙ tf.data's
        Dataset.shard errors). ``islice`` would treat a bad index as a
        plain offset — a negative index raises deep inside itertools
        and an out-of-range one silently yields nothing (an empty
        worker that deadlocks its peers in the first collective)."""
        if num_shards < 1:
            raise ValueError(
                f"num_shards must be >= 1, got {num_shards}")
        if not 0 <= index < num_shards:
            raise ValueError(
                f"shard index {index} out of range [0, {num_shards}); an "
                f"out-of-range index would silently yield no elements "
                f"(or alias another shard)")

    def shard(self, num_shards: int, index: int) -> "Dataset":
        """DATA-policy sharding: every ``num_shards``-th element
        (≙ tf.data Dataset.shard used by auto_shard_dataset)."""
        self._check_shard_args(num_shards, index)
        src = self._gen_fn
        return self._derive(
            lambda: itertools.islice(src(), index, None, num_shards),
            op=lambda d: d.shard(num_shards, index))

    def shard_files(self, num_shards: int, index: int) -> "Dataset":
        """FILE-policy sharding (≙ input_ops.py:28 FILE branch).

        Like tf.data's FILE auto-shard — which rewrites the source node
        of the input graph — this shards the ROOT file list and replays
        every downstream transform on top of the sharded source, so
        ``from_files(...).map(parse).batch(n)`` keeps its parsing and
        batching per shard."""
        if not self._files:
            raise ValueError("Dataset has no file list; use DATA sharding")
        self._check_shard_args(num_shards, index)
        if len(self._files) < num_shards:
            # Deterministic on EVERY worker (≙ tf.data FILE auto-shard's
            # 'not enough files' error) — erroring only on the
            # empty-shard workers would leave the others deadlocked in
            # collectives waiting for crashed peers.
            raise ValueError(
                f"FILE sharding needs >= num_shards files: "
                f"{len(self._files)} file(s) cannot be sharded "
                f"{num_shards} ways. Use more files or "
                f"AutoShardPolicy.DATA.")
        files, reader, chain = self.replay_spec()
        ds = Dataset.from_files(files[index::num_shards], reader)
        for op in reversed(chain):
            ds = op(ds)
        return ds

    def replay_spec(self):
        """The recorded rebuild recipe of a file-rooted pipeline:
        ``(files, reader, chain)`` where replaying ``chain`` (outermost
        last) over ``from_files(subset, reader)`` rebuilds this
        pipeline on any file subset. This is the FILE auto-shard
        machinery (:meth:`shard_files`) exposed for the disaggregated
        data service (input/split_provider.py), which replays the same
        chain per FILE split on remote input workers."""
        chain = []
        node = self
        while getattr(node, "_parent", None) is not None:
            if node._op is None:
                raise ValueError(
                    "FILE sharding cannot replay this pipeline (a "
                    "transform without a recorded rebuild op, e.g. a "
                    "Dataset.zip branch); use AutoShardPolicy.DATA")
            chain.append(node._op)
            node = node._parent
        if not node._files or not hasattr(node, "_reader"):
            raise ValueError(
                "pipeline root has no file source (e.g. Dataset.zip or "
                "a generator root); use AutoShardPolicy.DATA")
        return list(node._files), node._reader, chain

    def interleave(self, map_fn: Callable[..., "Dataset"],
                   cycle_length: int = 4,
                   block_length: int = 1,
                   num_parallel_calls: int | None = None,
                   name: str | None = None) -> "Dataset":
        """Round-robin interleave of ``cycle_length`` sub-datasets
        (≙ tf.data Dataset.interleave): ``map_fn(element)`` yields a
        Dataset per source element; ``block_length`` consecutive items
        are pulled from each open sub-iterator before rotating. This is
        the canonical many-files reading pattern together with
        ``from_files``/``shard_files``.

        ``num_parallel_calls`` (int or :data:`AUTOTUNE`) opens
        sub-datasets and fetches their next blocks on a thread pool —
        the round-robin output order stays bit-identical to the serial
        path (≙ deterministic ParallelInterleave)."""
        if cycle_length < 1:
            raise ValueError(f"cycle_length must be >= 1, got "
                             f"{cycle_length}")
        src = self._gen_fn
        if num_parallel_calls is not None:
            stats = profiler.StageStats(_stage_name("interleave", name))

            def pgen():
                yield from _parallel_interleave_iter(
                    src, map_fn, cycle_length, block_length,
                    num_parallel_calls, stats)

            ds = self._derive(
                pgen, None,
                op=lambda d: d.interleave(map_fn, cycle_length,
                                          block_length,
                                          num_parallel_calls, name))
            ds._stage_stats = stats
            return ds

        def gen():
            elements = src()
            open_its: list = []
            exhausted_src = False
            while True:
                while not exhausted_src and len(open_its) < cycle_length:
                    try:
                        element = next(elements)
                    except StopIteration:
                        exhausted_src = True
                        break
                    # map_fn runs OUTSIDE the except: a StopIteration
                    # leaked by user code must not masquerade as source
                    # exhaustion (PEP 479 semantics).
                    open_its.append(iter(map_fn(element)))
                if not open_its:
                    return
                keep = []
                for it in open_its:
                    alive = True
                    for _ in range(block_length):
                        try:
                            yield next(it)
                        except StopIteration:
                            alive = False
                            break
                    if alive:
                        keep.append(it)
                open_its = keep

        return self._derive(
            gen, None,
            op=lambda d: d.interleave(map_fn, cycle_length, block_length))

    def flat_map(self, map_fn: Callable[..., "Dataset"]) -> "Dataset":
        """Map each element to a Dataset and concatenate them in order
        (≙ tf.data Dataset.flat_map — interleave with cycle_length=1)."""
        src = self._gen_fn

        def gen():
            for el in src():
                yield from map_fn(el)

        return self._derive(gen, None, op=lambda d: d.flat_map(map_fn))

    def unbatch(self) -> "Dataset":
        """Split each element along its leading axis back into
        individual elements (≙ tf.data Dataset.unbatch)."""
        src = self._gen_fn

        def gen():
            for el in src():
                leaves = jax.tree_util.tree_leaves(el)
                if not leaves:
                    continue
                n = np.shape(leaves[0])[0]
                for i in range(n):
                    yield jax.tree_util.tree_map(
                        lambda a: np.asarray(a)[i], el)

        return self._derive(gen, None, op=lambda d: d.unbatch())

    def window(self, size: int, shift: int | None = None,
               stride: int = 1, drop_remainder: bool = False
               ) -> "Dataset":
        """Sliding windows of elements, each yielded as a Dataset
        (≙ tf.data Dataset.window; combine with flat_map/batch to
        flatten)."""
        if size < 1:
            raise ValueError(f"window size must be >= 1, got {size}")
        shift = shift or size
        src = self._gen_fn
        span = (size - 1) * stride + 1

        def gen():
            # tf.data semantics: window k covers stream positions
            # [k*shift, k*shift + span) sampled every `stride`; tail
            # windows (start < n but short) are kept unless
            # drop_remainder. Track absolute positions so shift > span
            # skips elements instead of silently reusing them.
            buf = collections.deque()
            pos0 = 0                    # stream index of buf[0]
            next_start = 0
            n = 0
            for el in src():
                buf.append(el)
                n += 1
                while next_start + span <= n:
                    lo = next_start - pos0
                    yield Dataset.from_iterable(
                        list(buf)[lo:lo + span:stride])
                    next_start += shift
                    while pos0 < next_start and buf:
                        buf.popleft()
                        pos0 += 1
            while next_start < n:
                lo = next_start - pos0
                win = list(buf)[lo:lo + span:stride][:size]
                if win and not (drop_remainder and len(win) < size):
                    yield Dataset.from_iterable(win)
                next_start += shift
                while pos0 < next_start and buf:
                    buf.popleft()
                    pos0 += 1

        return self._derive(
            gen, None,
            op=lambda d: d.window(size, shift, stride, drop_remainder))

    def bucket_by_sequence_length(
            self, element_length_func: Callable[[Any], int],
            bucket_boundaries: Sequence[int],
            bucket_batch_sizes: Sequence[int], *,
            pad_to_bucket_boundary: bool = False,
            drop_remainder: bool = False) -> "Dataset":
        """Group elements into length buckets and emit padded batches
        per bucket (≙ tf.data.Dataset.bucket_by_sequence_length,
        TF/python/data/experimental/ops/grouping.py) — the BERT-style
        variable-length text batching pattern. ``bucket_batch_sizes``
        needs len(bucket_boundaries)+1 entries; variable-length leading
        axes are zero-padded to the longest element in the batch (or to
        boundary-1 with ``pad_to_bucket_boundary``)."""
        boundaries = list(bucket_boundaries)
        batch_sizes = list(bucket_batch_sizes)
        if len(batch_sizes) != len(boundaries) + 1:
            raise ValueError(
                f"bucket_batch_sizes needs {len(boundaries) + 1} "
                f"entries (len(bucket_boundaries)+1), got "
                f"{len(batch_sizes)}")
        src = self._gen_fn

        def bucket_of(length: int) -> int:
            for b, bound in enumerate(boundaries):
                if length < bound:
                    return b
            return len(boundaries)

        def pad_stack(elements, bucket_idx):
            def pad_leaf(*leaves):
                arrs = [np.asarray(a) for a in leaves]
                if arrs[0].ndim == 0:
                    return np.stack(arrs)
                ndim = arrs[0].ndim
                if any(a.ndim != ndim for a in arrs):
                    raise ValueError(
                        "bucket_by_sequence_length: elements of one "
                        "bucket differ in rank")
                if pad_to_bucket_boundary:
                    if bucket_idx >= len(boundaries):
                        raise ValueError(
                            "pad_to_bucket_boundary needs a final "
                            "boundary covering the longest element")
                    bound = boundaries[bucket_idx] - 1
                    # tf.data pads every UNKNOWN (varying) dim to
                    # boundary-1 in this mode; statically-equal dims
                    # keep their size (grouping.py padded_batch with
                    # the None dims of the element spec).
                    targets = [bound] + [
                        (arrs[0].shape[d]
                         if all(a.shape[d] == arrs[0].shape[d]
                                for a in arrs) else bound)
                        for d in range(1, ndim)]
                else:
                    # pad EVERY dim to the batch max, not just the
                    # leading axis — e.g. (T, feat) with varying feat.
                    targets = [max(a.shape[d] for a in arrs)
                               for d in range(ndim)]
                out = []
                for a in arrs:
                    pad = [(0, t - s) for t, s in zip(targets, a.shape)]
                    out.append(np.pad(a, pad))
                return np.stack(out)
            return jax.tree_util.tree_map(pad_leaf, *elements)

        def gen():
            buckets: dict[int, list] = collections.defaultdict(list)
            for el in src():
                b = bucket_of(int(element_length_func(el)))
                buckets[b].append(el)
                if len(buckets[b]) >= batch_sizes[b]:
                    yield pad_stack(buckets.pop(b), b)
            if not drop_remainder:
                for b in sorted(buckets):
                    yield pad_stack(buckets[b], b)

        return self._derive(
            gen, None,
            op=lambda d: d.bucket_by_sequence_length(
                element_length_func, boundaries, batch_sizes,
                pad_to_bucket_boundary=pad_to_bucket_boundary,
                drop_remainder=drop_remainder))

    @classmethod
    def zip(cls, *datasets: "Dataset") -> "Dataset":
        """Elementwise tuples across datasets, stopping at the shortest
        (≙ tf.data.Dataset.zip)."""
        gens = [d._gen_fn for d in datasets]

        def gen():
            yield from zip(*(g() for g in gens))

        counts = [d._element_count for d in datasets]
        n = None if any(c is None for c in counts) else min(counts)
        return cls(gen, element_count=n)

    def cache(self) -> "Dataset":
        """Memoize elements on first full pass; later epochs replay the
        cache without re-running upstream transforms (≙ tf.data
        Dataset.cache, in-memory form)."""
        src = self._gen_fn
        store: dict = {"items": [], "complete": False}

        def gen():
            if store["complete"]:
                yield from store["items"]
                return
            items = []
            for x in src():
                items.append(x)
                yield x
            store["items"], store["complete"] = items, True

        return self._derive(gen, self._element_count,
                            op=lambda d: d.cache())

    def prefetch(self, buffer_size: int = 2,
                 name: str | None = None) -> "Dataset":
        """Decouple production from consumption: a background thread
        fills a bounded queue ``buffer_size`` deep (≙ tf.data
        Dataset.prefetch). Per-stage occupancy/wait counters register
        with :mod:`utils.profiler` (``pipeline_stats()``); the
        ``input.prefetch`` fault site fires per element so chaos tests
        can inject upstream decode failures."""
        src = self._gen_fn
        stats = profiler.StageStats(_stage_name("prefetch", name))

        def gen():
            yield from _BackgroundIterator(src(), buffer_size,
                                           stats=stats)

        ds = self._derive(gen, self._element_count,
                          op=lambda d: d.prefetch(buffer_size, name))
        ds._stage_stats = stats
        return ds

    def cardinality(self) -> int | None:
        return self._element_count

    def pipeline_stats(self) -> "list[dict]":
        """Snapshots of this pipeline's instrumented stages (parallel
        map/interleave, prefetch), root → here. Serial stages carry no
        counters (they are plain generators). For a process-wide view
        across pipelines use ``utils.profiler.pipeline_stats()``."""
        out = []
        node = self
        while node is not None:
            s = getattr(node, "_stage_stats", None)
            if s is not None:
                out.append(s.snapshot())
            node = getattr(node, "_parent", None)
        return list(reversed(out))

    def __iter__(self) -> Iterator:
        return self._gen_fn()


def _parallel_map_iter(src_fn: Callable[[], Iterator], fn: Callable,
                       num_parallel_calls: int,
                       stats: "profiler.StageStats") -> Iterator:
    """Ordered thread-pool fan-out for Dataset.map.

    A bounded window of futures keeps ``workers + 2`` elements in
    flight; results are yielded strictly in submission order, so the
    output is bit-identical to the serial path at any worker count.
    AUTOTUNE calibrates on the first elements (run serially) before the
    pool spins up. Exceptions from ``fn`` surface at the failing
    element's ordinal position; abandoning the iterator cancels the
    in-flight window.
    """
    _check_parallel_calls(num_parallel_calls)
    src = src_fn()
    calibrated: list = []
    if num_parallel_calls == AUTOTUNE:
        src_s = fn_s = 0.0
        n = 0
        for _ in range(3):
            t0 = time.monotonic()
            try:
                x = next(src)
            except StopIteration:
                break
            t1 = time.monotonic()
            y = fn(x)
            t2 = time.monotonic()
            src_s += t1 - t0
            fn_s += t2 - t1
            n += 1
            stats.record(elements=1, busy_s=t2 - t1,
                         producer_wait_s=t1 - t0)
            calibrated.append(y)
        workers = (_autotune_workers(src_s / n, fn_s / n) if n
                   else 1)
    else:
        workers = int(num_parallel_calls)
    stats.workers = workers

    def timed_fn(x):
        t0 = time.monotonic()
        y = fn(x)
        stats.record(elements=1, busy_s=time.monotonic() - t0)
        return y

    ex = concurrent.futures.ThreadPoolExecutor(
        max_workers=workers, thread_name_prefix=f"dtx-{stats.name}")
    pending: collections.deque = collections.deque()
    in_flight = workers + 2
    try:
        yield from calibrated
        exhausted = False
        while not exhausted or pending:
            while not exhausted and len(pending) < in_flight:
                t0 = time.monotonic()
                try:
                    x = next(src)
                except StopIteration:
                    exhausted = True
                    break
                stats.record(producer_wait_s=time.monotonic() - t0)
                pending.append(ex.submit(timed_fn, x))
            if not pending:
                break
            t0 = time.monotonic()
            y = pending.popleft().result()
            stats.record(consumer_wait_s=time.monotonic() - t0,
                         queue_depth=len(pending))
            yield y
    finally:
        for f in pending:
            f.cancel()
        ex.shutdown(wait=False)


def _parallel_interleave_iter(src_fn: Callable[[], Iterator],
                              map_fn: Callable, cycle_length: int,
                              block_length: int, num_parallel_calls: int,
                              stats: "profiler.StageStats") -> Iterator:
    """Deterministic parallel interleave: the round-robin rotation (and
    therefore the output order) is exactly the serial algorithm's, but
    each open slot's NEXT block — and the sub-dataset open itself — is
    fetched ahead on a thread pool while earlier slots drain."""
    _check_parallel_calls(num_parallel_calls)
    workers = (min(cycle_length, _worker_cap())
               if num_parallel_calls == AUTOTUNE
               else min(int(num_parallel_calls), cycle_length))
    stats.workers = workers

    def fetch_block(it):
        t0 = time.monotonic()
        out = []
        alive = True
        for _ in range(block_length):
            try:
                out.append(next(it))
            except StopIteration:
                alive = False
                break
        stats.record(elements=len(out), busy_s=time.monotonic() - t0)
        return out, alive

    def open_and_fetch(element):
        t0 = time.monotonic()
        it = iter(map_fn(element))
        stats.record(busy_s=time.monotonic() - t0)
        out, alive = fetch_block(it)
        return it, out, alive

    ex = concurrent.futures.ThreadPoolExecutor(
        max_workers=workers, thread_name_prefix=f"dtx-{stats.name}")
    elements = src_fn()
    slots: list[dict] = []
    try:
        exhausted = False
        while True:
            while not exhausted and len(slots) < cycle_length:
                t0 = time.monotonic()
                try:
                    el = next(elements)
                except StopIteration:
                    exhausted = True
                    break
                stats.record(producer_wait_s=time.monotonic() - t0)
                slots.append({"fut": ex.submit(open_and_fetch, el),
                              "it": None})
            if not slots:
                return
            keep = []
            for slot in slots:
                t0 = time.monotonic()
                if slot["it"] is None:
                    slot["it"], items, alive = slot["fut"].result()
                else:
                    items, alive = slot["fut"].result()
                stats.record(consumer_wait_s=time.monotonic() - t0)
                yield from items
                if alive:
                    slot["fut"] = ex.submit(fetch_block, slot["it"])
                    keep.append(slot)
            slots = keep
    finally:
        for slot in slots:
            slot["fut"].cancel()
        ex.shutdown(wait=False)


class _BackgroundIterator:
    """Background-thread prefetch with a bounded queue.

    Shuts down cleanly when abandoned: the worker parks on a bounded
    ``put`` that also watches a stop flag, and a ``weakref.finalize``
    (which the interpreter runs at exit for still-alive objects) stops
    and joins the thread — a daemon thread killed mid-``device_put``
    inside XLA aborts the whole process at teardown otherwise."""

    _SENTINEL = object()

    def __init__(self, it: Iterator, buffer_size: int,
                 stats: "profiler.StageStats | None" = None):
        self._q: queue.Queue = queue.Queue(maxsize=max(1, buffer_size))
        # One-element holder, NOT an attribute: the worker closure must
        # hold no reference to self, or the finalizer's strong args
        # (registry → thread → closure → self) would keep the iterator
        # alive forever and the GC teardown path would never fire.
        self._err_box: list[BaseException] = []
        self._done = False
        self._stop = threading.Event()
        self._stats = stats
        q, stop, sentinel = self._q, self._stop, self._SENTINEL
        err_box = self._err_box
        tag = stats.name if stats is not None else None

        def worker():
            try:
                src = iter(it)
                while True:
                    t0 = time.monotonic()
                    try:
                        x = next(src)
                    except StopIteration:
                        return
                    busy = time.monotonic() - t0
                    # Chaos site: a schedule can make the prefetch
                    # worker fail like a bad decode would — the
                    # exception lands in err_box and surfaces on the
                    # consumer's next() instead of hanging the queue.
                    faults.fire("input.prefetch", tag=tag)
                    t1 = time.monotonic()
                    ok = _put_unless_stopped(q, stop, x)
                    if stats is not None:
                        stats.record(
                            elements=1, busy_s=busy,
                            blocked_put_s=time.monotonic() - t1,
                            queue_depth=q.qsize())
                    if not ok:
                        return
            except BaseException as e:  # propagate to consumer
                err_box.append(e)
            finally:
                _put_unless_stopped(q, stop, sentinel)

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()
        self._finalizer = weakref.finalize(
            self, _stop_background_worker, stop, q, self._thread, sentinel)

    def close(self):
        self._finalizer()

    def __iter__(self):
        return self

    def __next__(self):
        # Exhaustion is sticky: the single sentinel is consumed on first
        # hit, and the dead worker will never put again — without the
        # flag a second next()/get_next_as_optional() would block
        # forever on the empty queue.
        if self._done:
            if self._err_box:
                raise self._err_box[0]
            raise StopIteration
        t0 = time.monotonic()
        x = self._q.get()
        if self._stats is not None:
            self._stats.record(consumer_wait_s=time.monotonic() - t0)
        if x is self._SENTINEL:
            self._done = True
            if self._err_box:
                raise self._err_box[0]
            raise StopIteration
        return x


def _put_unless_stopped(q: "queue.Queue", stop: "threading.Event",
                        item) -> bool:
    """Bounded put that also watches the stop flag; False once stopped."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


def _stop_background_worker(stop: "threading.Event", q: "queue.Queue",
                            thread: "threading.Thread",
                            sentinel) -> None:
    """Module-level so the finalizer holds no reference to the iterator."""
    stop.set()
    # Drain to unblock a worker parked on a full queue, then re-arm the
    # sentinel so a consumer parked in __next__'s blocking get() raises
    # StopIteration instead of hanging. Loop because a worker put
    # already in flight when stop was set can refill the slot between
    # our drain and our put (narrow race at buffer_size==1); after stop
    # is observed the worker puts nothing more, so this terminates.
    while True:
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        try:
            q.put_nowait(sentinel)
            break
        except queue.Full:
            continue
    # GC can run the finalizer on the worker thread itself (any
    # allocation there can trigger collection); joining yourself raises.
    if thread is not threading.current_thread():
        thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# Auto-sharding (≙ input_ops.auto_shard_dataset, input_ops.py:28)
# ---------------------------------------------------------------------------

def auto_shard_dataset(dataset: Dataset, num_shards: int, index: int,
                       policy: AutoShardPolicy = AutoShardPolicy.AUTO
                       ) -> Dataset:
    if num_shards <= 1 or policy is AutoShardPolicy.OFF:
        return dataset
    if policy is AutoShardPolicy.FILE:
        return dataset.shard_files(num_shards, index)
    if policy is AutoShardPolicy.DATA:
        return dataset.shard(num_shards, index)
    # AUTO: FILE when a file list exists and has enough files, else DATA.
    if dataset._files and len(dataset._files) >= num_shards:
        return dataset.shard_files(num_shards, index)
    return dataset.shard(num_shards, index)


# ---------------------------------------------------------------------------
# Distributed dataset
# ---------------------------------------------------------------------------

class DistributedDataset:
    """Per-worker view of a dataset, batches placed on the mesh.

    ≙ input_lib.DistributedDataset (input_lib.py:729). The incoming dataset
    yields *per-worker global* batches (leading dim = per-worker batch).
    Iteration yields batches as sharded ``jax.Array`` pytrees — the leading
    axis sharded over the strategy's data axes (native path). Under
    ``Strategy.run`` these shard correctly with no extra copies; for TF-style
    per-replica access, ``iter_per_replica`` yields ``PerReplica`` values.
    """

    def __init__(self, dataset, strategy, options: InputOptions | None = None):
        self._options = options or InputOptions()
        ds = Dataset.from_iterable(dataset)
        n_pipelines = jax.process_count()
        if n_pipelines > 1:
            ds = auto_shard_dataset(ds, n_pipelines, jax.process_index(),
                                    self._options.auto_shard_policy)
        self._dataset = ds
        self._strategy = strategy

    @property
    def element_spec(self):
        first = next(iter(self._dataset), None)
        if first is None:
            return None
        return jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype),
            first)

    def __iter__(self) -> "DistributedIterator":
        return DistributedIterator(self._dataset, self._strategy,
                                   self._options)

    def iter_per_replica(self) -> Iterator:
        """TF-parity iteration: PerReplica values for Strategy.run."""
        from distributed_tensorflow_tpu.parallel.values import PerReplica
        R = self._strategy.num_replicas_in_sync
        for batch in self._dataset:
            leaves, treedef = jax.tree_util.tree_flatten(batch)
            n = np.shape(leaves[0])[0] if leaves else 0
            if n % R:
                raise ValueError(
                    f"Per-worker batch size {n} is not divisible by "
                    f"{R} replicas; use drop_remainder=True or a divisible "
                    f"batch size")
            split = [np.split(np.asarray(l), R, axis=0) for l in leaves]
            yield jax.tree_util.tree_unflatten(
                treedef, [PerReplica(s) for s in split])


class DistributedIterator:
    """≙ input_lib.DistributedIterator (input_lib.py:574), with background
    host->device prefetch standing in for infeed (tpu_feed.py)."""

    def __init__(self, dataset: Dataset, strategy,
                 options: InputOptions):
        self._strategy = strategy
        self._fetch = options.experimental_fetch_to_device
        src = iter(dataset)
        if self._fetch:
            # Capture the strategy method, NOT self: a bound self._place
            # inside the worker's map() would make the worker thread (a
            # GC root) keep this iterator reachable, so an abandoned
            # half-consumed iterator would never be collected and its
            # prefetch thread would park forever holding device batches.
            place = self._strategy.shard_batch
            buffered = _BackgroundIterator(
                map(place, src),
                options.experimental_per_replica_buffer_size,
                stats=profiler.StageStats(_stage_name("device_put")))
            self._it = iter(buffered)
        else:
            self._it = src

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._it)

    def get_next(self):
        return next(self._it)

    def get_next_as_optional(self):
        """≙ get_next_as_optional (input_lib partial-batch handling):
        returns None at end instead of raising."""
        try:
            return next(self._it)
        except StopIteration:
            return None
