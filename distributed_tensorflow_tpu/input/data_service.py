"""Fault-tolerant disaggregated data service (tf.data-service equivalent).

≙ the reference's tf.data-service layer under ``input_lib.py`` (SURVEY
L5b): at pod scale the host input pipeline moves OFF the trainers onto
a fleet of **input workers** feeding them over the network, with a
**dispatcher** owning split assignment so the data contract — every
FILE split consumed **exactly once per epoch**, no loss, no
duplication — survives input-worker churn. This module is that layer
built on the repo's own control plane:

- **Transport + state = the coordination KV** (cluster/coordination.py,
  legacy-jaxlib discipline throughout: point reads, write-once claim
  keys, peer-written binary payloads chunked under the grpc cap — the
  checkpoint/peer_snapshot.py rules). Every key is generation-
  namespaced by the agent (cluster/elastic.py), so elastic trainer
  churn and straggler input workers are fenced exactly like the PR 11
  control-plane keys: a reformed generation's epoch state is disjoint
  from every dead incarnation's.
- **Splits** come from :class:`~distributed_tensorflow_tpu.input.
  split_provider.SplitProvider` — one FILE per split, rebuilt per
  worker by replaying the pipeline's recorded op chain (the
  ``shard_files`` machinery).
- **Leases are heartbeat-backed** (resilience/heartbeats.py, ridden
  under the job's own key prefix): the dispatcher assigns splits to
  workers it can see heartbeating; a lease whose worker goes stale is
  re-issued to a live worker (``data.reassign`` event + counter).
- **Exactly-once is by construction**, not by protocol luck: a split's
  completion is ONE write-once ``done`` record (first completing
  attempt wins — ``allow_overwrite=False`` is atomic on the service);
  payload chunks are keyed by the producing worker so a dead worker's
  partial write can never alias the winner's; the trainer consumes
  each (epoch, split) exactly once because it tracks the remaining
  split set of the epoch and each split has exactly one done record.
  Processing may be *at-least-once* under churn (the split pipeline is
  deterministic, so duplicate attempts produce identical bytes);
  delivery is exactly-once.
- **Trainer fetch** paces on :class:`~distributed_tensorflow_tpu.
  resilience.retry.RetryPolicy` with ``decorrelated=True`` jitter and
  accumulates ``total_wait_s`` with the same contract as
  ``training.loops.InfeedLoop`` — pass the client as
  ``StepTelemetry(infeed=client)`` and the fetch-wait lands in the
  ``infeed_wait`` badput bucket of the goodput ledger (live and
  event-walk paths both).

Chaos sites (resilience/faults.py): ``data.dispatch`` (per dispatcher
tick), ``data.fetch`` (per trainer split-fetch attempt; a ``raise``
is retried under the fetch policy), ``data.worker_step`` (per
input-worker split processing; ``raise`` crashes the worker mid-epoch,
``delay`` stalls it past the lease budget — both must end in a
re-issued lease and a complete epoch).

Generation contract: delivery is exactly-once *within a generation*.
When the supervisor reforms the cluster mid-epoch, the new generation's
namespace starts empty — the partially-delivered epoch is discarded and
re-delivered from scratch (deterministic: same seed, same splits, same
elements), the same replay-from-checkpoint semantics elastic training
already has for steps since the last save.
"""

from __future__ import annotations

import dataclasses
import json
import pickle
import threading
import time
from typing import Iterator

from distributed_tensorflow_tpu.cluster import coordination, elastic
from distributed_tensorflow_tpu.input.split_provider import SplitProvider
from distributed_tensorflow_tpu.resilience import faults
from distributed_tensorflow_tpu.resilience import heartbeats as _hb
from distributed_tensorflow_tpu.resilience.retry import Backoff, RetryPolicy
from distributed_tensorflow_tpu.telemetry import events as _events
from distributed_tensorflow_tpu.telemetry import registry as _registry


class DataServiceError(RuntimeError):
    """A data-service protocol failure (lost spec, fetch timeout)."""


@dataclasses.dataclass(frozen=True)
class DataServiceConfig:
    """Shared knobs of one data-service job.

    - ``job`` — the KV namespace of this job (``data/<job>/...``).
    - ``lease_timeout_s`` — heartbeat staleness past which a worker's
      leases are re-issued (the failure-detection budget; must exceed
      the worker's per-split processing time or healthy slow workers
      get their work stolen — stolen work is still correct, just
      wasted).
    - ``chunk_bytes`` — payload chunk ceiling (< the 4 MiB grpc cap,
      the peer_snapshot discipline).
    """

    job: str = "default"
    lease_timeout_s: float = 2.0
    poll_interval_s: float = 0.02
    chunk_bytes: int = 2 * 1024 * 1024
    hb_shard_size: int = 32
    fetch_timeout_s: float = 120.0

    @property
    def prefix(self) -> str:
        return f"data/{self.job}"


# -- key layout (all generation-namespaced by the agent) -----------------

def _spec_key(cfg: DataServiceConfig) -> str:
    return f"{cfg.prefix}/spec"


def _assign_key(cfg: DataServiceConfig, epoch: int, worker: int) -> str:
    return f"{cfg.prefix}/e{epoch}/assign/{worker}"


def _done_key(cfg: DataServiceConfig, epoch: int, split: int) -> str:
    return f"{cfg.prefix}/e{epoch}/s{split}/done"


def _chunk_key(cfg: DataServiceConfig, epoch: int, split: int,
               worker: int, k: int) -> str:
    return f"{cfg.prefix}/e{epoch}/s{split}/w{worker}/c{k}"


def _epoch_complete_key(cfg: DataServiceConfig, epoch: int) -> str:
    return f"{cfg.prefix}/e{epoch}/complete"


def _shutdown_key(cfg: DataServiceConfig) -> str:
    return f"{cfg.prefix}/shutdown"


# -- job registration ------------------------------------------------------

def register_job(agent, cfg: DataServiceConfig, provider: SplitProvider,
                 *, epochs: int, num_workers: int):
    """Publish the job spec (chief/dispatcher side). Split *identity*
    (file list order, epoch permutation seed) travels in the spec so
    every participant derives the identical universe."""
    agent.key_value_set(_spec_key(cfg), json.dumps({
        "num_splits": provider.num_splits, "epochs": int(epochs),
        "seed": provider.seed, "num_workers": int(num_workers)}))


def read_job_spec(agent, cfg: DataServiceConfig, *,
                  timeout_s: float = 30.0) -> dict:
    try:
        raw = agent.key_value_get(_spec_key(cfg), timeout_s=timeout_s)
    except coordination.CoordinationError as e:
        raise DataServiceError(
            f"data-service job {cfg.job!r} spec never published") from e
    return json.loads(raw.decode())


def signal_shutdown(agent, cfg: DataServiceConfig):
    """Trainer-side: release the input workers (this generation's)."""
    agent.key_value_set(_shutdown_key(cfg), b"1")


def _shutdown_requested(agent, cfg: DataServiceConfig) -> bool:
    return agent.key_value_try_get(_shutdown_key(cfg)) is not None


def acknowledge_shutdown(agent, cfg: DataServiceConfig, worker_id: int):
    """Input-worker side: confirm this worker saw the shutdown and will
    touch the KV no more. The trainer typically HOSTS the coordination
    service (process 0); tearing it down while workers still poll would
    turn a clean exit into a spurious failure the supervisor then
    'recovers' from."""
    agent.key_value_set(f"{cfg.prefix}/bye/{int(worker_id)}", b"1")


def await_shutdown_acks(agent, cfg: DataServiceConfig, num_workers: int,
                        *, timeout_s: float = 10.0) -> bool:
    """Trainer-side: wait (bounded) for every input worker's ack; False
    on timeout (dead workers never ack — exit anyway, their supervisor
    owns them)."""
    deadline = time.monotonic() + timeout_s
    pending = set(range(int(num_workers)))
    while pending and time.monotonic() < deadline:
        for w in sorted(pending):
            if agent.key_value_try_get(f"{cfg.prefix}/bye/{w}") \
                    is not None:
                pending.discard(w)
        if pending:
            time.sleep(0.02)
    return not pending


# ---------------------------------------------------------------------------
# Dispatcher
# ---------------------------------------------------------------------------

class DataServiceDispatcher:
    """Split-assignment authority of one job (≙ the tf.data-service
    dispatcher's split re-assignment of dead workers).

    Holds the lease table in memory and the *durable* facts in the KV
    (done records, assignment keys, epoch completion): a dispatcher
    reformed under a new generation re-derives everything it needs from
    the provider (deterministic split universe) and the new namespace
    (empty = restart the epoch), which is the same recover-by-replay
    contract the trainers have.

    Drive it with :meth:`tick` (deterministic tests / the simulated
    fleet) or :meth:`start`/:meth:`stop` (a background thread, the
    production shape). One tick: observe worker liveness -> collect
    done records -> re-issue leases of stale workers -> assign
    unleased splits -> publish changed assignments -> complete the
    epoch when every split is done.
    """

    def __init__(self, agent, provider: SplitProvider,
                 cfg: DataServiceConfig, *, num_workers: int,
                 epochs: int = 1, reg=None,
                 domains: "dict[int, str] | None" = None):
        self.agent = agent
        self.provider = provider
        self.cfg = cfg
        self.num_workers = int(num_workers)
        self.epochs = int(epochs)
        #: optional {worker_id: failure_domain} placement map: leases
        #: are spread across domains (least-loaded domain first, then
        #: least-loaded worker within it) and a dead worker's lease is
        #: re-issued OUTSIDE its domain when any other domain has a
        #: live member — a rack loss then stalls only that rack's
        #: in-flight splits, never a whole epoch's worth piled on one
        #: survivor rack.
        self.domains = dict(domains) if domains else None
        self.reader = _hb.ShardedKVHeartbeats(
            agent, shard_size=cfg.hb_shard_size,
            summary_stale_s=cfg.lease_timeout_s,
            key_prefix=cfg.prefix)
        # This dispatcher lives INSIDE one generation: capture it at
        # construction. The sharded reader pins its own generation on
        # every read (supervisor semantics — it outlives generations),
        # and tick() re-applies the override because the background
        # loop runs on its own thread — thread-local generation
        # overrides (fleet_sim) do not travel across threads, and a
        # reformed dispatcher polling the DEAD generation's keys would
        # never see a heartbeat or publish a visible assignment.
        self._gen = elastic.generation()
        self.reader.generation = self._gen
        self.epoch = 0
        self.splits_reassigned = 0
        self.epochs_completed = 0
        self._leases: "dict[int, int]" = {}       # split -> worker
        self._done: "set[int]" = set()
        self._assign_ver: "dict[int, int]" = {}
        self._published: "dict[int, list]" = {}
        self._thread: "threading.Thread | None" = None
        self._stop = threading.Event()
        self._t0 = time.monotonic()
        reg = reg or _registry.get_registry()
        self._m_reassigned = reg.counter(
            "data/splits_reassigned",
            "splits re-issued after input-worker death")
        self._m_epochs = reg.counter(
            "data/epochs_completed", "data-service epochs completed")
        self._m_outstanding = reg.gauge(
            "data/splits_outstanding", "splits not yet done this epoch")
        register_job(agent, cfg, provider, epochs=epochs,
                     num_workers=num_workers)

    # -- liveness ----------------------------------------------------------
    def _live_workers(self) -> "list[int]":
        hbs = self.reader.read_all(self.num_workers)
        now = time.time()
        return sorted(w for w, hb in hbs.items()
                      if hb[2] is not None
                      and now - hb[2] <= self.cfg.lease_timeout_s)

    # -- one tick ----------------------------------------------------------
    def tick(self) -> bool:
        """One dispatch round; returns True while the job is running
        (False once every epoch completed)."""
        with elastic.generation_override(self._gen):
            return self._tick()

    def _tick(self) -> bool:
        if self.epoch >= self.epochs:
            return False
        faults.fire("data.dispatch", tag=self.cfg.job)
        live = self._live_workers()
        self._collect_done()
        if live:
            self._reissue_stale(live)
            # Fleet-formation grace: the FIRST worker to heartbeat must
            # not be handed the whole epoch just because its peers are
            # a tick behind — wait for the full fleet (or the lease
            # budget, past which missing workers are treated as dead).
            if (len(live) >= self.num_workers
                    or time.monotonic() - self._t0
                    > 2 * self.cfg.lease_timeout_s):
                self._assign_unleased(live)
            self._publish_assignments()
        self._m_outstanding.set(
            self.provider.num_splits - len(self._done))
        if len(self._done) >= self.provider.num_splits:
            self.agent.key_value_set(
                _epoch_complete_key(self.cfg, self.epoch), b"1")
            _events.event("data.epoch_complete", job=self.cfg.job,
                          epoch=self.epoch,
                          reassigned=self.splits_reassigned)
            self._m_epochs.increment()
            self.epochs_completed += 1
            self.epoch += 1
            self._leases.clear()
            self._done.clear()
            self._published.clear()
        return self.epoch < self.epochs

    def _collect_done(self):
        for split in range(self.provider.num_splits):
            if split in self._done:
                continue
            if self.agent.key_value_try_get(
                    _done_key(self.cfg, self.epoch, split)) is not None:
                self._done.add(split)
                self._leases.pop(split, None)

    def _reissue_stale(self, live: "list[int]"):
        live_set = set(live)
        for split, worker in sorted(self._leases.items()):
            if worker in live_set or split in self._done:
                continue
            # a lease lost to a (likely whole-domain) failure is
            # re-placed outside the dead worker's domain when possible:
            # if the rest of that rack is about to be declared dead
            # too, re-issuing into it would just re-lose the lease
            new = self._least_loaded(live, avoid_domain=self._domain_of(worker))
            self._leases[split] = new
            self.splits_reassigned += 1
            self._m_reassigned.increment()
            _events.event("data.reassign", job=self.cfg.job,
                          epoch=self.epoch, split=split,
                          from_worker=worker, to_worker=new,
                          from_domain=self._domain_of(worker),
                          to_domain=self._domain_of(new))

    def _assign_unleased(self, live: "list[int]"):
        for split in self.provider.epoch_order(self.epoch):
            if split in self._done or split in self._leases:
                continue
            self._leases[split] = self._least_loaded(live)

    def _domain_of(self, worker: int) -> "str | None":
        if not self.domains:
            return None
        return self.domains.get(worker)

    def _least_loaded(self, live: "list[int]", *,
                      avoid_domain: "str | None" = None) -> int:
        load = {w: 0 for w in live}
        for w in self._leases.values():
            if w in load:
                load[w] += 1
        cands = sorted(load)
        if self.domains:
            if avoid_domain is not None:
                outside = [w for w in cands
                           if self._dom_key(w) != avoid_domain]
                if outside:
                    cands = outside
            dom_load: "dict[str, int]" = {}
            for w in cands:
                d = self._dom_key(w)
                dom_load[d] = dom_load.get(d, 0) + load[w]
            best = min(sorted(dom_load), key=lambda d: dom_load[d])
            cands = [w for w in cands if self._dom_key(w) == best]
        return min(cands, key=lambda w: load[w])

    def _dom_key(self, worker: int) -> str:
        """Placement key of a worker: its mapped domain, or a singleton
        pseudo-domain when unmapped (an unmapped worker never blocks
        domain spreading, never aliases another worker's domain)."""
        d = (self.domains or {}).get(worker)
        return d if d is not None else f"__w{worker}"

    def _publish_assignments(self):
        by_worker: "dict[int, list]" = {}
        for split, worker in self._leases.items():
            by_worker.setdefault(worker, []).append(split)
        for worker, splits in sorted(by_worker.items()):
            splits = sorted(splits)
            if self._published.get(worker) == splits:
                continue
            ver = self._assign_ver.get(worker, 0) + 1
            self._assign_ver[worker] = ver
            self.agent.key_value_set(
                _assign_key(self.cfg, self.epoch, worker),
                json.dumps({"ver": ver, "splits": splits}))
            self._published[worker] = splits

    # -- background loop ---------------------------------------------------
    def start(self) -> "DataServiceDispatcher":
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"dtx-dispatch-{self.cfg.job}")
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.is_set():
            try:
                if not self.tick():
                    return
            except coordination.CoordinationError:
                pass                    # transient KV blip: next tick
            self._stop.wait(self.cfg.poll_interval_s)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


# ---------------------------------------------------------------------------
# Input worker
# ---------------------------------------------------------------------------

class DataInputWorker:
    """One input worker: heartbeat, poll the assignment key, process
    leased splits (replay the recorded pipeline over the split's file),
    publish the payload, claim the write-once done record.

    ``run`` loops until the job's epochs are exhausted, a shutdown is
    signalled, or ``stop`` is set (the simulated fleet's cooperative
    SIGKILL). Processing is idempotent: losing the done-record race (a
    re-issued lease both sides completed) is not an error — the bytes
    are identical by determinism and only the winner is consumed.
    """

    def __init__(self, agent, provider: SplitProvider,
                 cfg: DataServiceConfig, *, worker_id: int,
                 num_workers: int, epochs: "int | None" = None,
                 heartbeat_fn=None, reg=None):
        self.agent = agent
        self.provider = provider
        self.cfg = cfg
        self.worker_id = int(worker_id)
        self.num_workers = int(num_workers)
        self.epochs = epochs
        self.heartbeat_fn = heartbeat_fn
        self.pub = _hb.ShardedHeartbeatPublisher(
            agent, pid=self.worker_id, num_workers=num_workers,
            shard_size=cfg.hb_shard_size, key_prefix=cfg.prefix)
        #: generation captured at construction (the data_service
        #: contract: protocol objects live inside ONE generation and
        #: re-apply it on their own threads — see Dispatcher.tick)
        self._gen = elastic.generation()
        self.splits_processed = 0
        self.elements_out = 0
        reg = reg or _registry.get_registry()
        self._m_splits = reg.counter(
            "data/splits_processed", "splits this input worker produced")
        self._m_elements = reg.counter(
            "data/elements_out", "elements this input worker produced")
        self._m_busy = reg.timer(
            "data/split_process_time", "per-split processing seconds")

    def run(self, stop: "threading.Event | None" = None):
        """Serve until RELEASED, not until the work looks done: even
        with every epoch's splits produced, this worker keeps
        heartbeating and waits for the trainer's shutdown signal — the
        trainer may still be consuming (or still compiling), and an
        input worker that exits early tears the shared distributed
        runtime down under it."""
        with elastic.generation_override(self._gen):
            self._run(stop)

    def _run(self, stop: "threading.Event | None"):
        stop = stop or threading.Event()
        if self.epochs is None:
            self.epochs = read_job_spec(self.agent, self.cfg)["epochs"]
        epoch = 0
        beat = 0
        while not stop.is_set():
            beat += 1
            self.pub.beat(beat)
            if self.heartbeat_fn is not None:
                self.heartbeat_fn(self.splits_processed)
            if _shutdown_requested(self.agent, self.cfg):
                break
            if epoch < self.epochs and self.agent.key_value_try_get(
                    _epoch_complete_key(self.cfg, epoch)) is not None:
                epoch += 1
                continue
            if epoch < self.epochs:
                for split in self._assigned(epoch):
                    if stop.is_set():
                        break
                    self._process(epoch, split)
            if stop.wait(self.cfg.poll_interval_s):
                break
        # clean exit only (a chaos crash propagates past this): tell
        # the trainer it is safe to tear the coordination service down
        acknowledge_shutdown(self.agent, self.cfg, self.worker_id)

    def _assigned(self, epoch: int) -> "list[int]":
        raw = self.agent.key_value_try_get(
            _assign_key(self.cfg, epoch, self.worker_id))
        if raw is None:
            return []
        try:
            return list(json.loads(raw.decode()).get("splits", []))
        except (ValueError, UnicodeDecodeError):
            return []

    def _process(self, epoch: int, split: int):
        if self.agent.key_value_try_get(
                _done_key(self.cfg, epoch, split)) is not None:
            return                          # someone already finished it
        # Chaos site: fires once per split-processing attempt (tag =
        # worker id, per-tag hit counter = this worker's attempt
        # number). A ``raise`` crashes the worker mid-epoch, a
        # ``delay`` stalls it past the lease budget — either way the
        # dispatcher must re-issue the lease and the epoch must still
        # complete exactly-once.
        faults.fire("data.worker_step", tag=self.worker_id)
        t0 = time.monotonic()
        elements = self.provider.elements(split)
        payload = pickle.dumps(elements, protocol=pickle.HIGHEST_PROTOCOL)
        chunks = [payload[i:i + self.cfg.chunk_bytes]
                  for i in range(0, len(payload), self.cfg.chunk_bytes)] \
            or [b""]
        for k, chunk in enumerate(chunks):
            self.agent.key_value_set(
                _chunk_key(self.cfg, epoch, split, self.worker_id, k),
                chunk)
        dur = time.monotonic() - t0
        record = json.dumps({"worker": self.worker_id,
                             "chunks": len(chunks),
                             "elements": len(elements)})
        try:
            # write-once claim: the FIRST completing attempt wins; a
            # racing attempt (re-issued lease both sides finished) just
            # loses — its chunks are unreachable garbage the generation
            # GC sweeps with the namespace
            self.agent.key_value_set(_done_key(self.cfg, epoch, split),
                                     record, allow_overwrite=False)
        except Exception:
            return                          # lost the race: not an error
        self.splits_processed += 1
        self.elements_out += len(elements)
        self._m_splits.increment()
        self._m_elements.increment(len(elements))
        self._m_busy.record(dur)
        _events.event("data.split_done", job=self.cfg.job, epoch=epoch,
                      split=split, worker=self.worker_id,
                      elements=len(elements), dur_s=round(dur, 6))


# ---------------------------------------------------------------------------
# Trainer-side client
# ---------------------------------------------------------------------------

class DataServiceClient:
    """Trainer-side consumption of one job, epoch by epoch.

    :meth:`epoch` yields the epoch's elements in split-completion
    order — the SEQUENCE depends on worker timing, the MULTISET is
    deterministic (the exactly-once contract's unit). Fetch pacing is
    a decorrelated-jitter :class:`RetryPolicy` backoff (the
    thundering-herd shape N trainers polling one namespace need);
    transient fetch failures (chaos site ``data.fetch``) retry under
    the same policy.

    ``total_wait_s`` follows the ``InfeedLoop`` contract (cumulative
    seconds the consumer blocked on input), so
    ``StepTelemetry(infeed=client)`` prices fetch-wait into the
    ``infeed_wait`` badput bucket with zero extra wiring.
    """

    def __init__(self, agent, cfg: DataServiceConfig, *,
                 num_splits: "int | None" = None,
                 retry: "RetryPolicy | None" = None,
                 heartbeat_fn=None):
        self.agent = agent
        self.cfg = cfg
        if num_splits is None:
            num_splits = read_job_spec(agent, cfg)["num_splits"]
        self.num_splits = int(num_splits)
        self.retry = retry or RetryPolicy(
            max_attempts=8, initial_backoff_s=0.005, max_backoff_s=0.25,
            decorrelated=True, seed=0,
            retryable=(coordination.CoordinationError,))
        self.heartbeat_fn = heartbeat_fn
        self._gen = elastic.generation()
        self.total_wait_s = 0.0
        self.splits_consumed = 0
        self.elements_consumed = 0

    def _fetch_split(self, epoch: int, split: int, record: dict) -> list:
        def get_chunks():
            faults.fire("data.fetch", tag=str(split),
                        exc=coordination.CoordinationError,
                        msg=f"injected data.fetch failure (split {split})")
            parts = []
            for k in range(int(record["chunks"])):
                parts.append(self.agent.key_value_get(
                    _chunk_key(self.cfg, epoch, split,
                               int(record["worker"]), k),
                    timeout_s=self.cfg.fetch_timeout_s))
            return pickle.loads(b"".join(parts))

        return self.retry.call(get_chunks)

    def epoch(self, epoch: int) -> Iterator:
        """Yield every element of ``epoch`` exactly once (per split,
        split-completion order). Raises :class:`DataServiceError` if no
        split completes within ``fetch_timeout_s`` — dead fleet, not a
        slow one."""
        with elastic.generation_override(self._gen):
            yield from self._epoch(epoch)

    def _epoch(self, epoch: int) -> Iterator:
        remaining = set(range(self.num_splits))
        backoff = Backoff(self.retry)
        last_progress = time.monotonic()
        epoch_elements = 0
        while remaining:
            progressed = False
            for split in sorted(remaining):
                raw = self.agent.key_value_try_get(
                    _done_key(self.cfg, epoch, split))
                if raw is None:
                    continue
                record = json.loads(raw.decode())
                t0 = time.monotonic()
                elements = self._fetch_split(epoch, split, record)
                self.total_wait_s += time.monotonic() - t0
                remaining.discard(split)
                progressed = True
                backoff.reset()
                last_progress = time.monotonic()
                self.splits_consumed += 1
                self.elements_consumed += len(elements)
                epoch_elements += len(elements)
                _events.event("data.split_consumed", job=self.cfg.job,
                              epoch=epoch, split=split,
                              worker=int(record["worker"]),
                              elements=len(elements))
                yield from elements
            if remaining and not progressed:
                if (time.monotonic() - last_progress
                        > self.cfg.fetch_timeout_s):
                    raise DataServiceError(
                        f"epoch {epoch}: no split completed in "
                        f"{self.cfg.fetch_timeout_s}s "
                        f"({len(remaining)} outstanding: "
                        f"{sorted(remaining)[:8]})")
                if self.heartbeat_fn is not None:
                    self.heartbeat_fn(None)
                t0 = time.monotonic()
                backoff.sleep(max_s=0.25)
                self.total_wait_s += time.monotonic() - t0
        _events.event("data.epoch_consumed", job=self.cfg.job,
                      epoch=epoch, splits=self.num_splits,
                      elements=epoch_elements)
