"""Host-side image ops: JPEG codec + preprocessing for the real-data
ResNet path.

≙ the reference's image input stack (`TF/python/ops/image_ops_impl.py`
`decode_jpeg` / `flip_left_right` / `crop_to_bounding_box` /
`central_crop` / `resize`, and `TFK/src/layers/preprocessing/`
`Rescaling` / `RandomFlip` / `RandomCrop`): everything runs on the HOST
as numpy — these feed ``Dataset.map(..., num_parallel_calls=...)``
workers, so they must release the GIL where possible (PIL's decoder
does) and never touch jax.

Numerics are parity-pinned against the installed ``tf.image`` in
``tests/test_image_ops.py``:

- ``flip_left_right`` / ``crop_to_bounding_box`` / ``central_crop``
  are bit-exact vs tf.image;
- ``resize_bilinear`` implements TF2's half-pixel-centers bilinear
  kernel (``ResizeBilinear`` with ``half_pixel_centers=True``, no
  antialias) and matches ``tf.image.resize`` to float32 round-off;
- ``decode_jpeg`` uses PIL's libjpeg; IDCT implementations may differ
  from TF's by a few counts per pixel, so parity is toleranced.

Random ops are STATELESS (≙ ``tf.image.stateless_random_*``): every
call takes an explicit per-element seed, so parallel map workers
produce bit-identical augmentation at any worker count and any thread
interleaving.
"""

from __future__ import annotations

import io
import os
import re
import zlib
from typing import Sequence

import numpy as np


def _require_pil():
    try:
        from PIL import Image
    except ImportError as e:                     # pragma: no cover
        raise ImportError(
            "image_ops needs Pillow for the JPEG host path "
            "(pip package 'Pillow')") from e
    return Image


# ---------------------------------------------------------------------------
# Codec
# ---------------------------------------------------------------------------

def decode_jpeg(data: bytes, channels: int = 3) -> np.ndarray:
    """JPEG bytes -> (H, W, channels) uint8 (≙ tf.io.decode_jpeg)."""
    Image = _require_pil()
    if channels not in (1, 3):
        raise ValueError(f"channels must be 1 or 3, got {channels}")
    img = Image.open(io.BytesIO(data))
    img = img.convert("RGB" if channels == 3 else "L")
    arr = np.asarray(img, dtype=np.uint8)
    if channels == 1 and arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def encode_jpeg(image: np.ndarray, quality: int = 95) -> bytes:
    """(H, W, 1|3) uint8 -> JPEG bytes (≙ tf.io.encode_jpeg)."""
    Image = _require_pil()
    image = np.asarray(image)
    if image.dtype != np.uint8:
        raise ValueError(f"encode_jpeg expects uint8, got {image.dtype}")
    if image.ndim == 3 and image.shape[-1] == 1:
        image = image[:, :, 0]
    buf = io.BytesIO()
    Image.fromarray(image).save(buf, format="JPEG", quality=quality)
    return buf.getvalue()


def read_image(path: str, channels: int = 3) -> np.ndarray:
    """Read + decode one JPEG file from disk."""
    with open(path, "rb") as f:
        return decode_jpeg(f.read(), channels)


# ---------------------------------------------------------------------------
# Deterministic geometry ops (bit-exact vs tf.image)
# ---------------------------------------------------------------------------

def flip_left_right(image: np.ndarray) -> np.ndarray:
    """≙ tf.image.flip_left_right (width axis reversal)."""
    return np.ascontiguousarray(np.asarray(image)[:, ::-1])


def crop_to_bounding_box(image: np.ndarray, offset_height: int,
                         offset_width: int, target_height: int,
                         target_width: int) -> np.ndarray:
    """≙ tf.image.crop_to_bounding_box, with its bounds validation."""
    image = np.asarray(image)
    h, w = image.shape[0], image.shape[1]
    if offset_height < 0 or offset_width < 0:
        raise ValueError("crop offsets must be non-negative")
    if offset_height + target_height > h or offset_width + target_width > w:
        raise ValueError(
            f"crop [{offset_height}:{offset_height + target_height}, "
            f"{offset_width}:{offset_width + target_width}] exceeds image "
            f"shape {(h, w)}")
    return image[offset_height:offset_height + target_height,
                 offset_width:offset_width + target_width]


def central_crop(image: np.ndarray, central_fraction: float) -> np.ndarray:
    """≙ tf.image.central_crop: crop the central ``fraction`` of each
    spatial dim (TF's exact offset arithmetic, so shapes match)."""
    if not 0.0 < central_fraction <= 1.0:
        raise ValueError("central_fraction must be in (0, 1]")
    image = np.asarray(image)
    if central_fraction == 1.0:
        return image
    h, w = image.shape[0], image.shape[1]
    start_h = int((h - h * central_fraction) / 2)
    start_w = int((w - w * central_fraction) / 2)
    return image[start_h:h - start_h, start_w:w - start_w]


def resize_bilinear(image: np.ndarray, target_height: int,
                    target_width: int) -> np.ndarray:
    """TF2 bilinear resize (half-pixel centers, no antialias) -> float32.

    ≙ tf.image.resize(method="bilinear"): source coordinate for output
    pixel i is ``(i + 0.5) * in/out - 0.5``, clamped; corners blend the
    two nearest source pixels with the fractional weight.
    """
    image = np.asarray(image)
    in_h, in_w = image.shape[0], image.shape[1]
    out = image.astype(np.float32)

    def axis_coords(n_in, n_out):
        src = (np.arange(n_out, dtype=np.float32) + 0.5) \
            * (n_in / n_out) - 0.5
        src = np.clip(src, 0.0, n_in - 1)
        lo = np.floor(src).astype(np.int64)
        hi = np.minimum(lo + 1, n_in - 1)
        frac = (src - lo).astype(np.float32)
        return lo, hi, frac

    if in_h != target_height:
        lo, hi, frac = axis_coords(in_h, target_height)
        frac = frac.reshape(-1, *([1] * (out.ndim - 1)))
        out = out[lo] * (1.0 - frac) + out[hi] * frac
    if in_w != target_width:
        lo, hi, frac = axis_coords(in_w, target_width)
        frac = frac.reshape(1, -1, *([1] * (out.ndim - 2)))
        out = out[:, lo] * (1.0 - frac) + out[:, hi] * frac
    return out


# ---------------------------------------------------------------------------
# Preprocessing (≙ TFK/src/layers/preprocessing/*, stateless-seeded)
# ---------------------------------------------------------------------------

class Rescaling:
    """≙ keras.layers.Rescaling: ``x * scale + offset`` as float32."""

    def __init__(self, scale: float, offset: float = 0.0):
        self.scale = float(scale)
        self.offset = float(offset)

    def __call__(self, image: np.ndarray) -> np.ndarray:
        return np.asarray(image).astype(np.float32) * self.scale \
            + self.offset


class RandomFlip:
    """≙ keras.layers.RandomFlip("horizontal"), stateless per-element:
    ``flip(image, seed)`` draws the coin from ``(base_seed, seed)`` only
    — identical at any map worker count."""

    def __init__(self, mode: str = "horizontal", seed: int = 0):
        if mode != "horizontal":
            raise ValueError(
                f"RandomFlip supports mode='horizontal', got {mode!r} "
                f"(vertical flips are not part of the ResNet recipe)")
        self.mode = mode
        self.seed = int(seed)

    def __call__(self, image: np.ndarray, seed: int = 0) -> np.ndarray:
        rng = np.random.default_rng((self.seed, int(seed) & 0xFFFFFFFF))
        if rng.random() < 0.5:
            return flip_left_right(image)
        return np.asarray(image)


class RandomCrop:
    """≙ keras.layers.RandomCrop(h, w), stateless per-element; images
    smaller than the target are bilinearly upsized first (keras's own
    fallback behavior)."""

    def __init__(self, height: int, width: int, seed: int = 0):
        self.height = int(height)
        self.width = int(width)
        self.seed = int(seed)

    def __call__(self, image: np.ndarray, seed: int = 0) -> np.ndarray:
        image = np.asarray(image)
        h, w = image.shape[0], image.shape[1]
        if h < self.height or w < self.width:
            image = resize_bilinear(image, max(h, self.height),
                                    max(w, self.width))
            h, w = image.shape[0], image.shape[1]
        rng = np.random.default_rng((self.seed, int(seed) & 0xFFFFFFFF))
        oy = int(rng.integers(0, h - self.height + 1))
        ox = int(rng.integers(0, w - self.width + 1))
        return crop_to_bounding_box(image, oy, ox, self.height, self.width)


def element_seed(path: str) -> int:
    """Stable per-element augmentation seed from the file path — shard-
    and worker-count-independent (a counter would not be)."""
    return zlib.crc32(os.path.basename(path).encode())


# ---------------------------------------------------------------------------
# On-disk dataset helpers (example / bench / tests)
# ---------------------------------------------------------------------------

_LABEL_RE = re.compile(r"_cls(\d+)\.")


def label_from_path(path: str) -> int:
    """Parse the label a :func:`generate_jpeg_directory` filename
    carries (``..._cls<label>.jpg``)."""
    m = _LABEL_RE.search(os.path.basename(path))
    if not m:
        raise ValueError(
            f"cannot parse label from {path!r}; expected a "
            f"'..._cls<label>.jpg' filename "
            f"(generate_jpeg_directory layout)")
    return int(m.group(1))


def generate_jpeg_directory(path: str, num_images: int,
                            image_size: int = 96, num_classes: int = 10,
                            seed: int = 0, quality: int = 90
                            ) -> "list[str]":
    """Write ``num_images`` real JPEG files (labels in the filename)
    and return the sorted file list. Content is structured (per-class
    gradient + noise), so decode cost and compressibility are
    realistic, not flat-color degenerate."""
    os.makedirs(path, exist_ok=True)
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:image_size, 0:image_size].astype(np.float32)
    files = []
    for i in range(num_images):
        label = int(rng.integers(num_classes))
        phase = 2 * np.pi * label / num_classes
        base = (np.sin(xx / image_size * 4 + phase)
                + np.cos(yy / image_size * 3 - phase))
        img = np.stack([base * (c + 1) for c in range(3)], axis=-1)
        img = img + rng.normal(0, 0.35, img.shape)
        img = ((img - img.min()) / (np.ptp(img) + 1e-6) * 255).astype(
            np.uint8)
        fname = os.path.join(path, f"img_{i:06d}_cls{label:04d}.jpg")
        with open(fname, "wb") as f:
            f.write(encode_jpeg(img, quality=quality))
        files.append(fname)
    return sorted(files)


def make_decode_fn(image_size: int, *, rescale: bool = True,
                   random_flip: bool = True, crop: str = "random",
                   seed: int = 0):
    """One path -> {"image": (S, S, 3) float32, "label": int32} element
    fn for ``Dataset.map`` — the standard ResNet train recipe (decode,
    crop to S×S, horizontal flip, rescale to [0, 1])."""
    if crop not in ("random", "central"):
        raise ValueError(f"crop must be 'random' or 'central', got {crop!r}")
    cropper = RandomCrop(image_size, image_size, seed=seed)
    flipper = RandomFlip(seed=seed + 1)
    rescaler = Rescaling(1.0 / 255) if rescale else None

    def decode_one(path: str) -> dict:
        path = os.fspath(path)
        img = read_image(path)
        es = element_seed(path)
        if crop == "random":
            img = cropper(img, seed=es)
        else:
            h, w = img.shape[0], img.shape[1]
            side = min(h, w)
            img = crop_to_bounding_box(img, (h - side) // 2,
                                       (w - side) // 2, side, side)
            if side != image_size:
                img = resize_bilinear(img, image_size, image_size)
        if random_flip:
            img = flipper(img, seed=es)
        img = rescaler(img) if rescaler else img.astype(np.float32)
        return {"image": img,
                "label": np.int32(label_from_path(path))}

    return decode_one


def jpeg_pipeline(files: Sequence[str], *, batch_size: int,
                  image_size: int, num_parallel_calls: int | None = None,
                  prefetch_depth: int = 4, repeat: bool = True,
                  drop_remainder: bool = True, rescale: bool = True,
                  random_flip: bool = True, crop: str = "random",
                  seed: int = 0, num_shards: int = 1,
                  shard_index: int = 0):
    """The full real-JPEG host pipeline for ResNet training.

    files -> FILE auto-shard -> repeat -> parallel decode+augment ->
    batch -> prefetch. With ``num_parallel_calls=None`` and
    ``prefetch_depth=0`` this is the serial reference configuration the
    bench compares against.
    """
    from distributed_tensorflow_tpu.input.dataset import (
        AutoShardPolicy, Dataset, auto_shard_dataset)

    ds = Dataset.from_files(list(files), reader=lambda f: iter([f]))
    if num_shards > 1:
        ds = auto_shard_dataset(ds, num_shards, shard_index,
                                AutoShardPolicy.FILE)
    if repeat:
        ds = ds.repeat()
    ds = ds.map(make_decode_fn(image_size, rescale=rescale,
                               random_flip=random_flip, crop=crop,
                               seed=seed),
                num_parallel_calls=num_parallel_calls, name="jpeg_decode")
    ds = ds.batch(batch_size, drop_remainder=drop_remainder)
    if prefetch_depth > 0:
        ds = ds.prefetch(prefetch_depth, name="jpeg_batches")
    return ds


# ---------------------------------------------------------------------------
# Native-loader route: JPEG bytes inside TFRecords, framing/crc/shuffle/
# shard in C++ threads (native/pipeline.cc), decode in the parallel map
# ---------------------------------------------------------------------------

def write_jpeg_tfrecords(path: str, jpeg_files: Sequence[str],
                         labels: Sequence[int] | None = None) -> int:
    """Pack JPEG files into ONE TFRecord shard of tf.train.Examples
    ({"image": jpeg bytes, "label": int64}) — readable by the native
    C++ reader (:class:`input.native_loader.NativeTFRecordDataset`) and
    by TensorFlow. Labels default to the filename encoding. Returns the
    record count."""
    from distributed_tensorflow_tpu.input.example_parser import (
        encode_example)
    from distributed_tensorflow_tpu.input.native_loader import (
        write_tfrecords)

    jpeg_files = list(jpeg_files)
    if labels is None:
        labels = [label_from_path(f) for f in jpeg_files]
    if len(labels) != len(jpeg_files):
        raise ValueError(f"{len(labels)} labels for "
                         f"{len(jpeg_files)} files")

    def payloads():
        for f, lab in zip(jpeg_files, labels):
            with open(f, "rb") as fh:
                yield encode_example({"image": fh.read(),
                                      "label": np.int64(lab)})

    write_tfrecords(path, payloads())
    return len(jpeg_files)


def jpeg_tfrecord_pipeline(paths, *, batch_size: int, image_size: int,
                           num_parallel_calls: int | None = None,
                           prefetch_depth: int = 4, repeat: bool = True,
                           shuffle: bool = False, seed: int = 0,
                           num_threads: int = 2,
                           num_shards: int = 1, shard_index: int = 0,
                           rescale: bool = True, random_flip: bool = True,
                           crop: str = "random"):
    """The native-loader variant of :func:`jpeg_pipeline`: the TFRecord
    framing scan, crc verification, per-epoch shuffle, DATA-policy
    sharding and record-batch assembly run in C++ worker threads; the
    Example payloads stream into the SAME parallel decode+augment map.
    Augmentation seeds derive from the JPEG bytes (records carry no
    filename), so elements stay deterministic at any worker count."""
    from distributed_tensorflow_tpu.input.dataset import Dataset
    from distributed_tensorflow_tpu.input.example_parser import (
        FixedLenFeature, parse_single_example)
    from distributed_tensorflow_tpu.input.native_loader import (
        NativeTFRecordDataset)

    spec = {"image": FixedLenFeature((), object),
            "label": FixedLenFeature((), np.int64)}
    cropper = RandomCrop(image_size, image_size, seed=seed)
    flipper = RandomFlip(seed=seed + 1)
    rescaler = Rescaling(1.0 / 255) if rescale else None

    def records():
        native = NativeTFRecordDataset(
            paths, batch_size=batch_size, shuffle=shuffle, seed=seed,
            num_threads=num_threads, num_shards=num_shards,
            shard_index=shard_index, drop_remainder=True)
        try:
            while True:
                recs, epoch = native.next_records()
                if not repeat and epoch > 0:
                    return
                yield from recs
        except StopIteration:
            return
        finally:
            native.close()

    def decode_one(payload: bytes) -> dict:
        ex = parse_single_example(payload, spec)
        data = ex["image"] if isinstance(ex["image"], bytes) \
            else bytes(np.asarray(ex["image"]).item())
        img = decode_jpeg(data)
        es = zlib.crc32(data[:512])
        if crop == "random":
            img = cropper(img, seed=es)
        else:
            h, w = img.shape[0], img.shape[1]
            side = min(h, w)
            img = crop_to_bounding_box(img, (h - side) // 2,
                                       (w - side) // 2, side, side)
            if side != image_size:
                img = resize_bilinear(img, image_size, image_size)
        if random_flip:
            img = flipper(img, seed=es)
        img = rescaler(img) if rescaler else img.astype(np.float32)
        return {"image": img,
                "label": np.asarray(ex["label"], np.int32).reshape(())}

    ds = Dataset.from_generator(records)
    ds = ds.map(decode_one, num_parallel_calls=num_parallel_calls,
                name="tfrecord_jpeg_decode")
    ds = ds.batch(batch_size, drop_remainder=True)
    if prefetch_depth > 0:
        ds = ds.prefetch(prefetch_depth, name="tfrecord_jpeg_batches")
    return ds
