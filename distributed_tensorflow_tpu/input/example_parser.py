"""tf.Example parsing: the reference's wire format for training data.

≙ tf.io.parse_example / parse_single_example (reference:
tensorflow/python/ops/parsing_ops.py) — the reference's input pipelines
read TFRecord files of serialized ``tf.train.Example`` protos and parse
them against a feature spec. A user switching from the reference brings
those files along, so this module decodes the proto wire format
directly (no TF dependency): Example{features=1} → Features{feature=1
map<string, Feature>} → Feature{bytes_list=1, float_list=2,
int64_list=3}.

Specs mirror the reference's:
- ``FixedLenFeature(shape, dtype, default_value=None)`` — dense output,
  per-example values reshaped to ``shape``; missing features use the
  default or raise.
- ``VarLenFeature(dtype)`` — ragged output, returned per example as a
  1-D numpy array (the reference returns a SparseTensor; the TPU-native
  framework keeps host data dense/ragged and lets the embedding layer's
  combiners handle variable length).

Wire-format notes: ``float_list`` and ``int64_list`` values are packed
(one length-delimited payload) or repeated scalars — both occur in real
files and both are handled.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Any, Iterator

import numpy as np


@dataclasses.dataclass(frozen=True)
class FixedLenFeature:
    shape: tuple = ()
    dtype: Any = np.float32
    default_value: Any = None


@dataclasses.dataclass(frozen=True)
class VarLenFeature:
    dtype: Any = np.float32


# ---------------------------------------------------------------------------
# Proto wire decoding
# ---------------------------------------------------------------------------

def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated message")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("malformed varint")


def _fields(buf: bytes) -> Iterator[tuple[int, int, Any]]:
    """Yield (field_number, wire_type, value) over a message payload.
    Length-delimited values are returned as memoryview slices."""
    pos, n = 0, len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 1:
            if pos + 8 > n:
                raise ValueError("truncated message")
            val = buf[pos:pos + 8]
            pos += 8
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            if pos + ln > n:
                # A declared length running past the buffer end means a
                # truncated/corrupt proto; silently clipping the slice
                # would yield WRONG feature values downstream.
                raise ValueError("truncated message")
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            if pos + 4 > n:
                raise ValueError("truncated message")
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def _zigzag_passthrough_int64(v: int) -> int:
    """int64_list values are plain (non-zigzag) varints; reinterpret the
    unsigned decode as two's-complement int64."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _decode_float_list(payload: bytes) -> np.ndarray:
    floats: list = []
    for field, wire, val in _fields(payload):
        if field != 1:
            continue
        if wire == 2:               # packed
            floats.extend(
                struct.unpack(f"<{len(val) // 4}f", bytes(val)))
        elif wire == 5:             # repeated scalar
            floats.append(struct.unpack("<f", bytes(val))[0])
    return np.asarray(floats, np.float32)


def _decode_int64_list(payload: bytes) -> np.ndarray:
    ints: list = []
    for field, wire, val in _fields(payload):
        if field != 1:
            continue
        if wire == 2:               # packed varints
            pos, ln = 0, len(val)
            while pos < ln:
                v, pos = _read_varint(val, pos)
                ints.append(_zigzag_passthrough_int64(v))
        elif wire == 0:
            ints.append(_zigzag_passthrough_int64(val))
    return np.asarray(ints, np.int64)


def _decode_bytes_list(payload: bytes) -> list:
    return [bytes(val) for field, wire, val in _fields(payload)
            if field == 1 and wire == 2]


def _decode_feature(payload: bytes):
    """Feature { bytes_list=1, float_list=2, int64_list=3 } — each a
    wire-type-2 submessage; other wire types are malformed and skipped."""
    for field, wire, val in _fields(payload):
        if wire != 2:
            continue
        if field == 1:
            return _decode_bytes_list(bytes(val))
        if field == 2:
            return _decode_float_list(bytes(val))
        if field == 3:
            return _decode_int64_list(bytes(val))
    return np.asarray([], np.float32)      # empty Feature


def parse_single_example(serialized: bytes, features: dict) -> dict:
    """Parse ONE serialized tf.train.Example against a feature spec
    (≙ tf.io.parse_single_example)."""
    raw: dict = {}
    # Submessages are ALWAYS wire type 2; a matching field number with a
    # different wire type is garbage input (e.g. a non-Example payload
    # whose varint would otherwise be misread as a huge bytes length).
    for field, wire, val in _fields(bytes(serialized)):
        if field != 1 or wire != 2:         # Example.features
            continue
        for f2, w2, fval in _fields(bytes(val)):
            if f2 != 1 or w2 != 2:          # Features.feature (map entry)
                continue
            name = value = None
            for f3, w3, v3 in _fields(bytes(fval)):
                if w3 != 2:
                    continue
                if f3 == 1:
                    name = bytes(v3).decode()
                elif f3 == 2:
                    value = _decode_feature(bytes(v3))
            if name is not None:
                raw[name] = value

    out = {}
    for name, spec in features.items():
        value = raw.get(name)
        if isinstance(spec, VarLenFeature):
            if value is None:
                value = np.asarray([], spec.dtype)
            out[name] = np.asarray(value).astype(spec.dtype) \
                if not isinstance(value, list) else value
            continue
        if value is None or (hasattr(value, "__len__")
                             and len(value) == 0):
            if spec.default_value is None:
                raise ValueError(
                    f"feature {name!r} missing and no default_value")
            value = np.broadcast_to(
                np.asarray(spec.default_value, spec.dtype),
                spec.shape).copy()
        n_expect = int(np.prod(spec.shape)) if spec.shape else 1
        arr = np.asarray(value)
        if arr.size != n_expect:
            raise ValueError(
                f"feature {name!r}: got {arr.size} values, spec shape "
                f"{spec.shape} needs {n_expect}")
        out[name] = arr.reshape(spec.shape).astype(spec.dtype) \
            if spec.shape else arr.reshape(()).astype(spec.dtype)
    return out


def parse_example(serialized_batch, features: dict) -> dict:
    """Parse a batch of serialized Examples into stacked dense arrays
    (FixedLenFeature) / lists of ragged arrays (VarLenFeature)
    (≙ tf.io.parse_example)."""
    parsed = [parse_single_example(s, features) for s in serialized_batch]
    out: dict = {}
    for name, spec in features.items():
        vals = [p[name] for p in parsed]
        out[name] = vals if isinstance(spec, VarLenFeature) \
            else np.stack(vals)
    return out


def example_reader(features: dict):
    """Reader for ``Dataset.from_files``: TFRecord file of tf.Examples →
    per-example parsed dicts (streaming, crc32c-verified). For raw
    fixed-size numeric records, ``input/native_loader`` has the C++
    threaded scanner; tf.Example payloads are variable-length and
    parsed here on the host."""

    def read(path: str) -> Iterator[dict]:
        for payload in iter_tfrecords(path):
            yield parse_single_example(payload, features)

    return read


def iter_tfrecords(path: str) -> Iterator[bytes]:
    """Stream TFRecord framing (length + masked-crc + payload + crc),
    verifying the payload crc32c — a bit-flipped record raises instead
    of silently parsing into wrong feature values (same contract as the
    native scanner and TF's reader). Memory stays O(one record)."""
    from distributed_tensorflow_tpu.utils.summary import _masked_crc
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if not header:
                return
            if len(header) < 12:
                raise ValueError(f"truncated TFRecord header in {path}")
            (ln,) = struct.unpack("<Q", header[:8])
            (len_crc,) = struct.unpack("<I", header[8:12])
            if _masked_crc(header[:8]) != len_crc:
                raise ValueError(
                    f"TFRecord length crc mismatch in {path} (corrupt "
                    f"framing)")
            payload = f.read(ln)
            crc = f.read(4)
            if len(payload) < ln or len(crc) < 4:
                raise ValueError(f"truncated TFRecord payload in {path}")
            (expect,) = struct.unpack("<I", crc)
            if _masked_crc(payload) != expect:
                raise ValueError(
                    f"TFRecord payload crc mismatch in {path} (corrupt "
                    f"record of {ln} bytes)")
            yield payload


# ---------------------------------------------------------------------------
# Writer (tests / data prep): encode tf.train.Example — reuses the proto
# wire helpers from utils/summary (one implementation of varint framing).
# ---------------------------------------------------------------------------

from distributed_tensorflow_tpu.utils.summary import (  # noqa: E402
    _len_delim, _varint)


def encode_example(feature_dict: dict) -> bytes:
    """Serialize {name: value} into a tf.train.Example wire message.
    floats → float_list (packed), ints → int64_list (packed),
    bytes/str (scalar, list/tuple, or numpy S/U/O array) → bytes_list.
    Empty values must come as a typed empty numpy array — a bare ``[]``
    is ambiguous between the three list types and raises."""
    entries = b""
    for name, value in feature_dict.items():
        if isinstance(value, (bytes, str)):
            value = [value]
        if isinstance(value, tuple):
            value = list(value)
        if isinstance(value, np.ndarray) and value.dtype.kind in "SUO":
            value = list(value.ravel())
        if isinstance(value, list) and not value:
            raise ValueError(
                f"feature {name!r}: empty list is ambiguous (bytes/"
                f"float/int64); pass a typed empty numpy array")
        if isinstance(value, list) \
                and isinstance(value[0], (bytes, str, np.bytes_, np.str_)):
            payload = b"".join(
                _len_delim(1, v.encode() if isinstance(v, str)
                           else bytes(v))
                for v in value)
            feat = _len_delim(1, payload)           # bytes_list = 1
        else:
            arr = np.asarray(value).ravel()
            if arr.dtype == bool:
                # np.bool_ is not a np.integer subtype; without this a
                # bool feature lands in float_list and then fails the
                # int64 FixedLenFeature spec a migrating user writes.
                arr = arr.astype(np.int64)
            mask = (1 << 64) - 1
            if np.issubdtype(arr.dtype, np.integer):
                packed = b"".join(_varint(int(v) & mask) for v in arr)
                feat = _len_delim(3, _len_delim(1, packed))  # int64_list
            else:
                packed = b"".join(struct.pack("<f", float(v))
                                  for v in arr)
                feat = _len_delim(2, _len_delim(1, packed))  # float_list
        entry = _len_delim(1, name.encode()) + _len_delim(2, feat)
        entries += _len_delim(1, entry)
    return _len_delim(1, entries)           # Example { features = 1 }
