"""tf.Example parsing: the reference's wire format for training data.

≙ tf.io.parse_example / parse_single_example (reference:
tensorflow/python/ops/parsing_ops.py) — the reference's input pipelines
read TFRecord files of serialized ``tf.train.Example`` protos and parse
them against a feature spec. A user switching from the reference brings
those files along, so this module decodes the proto wire format
directly (no TF dependency): Example{features=1} → Features{feature=1
map<string, Feature>} → Feature{bytes_list=1, float_list=2,
int64_list=3}.

Specs mirror the reference's:
- ``FixedLenFeature(shape, dtype, default_value=None)`` — dense output,
  per-example values reshaped to ``shape``; missing features use the
  default or raise.
- ``VarLenFeature(dtype)`` — ragged output, returned per example as a
  1-D numpy array (the reference returns a SparseTensor; the TPU-native
  framework keeps host data dense/ragged and lets the embedding layer's
  combiners handle variable length).

Wire-format notes: ``float_list`` and ``int64_list`` values are packed
(one length-delimited payload) or repeated scalars — both occur in real
files and both are handled.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Any, Iterator

import numpy as np


@dataclasses.dataclass(frozen=True)
class FixedLenFeature:
    shape: tuple = ()
    dtype: Any = np.float32
    default_value: Any = None


@dataclasses.dataclass(frozen=True)
class VarLenFeature:
    dtype: Any = np.float32


@dataclasses.dataclass(frozen=True)
class FixedLenSequenceFeature:
    """Per-step dense feature of a SequenceExample FeatureList
    (≙ tf.io.FixedLenSequenceFeature, TF/python/ops/parsing_config.py):
    parses to (num_steps, *shape)."""
    shape: tuple = ()
    dtype: Any = np.float32
    allow_missing: bool = False


@dataclasses.dataclass(frozen=True)
class SparseFeature:
    """≙ tf.io.SparseFeature: a sparse value assembled from an
    index-carrying feature and a value-carrying feature of the SAME
    Example. Parses to a :class:`SparseValue`."""
    index_key: str
    value_key: str
    dtype: Any = np.float32
    size: int = 0
    already_sorted: bool = False


@dataclasses.dataclass(frozen=True)
class RaggedFeature:
    """≙ tf.io.RaggedFeature. The value-only form parses to a 1-D array
    per example; with ``partitions`` (outermost first, the tf.io inner
    classes below — TF/python/ops/parsing_config.py RaggedFeature) it
    parses to a :class:`RaggedValue` carrying the nested row-splits,
    matching ``tf.RaggedTensor.from_nested_row_splits`` semantics."""
    dtype: Any = np.float32
    value_key: str | None = None
    partitions: tuple = ()
    row_splits_dtype: Any = np.int64

    @dataclasses.dataclass(frozen=True)
    class RowLengths:
        key: str

    @dataclasses.dataclass(frozen=True)
    class RowSplits:
        key: str

    @dataclasses.dataclass(frozen=True)
    class RowStarts:
        key: str

    @dataclasses.dataclass(frozen=True)
    class RowLimits:
        key: str

    @dataclasses.dataclass(frozen=True)
    class ValueRowIds:
        key: str

    @dataclasses.dataclass(frozen=True)
    class UniformRowLength:
        length: int


@dataclasses.dataclass(frozen=True)
class RaggedValue:
    """Host-side ragged tensor: flat ``values`` + ``nested_row_splits``
    (outermost first) — ≙ tf.RaggedTensor.from_nested_row_splits."""
    values: np.ndarray
    nested_row_splits: tuple

    def to_list(self):
        def build(level, lo, hi):
            if level == len(self.nested_row_splits):
                return self.values[lo:hi].tolist()
            splits = self.nested_row_splits[level]
            return [build(level + 1, int(splits[i]), int(splits[i + 1]))
                    for i in range(lo, hi)]
        outer = self.nested_row_splits[0]
        return [build(1, int(outer[i]), int(outer[i + 1]))
                for i in range(len(outer) - 1)]


@dataclasses.dataclass(frozen=True)
class SparseValue:
    """Host-side sparse triplet (≙ tf.SparseTensor restricted to 1-D)."""
    indices: np.ndarray
    values: np.ndarray
    dense_shape: tuple

    def to_dense(self) -> np.ndarray:
        out = np.zeros(self.dense_shape, self.values.dtype)
        np.add.at(out, self.indices.astype(np.int64), self.values)
        return out


# ---------------------------------------------------------------------------
# Proto wire decoding
# ---------------------------------------------------------------------------

def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated message")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("malformed varint")


def _fields(buf: bytes) -> Iterator[tuple[int, int, Any]]:
    """Yield (field_number, wire_type, value) over a message payload.
    Length-delimited values are returned as memoryview slices."""
    pos, n = 0, len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 1:
            if pos + 8 > n:
                raise ValueError("truncated message")
            val = buf[pos:pos + 8]
            pos += 8
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            if pos + ln > n:
                # A declared length running past the buffer end means a
                # truncated/corrupt proto; silently clipping the slice
                # would yield WRONG feature values downstream.
                raise ValueError("truncated message")
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            if pos + 4 > n:
                raise ValueError("truncated message")
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def _zigzag_passthrough_int64(v: int) -> int:
    """int64_list values are plain (non-zigzag) varints; reinterpret the
    unsigned decode as two's-complement int64."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _decode_float_list(payload: bytes) -> np.ndarray:
    floats: list = []
    for field, wire, val in _fields(payload):
        if field != 1:
            continue
        if wire == 2:               # packed
            floats.extend(
                struct.unpack(f"<{len(val) // 4}f", bytes(val)))
        elif wire == 5:             # repeated scalar
            floats.append(struct.unpack("<f", bytes(val))[0])
    return np.asarray(floats, np.float32)


def _decode_int64_list(payload: bytes) -> np.ndarray:
    ints: list = []
    for field, wire, val in _fields(payload):
        if field != 1:
            continue
        if wire == 2:               # packed varints
            pos, ln = 0, len(val)
            while pos < ln:
                v, pos = _read_varint(val, pos)
                ints.append(_zigzag_passthrough_int64(v))
        elif wire == 0:
            ints.append(_zigzag_passthrough_int64(val))
    return np.asarray(ints, np.int64)


def _decode_bytes_list(payload: bytes) -> list:
    return [bytes(val) for field, wire, val in _fields(payload)
            if field == 1 and wire == 2]


def _decode_feature(payload: bytes):
    """Feature { bytes_list=1, float_list=2, int64_list=3 } — each a
    wire-type-2 submessage; other wire types are malformed and skipped."""
    for field, wire, val in _fields(payload):
        if wire != 2:
            continue
        if field == 1:
            return _decode_bytes_list(bytes(val))
        if field == 2:
            return _decode_float_list(bytes(val))
        if field == 3:
            return _decode_int64_list(bytes(val))
    return np.asarray([], np.float32)      # empty Feature


def _parse_features_map(buf: bytes) -> dict:
    """Features { feature = 1 map<string, Feature> } payload → raw
    {name: decoded values}."""
    raw: dict = {}
    for f2, w2, fval in _fields(buf):
        if f2 != 1 or w2 != 2:              # Features.feature (map entry)
            continue
        name = value = None
        for f3, w3, v3 in _fields(bytes(fval)):
            if w3 != 2:
                continue
            if f3 == 1:
                name = bytes(v3).decode()
            elif f3 == 2:
                value = _decode_feature(bytes(v3))
        if name is not None:
            raw[name] = value
    return raw


def _dense_from_raw(name, spec, value):
    """Resolve one FixedLenFeature against a raw decoded value."""
    if value is None or (hasattr(value, "__len__") and len(value) == 0):
        if spec.default_value is None:
            raise ValueError(
                f"feature {name!r} missing and no default_value")
        value = np.broadcast_to(
            np.asarray(spec.default_value, spec.dtype),
            spec.shape).copy()
    n_expect = int(np.prod(spec.shape)) if spec.shape else 1
    arr = np.asarray(value)
    if arr.size != n_expect:
        raise ValueError(
            f"feature {name!r}: got {arr.size} values, spec shape "
            f"{spec.shape} needs {n_expect}")
    return arr.reshape(spec.shape).astype(spec.dtype) \
        if spec.shape else arr.reshape(()).astype(spec.dtype)


def _ragged_from_raw(spec, value):
    if value is None:
        value = np.asarray([], spec.dtype)
    return np.asarray(value).astype(spec.dtype) \
        if not isinstance(value, list) else value


def _partition_splits(name, part, raw, n_next, splits_dtype):
    """Row splits for ONE ragged partition level over ``n_next`` inner
    items (≙ each RaggedFeature partition class's semantics in
    TF/python/ops/parsing_ops.py _parse_ragged_feature)."""
    RF = RaggedFeature
    if isinstance(part, RF.UniformRowLength):
        L = int(part.length)
        if L <= 0:
            raise ValueError(
                f"RaggedFeature {name!r}: UniformRowLength must be "
                f"positive, got {L}")
        if n_next % L:
            raise ValueError(
                f"RaggedFeature {name!r}: {n_next} inner items do not "
                f"divide into uniform rows of length {L}")
        return np.arange(0, n_next + 1, L, dtype=splits_dtype)
    key = np.asarray(raw.get(part.key, []), np.int64)
    if isinstance(part, RF.RowLengths):
        splits = np.concatenate([[0], np.cumsum(key)])
    elif isinstance(part, RF.RowSplits):
        splits = key
    elif isinstance(part, RF.RowStarts):
        splits = np.concatenate([key, [n_next]])
    elif isinstance(part, RF.RowLimits):
        splits = np.concatenate([[0], key])
    elif isinstance(part, RF.ValueRowIds):
        nrows = int(key.max()) + 1 if key.size else 0
        if key.size and (np.any(np.diff(key) < 0) or key.min() < 0):
            raise ValueError(
                f"RaggedFeature {name!r}: ValueRowIds feature "
                f"{part.key!r} must be nonnegative and nondecreasing")
        splits = np.concatenate(
            [[0], np.cumsum(np.bincount(key, minlength=nrows))])
    else:
        raise TypeError(
            f"RaggedFeature {name!r}: unsupported partition "
            f"{type(part).__name__}")
    splits = np.asarray(splits, splits_dtype)
    if (splits.size == 0 or splits[0] != 0
            or np.any(np.diff(splits) < 0) or splits[-1] != n_next):
        raise ValueError(
            f"RaggedFeature {name!r}: partition "
            f"{type(part).__name__} yields invalid row_splits "
            f"{splits.tolist()} over {n_next} inner items")
    return splits


def _ragged_with_partitions(name, spec, raw):
    """RaggedValue from values + partition features, innermost level
    partitioning the flat values (≙ tf.io.RaggedFeature parsing with
    ``partitions``; output matches
    tf.RaggedTensor.from_nested_row_splits)."""
    values = _ragged_from_raw(spec, raw.get(spec.value_key or name))
    values = np.asarray(values, spec.dtype)
    nested = []
    n_next = values.size
    for part in reversed(spec.partitions):
        splits = _partition_splits(name, part, raw, n_next,
                                   spec.row_splits_dtype)
        nested.append(splits)
        n_next = splits.size - 1
    return RaggedValue(values, tuple(reversed(nested)))


def parse_single_example(serialized: bytes, features: dict) -> dict:
    """Parse ONE serialized tf.train.Example against a feature spec
    (≙ tf.io.parse_single_example). Specs: FixedLenFeature,
    VarLenFeature, SparseFeature, RaggedFeature."""
    raw: dict = {}
    # Submessages are ALWAYS wire type 2; a matching field number with a
    # different wire type is garbage input (e.g. a non-Example payload
    # whose varint would otherwise be misread as a huge bytes length).
    for field, wire, val in _fields(bytes(serialized)):
        if field != 1 or wire != 2:         # Example.features
            continue
        raw.update(_parse_features_map(bytes(val)))

    return {name: _resolve_example_spec(name, spec, raw)
            for name, spec in features.items()}


def _resolve_example_spec(name, spec, raw: dict):
    """Resolve one Example-level spec (FixedLen/VarLen/Sparse/Ragged)
    against the raw decoded feature map — shared by Example parsing and
    SequenceExample context parsing."""
    if isinstance(spec, SparseFeature):
        idx = np.asarray(raw.get(spec.index_key, []), np.int64)
        vals = np.asarray(raw.get(spec.value_key, []), spec.dtype)
        if idx.shape != vals.shape:
            raise ValueError(
                f"SparseFeature {name!r}: index feature "
                f"{spec.index_key!r} has {idx.size} entries but value "
                f"feature {spec.value_key!r} has {vals.size}")
        if not spec.already_sorted and idx.size:
            order = np.argsort(idx, kind="stable")
            idx, vals = idx[order], vals[order]
        return SparseValue(idx, vals, (spec.size,))
    if isinstance(spec, RaggedFeature):
        if spec.partitions:
            return _ragged_with_partitions(name, spec, raw)
        return _ragged_from_raw(spec, raw.get(spec.value_key or name))
    if isinstance(spec, VarLenFeature):
        return _ragged_from_raw(spec, raw.get(name))
    if isinstance(spec, FixedLenFeature):
        return _dense_from_raw(name, spec, raw.get(name))
    raise TypeError(f"feature {name!r}: unsupported spec "
                    f"{type(spec).__name__}")


def parse_single_sequence_example(serialized: bytes,
                                  context_features: dict | None = None,
                                  sequence_features: dict | None = None
                                  ) -> tuple[dict, dict]:
    """Parse ONE tf.train.SequenceExample (≙
    tf.io.parse_single_sequence_example, TF/python/ops/parsing_ops.py).

    Wire: SequenceExample { context = 1 (Features),
    feature_lists = 2 (FeatureLists { feature_list = 1
    map<string, FeatureList { feature = 1 repeated Feature }> }) }.

    context_features: FixedLen/VarLen/Sparse/Ragged specs over the
    context. sequence_features: FixedLenSequenceFeature → (T, *shape)
    dense; VarLenFeature / RaggedFeature → list of per-step 1-D arrays.
    """
    context_raw: dict = {}
    lists_raw: dict = {}
    for field, wire, val in _fields(bytes(serialized)):
        if wire != 2:
            continue
        if field == 1:                      # context Features
            context_raw.update(_parse_features_map(bytes(val)))
        elif field == 2:                    # FeatureLists
            for f2, w2, fval in _fields(bytes(val)):
                if f2 != 1 or w2 != 2:      # feature_list map entry
                    continue
                name, steps = None, []
                for f3, w3, v3 in _fields(bytes(fval)):
                    if w3 != 2:
                        continue
                    if f3 == 1:
                        name = bytes(v3).decode()
                    elif f3 == 2:           # FeatureList
                        steps = [_decode_feature(bytes(v4))
                                 for f4, w4, v4 in _fields(bytes(v3))
                                 if f4 == 1 and w4 == 2]
                if name is not None:
                    lists_raw[name] = steps

    context = {name: _resolve_example_spec(name, spec, context_raw)
               for name, spec in (context_features or {}).items()}

    sequences = {}
    for name, spec in (sequence_features or {}).items():
        steps = lists_raw.get(name)
        if isinstance(spec, FixedLenSequenceFeature):
            if steps is None:
                if not spec.allow_missing:
                    raise ValueError(
                        f"sequence feature {name!r} missing and "
                        f"allow_missing=False")
                steps = []
            n_expect = int(np.prod(spec.shape)) if spec.shape else 1
            rows = []
            for t, step in enumerate(steps):
                arr = np.asarray(step)
                if arr.size != n_expect:
                    raise ValueError(
                        f"sequence feature {name!r} step {t}: got "
                        f"{arr.size} values, spec shape {spec.shape} "
                        f"needs {n_expect}")
                rows.append(arr.reshape(spec.shape)
                            if spec.shape else arr.reshape(()))
            out_shape = (len(rows), *spec.shape)
            sequences[name] = (np.stack(rows).astype(spec.dtype)
                               if rows else
                               np.zeros(out_shape, spec.dtype))
        elif isinstance(spec, (VarLenFeature, RaggedFeature)):
            steps = steps or []
            sequences[name] = [
                np.asarray(s).astype(spec.dtype)
                if not isinstance(s, list) else s for s in steps]
        else:
            raise TypeError(f"sequence feature {name!r}: unsupported "
                            f"spec {type(spec).__name__}")
    return context, sequences


def parse_sequence_example(serialized_batch,
                           context_features: dict | None = None,
                           sequence_features: dict | None = None
                           ) -> tuple[dict, dict]:
    """Batched SequenceExample parsing (≙ tf.io.parse_sequence_example):
    context FixedLen features stack densely; everything else comes back
    as per-example lists (sequence lengths differ across examples)."""
    parsed = [parse_single_sequence_example(s, context_features,
                                            sequence_features)
              for s in serialized_batch]
    ctx_out: dict = {}
    for name, spec in (context_features or {}).items():
        vals = [p[0][name] for p in parsed]
        ctx_out[name] = np.stack(vals) \
            if isinstance(spec, FixedLenFeature) else vals
    seq_out = {name: [p[1][name] for p in parsed]
               for name in (sequence_features or {})}
    return ctx_out, seq_out


def parse_example(serialized_batch, features: dict) -> dict:
    """Parse a batch of serialized Examples into stacked dense arrays
    (FixedLenFeature) / lists of ragged arrays (VarLenFeature)
    (≙ tf.io.parse_example)."""
    parsed = [parse_single_example(s, features) for s in serialized_batch]
    out: dict = {}
    for name, spec in features.items():
        vals = [p[name] for p in parsed]
        out[name] = vals if isinstance(spec, VarLenFeature) \
            else np.stack(vals)
    return out


def example_reader(features: dict):
    """Reader for ``Dataset.from_files``: TFRecord file of tf.Examples →
    per-example parsed dicts (streaming, crc32c-verified). For raw
    fixed-size numeric records, ``input/native_loader`` has the C++
    threaded scanner; tf.Example payloads are variable-length and
    parsed here on the host."""

    def read(path: str) -> Iterator[dict]:
        for payload in iter_tfrecords(path):
            yield parse_single_example(payload, features)

    return read


class _ZlibStream:
    """Streaming decompressor with a file-like read() — keeps
    iter_tfrecords' O(one record) memory contract for ZLIB files."""

    _CHUNK = 1 << 16

    def __init__(self, f):
        import zlib
        self._f = f
        self._d = zlib.decompressobj()
        self._buf = b""

    def read(self, n: int) -> bytes:
        while len(self._buf) < n:
            raw = self._f.read(self._CHUNK)
            if not raw:
                self._buf += self._d.flush()
                break
            self._buf += self._d.decompress(raw)
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _open_maybe_compressed(path: str):
    """Open a TFRecord file, transparently decompressing GZIP/ZLIB
    streams (≙ TFRecordOptions compression_type,
    TF/python/lib/io/tf_record.py — real corpora are very often gzip
    TFRecords).

    Detection order matters: a VALID plain TFRecord header (length
    crc32c at offset 8 matches) wins over any magic-byte coincidence —
    an uncompressed file whose first record length encodes to
    0x78 0x01/0x5e/0x9c/0xda would otherwise be misread as ZLIB."""
    from distributed_tensorflow_tpu.utils.summary import _masked_crc
    with open(path, "rb") as probe:
        head = probe.read(12)
    if len(head) == 12 and _masked_crc(head[:8]) == struct.unpack(
            "<I", head[8:12])[0]:
        return open(path, "rb")              # valid plain framing
    if head[:2] == b"\x1f\x8b":
        import gzip
        return gzip.open(path, "rb")
    if len(head) >= 2 and head[0] == 0x78 and head[1] in (
            0x01, 0x5e, 0x9c, 0xda):
        return _ZlibStream(open(path, "rb"))
    return open(path, "rb")


def iter_tfrecords(path: str) -> Iterator[bytes]:
    """Stream TFRecord framing (length + masked-crc + payload + crc),
    verifying the payload crc32c — a bit-flipped record raises instead
    of silently parsing into wrong feature values (same contract as the
    native scanner and TF's reader). Memory stays O(one record);
    GZIP/ZLIB files are decompressed transparently."""
    from distributed_tensorflow_tpu.utils.summary import _masked_crc
    with _open_maybe_compressed(path) as f:
        while True:
            header = f.read(12)
            if not header:
                return
            if len(header) < 12:
                raise ValueError(f"truncated TFRecord header in {path}")
            (ln,) = struct.unpack("<Q", header[:8])
            (len_crc,) = struct.unpack("<I", header[8:12])
            if _masked_crc(header[:8]) != len_crc:
                raise ValueError(
                    f"TFRecord length crc mismatch in {path} (corrupt "
                    f"framing)")
            payload = f.read(ln)
            crc = f.read(4)
            if len(payload) < ln or len(crc) < 4:
                raise ValueError(f"truncated TFRecord payload in {path}")
            (expect,) = struct.unpack("<I", crc)
            if _masked_crc(payload) != expect:
                raise ValueError(
                    f"TFRecord payload crc mismatch in {path} (corrupt "
                    f"record of {ln} bytes)")
            yield payload


# ---------------------------------------------------------------------------
# Writer (tests / data prep): encode tf.train.Example — reuses the proto
# wire helpers from utils/summary (one implementation of varint framing).
# ---------------------------------------------------------------------------

from distributed_tensorflow_tpu.utils.summary import (  # noqa: E402
    _len_delim, _varint)


def _encode_feature(name, value) -> bytes:
    """One Feature message body: floats → float_list (packed), ints →
    int64_list (packed), bytes/str → bytes_list."""
    if isinstance(value, (bytes, str)):
        value = [value]
    if isinstance(value, tuple):
        value = list(value)
    if isinstance(value, np.ndarray) and value.dtype.kind in "SUO":
        value = list(value.ravel())
    if isinstance(value, list) and not value:
        raise ValueError(
            f"feature {name!r}: empty list is ambiguous (bytes/"
            f"float/int64); pass a typed empty numpy array")
    if isinstance(value, list) \
            and isinstance(value[0], (bytes, str, np.bytes_, np.str_)):
        payload = b"".join(
            _len_delim(1, v.encode() if isinstance(v, str)
                       else bytes(v))
            for v in value)
        return _len_delim(1, payload)           # bytes_list = 1
    arr = np.asarray(value).ravel()
    if arr.dtype == bool:
        # np.bool_ is not a np.integer subtype; without this a
        # bool feature lands in float_list and then fails the
        # int64 FixedLenFeature spec a migrating user writes.
        arr = arr.astype(np.int64)
    mask = (1 << 64) - 1
    if np.issubdtype(arr.dtype, np.integer):
        packed = b"".join(_varint(int(v) & mask) for v in arr)
        return _len_delim(3, _len_delim(1, packed))      # int64_list
    packed = b"".join(struct.pack("<f", float(v)) for v in arr)
    return _len_delim(2, _len_delim(1, packed))          # float_list


def _encode_features_map(feature_dict: dict) -> bytes:
    entries = b""
    for name, value in feature_dict.items():
        feat = _encode_feature(name, value)
        entry = _len_delim(1, name.encode()) + _len_delim(2, feat)
        entries += _len_delim(1, entry)
    return entries


def encode_example(feature_dict: dict) -> bytes:
    """Serialize {name: value} into a tf.train.Example wire message.
    floats → float_list (packed), ints → int64_list (packed),
    bytes/str (scalar, list/tuple, or numpy S/U/O array) → bytes_list.
    Empty values must come as a typed empty numpy array — a bare ``[]``
    is ambiguous between the three list types and raises."""
    return _len_delim(1, _encode_features_map(feature_dict))


def encode_sequence_example(context: dict, feature_lists: dict) -> bytes:
    """Serialize a tf.train.SequenceExample: ``context`` is an Example-
    style {name: value} dict; ``feature_lists`` maps name → list of
    per-step values (each encoded as one Feature)."""
    lists = b""
    for name, steps in feature_lists.items():
        flist = b"".join(_len_delim(1, _encode_feature(name, s))
                         for s in steps)
        entry = _len_delim(1, name.encode()) + _len_delim(2, flist)
        lists += _len_delim(1, entry)
    return (_len_delim(1, _encode_features_map(context))
            + _len_delim(2, lists))
